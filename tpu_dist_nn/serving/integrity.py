"""Silent-corruption defense plane (docs/ROBUSTNESS.md "Silent
corruption & quarantine").

Every resilience layer so far assumes a failing replica fails LOUDLY —
UNAVAILABLE, DEADLINE_EXCEEDED, a crash the flight recorder catches.
The failure class that actually corrupts results at fleet scale is the
replica that answers fast and *wrong*: flipped weight bits after a bad
checkpoint read, a mercurial core producing garbage matmuls ("Cores
that don't count", Hochschild et al., HotOS '21; "Silent Data
Corruptions at Scale", Dixit et al. '21 — PAPERS.md), NaN/Inf blowups
that argmax into confident nonsense. This module is the detector
ladder the router uses to PROVE a replica computes correctly, not just
that it is reachable:

* **Checkpoint fingerprints** — per-array SHA-256 checksums over the
  raw bytes (dtype + shape + buffer), folded into one whole-model
  fingerprint. Written into checkpoint metadata at save, verified at
  restore (:mod:`tpu_dist_nn.checkpoint.orbax_store`), exposed on
  ``/healthz`` so the pool refuses to admit a replica whose loaded
  weights disagree with the fleet's.
* **Numeric guards** (:class:`NumericGuard`) — a cheap per-row
  ``isfinite`` + magnitude reduction at the existing launch
  boundaries (the serving batcher's fetch, the continuous scheduler's
  decode step). Affected rows fail with
  :class:`~tpu_dist_nn.utils.errors.IntegrityError` (wire: DATA_LOSS)
  instead of shipping NaN activations; unaffected rows in the same
  launch are untouched (bit-parity preserved). ``TDN_INTEGRITY_GUARD=0``
  or ``GUARD.enabled = False`` opts out (benches).
* **Canary probes** (:class:`CanaryProber`) — a fixed seeded input
  with a golden temperature-0 answer, ridden on the pool's scrape
  loop. The serving stack is bit-identical at temperature 0 across
  replicas of the same weights (the PR-15/16 replay guarantee), so the
  golden digest is established from the first healthy answer and every
  later disagreement is a corruption verdict, not noise.
* **Shadow spot-checks** (:class:`SpotChecker`) — a sampled fraction
  of real Process traffic duplicated to a second replica off the
  request path; reply-byte disagreement is arbitrated by an immediate
  canary probe of both replicas (two replicas disagreeing only says
  SOMEONE is wrong).

A verdict from any rung moves the replica to the pool's QUARANTINED
state (:meth:`~tpu_dist_nn.serving.pool.ReplicaPool.quarantine`) —
placement stops, an incident bundle freezes the evidence, and
re-admission requires fingerprint + canary to pass again. Deliberately
distinct from the circuit breaker: a breaker half-open probe asks "are
you reachable?", which a wrong replica answers perfectly.
"""

from __future__ import annotations

import hashlib
import os
import random
import threading

import numpy as np

from tpu_dist_nn.obs.log import get_logger
from tpu_dist_nn.obs.registry import REGISTRY

slog = get_logger(__name__)

# One fixed seed for every canary input in the fleet: the probe's whole
# value is that every replica of the same weights computes the SAME
# answer, so the input must be a constant of the system, not a knob.
CANARY_SEED = 0x7DD

# rows the numeric guard failed with INTEGRITY instead of shipping
# non-finite (or absurd-magnitude) activations downstream.
GUARD_ROWS_FAILED = REGISTRY.counter(
    "tdn_integrity_guard_rows_total",
    "rows failed by the numeric guard (non-finite or out-of-magnitude "
    "activations caught at the launch boundary)",
)
GUARD_LAUNCHES = REGISTRY.counter(
    "tdn_integrity_guard_launches_total",
    "device launches in which the numeric guard failed at least one row",
)
CANARY_PROBES = REGISTRY.counter(
    "tdn_canary_probes_total",
    "canary probes by verdict (pass / fail / error; error = transport "
    "failure, NOT an integrity verdict — the breaker owns reachability)",
    labels=("verdict",),
)
SPOTCHECKS = REGISTRY.counter(
    "tdn_integrity_spotchecks_total",
    "shadow spot-checks by verdict (match / mismatch / error)",
    labels=("verdict",),
)


# --------------------------------------------------------- fingerprints


def array_checksum(a) -> str:
    """SHA-256 over an array's dtype + shape + raw little-endian bytes.

    Deterministic across processes and hosts for equal values: the
    buffer is canonicalized to C-contiguous before hashing, and dtype
    is part of the digest so an f32/f64 confusion cannot collide."""
    a = np.asarray(a)
    h = hashlib.sha256()
    h.update(str(a.dtype).encode())
    h.update(repr(tuple(a.shape)).encode())
    h.update(np.ascontiguousarray(a).tobytes())
    return h.hexdigest()


def _named_leaves(tree) -> list[tuple[str, object]]:
    """(path, leaf) pairs for every array-like leaf of a pytree. A
    plain ``{name: array}`` dict short-circuits without jax so the
    fingerprint helpers work where jax is absent (router-only
    processes)."""
    if isinstance(tree, dict) and all(
        hasattr(v, "shape") and hasattr(v, "dtype") for v in tree.values()
    ):
        return sorted(tree.items())
    import jax

    pairs, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [
        (jax.tree_util.keystr(path), leaf)
        for path, leaf in pairs
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype")
    ]


def fingerprint_tree(tree) -> dict:
    """Per-array checksums plus the whole-model fingerprint.

    Returns ``{"model": sha, "arrays": {path: sha}, "count": n}``.
    The model fingerprint hashes the sorted ``path=checksum`` lines, so
    it pins both every array's bytes AND the tree structure (a renamed
    or dropped array changes it)."""
    arrays = {path: array_checksum(leaf) for path, leaf in _named_leaves(tree)}
    h = hashlib.sha256()
    for path in sorted(arrays):
        h.update(f"{path}={arrays[path]}\n".encode())
    return {"model": h.hexdigest(), "arrays": arrays, "count": len(arrays)}


def verify_tree(tree, expected: dict) -> list[str]:
    """Check a pytree against a fingerprint written at save time.

    Returns human-readable mismatch descriptions (empty = verified).
    Structure drift (missing/extra arrays) is reported alongside value
    drift — a truncated restore is as corrupt as a flipped bit."""
    got = fingerprint_tree(tree)
    exp_arrays = dict(expected.get("arrays") or {})
    mismatches = []
    for path, sha in sorted(got["arrays"].items()):
        want = exp_arrays.pop(path, None)
        if want is None:
            mismatches.append(f"{path}: not in saved fingerprint")
        elif want != sha:
            mismatches.append(
                f"{path}: checksum {sha[:12]}… != saved {want[:12]}…"
            )
    for path in sorted(exp_arrays):
        mismatches.append(f"{path}: missing from restored state")
    want_model = expected.get("model")
    if not mismatches and want_model and want_model != got["model"]:
        mismatches.append(
            f"model fingerprint {got['model'][:12]}… != saved "
            f"{want_model[:12]}…"
        )
    return mismatches


# ------------------------------------------------------- numeric guard


class NumericGuard:
    """Cheap per-row corruption screen at a launch boundary.

    ``bad_rows(out)`` reduces a materialized float batch to a ``(N,)``
    bool mask of rows carrying non-finite values or magnitudes past
    ``abs_limit`` — one vectorized pass over memory the caller just
    materialized anyway, so arming it costs well under the 5%
    throughput budget the bench gates. Callers fail exactly the masked
    rows with IntegrityError and ship the rest untouched.

    Disabled via ``TDN_INTEGRITY_GUARD=0`` at import, or
    ``GUARD.enabled = False`` at runtime (the bench A/B's disarmed
    arm)."""

    def __init__(self, enabled: bool | None = None,
                 abs_limit: float = 1e8):
        if enabled is None:
            enabled = os.environ.get("TDN_INTEGRITY_GUARD", "1") != "0"
        self.enabled = bool(enabled)
        self.abs_limit = float(abs_limit)

    def bad_rows(self, out) -> np.ndarray | None:
        """``(N,)`` bool mask of corrupt rows; None when the guard is
        disabled or the output is not a float batch (token ids are
        screened in-kernel by the continuous scheduler instead)."""
        if not self.enabled:
            return None
        out = np.asarray(out)
        if out.dtype.kind != "f" or out.ndim == 0 or out.size == 0:
            return None
        axes = tuple(range(1, out.ndim))
        finite = np.isfinite(out)
        ok = finite.all(axis=axes) if axes else finite
        if self.abs_limit:
            # where() masks the non-finite entries first: abs(inf) >
            # limit is already caught by the finite check, and abs(nan)
            # comparisons would warn.
            bounded = np.abs(np.where(finite, out, 0.0)) <= self.abs_limit
            ok = ok & (bounded.all(axis=axes) if axes else bounded)
        bad = ~ok
        if bad.any():
            GUARD_ROWS_FAILED.inc(int(bad.sum()))
            GUARD_LAUNCHES.inc()
        return bad


# Process-wide guard instance — the serving batcher, the continuous
# scheduler, and the bench A/B all arm/disarm THIS object.
GUARD = NumericGuard()


# ------------------------------------------------------- canary probes


def canary_rows(dim: int, rows: int = 2,
                seed: int = CANARY_SEED) -> np.ndarray:
    """The fixed seeded Process canary input: same (rows, dim) batch on
    every prober in the fleet."""
    rng = np.random.default_rng(seed)
    return rng.uniform(0.0, 1.0, (int(rows), int(dim)))


def canary_prompts(prompt_len: int, vocab_size: int, rows: int = 1,
                   seed: int = CANARY_SEED) -> np.ndarray:
    """The fixed seeded Generate canary prompt(s) — token ids ride the
    Matrix wire as exact doubles."""
    rng = np.random.default_rng(seed)
    return rng.integers(
        0, int(vocab_size), (int(rows), int(prompt_len))
    ).astype(np.float64)


def reply_digest(reply_bytes: bytes) -> str:
    """Digest of a raw wire reply. The encoder is deterministic and the
    serving stack bit-identical at temperature 0, so equal answers
    yield equal bytes — comparing digests needs no decode."""
    return hashlib.sha256(reply_bytes).hexdigest()


class CanaryProber:
    """Golden-answer probing for one fleet.

    The first successful answer per method establishes the golden
    digest (recording which replica set it); every later probe is an
    exact-match check against it. Thread-safe — the pool's scrape loop
    fans probes out across replicas concurrently.

    ``probe(rep)`` returns ``(verdict, evidence)``:

    * ``True`` — answered on-golden (or just established the golden).
    * ``False`` — answered OFF-golden: a corruption verdict.
    * ``None`` — no answer (transport error/timeout): reachability is
      the breaker's problem, not an integrity verdict.
    """

    def __init__(self, *, dim: int | None = None,
                 prompt_len: int | None = None,
                 vocab_size: int | None = None,
                 interval: float = 5.0, timeout: float = 5.0,
                 rows: int = 2, seed: int = CANARY_SEED):
        from tpu_dist_nn.serving.wire import encode_matrix

        self.interval = float(interval)
        self.timeout = float(timeout)
        self._lock = threading.Lock()
        self.golden: dict[str, str] = {}  # guarded-by: _lock
        self.golden_source: dict[str, str] = {}  # guarded-by: _lock
        self._payloads: dict[str, bytes] = {}
        if dim is not None:
            self._payloads["Process"] = encode_matrix(
                canary_rows(dim, rows=rows, seed=seed)
            )
        if prompt_len is not None:
            self._payloads["Generate"] = encode_matrix(
                canary_prompts(prompt_len, vocab_size or 64, seed=seed)
            )
        if not self._payloads:
            raise ValueError(
                "CanaryProber needs dim= (Process) and/or prompt_len= "
                "(Generate)"
            )

    def methods(self) -> tuple[str, ...]:
        return tuple(self._payloads)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "methods": list(self._payloads),
                "golden": dict(self.golden),
                "golden_source": dict(self.golden_source),
                "interval": self.interval,
            }

    def check_reply(self, method: str, reply_bytes: bytes,
                    source: str) -> tuple[bool, dict]:
        """Compare one raw reply against the golden digest,
        establishing it from ``source`` when first seen."""
        digest = reply_digest(reply_bytes)
        with self._lock:
            golden = self.golden.get(method)
            if golden is None:
                self.golden[method] = digest
                self.golden_source[method] = source
                slog.info("integrity.canary_golden", method=method,
                          source=source, digest=digest[:12])
                return True, {"method": method, "digest": digest,
                              "established": True}
            golden_source = self.golden_source.get(method)
        if digest == golden:
            return True, {"method": method, "digest": digest}
        return False, {
            "method": method, "digest": digest, "golden": golden,
            "golden_source": golden_source,
        }

    def probe(self, rep) -> tuple[bool | None, dict]:
        """Probe one replica (a :class:`~tpu_dist_nn.serving.pool.
        Replica` or anything with ``.call(method, payload, timeout=)``
        and ``.target``) across every armed method."""
        target = getattr(rep, "target", "?")
        evidence: dict = {"target": target}
        for method, payload in self._payloads.items():
            try:
                reply = rep.call(method, payload, timeout=self.timeout)
            except Exception as e:  # noqa: BLE001 — transport, not verdict
                CANARY_PROBES.labels(verdict="error").inc()
                evidence.update({"method": method, "error": repr(e)[:200]})
                return None, evidence
            ok, ev = self.check_reply(method, reply, target)
            if not ok:
                CANARY_PROBES.labels(verdict="fail").inc()
                evidence.update(ev)
                slog.warning("integrity.canary_mismatch", replica=target,
                             **{k: v for k, v in ev.items()
                                if k in ("method", "digest", "golden")})
                return False, evidence
            CANARY_PROBES.labels(verdict="pass").inc()
        evidence["methods"] = list(self._payloads)
        return True, evidence


# ------------------------------------------------------- spot-checking


class SpotChecker:
    """Low-rate shadow duplication of real Process traffic.

    The router hands each successful (request, reply, replica) triple
    to :meth:`maybe_check`; a seeded coin at ``rate`` picks requests to
    duplicate to a second replica on a background thread (zero added
    latency on the request path; at most ``max_inflight`` shadows in
    flight, excess samples dropped). Reply-byte mismatch is arbitrated
    by an immediate canary probe of BOTH replicas — disagreement alone
    cannot say which side is wrong — and the losing replica is handed
    to ``on_verdict``."""

    def __init__(self, pool, *, rate: float = 0.02, seed: int = 0,
                 timeout: float = 5.0, canary: CanaryProber | None = None,
                 on_verdict=None, max_inflight: int = 2):
        if not 0.0 <= rate <= 1.0:
            raise ValueError(f"rate must be in [0, 1], got {rate}")
        self.pool = pool
        self.rate = float(rate)
        self.timeout = float(timeout)
        self.canary = canary
        # on_verdict(target, reason, evidence) — the router wires this
        # to pool.quarantine.
        self.on_verdict = on_verdict
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._inflight = 0
        self._max_inflight = int(max_inflight)
        self.mismatches = 0

    def maybe_check(self, method: str, payload: bytes, reply: bytes,
                    primary_target: str) -> bool:
        """Sample-and-dispatch; returns True when a shadow launched."""
        if method != "Process" or self.rate <= 0.0:
            return False
        with self._lock:
            # One seeded stream under a lock: the sampled request
            # indices replay deterministically for a serial driver.
            if self._rng.random() >= self.rate:
                return False
            if self._inflight >= self._max_inflight:
                return False
            self._inflight += 1
        t = threading.Thread(
            target=self._run, args=(method, payload, reply, primary_target),
            name="tdn-spotcheck", daemon=True,
        )
        t.start()
        return True

    def _run(self, method: str, payload: bytes, reply: bytes,
             primary_target: str) -> None:
        try:
            shadow = self.pool.place(exclude=frozenset((primary_target,)))
            if shadow is None:
                return
            try:
                self.pool.begin(shadow)
                try:
                    shadow_reply = shadow.call(
                        method, payload, timeout=self.timeout
                    )
                finally:
                    self.pool.done(shadow)
            except Exception:  # noqa: BLE001 — transport, not verdict
                SPOTCHECKS.labels(verdict="error").inc()
                return
            if reply_digest(shadow_reply) == reply_digest(reply):
                SPOTCHECKS.labels(verdict="match").inc()
                return
            SPOTCHECKS.labels(verdict="mismatch").inc()
            with self._lock:
                self.mismatches += 1
            slog.warning("integrity.spotcheck_mismatch",
                         primary=primary_target, shadow=shadow.target)
            self._arbitrate(primary_target, shadow)
        finally:
            with self._lock:
                self._inflight -= 1

    def _arbitrate(self, primary_target: str, shadow) -> None:
        """Two replicas disagreed on the same input: canary-probe both
        and indict whichever answers off-golden."""
        if self.canary is None or self.on_verdict is None:
            return
        suspects = []
        primary = None
        for rep in self.pool.replicas():
            if rep.target == primary_target:
                primary = rep
        for name, rep in (("primary", primary), ("shadow", shadow)):
            if rep is None:
                continue
            verdict, ev = self.canary.probe(rep)
            if verdict is False:
                suspects.append((rep.target, name, ev))
        for target, name, ev in suspects:
            ev = dict(ev)
            ev["detector"] = "spotcheck"
            ev["disagreed_with"] = (
                shadow.target if name == "primary" else primary_target
            )
            self.on_verdict(target, "spotcheck", ev)


def overhead_snapshot() -> dict:
    """Counter totals for bench artifacts (absent families read 0)."""
    def total(name: str) -> float:
        m = REGISTRY.get(name)
        if m is None:
            return 0.0
        return float(sum(child.value for _, child in m.samples()))

    return {
        "guard_rows_failed": total("tdn_integrity_guard_rows_total"),
        "canary_probes": total("tdn_canary_probes_total"),
        "spotchecks": total("tdn_integrity_spotchecks_total"),
        "quarantines": total("tdn_quarantines_total"),
    }
