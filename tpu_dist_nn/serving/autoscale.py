"""Closed-loop fleet autopilot: burn-rate-driven autoscaling.

PR 9 built the sensor plane (``tdn_slo_burn_rate``, slot-occupancy and
pending-rows gauges scraped into the router's pool view) and PR 8
built the actuator plane (``ReplicaPool.spawn_local`` / drain /
``remove`` with the full drain-rejoin choreography). This module is
the controller between them: an :class:`Autoscaler` that runs on the
router's EXISTING runtime-sampler tick
(:meth:`~tpu_dist_nn.obs.runtime.RuntimeSampler.add_autoscaler`),
reads the fleet state the pool has already scraped, and grows or
shrinks the fleet exclusively through the existing choreography — so
every replica the autoscaler touches gets the same zero-downtime
guarantees an operator's ``--drain-replica`` does.

**Signals** (all host-side reads, never a request-path cost):

* SLO burn rate — the fast-window verdict the attached
  :class:`~tpu_dist_nn.obs.slo.SLOTracker` computed earlier in the
  same sampler tick (the tracker evaluates before autoscalers tick).
  Fast burn > 1 means the fleet is on track to blow its error budget:
  the page condition, and here the scale-up condition.
* Fleet utilization — per active replica, the scraped continuous-
  decode slot occupancy plus the row backlog (scraped pending rows +
  the router's own live outstanding count) normalized by
  ``rows_capacity`` and the replica's capacity weight; averaged over
  the fleet. Above the hysteresis ceiling = saturated, below the
  floor = over-provisioned.

**Decisions** are deliberately slower than the signals:

* Hysteresis — the target occupancy is a BAND
  (``target * (1 ± hysteresis)``); inside it the fleet is left alone.
* Consecutive-tick stability — a breach must persist for
  ``up_stable_ticks`` / ``down_stable_ticks`` sampler ticks before it
  becomes a decision (one slow scrape is noise, not load).
* Cooldowns — at most one scale-up per ``up_cooldown`` seconds and
  one scale-down per ``down_cooldown`` (down is slower: adding
  capacity under load is urgent, removing it never is).
* Flap suppression — a direction reversal (up then down, or down then
  up) within ``flap_window`` seconds is a flap; at
  ``flap_reversals`` reversals the autoscaler SUPPRESSES itself for
  ``flap_cooldown``, bumps ``tdn_autoscale_flaps_total`` (the
  ``autoscale.flap`` incident detector rides the delta), and emits a
  structured warning. A crash-respawn storm cannot double-trigger
  either way: a replica mid-respawn still counts toward the fleet
  size (see :meth:`Autoscaler.current_size`), so a crash does not
  read as "fleet shrank, spawn another".

**Actuation**:

* Scale-up calls the injected ``spawner`` (the CLI wires
  ``pool.spawn_local``) on its own thread — an engine boot takes
  minutes and must never block the sampler tick; the in-flight spawn
  counts toward the fleet size so the next ticks do not double-spawn.
* Scale-down picks the least-loaded active replica and runs
  :meth:`~tpu_dist_nn.serving.pool.ReplicaPool.decommission` — the
  observed-drain choreography (stop placing → SIGTERM a spawned
  child → its GracefulDrain finishes in-flight work → exit) — and
  only calls ``remove`` once the router holds zero outstanding
  forwards on it, so a scale-down NEVER drops an in-flight request.

**Manual override**: ``POST /router/scale?replicas=N`` on the
router's admin surface parks the fleet at N (still clamped to
min/max, still through the same choreography, cooldowns and flap
suppression bypassed — the operator said so); ``?mode=auto`` hands
control back to the policy.

Everything is stdlib + in-repo modules; docs/SCALING.md "Autopilot"
is the operator guide.
"""

from __future__ import annotations

import collections
import logging
import threading
import time

from tpu_dist_nn.obs.log import get_logger
from tpu_dist_nn.obs.registry import REGISTRY
from tpu_dist_nn.serving.pool import ACTIVE, DRAINING, ReplicaPool

log = logging.getLogger(__name__)
slog = get_logger(__name__)
# A scale-up with no actuator (static fleet, nothing parked) can
# recur every sampler tick for as long as the overload lasts — news
# the first couple of times, log spam per-tick. Tight bucket, the
# slo.burn pattern.
_noact_log = get_logger(__name__ + ".no_actuator", rate=1.0 / 60.0,
                        burst=2)

AUTOSCALE_DESIRED = REGISTRY.gauge(
    "tdn_autoscale_desired_replicas",
    "fleet size the autoscaler is converging to (min/max-clamped; "
    "equals the current size while no decision is pending)",
)
AUTOSCALE_UTIL = REGISTRY.gauge(
    "tdn_autoscale_fleet_utilization",
    "blended fleet utilization the policy compares to its target "
    "band: mean over active replicas of slot occupancy + row backlog "
    "/ rows_capacity (1.0 ~ every replica exactly saturated)",
)
AUTOSCALE_DECISIONS = REGISTRY.counter(
    "tdn_autoscale_decisions_total",
    "scale decisions actually actuated, per direction",
    labels=("action",),
)
AUTOSCALE_FLAPS = REGISTRY.counter(
    "tdn_autoscale_flaps_total",
    "flap suppressions: scale decisions reversed direction within the "
    "flap window often enough that the autoscaler muted itself (the "
    "autoscale.flap incident detector fires on this delta)",
)
AUTOSCALE_SUPPRESSED = REGISTRY.gauge(
    "tdn_autoscale_flap_suppressed",
    "1 while flap suppression is muting automatic scale decisions",
)


class Autoscaler:
    """The policy engine. Construct it next to the router's pool and
    register with :meth:`RuntimeSampler.add_autoscaler`; every sampler
    tick calls :meth:`tick` once. Tests drive :meth:`tick` directly
    with an injected ``clock``.

    ``spawner`` is a zero-arg callable that adds one replica to the
    pool and blocks until it serves (the CLI wires
    ``pool.spawn_local(config, ...)``; tests and the bench inject
    in-process fakes). ``slo`` is the router's
    :class:`~tpu_dist_nn.obs.slo.SLOTracker` (None = utilization-only
    policy).
    """

    def __init__(self, pool: ReplicaPool, *,
                 min_replicas: int = 1, max_replicas: int = 4,
                 spawner=None, slo=None,
                 target_occupancy: float = 0.6,
                 hysteresis: float = 0.25,
                 burn_threshold: float = 1.0,
                 rows_capacity: float = 32.0,
                 up_cooldown: float = 15.0,
                 down_cooldown: float = 60.0,
                 up_stable_ticks: int = 2,
                 down_stable_ticks: int = 5,
                 flap_window: float = 300.0,
                 flap_reversals: int = 2,
                 flap_cooldown: float = 600.0,
                 decommission_grace: float = 30.0,
                 clock=time.monotonic):
        if not 1 <= min_replicas <= max_replicas:
            raise ValueError(
                f"need 1 <= min_replicas <= max_replicas, got "
                f"{min_replicas}..{max_replicas}"
            )
        if not 0.0 < target_occupancy <= 1.5:
            raise ValueError(
                f"target_occupancy must be in (0, 1.5], got "
                f"{target_occupancy}"
            )
        if not 0.0 < hysteresis < 1.0:
            raise ValueError(
                f"hysteresis must be in (0, 1), got {hysteresis}"
            )
        self.pool = pool
        self.spawner = spawner
        self.slo = slo
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.target_occupancy = float(target_occupancy)
        self.hysteresis = float(hysteresis)
        self.burn_threshold = float(burn_threshold)
        self.rows_capacity = float(rows_capacity)
        self.up_cooldown = float(up_cooldown)
        self.down_cooldown = float(down_cooldown)
        self.up_stable_ticks = int(up_stable_ticks)
        self.down_stable_ticks = int(down_stable_ticks)
        self.flap_window = float(flap_window)
        self.flap_reversals = int(flap_reversals)
        self.flap_cooldown = float(flap_cooldown)
        self.decommission_grace = float(decommission_grace)
        self._clock = clock
        self._lock = threading.RLock()
        self._above = 0  # guarded-by: _lock
        self._below = 0  # guarded-by: _lock
        # guarded-by: _lock
        self._last_up = self._last_down = None  # type: float | None
        self._history: collections.deque = (  # guarded-by: _lock
            collections.deque(maxlen=32)
        )
        self._suppressed_until = 0.0  # guarded-by: _lock
        self._override: int | None = None  # guarded-by: _lock
        self._spawning = 0  # guarded-by: _lock
        # target -> removal deadline for POOL-SPAWNED replicas we are
        # draining out (the exit frees their resources, so membership
        # removal is the right end state).
        self._decommissions: dict[str, float] = {}  # guarded-by: _lock
        # Replicas the autoscaler PARKED instead of removed: a
        # non-spawned (static / orchestrator-managed) replica's process
        # is not ours to reclaim, and removing its membership would
        # ratchet the fleet down forever (nothing could ever re-add
        # the address). Parked replicas stay in the pool, drained and
        # rejoin-exempt; scale-up un-parks before it spawns.
        self._parked: set[str] = set()  # guarded-by: _lock
        self._last_signals: dict = {}  # guarded-by: _lock
        self.ticks_total = 0

    # --------------------------------------------------------- signals

    def signals(self, now: float | None = None):
        """-> (utilization, fast_burn): the two policy inputs, read
        from state the pool scraper / SLO tracker already computed
        this tick (never an HTTP fetch from here)."""
        mono = time.monotonic()
        utils = []
        for rep in self.pool.replicas():
            if rep.state != ACTIVE or rep.decommissioning:
                continue
            occ = pend = 0.0
            if rep.fresh(mono, self.pool.load_staleness):
                occ = float(rep.occupancy or 0.0)
                pend = float(rep.pending_rows or 0.0)
            rows = (pend + float(rep.outstanding)) / (
                self.rows_capacity * rep.capacity_weight
            )
            utils.append(occ + rows)
        util = sum(utils) / len(utils) if utils else None
        burn = None
        if self.slo is not None:
            doc = self.slo.status()
            for obj in doc.get("objectives", ()):
                fast = (obj.get("windows") or {}).get("fast") or {}
                if fast.get("total", 0.0) > 0:
                    b = float(fast.get("burn_rate", 0.0))
                    burn = b if burn is None else max(burn, b)
        return util, burn

    def current_size(self) -> int:
        """Replicas that are — or are about to be back — in service:
        ACTIVE ones, DRAINING ones that are NOT being decommissioned
        (a crash-respawn or rolling restart returns them on the same
        address; counting them gone would make every crash storm look
        like a shrunken fleet and double-trigger a spawn), plus spawns
        already in flight."""
        n = 0
        for rep in self.pool.replicas():
            if rep.state == ACTIVE and not rep.decommissioning:
                n += 1
            elif rep.state == DRAINING and not rep.decommissioning:
                n += 1
        with self._lock:
            return n + self._spawning

    # -------------------------------------------------------- override

    def set_override(self, n: int) -> int:
        """Park the fleet at ``n`` (clamped to min/max); returns the
        clamped value. The policy stops deciding; convergence still
        runs one step per tick through the same choreography."""
        n = max(self.min_replicas, min(self.max_replicas, int(n)))
        with self._lock:
            self._override = n
        slog.info("autoscale.override", replicas=n)
        return n

    def clear_override(self) -> None:
        with self._lock:
            self._override = None
        slog.info("autoscale.override", mode="auto")

    # ------------------------------------------------------------ tick

    def tick(self, now: float | None = None) -> None:
        """One control-loop evaluation (the sampler tick): finish any
        in-flight decommissions, read signals, decide, actuate."""
        t = self._clock() if now is None else float(now)
        self.ticks_total += 1
        self._finish_decommissions(t)
        self._prune_stale_parks()
        util, burn = self.signals(t)
        AUTOSCALE_UTIL.set(util if util is not None else 0.0)
        n = self.current_size()
        # The decision state (stability counters, last signals) shares
        # the lock with _admit/set_override/status: the tick thread is
        # normally the only writer, but an operator override landing
        # mid-decision must not interleave with a half-updated streak.
        with self._lock:
            suppressed = t < self._suppressed_until
            override = self._override
            desired = n
            if override is not None:
                # The stability counters restart when control returns
                # to auto: a breach tick frozen from BEFORE the
                # override must not let one noisy scrape afterward
                # complete the streak.
                self._above = self._below = 0
                desired = override
            else:
                high = self.target_occupancy * (1.0 + self.hysteresis)
                low = self.target_occupancy * (1.0 - self.hysteresis)
                over = (
                    burn is not None and burn > self.burn_threshold
                ) or (util is not None and util > high)
                # Never shrink while the SLO burns: low occupancy with
                # a burning budget means the fleet is slow, not idle.
                under = (
                    util is not None and util < low
                    and (burn is None or burn <= self.burn_threshold)
                )
                self._above = self._above + 1 if over else 0
                self._below = self._below + 1 if under else 0
                if self._above >= self.up_stable_ticks:
                    desired = n + 1
                elif self._below >= self.down_stable_ticks:
                    desired = n - 1
            desired = max(self.min_replicas,
                          min(self.max_replicas, desired))
            self._last_signals = {
                "utilization": round(util, 4) if util is not None
                else None,
                "burn_fast": round(burn, 4) if burn is not None
                else None,
                "current": n,
                "desired": desired,
            }
        AUTOSCALE_SUPPRESSED.set(1.0 if suppressed else 0.0)
        AUTOSCALE_DESIRED.set(desired)
        if desired > n:
            self._scale_up(t, n, desired, util, burn,
                           manual=override is not None)
        elif desired < n:
            self._scale_down(t, n, desired, util, burn,
                             manual=override is not None)

    # ------------------------------------------------------- actuation

    def _admit(self, action: str, t: float, *, manual: bool) -> bool:
        """Cooldown + flap gate for one decision. Manual overrides
        bypass both (the operator said so) but still RECORD the action
        so a later automatic reversal is judged against it."""
        with self._lock:
            if not manual:
                if t < self._suppressed_until:
                    return False
                last = self._last_up if action == "up" else self._last_down
                cool = (self.up_cooldown if action == "up"
                        else self.down_cooldown)
                if last is not None and t - last < cool:
                    return False
                # Flap detection BEFORE actuating: the reversal that
                # crosses the threshold is itself suppressed — a
                # crash-respawn storm oscillating the signals gets
                # muted, not amplified.
                reversals = 0
                prev = None
                for ht, ha in list(self._history) + [(t, action)]:
                    if t - ht > self.flap_window:
                        continue
                    if prev is not None and ha != prev:
                        reversals += 1
                    prev = ha
                if reversals >= self.flap_reversals:
                    self._suppressed_until = t + self.flap_cooldown
                    self._history.clear()
                    AUTOSCALE_FLAPS.inc()
                    AUTOSCALE_SUPPRESSED.set(1.0)
                    slog.warning(
                        "autoscale.flap", reversals=reversals,
                        window_s=self.flap_window,
                        suppressed_for_s=self.flap_cooldown,
                    )
                    return False
            self._history.append((t, action))
            if action == "up":
                self._last_up = t
                self._above = 0
            else:
                self._last_down = t
                self._below = 0
            return True

    def _scale_up(self, t, n, desired, util, burn, *, manual) -> None:
        with self._lock:
            can_unpark = bool(self._parked)
        if not can_unpark and self.spawner is None:
            # No actuator at all: do not burn a cooldown slot / flap
            # history entry on a decision that cannot happen.
            _noact_log.warning(
                "autoscale.no_actuator", current=n, desired=desired,
                detail="no spawner (static fleet without --config) "
                       "and nothing parked to un-park",
            )
            return
        if not self._admit("up", t, manual=manual):
            return
        # Un-parking a previously scaled-down replica is instant and
        # free; spawning costs an engine boot — always prefer the park.
        unparked = self._unpark_one()
        if unparked is not None:
            AUTOSCALE_DECISIONS.labels(action="up").inc()
            slog.info(
                "autoscale.decision", action="up", current=n,
                desired=desired, replica=unparked, via="unpark",
                utilization=util, burn_fast=burn, manual=manual,
            )
            return
        if self.spawner is None:
            return
        AUTOSCALE_DECISIONS.labels(action="up").inc()
        slog.info(
            "autoscale.decision", action="up", current=n,
            desired=desired, via="spawn", utilization=util,
            burn_fast=burn, manual=manual,
        )
        with self._lock:
            self._spawning += 1
        threading.Thread(
            target=self._spawn_one, name="tdn-autoscale-spawn",
            daemon=True,
        ).start()

    def _unpark_one(self) -> str | None:
        """Re-admit one parked replica (scale-up on a static fleet).
        Stale park entries — the operator undrained or removed the
        replica meanwhile — are discarded, never acted on."""
        with self._lock:
            parked = sorted(self._parked)
        for target in parked:
            ok = self.pool.undrain(target)
            with self._lock:
                self._parked.discard(target)
            if ok:
                return target
        return None

    def _prune_stale_parks(self) -> None:
        """Drop park entries whose replica is no longer ours to
        un-park (operator undrained it back into service, or removed
        it). Run every tick BEFORE decisions: a stale entry must not
        make ``_scale_up`` consume a cooldown slot and a flap-history
        action on an un-park that cannot happen — and ``status()``'s
        parked list stays honest."""
        with self._lock:
            parked = list(self._parked)
        if not parked:
            return
        reps = {r.target: r for r in self.pool.replicas()}
        for target in parked:
            rep = reps.get(target)
            if (rep is None or rep.state != DRAINING
                    or not rep.decommissioning):
                with self._lock:
                    self._parked.discard(target)

    def _spawn_one(self) -> None:
        # On its own thread: an engine boot (compile + warmup) can
        # take minutes and the sampler tick must keep ticking — the
        # in-flight spawn counts toward current_size() so later ticks
        # do not double-spawn meanwhile.
        try:
            self.spawner()
        except Exception:  # noqa: BLE001 — a failed spawn must not kill ticks
            log.exception("autoscale spawn failed")
            slog.warning("autoscale.spawn_failed")
        finally:
            with self._lock:
                self._spawning -= 1

    def _scale_down(self, t, n, desired, util, burn, *, manual) -> None:
        victim = self._pick_victim()
        if victim is None:
            return
        if not self._admit("down", t, manual=manual):
            return
        # A pool-spawned victim is drained then REMOVED (its process
        # exit frees the resources). A non-spawned victim — static
        # fleet, orchestrator-managed pod — is drained and PARKED:
        # membership removal would be irreversible (nothing can re-add
        # the address), so the replica stays in the pool out of
        # rotation and scale-up un-parks it.
        spawned = any(
            r.target == victim and r.spawn_argv is not None
            for r in self.pool.replicas()
        )
        AUTOSCALE_DECISIONS.labels(action="down").inc()
        slog.info(
            "autoscale.decision", action="down", current=n,
            desired=desired, replica=victim,
            via="decommission" if spawned else "park",
            utilization=util, burn_fast=burn, manual=manual,
        )
        if self.pool.decommission(victim):
            with self._lock:
                if spawned:
                    self._decommissions[victim] = (
                        t + self.decommission_grace
                    )
                else:
                    self._parked.add(victim)

    def _pick_victim(self) -> str | None:
        """Least-loaded active replica (fewest in-flight rows to wait
        out — and the one the fleet will miss least)."""
        now = time.monotonic()
        cands = [
            r for r in self.pool.replicas()
            if r.state == ACTIVE and not r.decommissioning
        ]
        if not cands:
            return None
        return min(
            cands,
            key=lambda r: r.load_score(now, self.pool.load_staleness,
                                       self.pool.occupancy_weight),
        ).target

    def _finish_decommissions(self, t: float) -> None:
        """Complete scale-downs whose drain has been observed: remove
        the replica once the router holds nothing in flight on it. A
        replica past its grace deadline but still carrying outstanding
        forwards is NOT force-removed (remove() would CANCEL them) —
        it stays drained and out of placement, which is already the
        safe state; only the removal waits."""
        with self._lock:
            pending = list(self._decommissions.items())
        for target, deadline in pending:
            rep = next(
                (r for r in self.pool.replicas() if r.target == target),
                None,
            )
            if rep is not None and not rep.decommissioning:
                # The operator undrained the replica mid-scale-down
                # (pool.undrain clears the flag): the scale-down is
                # CANCELLED — removing a replica that is back in
                # service would turn an operator override into an
                # outage one tick later.
                with self._lock:
                    self._decommissions.pop(target, None)
                slog.info("autoscale.decommission_cancelled",
                          replica=target)
                continue
            if self.pool.drained_for_removal(target):
                self.pool.remove(target)
                with self._lock:
                    self._decommissions.pop(target, None)
                slog.info("autoscale.decommissioned", replica=target)
            elif t >= deadline:
                # Evidence for the operator, once per grace window.
                with self._lock:
                    self._decommissions[target] = t + self.decommission_grace
                slog.warning(
                    "autoscale.decommission_stalled", replica=target,
                    grace_s=self.decommission_grace,
                )

    # ---------------------------------------------------------- status

    def status(self) -> dict:
        """The ``GET /router/autoscale`` body."""
        with self._lock:
            override = self._override
            suppressed_until = self._suppressed_until
            spawning = self._spawning
            decommissioning = sorted(self._decommissions)
            parked = sorted(self._parked)
            signals = dict(self._last_signals)
        now = self._clock()
        return {
            # Last tick's signal snapshot FIRST: the fresh fields
            # below must win (a tick-old "current" shadowing the live
            # fleet size misreported every mid-spawn status read).
            **signals,
            "mode": "manual" if override is not None else "auto",
            "override": override,
            "min_replicas": self.min_replicas,
            "max_replicas": self.max_replicas,
            "target_occupancy": self.target_occupancy,
            "hysteresis": self.hysteresis,
            "burn_threshold": self.burn_threshold,
            "current": self.current_size(),
            "spawning": spawning,
            "decommissioning": decommissioning,
            "parked": parked,
            "flap_suppressed": now < suppressed_until,
            "ticks_total": self.ticks_total,
        }
