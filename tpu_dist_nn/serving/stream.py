"""Per-request token streaming channel (PR 16, ISSUE 16).

The continuous scheduler (serving/continuous.py) produces one token
per resident row per device step — Orca's iteration-level scheduling —
but until this module the RPC boundary collapsed that back to
"everything at retirement". :class:`TokenStream` is the seam that
carries tokens OUT at step granularity: a bounded, lock-protected
channel between the scheduler thread (producer) and the
``GenerateStream`` gRPC handler thread (consumer).

Contract (docs/ROBUSTNESS.md "Stream deadline + cancellation"):

* **Producer never blocks.** The scheduler publishes from its decode
  loop; a slow/stuck consumer must not stall every other resident
  row's decode. The channel is bounded: past ``max_buffer`` undelivered
  tokens the stream flips to cancelled (backpressure-by-cancellation)
  and the scheduler frees the slot on its next iteration, exactly as
  if the client had disconnected.
* **Publish is idempotent over the known-token list.** The scheduler
  hands the FULL ``occ["tokens"]`` list each time; the channel's
  ``sent`` cursor enqueues only the unseen suffix. That single cursor
  is what makes failover/preemption replay exactly-once: a re-bound
  row rebuilds ``occ["tokens"]`` from scratch (forced-token replay,
  PR 15), republishing tokens the stream already delivered — the
  cursor suppresses them without any scheduler-side bookkeeping.
* **Exactly one terminal.** ``finish()`` is idempotent; the first
  call wins. Every scheduler exit path (retire, expiry, device fault,
  close-time sweep) reaches it through :class:`StreamDone`, the
  ``item["done"]`` Event subclass that converts the item's terminal
  state into the END frame as a side effect of ``set()``.

The wire framing itself (TOKENS / END frames) lives in
serving/wire.py with every other byte format; this module owns only
the channel semantics and the stream-plane metrics
(docs/OBSERVABILITY.md catalog).
"""

from __future__ import annotations

import threading
import time

from tpu_dist_nn.obs.registry import REGISTRY

# Stream-plane metrics (docs/OBSERVABILITY.md). Requests/frames/
# cancellations count the channel's lifecycle; the inter-token
# histogram is the stream-latency twin of tdn_gen_ttft_seconds —
# observed at PUBLISH time (scheduler-side token production cadence),
# so a slow consumer shows up in the buffer depth, not here.
_STREAM_REQUESTS = REGISTRY.counter(
    "tdn_gen_stream_requests_total",
    "GenerateStream requests admitted to the continuous scheduler",
)
_STREAM_FRAMES = REGISTRY.counter(
    "tdn_gen_stream_frames_total",
    "stream frames flushed to clients, by kind (tokens / end)",
    labels=("kind",),
)
_STREAM_CANCELLED = REGISTRY.counter(
    "tdn_gen_stream_cancelled_total",
    "streams cancelled before their terminal frame (client abandon, "
    "gRPC cancellation, or buffer-overflow backpressure)",
)
_STREAM_RESUMED = REGISTRY.counter(
    "tdn_gen_stream_resumed_total",
    "GenerateStream requests admitted WITH a resume prefix (router "
    "mid-stream failover replaying already-delivered tokens)",
)
_INTERTOKEN = REGISTRY.histogram(
    "tdn_gen_intertoken_seconds",
    "gap between consecutive published tokens of one stream (after "
    "the first token; TTFT owns submit -> first)",
)


class TokenStream:
    """Bounded single-producer/single-consumer token channel for one
    GenerateStream request."""

    def __init__(self, max_buffer: int = 4096):
        self._cond = threading.Condition()
        self._max = int(max_buffer)
        self._pending: list[int] = []  # guarded-by: _cond
        self._sent = 0  # guarded-by: _cond
        self._terminal: dict | None = None  # guarded-by: _cond
        self._cancelled = False  # guarded-by: _cond
        self._last_publish: float | None = None  # guarded-by: _cond
        _STREAM_REQUESTS.inc()

    # ---------------------------------------------------- producer side

    def seed(self, n: int) -> None:
        """Advance the sent cursor past ``n`` tokens the CLIENT already
        holds (router failover resume): the scheduler will republish
        the whole replayed prefix and the cursor swallows it."""
        with self._cond:
            self._sent = max(self._sent, int(n))

    def publish(self, tokens) -> bool:
        """Enqueue the unseen suffix of the full known-token list.

        Called from the scheduler loop with ``occ["tokens"]`` after
        every append; never blocks. Returns False once the stream is
        cancelled (client gone or buffer overflowed) — the scheduler's
        cue to abandon the row and free its slot.
        """
        with self._cond:
            if self._cancelled or self._terminal is not None:
                return not self._cancelled
            fresh = tokens[self._sent:]
            if not fresh:
                return True
            now = time.monotonic()
            if self._last_publish is not None:
                _INTERTOKEN.observe(now - self._last_publish)
            self._last_publish = now
            self._sent += len(fresh)
            self._pending.extend(int(t) for t in fresh)
            if len(self._pending) > self._max:
                # Backpressure-by-cancellation: the consumer stopped
                # draining (wedged client) — the producer must never
                # block the shared decode loop, so the stream dies
                # instead.
                self._cancelled = True
                _STREAM_CANCELLED.inc()
                self._cond.notify_all()
                return False
            self._cond.notify_all()
            return True

    def finish(self, reason: str, code: str = "",
               message: str = "") -> None:
        """Idempotent terminal: "eos" / "max_tokens", or "error" with
        the canonical code name + message. First call wins."""
        with self._cond:
            if self._terminal is not None:
                return
            self._terminal = {"reason": reason, "code": code,
                              "message": message}
            self._cond.notify_all()

    # ---------------------------------------------------- consumer side

    def cancel(self) -> None:
        """Consumer-side teardown (client disconnected / handler
        exiting early): flips the channel so the next publish returns
        False and the scheduler reaps the slot."""
        with self._cond:
            if self._cancelled or self._terminal is not None:
                return
            self._cancelled = True
            _STREAM_CANCELLED.inc()
            self._cond.notify_all()

    @property
    def cancelled(self) -> bool:
        with self._cond:
            return self._cancelled

    @property
    def delivered(self) -> int:
        """Tokens handed to the consumer so far (the resume ledger)."""
        with self._cond:
            return self._sent - len(self._pending)

    def next_event(self, timeout: float | None = None):
        """Block for the next thing to flush: ``("tokens", [ids])``
        (the whole buffered delta, one frame), ``("end", {...})`` after
        the buffer drains, or ``None`` on timeout — the handler's
        per-token-gap deadline hook."""
        with self._cond:
            deadline = (None if timeout is None
                        else time.monotonic() + timeout)
            while True:
                if self._pending:
                    batch = self._pending
                    self._pending = []
                    _STREAM_FRAMES.labels(kind="tokens").inc()
                    return "tokens", batch
                if self._terminal is not None:
                    _STREAM_FRAMES.labels(kind="end").inc()
                    return "end", dict(self._terminal)
                if self._cancelled:
                    return "end", {"reason": "error", "code": "CANCELLED",
                                   "message": "stream cancelled"}
                if deadline is None:
                    self._cond.wait()
                    continue
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return None
                self._cond.wait(remaining)


class StreamDone(threading.Event):
    """The ``item["done"]`` Event of a streaming request.

    Every terminal path in the scheduler/admission stack —
    ``_retire``, ``_free_slot_on_error``, queue expiry, close-time
    sweeps — already calls ``item["done"].set()`` after stamping
    ``item["err"]`` / ``item["finish_reason"]``. Subclassing the Event
    converts that existing contract into the stream's END frame
    without touching any of those call sites: ``set()`` reads the
    item's terminal state and finishes the channel.
    """

    def __init__(self, item: dict, stream: TokenStream):
        super().__init__()
        self._item = item
        self._stream = stream

    def set(self) -> None:  # noqa: A003 — matching threading.Event
        err = self._item.get("err")
        if err is not None:
            self._stream.finish(
                "error", getattr(err, "code", "INTERNAL"), str(err)
            )
        else:
            self._stream.finish(
                self._item.get("finish_reason") or "max_tokens"
            )
        super().set()


def note_stream_resumed() -> None:
    """Tick the failover-resume counter (called at admission when a
    resume prefix rides in — serving/server.py)."""
    _STREAM_RESUMED.inc()
