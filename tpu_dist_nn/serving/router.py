"""Load-aware gRPC front door over a :class:`ReplicaPool`.

The router speaks the SAME wire surface as one engine server —
``LayerService/Process`` and ``/Generate``, raw Matrix bytes — so any
existing client (``GrpcClient``, the reference's stubs, ``tdn infer
--target``) points at the router unchanged and transparently gains a
fleet behind it. Per request the router:

1. joins the caller's trace (``x-tdn-trace``) so the hop shows up as
   a ``router.forward`` stage in ``/profile`` (placement time is the
   ``tdn_router_placement_seconds`` histogram — microseconds, not
   worth a span per attempt) — the router hop is attributable, never
   a black box between client and engine;
2. picks a replica by power-of-two-choices over live load
   (:meth:`ReplicaPool.place`), honoring session affinity
   (``x-tdn-session``) so a follow-up Generate lands on the replica
   already holding its KV/prefix-cache state;
3. forwards the RAW request bytes over a persistent channel (the
   router never decodes a Matrix — the hop costs metadata handling
   plus one TCP round trip, not a codec pass);
4. on a TRANSIENT failure (UNAVAILABLE / DEADLINE_EXCEEDED) records
   the breaker outcome and FAILS OVER to another replica within the
   caller's remaining budget (deadline and/or ``x-tdn-timeout-ms``
   hint) — the reference's "clients may retry elsewhere" done FOR the
   client, with the same budget-carving rule as
   :class:`~tpu_dist_nn.serving.resilience.RetryPolicy`;
5. propagates a non-transient status (INVALID_ARGUMENT, INTERNAL,
   RESOURCE_EXHAUSTED...) verbatim — deterministic failures are the
   replica's verdict, retrying them elsewhere only doubles the damage.

Metrics (docs/OBSERVABILITY.md): ``tdn_router_requests_total{replica,
outcome}``, ``tdn_router_placement_seconds``,
``tdn_router_failovers_total``, plus the pool's
``tdn_router_replica_healthy{replica}``. Admin: :func:`admin_routes`
serves the read side (``/router/replicas``, ``/router/autoscale``,
``/trace/fleet``) and :func:`admin_post_routes` the state-changing
verbs (``POST /router/drain`` / ``/router/undrain`` /
``/router/scale``) on the metrics endpoint — the ``tdn router
--drain-replica`` path for zero-downtime rolling restarts
(docs/SCALING.md).
"""

from __future__ import annotations

import json
import logging
import queue
import time
import urllib.parse

import grpc

from tpu_dist_nn.obs import trace as _trace
from tpu_dist_nn.obs.log import get_logger
from tpu_dist_nn.obs.registry import REGISTRY
from tpu_dist_nn.serving.pool import ACTIVE, ReplicaPool
from tpu_dist_nn.serving.resilience import (
    RETRYABLE_CODES,
    CircuitBreaker,
    RetryPolicy,
    _code_name,
)
from tpu_dist_nn.serving.sched_core import normalize_class
from tpu_dist_nn.serving.server import _new_grpc_server, _request_span
from tpu_dist_nn.serving.wire import (
    CLASS_HEADER,
    RETRY_AFTER_HEADER,
    SERVICE_NAME,
    SESSION_HEADER,
    STREAM_RESUME_HEADER,
    STREAM_RESUME_MAX_TOKENS,
    decode_frame,
)

log = logging.getLogger(__name__)
slog = get_logger(__name__)

ROUTER_REQUESTS = REGISTRY.counter(
    "tdn_router_requests_total",
    "requests the router forwarded (or failed), per replica and "
    "outcome ('ok' or the gRPC status name; replica 'none' = no "
    "placement possible)",
    labels=("replica", "outcome"),
)
ROUTER_PLACEMENT = REGISTRY.histogram(
    "tdn_router_placement_seconds",
    "time spent choosing a replica for one attempt (p2c + session "
    "lookup; excludes the forward itself)",
)
ROUTER_FAILOVERS = REGISTRY.counter(
    "tdn_router_failovers_total",
    "attempts re-placed onto ANOTHER replica after a transient "
    "failure (the fleet absorbing a replica loss)",
)
ROUTER_LATENCY = REGISTRY.histogram(
    "tdn_router_request_seconds",
    "request wall time through the router, per method (placement + "
    "every forward attempt + failover backoff; the latency-SLO family "
    "for the fleet's front door)",
    labels=("method",),
)
ROUTER_HEDGES = REGISTRY.counter(
    "tdn_router_hedges_total",
    "hedge attempts fired: the primary replica sat past the "
    "p99-derived patience with no reply, so a second attempt raced it "
    "on another replica",
    labels=("method",),
)
ROUTER_HEDGE_WINS = REGISTRY.counter(
    "tdn_router_hedge_wins_total",
    "hedged requests where the HEDGE replied first (the primary was "
    "cancelled) — the tail the hedge actually cut",
    labels=("method",),
)
ROUTER_STREAM_RESUMES = REGISTRY.counter(
    "tdn_router_stream_resumes_total",
    "GenerateStream failovers resumed mid-stream on another replica "
    "(already-delivered tokens replayed as forced tokens — the client "
    "sees one uninterrupted, exactly-once stream)",
)
ROUTER_STREAM_RESUME_OVERFLOW = REGISTRY.counter(
    "tdn_router_stream_resume_overflow_total",
    "GenerateStream failovers ABANDONED because the delivered-token "
    "ledger outgrew the metadata-borne resume bound "
    "(STREAM_RESUME_MAX_TOKENS) — surfaced as OUT_OF_RANGE instead of "
    "an opaque gRPC metadata error mid-failover",
)

_CLIENT_DEFAULT = object()


class HedgePolicy:
    """Router-side request hedging (Dean & Barroso, *The Tail at
    Scale*): after ``p99_ratio`` x the router's own measured p99 for
    the method with no reply, fire ONE second attempt at a different
    replica; the first reply wins and the loser is cancelled.

    The delay is derived from ``tdn_router_request_seconds`` — the
    very histogram the router observes — so patience tracks the
    fleet's actual tail instead of a hand-tuned constant, and hedging
    stays off (``delay()`` is None) until ``min_observations``
    requests have built a trustworthy estimate.

    ``methods`` defaults to ``("Process",)`` only: ``Generate`` is
    NOT idempotent under sampling (temperature > 0 draws fresh tokens
    on the hedge replica, and both replicas burn decode slots), so it
    must be opted in explicitly (``--hedge-generate``) by operators
    running greedy decoding or accepting the cost.

    ``GenerateStream`` can never be hedged and is rejected here: a
    stream is non-idempotent MID-FLIGHT — by the time patience expires,
    tokens have already been delivered to the client, so "first reply
    wins" has no meaning (two replicas would race to continue one
    half-consumed sequence). Streams get replay-resume failover
    instead (docs/SCALING.md "Streaming failover"): strictly
    sequential, resumed from exactly the delivered prefix.
    """

    def __init__(self, p99_ratio: float = 2.0, *,
                 methods=("Process",), min_delay_s: float = 0.002,
                 max_delay_s: float = 10.0, min_observations: int = 20,
                 latency=None):
        if p99_ratio <= 0:
            raise ValueError(
                f"hedge p99_ratio must be > 0, got {p99_ratio}"
            )
        if "GenerateStream" in methods:
            raise ValueError(
                "GenerateStream cannot be hedged: a stream is "
                "non-idempotent mid-flight (tokens already delivered); "
                "streams fail over by replay-resume instead "
                "(docs/SCALING.md \"Streaming failover\")"
            )
        self.p99_ratio = float(p99_ratio)
        self.methods = frozenset(methods)
        self.min_delay_s = float(min_delay_s)
        self.max_delay_s = float(max_delay_s)
        self.min_observations = int(min_observations)
        # Injectable for tests; the router's own latency family by
        # default — process-global, so history survives pool churn.
        self._latency = latency if latency is not None else ROUTER_LATENCY

    def applies(self, method: str) -> bool:
        return method in self.methods

    def delay(self, method: str) -> float | None:
        """Seconds to wait on the primary before hedging; None = do
        not hedge (no/too-little latency history for the method)."""
        for values, child in self._latency.samples():
            if values == (method,):
                if child.value < self.min_observations:
                    return None
                q = child.quantile(0.99)
                if q is None:
                    return None
                return min(max(q * self.p99_ratio, self.min_delay_s),
                           self.max_delay_s)
        return None


class _SyntheticRpcError(grpc.RpcError):
    """A local verdict shaped like a wire error (cancelled hedge
    future, wedged in-process fake): carries a real status code so the
    failover loop's classification works unchanged."""

    def __init__(self, code, message: str):
        super().__init__()
        self._code = code
        self._message = message

    def code(self):
        return self._code

    def details(self):
        return self._message


def _future_outcome(fut):
    """(reply, err) from a COMPLETED forward future."""
    try:
        return fut.result(timeout=0), None
    except grpc.RpcError as e:
        return None, e
    except Exception as e:  # noqa: BLE001 — cancelled / in-process fakes
        return None, _SyntheticRpcError(
            grpc.StatusCode.CANCELLED, repr(e)
        )


class Router:
    """The forwarding core behind both RPC methods (one instance per
    server; stateless between requests except through the pool)."""

    def __init__(self, pool: ReplicaPool, *, retry=_CLIENT_DEFAULT,
                 forward_timeout: float | None = 120.0,
                 hedge: HedgePolicy | None = None,
                 spotcheck=None):
        self.pool = pool
        # Off by default; a HedgePolicy races a second attempt on the
        # fleet's tail requests (docs/SCALING.md "Request hedging").
        self._hedge = hedge
        # Off by default; a SpotChecker shadows a sampled fraction of
        # Process traffic onto a second replica and compares replies
        # (docs/ROBUSTNESS.md "Silent corruption & quarantine").
        self.spotcheck = spotcheck
        # max_attempts bounds attempts per REQUEST (across replicas);
        # failover to a fresh replica is immediate, the jittered
        # backoff only paces a second pass over the same replicas.
        self._retry = (
            RetryPolicy(base_delay=0.01, max_delay=0.25)
            if retry is _CLIENT_DEFAULT else retry
        )
        # Per-forward cap when the caller sent NO deadline and no
        # x-tdn-timeout-ms hint: a replica that accepts TCP but never
        # answers (SIGSTOP, blackhole) must not hold a router worker
        # thread forever — 32 such requests would wedge the whole
        # front door. Deadline-carrying requests keep their own budget
        # (the engine path bounds these the same way: the batcher's
        # submit_timeout defaults to 120s). None disables the cap.
        self._forward_timeout = forward_timeout

    # ----------------------------------------------------------- serve

    def handle(self, method: str, payload: bytes, context) -> bytes:
        span, budget, md = _request_span(context, f"{method}")
        session = md.get(SESSION_HEADER)
        # SLO class: forwarded verbatim to the replica's scheduler and
        # read here for the hedging exemption (best_effort traffic is
        # the load the fleet sheds under pressure — racing a second
        # copy of it would spend tail-latency budget on the class that
        # has none). None when the caller sent no header (nothing to
        # forward; the replica defaults to standard).
        slo_class = md.get(CLASS_HEADER)
        t0 = time.monotonic()
        try:
            return self._route(method, payload, context, span, budget,
                               session, slo_class)
        finally:
            # Observed on EVERY outcome (abort raises through here):
            # an SLO over this family must see the slow failures, not
            # just the successes.
            ROUTER_LATENCY.labels(method=method).observe(
                time.monotonic() - t0
            )
            span.end()

    def _abort(self, context, replica: str, code, message: str):
        ROUTER_REQUESTS.labels(
            replica=replica, outcome=_code_name(code)
        ).inc()
        context.abort(code, message)

    def _route(self, method: str, payload: bytes, context, span, budget,
               session: str | None, slo_class: str | None = None) -> bytes:
        policy = self._retry
        deadline = time.monotonic() + budget if budget is not None else None
        attempt = 0
        tried: set[str] = set()
        last: grpc.RpcError | None = None
        prev_failed: str | None = None
        while True:
            attempt += 1
            t0 = time.monotonic()
            rep = self.pool.place(session_key=session, exclude=tried)
            if rep is None and tried:
                # Every placeable replica failed this request once:
                # widen back to the full set for the next pass.
                tried.clear()
                rep = self.pool.place(session_key=session)
            ROUTER_PLACEMENT.observe(time.monotonic() - t0)
            if rep is None:
                span.annotate("no placeable replica")
                if last is not None:
                    self._abort(
                        context, "none", _status_of(last),
                        f"no replica left to fail over to: "
                        f"{_details_of(last)}",
                    )
                self._abort(
                    context, "none", grpc.StatusCode.UNAVAILABLE,
                    "no healthy replica available (pool empty, all "
                    "draining, or all breakers open)",
                )
            remaining = None
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0.001:
                    # Label "none": this replica never saw the request
                    # — the budget died on earlier attempts elsewhere.
                    span.annotate("budget exhausted before forward")
                    self._abort(
                        context, "none",
                        grpc.StatusCode.DEADLINE_EXCEEDED,
                        "request budget exhausted during failover",
                    )
            metadata = [(_trace.TRACE_HEADER, span.ctx.header())]
            if remaining is not None:
                metadata.append(
                    (_trace.TIMEOUT_HEADER,
                     str(max(0, int(remaining * 1000))))
                )
            if session is not None:
                metadata.append((SESSION_HEADER, session))
            if slo_class is not None:
                metadata.append((CLASS_HEADER, slo_class))
            if prev_failed is not None and rep.target != prev_failed:
                # Only an actual re-placement onto ANOTHER replica is a
                # failover — a same-replica retry (single-replica pool,
                # or the widened pass landing back) is not the fleet
                # absorbing anything.
                ROUTER_FAILOVERS.inc()
            reply, err, serving, hedged = self._forward(
                method, payload, rep, remaining, metadata, span,
                attempt, tried, slo_class,
            )
            if err is None:
                serving.breaker.record_success()
                ROUTER_REQUESTS.labels(
                    replica=serving.target, outcome="ok"
                ).inc()
                if session is not None:
                    self.pool.pin(session, serving.target)
                if self.spotcheck is not None:
                    # Shadow spot-check AFTER the reply is secured: the
                    # duplicate runs off-thread against a second replica
                    # and never touches this request's latency.
                    self.spotcheck.maybe_check(
                        method, payload, reply, serving.target
                    )
                if attempt > 1 or serving is not rep:
                    span.annotate(
                        f"served by {serving.target} on attempt "
                        f"{attempt}" + (" (hedge won)" if hedged
                                        and serving is not rep else "")
                    )
                return reply
            # On failure the error handled below belongs to the last
            # replica that produced one (the hedge target when a
            # hedge fired and also failed).
            rep = serving
            code = _status_of(err)
            failover = self._failover_worthy(code)
            self._observe_failure(rep, code)
            ROUTER_REQUESTS.labels(
                replica=rep.target, outcome=_code_name(code)
            ).inc()
            if not failover:
                # Deterministic verdicts propagate verbatim — another
                # replica would say the same thing. A shed's backoff
                # hint (x-tdn-retry-after-ms) crosses the hop too:
                # the replica's drain rate is the number the client
                # must pace on, router or no router.
                _copy_retry_after(context, err)
                span.annotate(
                    f"{_code_name(code)} from {rep.target}: propagated"
                )
                context.abort(code, _details_of(err))
            last = err
            tried.add(rep.target)
            # A fresh replica is tried immediately; the backoff only
            # paces a renewed pass once every PLACEABLE replica has
            # failed. Draining / breaker-open replicas don't count —
            # place() will never return them, and letting them mask
            # the pacing would hammer the one struggling replica
            # back-to-back with zero delay.
            placeable = {
                r.target for r in self.pool.replicas()
                if r.state == ACTIVE
                and r.breaker.state == CircuitBreaker.CLOSED
            }
            retry_same_set = not (placeable - tried)
            # The attempt cap scales with the fleet: policy.max_attempts
            # is a client-oriented default (3) — on a 5-replica pool
            # where 3 died together (their breakers still closed, and
            # dead-fast failures make p2c PREFER them), a fixed cap
            # aborts with healthy replicas never tried. Every replica
            # in this request's view gets at least one shot.
            out_of_attempts = (
                policy is None
                or attempt >= max(policy.max_attempts,
                                  len(placeable | tried))
            )
            delay = (
                policy.backoff(attempt)
                if not out_of_attempts and retry_same_set else 0.0
            )
            out_of_budget = (
                deadline is not None
                and time.monotonic() + delay >= deadline
            )
            if out_of_attempts or out_of_budget:
                why = ("attempts exhausted" if out_of_attempts
                       else "budget exhausted")
                span.annotate(
                    f"failover stopped after attempt {attempt} ({why})"
                )
                slog.warning(
                    "router.request_failed", method=method,
                    replica=rep.target, code=_code_name(code),
                    attempts=attempt, why=why,
                )
                context.abort(code, _details_of(err))
            prev_failed = rep.target
            span.annotate(
                f"failover after {_code_name(code)} from {rep.target}"
            )
            if delay:
                policy.sleep(delay)

    # --------------------------------------------------------- streams

    def handle_stream(self, method: str, payload: bytes, context):
        """The GenerateStream hop: relay the replica's frame bytes
        WITHOUT re-encoding (the router shallow-parses each frame's
        type byte + token ids only, to keep the resume ledger), and
        redefine failover for the streaming case — a transient failure
        MID-STREAM re-places onto another replica carrying the prompt
        plus every already-delivered token as ``x-tdn-stream-resume``;
        the replica replays that prefix as forced tokens (the PR-15
        preemption-resume path) and its stream cursor suppresses
        re-delivery, so the client sees one uninterrupted stream,
        bit-identical at temperature 0, with zero duplicated or
        dropped tokens.

        Hedging never applies here (structurally: this path never
        consults the HedgePolicy, and the policy itself rejects
        ``GenerateStream`` at construction): a stream is non-idempotent
        the moment its first token is delivered.
        """
        span, _budget, md = _request_span(context, method)
        session = md.get(SESSION_HEADER)
        slo_class = md.get(CLASS_HEADER)
        t0 = time.monotonic()
        try:
            yield from self._route_stream(method, payload, context, span,
                                          md, session, slo_class)
        finally:
            ROUTER_LATENCY.labels(method=method).observe(
                time.monotonic() - t0
            )
            span.end()

    def _route_stream(self, method, payload, context, span, md,
                      session, slo_class):
        policy = self._retry
        # Stream deadline semantics (docs/ROBUSTNESS.md): the
        # x-tdn-timeout-ms hint is a NEXT-TOKEN-GAP budget, not a total
        # — it is forwarded VERBATIM on every attempt (never carved
        # down), because a healthy long stream outlives any per-request
        # budget by design. Only a real gRPC deadline (the client
        # explicitly bounding the whole stream) is carved across
        # failover attempts. _forward_timeout is NOT applied: a stream
        # legitimately holds its worker for the whole generation, and
        # the replica's gap deadline is what kills a wedged one.
        gap_hint = md.get(_trace.TIMEOUT_HEADER)
        deadline = None
        try:
            rem = context.time_remaining()
            if rem is not None and rem < 1e9:  # far-future sentinel
                deadline = time.monotonic() + rem
        except Exception:  # noqa: BLE001 — in-process fakes
            pass
        # The resume ledger: token ids this router has handed to gRPC
        # for delivery. Seeded from an INBOUND resume header so a
        # resuming caller (stacked router) composes.
        delivered: list[int] = []
        raw = md.get(STREAM_RESUME_HEADER)
        if raw:
            try:
                delivered = [int(t) for t in raw.split(",")]
            except ValueError:
                self._abort(
                    context, "none", grpc.StatusCode.INVALID_ARGUMENT,
                    f"bad {STREAM_RESUME_HEADER}: expected "
                    "comma-separated token ids",
                )
        # Streams surface the trace id in INITIAL metadata (the replica
        # handler does the same): trailing metadata only lands at
        # stream end — useless against a wedged stream.
        try:
            context.send_initial_metadata(
                ((_trace.TRACE_ID_HEADER, span.ctx.trace_id),)
            )
        except Exception:  # noqa: BLE001 — in-process fakes
            pass
        attempt = 0
        tried: set[str] = set()
        last: grpc.RpcError | None = None
        prev_failed: str | None = None
        while True:
            attempt += 1
            t0 = time.monotonic()
            rep = self.pool.place(session_key=session, exclude=tried)
            if rep is None and tried:
                tried.clear()
                rep = self.pool.place(session_key=session)
            ROUTER_PLACEMENT.observe(time.monotonic() - t0)
            if rep is None:
                span.annotate("no placeable replica")
                if last is not None:
                    self._abort(
                        context, "none", _status_of(last),
                        f"no replica left to fail over to: "
                        f"{_details_of(last)}",
                    )
                self._abort(
                    context, "none", grpc.StatusCode.UNAVAILABLE,
                    "no healthy replica available (pool empty, all "
                    "draining, or all breakers open)",
                )
            remaining = None
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0.001:
                    span.annotate("budget exhausted before forward")
                    self._abort(
                        context, "none",
                        grpc.StatusCode.DEADLINE_EXCEEDED,
                        "request budget exhausted during failover",
                    )
            metadata = [(_trace.TRACE_HEADER, span.ctx.header())]
            if gap_hint is not None:
                metadata.append((_trace.TIMEOUT_HEADER, gap_hint))
            if session is not None:
                metadata.append((SESSION_HEADER, session))
            if slo_class is not None:
                metadata.append((CLASS_HEADER, slo_class))
            if delivered:
                if len(delivered) > STREAM_RESUME_MAX_TOKENS:
                    # The ledger no longer fits the metadata-borne
                    # resume path (~8 KB gRPC budget; see wire.py).
                    # A clamped suffix would replay against KV state
                    # the fallback replica does not have, so the only
                    # honest outcome is a CLEAR failure the client can
                    # retry from scratch — not an opaque metadata
                    # error. Counter + annotated span for the autopsy.
                    ROUTER_STREAM_RESUME_OVERFLOW.inc()
                    span.annotate(
                        f"resume ledger {len(delivered)} tokens > "
                        f"bound {STREAM_RESUME_MAX_TOKENS}: failover "
                        "abandoned"
                    )
                    self._abort(
                        context, "none", grpc.StatusCode.OUT_OF_RANGE,
                        f"stream failover needs to resume "
                        f"{len(delivered)} delivered tokens but the "
                        f"metadata-borne resume path is bounded at "
                        f"{STREAM_RESUME_MAX_TOKENS}; restart the "
                        f"stream from the prompt",
                    )
                metadata.append(
                    (STREAM_RESUME_HEADER,
                     ",".join(str(t) for t in delivered))
                )
            if prev_failed is not None and rep.target != prev_failed:
                ROUTER_FAILOVERS.inc()
            n_before = len(delivered)
            err: grpc.RpcError | None = None
            ended = False
            self.pool.begin(rep)
            t_fwd = time.monotonic()
            try:
                call = rep.call_stream(method, payload, timeout=remaining,
                                       metadata=metadata)
                for frame in call:
                    kind = frame[0] if frame else None
                    if kind == 1:  # TOKENS: ledger BEFORE the relay
                        _k, ids = decode_frame(frame)
                        delivered.extend(ids)
                    yield frame
                    if kind == 2:  # END: the terminal — stream is done
                        ended = True
                        break
                if not ended:
                    # The replica closed the stream OK but never sent
                    # its END frame: it died between flushes. Shape it
                    # like the wire failure it is so failover resumes.
                    err = _SyntheticRpcError(
                        grpc.StatusCode.UNAVAILABLE,
                        "replica stream closed without a terminal frame",
                    )
            except grpc.RpcError as e:
                err = e
            finally:
                self.pool.done(rep)
                _trace.TRACER.record_span(
                    "router.forward", span.ctx, t_fwd,
                    time.monotonic() - t_fwd,
                    attrs={"replica": rep.target, "attempt": attempt,
                           "ok": err is None, "stream": True,
                           "tokens": len(delivered) - n_before},
                )
            if err is None:
                rep.breaker.record_success()
                ROUTER_REQUESTS.labels(
                    replica=rep.target, outcome="ok"
                ).inc()
                if session is not None:
                    self.pool.pin(session, rep.target)
                if attempt > 1:
                    span.annotate(
                        f"served by {rep.target} on attempt {attempt}"
                    )
                return
            code = _status_of(err)
            failover = self._failover_worthy(code)
            self._observe_failure(rep, code)
            ROUTER_REQUESTS.labels(
                replica=rep.target, outcome=_code_name(code)
            ).inc()
            if not failover:
                _copy_retry_after(context, err)
                span.annotate(
                    f"{_code_name(code)} from {rep.target}: propagated"
                )
                context.abort(code, _details_of(err))
            if len(delivered) > n_before or n_before > 0:
                # Tokens are mid-flight: the re-placement below is a
                # RESUME, not a plain failover — the next attempt
                # carries the delivered prefix for forced-token replay.
                ROUTER_STREAM_RESUMES.inc()
                span.annotate(
                    f"mid-stream {_code_name(code)} from {rep.target}: "
                    f"resuming at token {len(delivered)}"
                )
            last = err
            tried.add(rep.target)
            placeable = {
                r.target for r in self.pool.replicas()
                if r.state == ACTIVE
                and r.breaker.state == CircuitBreaker.CLOSED
            }
            retry_same_set = not (placeable - tried)
            out_of_attempts = (
                policy is None
                or attempt >= max(policy.max_attempts,
                                  len(placeable | tried))
            )
            delay = (
                policy.backoff(attempt)
                if not out_of_attempts and retry_same_set else 0.0
            )
            out_of_budget = (
                deadline is not None
                and time.monotonic() + delay >= deadline
            )
            if out_of_attempts or out_of_budget:
                why = ("attempts exhausted" if out_of_attempts
                       else "budget exhausted")
                span.annotate(
                    f"stream failover stopped after attempt {attempt} "
                    f"({why})"
                )
                slog.warning(
                    "router.request_failed", method=method,
                    replica=rep.target, code=_code_name(code),
                    attempts=attempt, why=why,
                )
                context.abort(code, _details_of(err))
            prev_failed = rep.target
            span.annotate(
                f"failover after {_code_name(code)} from {rep.target}"
            )
            if delay:
                policy.sleep(delay)

    # -------------------------------------------------------- forwards

    def _forward(self, method, payload, rep, remaining, metadata, span,
                 attempt, tried, slo_class=None):
        """One forward attempt — plain, or hedged when the policy
        applies and its p99-derived delay leaves room inside the
        budget. ``best_effort`` requests are NEVER hedged: the class
        the degradation ladder sheds first must not spend a second
        replica's slot chasing its tail (docs/SCALING.md). Returns
        ``(reply, err, serving_replica, hedged)``: ``serving_replica``
        is the winner on success, the last errored replica on
        failure."""
        timeout = (remaining if remaining is not None
                   else self._forward_timeout)
        delay = None
        if (self._hedge is not None and self._hedge.applies(method)
                and (slo_class is None
                     or normalize_class(slo_class) != "best_effort")):
            delay = self._hedge.delay(method)
            if (delay is not None and timeout is not None
                    and delay >= timeout):
                # No room for a second attempt inside what is left of
                # the caller's budget: hedging would only add load.
                delay = None
        if delay is None:
            err: grpc.RpcError | None = None
            reply = None
            self.pool.begin(rep)
            t_fwd = time.monotonic()
            try:
                reply = rep.call(method, payload, timeout=timeout,
                                 metadata=metadata)
            except grpc.RpcError as e:
                err = e
            finally:
                self.pool.done(rep)
                _trace.TRACER.record_span(
                    "router.forward", span.ctx, t_fwd,
                    time.monotonic() - t_fwd,
                    attrs={"replica": rep.target, "attempt": attempt,
                           "ok": err is None},
                )
            return reply, err, rep, False
        return self._forward_hedged(method, payload, rep, timeout,
                                    metadata, span, attempt, tried,
                                    delay)

    def _forward_hedged(self, method, payload, rep, timeout, metadata,
                        span, attempt, tried, delay):
        """Race the primary against one hedge: wait ``delay`` on the
        primary; if it has not replied, fire the same request at a
        DIFFERENT replica (session affinity deliberately ignored — the
        pinned replica is the slow one). First reply wins, the loser
        is cancelled. At most ONE hedge per attempt: past two
        in-flight copies the marginal tail win cannot pay for the
        doubled load (Tail at Scale §hedged-requests)."""
        q: queue.Queue = queue.Queue()
        started = time.monotonic()
        entries: dict[int, tuple] = {}

        def fire(r, tmo):
            self.pool.begin(r)
            try:
                fut = r.call_future(method, payload, timeout=tmo,
                                    metadata=metadata)
            except Exception:
                self.pool.done(r)
                raise
            entries[id(fut)] = (fut, r)
            # done callbacks run once, including on cancel — the
            # outstanding count stays exact for both copies.
            fut.add_done_callback(
                lambda f, _r=r: (self.pool.done(_r), q.put(f))
            )
            return fut

        fire(rep, timeout)
        first = None
        try:
            first = q.get(timeout=delay)
        except queue.Empty:
            pass
        hedged = False
        if first is None:
            hedge_rep = self.pool.place(
                exclude=set(tried) | {rep.target}
            )
            if hedge_rep is not None:
                tmo2 = timeout
                if timeout is not None:
                    tmo2 = max(0.001,
                               timeout - (time.monotonic() - started))
                try:
                    fire(hedge_rep, tmo2)
                    hedged = True
                    ROUTER_HEDGES.labels(method=method).inc()
                    span.annotate(
                        f"hedged to {hedge_rep.target} after "
                        f"{delay * 1e3:.0f}ms"
                    )
                except Exception:  # noqa: BLE001 — failed fire = no hedge
                    log.debug("hedge fire on %s failed",
                              hedge_rep.target, exc_info=True)
        last_err: grpc.RpcError | None = None
        last_rep = rep
        pending = len(entries)
        # Slack past the grpc deadline: every future completes on its
        # own once its deadline fires; the cap only guards a wedged
        # in-process fake from holding the worker thread forever.
        wait_cap = None if timeout is None else started + timeout + 5.0
        while pending:
            if first is None:
                try:
                    first = q.get(timeout=(
                        None if wait_cap is None
                        else max(0.01, wait_cap - time.monotonic())
                    ))
                except queue.Empty:
                    # Cancel whatever is still pending before bailing:
                    # each un-finished future holds a pool.begin() that
                    # only its done callback releases — leaking it
                    # biases p2c away from the replica forever and
                    # wedges any later drain's outstanding==0 barrier.
                    for ofut, _other in entries.values():
                        try:
                            if not ofut.done():
                                ofut.cancel()
                        except Exception:  # noqa: BLE001 — best-effort
                            pass
                    break
            fut, r = entries[id(first)]
            first = None
            pending -= 1
            cancelled = False
            try:
                cancelled = bool(fut.cancelled())
            except Exception:  # noqa: BLE001 — duck-typed fakes
                pass
            reply, err = _future_outcome(fut)
            if err is None and not cancelled:
                for ofut, _other in entries.values():
                    if ofut is not fut:
                        try:
                            ofut.cancel()
                        except Exception:  # noqa: BLE001 — best-effort
                            pass
                if r is not rep:
                    ROUTER_HEDGE_WINS.labels(method=method).inc()
                _trace.TRACER.record_span(
                    "router.forward", span.ctx, started,
                    time.monotonic() - started,
                    attrs={"replica": r.target, "attempt": attempt,
                           "ok": True, "hedged": hedged,
                           "hedge_won": r is not rep},
                )
                return reply, None, r, hedged
            if err is not None and not cancelled:
                if not self._failover_worthy(_status_of(err)):
                    # A deterministic verdict propagates IMMEDIATELY —
                    # another replica would say the same thing, so
                    # waiting out the other in-flight copy (possibly
                    # the full forward timeout) only adds latency the
                    # un-hedged path never paid. Cancel it and return.
                    for ofut, _other in entries.values():
                        if ofut is not fut:
                            try:
                                ofut.cancel()
                            except Exception:  # noqa: BLE001
                                pass
                    _trace.TRACER.record_span(
                        "router.forward", span.ctx, started,
                        time.monotonic() - started,
                        attrs={"replica": r.target, "attempt": attempt,
                               "ok": False, "hedged": hedged},
                    )
                    return None, err, r, hedged
                # A transient loser: its verdict feeds the breaker,
                # the per-replica counter, AND the failover exclusion
                # set now — the next attempt must not be handed
                # straight back to a replica that failed this very
                # request (the outer loop only records the FINAL
                # errored replica).
                tried.add(r.target)
                if pending:
                    self._record_loser(r, err)
                last_err, last_rep = err, r
        if last_err is None:
            # Both copies vanished without a verdict (cancel race on a
            # fake, wait-cap breach): surface a budget-shaped error so
            # the failover loop can do its job.
            last_err = _SyntheticRpcError(
                grpc.StatusCode.DEADLINE_EXCEEDED,
                "hedged forward produced no reply within the budget",
            )
        _trace.TRACER.record_span(
            "router.forward", span.ctx, started,
            time.monotonic() - started,
            attrs={"replica": last_rep.target, "attempt": attempt,
                   "ok": False, "hedged": hedged},
        )
        return None, last_err, last_rep, hedged

    def _transient(self, code) -> bool:
        """One classification for every path (plain failover, hedged
        losers, loser recording): a divergence here would let the
        hedged and unhedged paths disagree on which errors trip
        breakers and fail over."""
        if self._retry is not None:
            return self._retry.retryable(code)
        return _code_name(code) in RETRYABLE_CODES

    def _failover_worthy(self, code) -> bool:
        """Transient errors fail over; so does DATA_LOSS (an integrity
        guard refusing to ship an untrustworthy answer) — the one
        non-transient code where another replica WILL say something
        different, because the defect is this replica's weights or
        arithmetic, not the request. DATA_LOSS is deliberately absent
        from RETRYABLE_CODES so direct clients never retry the same
        replica; the router's exclusion set gives it failover-to-
        DIFFERENT-replica semantics instead."""
        if code == grpc.StatusCode.DATA_LOSS:
            return True
        return self._transient(code)

    def _observe_failure(self, rep, code) -> None:
        """Feed one failed attempt's verdict to the right tripwire.
        DATA_LOSS closes the breaker probe (the replica ANSWERED —
        reachability is fine) but counts an integrity strike toward
        quarantine: the breaker must stay out of it, or the replica
        could half-open its way back while still computing garbage."""
        if code == grpc.StatusCode.DATA_LOSS:
            rep.breaker.record_success()
            self.pool.note_integrity_error(rep.target)
        elif self._transient(code):
            rep.breaker.record_failure()
        else:
            # The replica ANSWERED (reachability): close a probe
            # instead of wedging it, exactly like GrpcClient.
            rep.breaker.record_success()

    def _record_loser(self, rep, err) -> None:
        code = _status_of(err)
        self._observe_failure(rep, code)
        ROUTER_REQUESTS.labels(
            replica=rep.target, outcome=_code_name(code)
        ).inc()


def _copy_retry_after(context, err) -> None:
    """Forward a replica's x-tdn-retry-after-ms trailing metadata onto
    the router's own reply (extending — not replacing — the trace-id
    trailing metadata `_request_span` stashed). Best-effort: fakes may
    lack metadata on either side."""
    try:
        for k, v in err.trailing_metadata() or ():
            if k == RETRY_AFTER_HEADER:
                base = tuple(getattr(context, "_tdn_trailing", ()))
                context.set_trailing_metadata(
                    base + ((RETRY_AFTER_HEADER, v),)
                )
                return
    except Exception:  # noqa: BLE001 — enrichment only
        pass


def _status_of(e: grpc.RpcError):
    try:
        code = e.code()
    except Exception:  # noqa: BLE001 — in-process fakes
        code = None
    return code if code is not None else grpc.StatusCode.UNKNOWN


def _details_of(e: grpc.RpcError) -> str:
    try:
        return e.details() or str(e)
    except Exception:  # noqa: BLE001
        return str(e)


def _make_router_handler(router: Router):
    def bind(method: str):
        def handle(request_bytes: bytes, context) -> bytes:
            return router.handle(method, request_bytes, context)

        return grpc.unary_unary_rpc_method_handler(
            handle, request_deserializer=bytes, response_serializer=bytes
        )

    def handle_stream(request_bytes: bytes, context):
        yield from router.handle_stream(
            "GenerateStream", request_bytes, context
        )

    return grpc.method_handlers_generic_handler(
        SERVICE_NAME,
        {
            "Process": bind("Process"),
            "Generate": bind("Generate"),
            "GenerateStream": grpc.unary_stream_rpc_method_handler(
                handle_stream, request_deserializer=bytes,
                response_serializer=bytes,
            ),
        },
    )


def serve_router(pool: ReplicaPool, port: int, *,
                 host: str = "0.0.0.0", max_workers: int = 32,
                 retry=_CLIENT_DEFAULT, interceptors=(),
                 forward_timeout: float | None = 120.0,
                 hedge: HedgePolicy | None = None,
                 canary=None, spotcheck=None):
    """Start the router on ``host:port``; returns ``(server,
    bound_port)``. ``server.router`` / ``server.pool`` expose the
    internals; ``port=0`` picks an ephemeral port (printed by ``tdn
    router`` as a JSON line). ``retry=None`` disables failover (one
    attempt per request — the A/B control arm); ``interceptors`` is
    the fault-injection seam, same as the engine servers;
    ``forward_timeout`` caps each forward for deadline-less callers
    (a wedged replica must not hold worker threads forever);
    ``hedge`` arms tail-latency request hedging (off by default —
    docs/SCALING.md "Request hedging"); ``canary`` (a
    :class:`~tpu_dist_nn.serving.integrity.CanaryProber`) arms
    golden-answer probing in the pool's scrape loop and ``spotcheck``
    (a :class:`~tpu_dist_nn.serving.integrity.SpotChecker`) shadows
    sampled Process traffic — both off by default
    (docs/ROBUSTNESS.md "Silent corruption & quarantine")."""
    if canary is not None:
        pool.canary = canary
    router = Router(pool, retry=retry, forward_timeout=forward_timeout,
                    hedge=hedge, spotcheck=spotcheck)
    server = _new_grpc_server(max_workers, interceptors)
    server.add_generic_rpc_handlers((_make_router_handler(router),))
    bound = server.add_insecure_port(f"{host}:{port}")
    if bound == 0:
        raise OSError(f"could not bind router to port {port}")
    server.router = router
    server.pool = pool
    server.start()
    slog.info("router.start", port=bound, replicas=pool.targets())
    return server, bound


def router_health(pool: ReplicaPool):
    """A ``/healthz`` closure for the router's metrics endpoint: ready
    while at least one replica is placeable (the condition under which
    the router can serve anything)."""

    def health():
        snap = pool.snapshot()
        placeable = [
            s for s in snap
            if s["state"] == "active" and s["breaker"] != "open"
        ]
        return {
            "ready": bool(placeable),
            "role": "router",
            "replicas": len(snap),
            "placeable": len(placeable),
            "quarantined": sum(
                1 for s in snap if s["state"] == "quarantined"
            ),
        }

    return health


def admin_routes(pool: ReplicaPool, recorder=None,
                 autoscaler=None) -> dict:
    """The rolling-restart admin surface, mounted on the router's
    metrics endpoint (:class:`~tpu_dist_nn.obs.exposition.MetricsServer`
    ``routes=``): fleet introspection for ``tdn metrics --aggregate``,
    the drain choreography for ``tdn router --drain-replica``, and the
    server-side stitched fleet trace (``GET /trace/fleet`` — the
    router's own spans merged with every replica's ``/trace`` pull,
    one lane per process; ``tdn trace --aggregate`` is the client-side
    twin).

    State-CHANGING admin verbs (drain, undrain, scale) are POST-only
    (:func:`admin_post_routes`) so a scraper or crawler sweeping every
    GET path can never actuate the fleet; this function mounts only
    the read side.

    ``recorder`` (a :class:`~tpu_dist_nn.obs.incident.FlightRecorder`
    fronting this pool) additionally mounts the incident surface —
    ``/incidents``, ``/incidents/get``, and a ``/debug/bundle`` that
    captures the WHOLE fleet (every replica's bundle pulled and the
    traces stitched) instead of the endpoint's process-local default."""

    def replicas(query: str):
        return 200, "application/json", (
            json.dumps(pool.snapshot()).encode() + b"\n"
        )

    def autoscale_status(query: str):
        if autoscaler is None:
            return 404, "application/json", (
                b'{"error": "no autoscaler (start tdn router with '
                b'--autoscale-min/--autoscale-max)"}\n'
            )
        return 200, "application/json", json.dumps(
            autoscaler.status()
        ).encode() + b"\n"

    from tpu_dist_nn.obs.collect import fleet_trace_route

    routes = {
        "/router/replicas": replicas,
        "/router/autoscale": autoscale_status,
        "/trace/fleet": fleet_trace_route(pool),
    }
    if recorder is not None:
        from tpu_dist_nn.obs.incident import incident_routes

        routes.update(incident_routes(recorder))
    return routes


def admin_post_routes(pool: ReplicaPool | None = None,
                      autoscaler=None) -> dict:
    """POST routes for the router's metrics endpoint
    (:meth:`~tpu_dist_nn.obs.exposition.MetricsServer.add_post_routes`)
    — every verb that CHANGES fleet state lives here, POST-only, so a
    GET sweep of the admin surface can never actuate anything:

    * ``POST /router/drain?replica=T`` / ``POST /router/undrain?replica=T``
      — the rolling-restart choreography (``tdn router --drain-replica``);
    * ``POST /router/scale?replicas=N`` — park the fleet at N (manual
      autoscaler override, clamped to min/max, actuated through the
      same drain/spawn choreography); ``?mode=auto`` hands control
      back to the policy. Mounted even without an autoscaler so the
      operator gets a hint instead of a 404;
    * ``POST /router/quarantine?replica=T`` /
      ``POST /router/unquarantine?replica=T[&force=1]`` — the
      operator's integrity verbs: quarantine pulls a suspect replica
      out of placement immediately (reason ``operator``); unquarantine
      re-admits only after the fingerprint + canary reverify passes,
      unless ``force=1`` overrides the checks
      (docs/ROBUSTNESS.md "Silent corruption & quarantine")."""

    def _one_target(query: str) -> str | None:
        q = urllib.parse.parse_qs(query)
        vals = q.get("replica")
        return vals[0] if vals else None

    def drain(query: str):
        target = _one_target(query)
        if target is None:
            return 400, "application/json", \
                b'{"error": "replica= query parameter required"}\n'
        ok = pool.drain(target)
        status = 200 if ok else 404
        return status, "application/json", json.dumps(
            {"replica": target, "draining": ok}
        ).encode() + b"\n"

    def undrain(query: str):
        target = _one_target(query)
        if target is None:
            return 400, "application/json", \
                b'{"error": "replica= query parameter required"}\n'
        ok = pool.undrain(target)
        status = 200 if ok else 404
        return status, "application/json", json.dumps(
            {"replica": target, "active": ok}
        ).encode() + b"\n"

    def scale(query: str):
        if autoscaler is None:
            return 409, "application/json", (
                b'{"error": "no autoscaler (start tdn router with '
                b'--autoscale-min/--autoscale-max)"}\n'
            )
        q = urllib.parse.parse_qs(query)
        mode = (q.get("mode") or [None])[0]
        replicas = (q.get("replicas") or [None])[0]
        if mode == "auto":
            autoscaler.clear_override()
            return 200, "application/json", json.dumps(
                autoscaler.status()
            ).encode() + b"\n"
        if replicas is None:
            return 400, "application/json", (
                b'{"error": "replicas=N (or mode=auto) query '
                b'parameter required"}\n'
            )
        try:
            n = int(replicas)
        except ValueError:
            return 400, "application/json", \
                b'{"error": "replicas must be an integer"}\n'
        if n < 1:
            return 400, "application/json", \
                b'{"error": "replicas must be >= 1"}\n'
        granted = autoscaler.set_override(n)
        doc = autoscaler.status()
        doc["requested"] = n
        doc["granted"] = granted
        return 200, "application/json", json.dumps(doc).encode() + b"\n"

    def quarantine(query: str):
        target = _one_target(query)
        if target is None:
            return 400, "application/json", \
                b'{"error": "replica= query parameter required"}\n'
        ok = pool.quarantine(target, reason="operator")
        status = 200 if ok else 404
        return status, "application/json", json.dumps(
            {"replica": target, "quarantined": ok}
        ).encode() + b"\n"

    def unquarantine(query: str):
        target = _one_target(query)
        if target is None:
            return 400, "application/json", \
                b'{"error": "replica= query parameter required"}\n'
        q = urllib.parse.parse_qs(query)
        force = (q.get("force") or ["0"])[0] not in ("0", "", "false")
        doc = pool.unquarantine(target, force=force)
        status = 200 if doc.get("ok") else (
            404 if doc.get("error") == "not quarantined" else 409
        )
        return status, "application/json", \
            json.dumps(doc).encode() + b"\n"

    routes = {"/router/scale": scale}
    if pool is not None:
        routes["/router/drain"] = drain
        routes["/router/undrain"] = undrain
        routes["/router/quarantine"] = quarantine
        routes["/router/unquarantine"] = unquarantine
    return routes
