"""Load-aware gRPC front door over a :class:`ReplicaPool`.

The router speaks the SAME wire surface as one engine server —
``LayerService/Process`` and ``/Generate``, raw Matrix bytes — so any
existing client (``GrpcClient``, the reference's stubs, ``tdn infer
--target``) points at the router unchanged and transparently gains a
fleet behind it. Per request the router:

1. joins the caller's trace (``x-tdn-trace``) so the hop shows up as
   a ``router.forward`` stage in ``/profile`` (placement time is the
   ``tdn_router_placement_seconds`` histogram — microseconds, not
   worth a span per attempt) — the router hop is attributable, never
   a black box between client and engine;
2. picks a replica by power-of-two-choices over live load
   (:meth:`ReplicaPool.place`), honoring session affinity
   (``x-tdn-session``) so a follow-up Generate lands on the replica
   already holding its KV/prefix-cache state;
3. forwards the RAW request bytes over a persistent channel (the
   router never decodes a Matrix — the hop costs metadata handling
   plus one TCP round trip, not a codec pass);
4. on a TRANSIENT failure (UNAVAILABLE / DEADLINE_EXCEEDED) records
   the breaker outcome and FAILS OVER to another replica within the
   caller's remaining budget (deadline and/or ``x-tdn-timeout-ms``
   hint) — the reference's "clients may retry elsewhere" done FOR the
   client, with the same budget-carving rule as
   :class:`~tpu_dist_nn.serving.resilience.RetryPolicy`;
5. propagates a non-transient status (INVALID_ARGUMENT, INTERNAL,
   RESOURCE_EXHAUSTED...) verbatim — deterministic failures are the
   replica's verdict, retrying them elsewhere only doubles the damage.

Metrics (docs/OBSERVABILITY.md): ``tdn_router_requests_total{replica,
outcome}``, ``tdn_router_placement_seconds``,
``tdn_router_failovers_total``, plus the pool's
``tdn_router_replica_healthy{replica}``. Admin: :func:`admin_routes`
serves ``/router/replicas`` / ``/router/drain`` / ``/router/undrain``
on the metrics endpoint — the ``tdn router --drain-replica`` path for
zero-downtime rolling restarts (docs/SCALING.md).
"""

from __future__ import annotations

import json
import logging
import time
import urllib.parse

import grpc

from tpu_dist_nn.obs import trace as _trace
from tpu_dist_nn.obs.log import get_logger
from tpu_dist_nn.obs.registry import REGISTRY
from tpu_dist_nn.serving.pool import ACTIVE, ReplicaPool
from tpu_dist_nn.serving.resilience import (
    RETRYABLE_CODES,
    CircuitBreaker,
    RetryPolicy,
    _code_name,
)
from tpu_dist_nn.serving.server import _new_grpc_server, _request_span
from tpu_dist_nn.serving.wire import SERVICE_NAME, SESSION_HEADER

log = logging.getLogger(__name__)
slog = get_logger(__name__)

ROUTER_REQUESTS = REGISTRY.counter(
    "tdn_router_requests_total",
    "requests the router forwarded (or failed), per replica and "
    "outcome ('ok' or the gRPC status name; replica 'none' = no "
    "placement possible)",
    labels=("replica", "outcome"),
)
ROUTER_PLACEMENT = REGISTRY.histogram(
    "tdn_router_placement_seconds",
    "time spent choosing a replica for one attempt (p2c + session "
    "lookup; excludes the forward itself)",
)
ROUTER_FAILOVERS = REGISTRY.counter(
    "tdn_router_failovers_total",
    "attempts re-placed onto ANOTHER replica after a transient "
    "failure (the fleet absorbing a replica loss)",
)
ROUTER_LATENCY = REGISTRY.histogram(
    "tdn_router_request_seconds",
    "request wall time through the router, per method (placement + "
    "every forward attempt + failover backoff; the latency-SLO family "
    "for the fleet's front door)",
    labels=("method",),
)

_CLIENT_DEFAULT = object()


class Router:
    """The forwarding core behind both RPC methods (one instance per
    server; stateless between requests except through the pool)."""

    def __init__(self, pool: ReplicaPool, *, retry=_CLIENT_DEFAULT,
                 forward_timeout: float | None = 120.0):
        self.pool = pool
        # max_attempts bounds attempts per REQUEST (across replicas);
        # failover to a fresh replica is immediate, the jittered
        # backoff only paces a second pass over the same replicas.
        self._retry = (
            RetryPolicy(base_delay=0.01, max_delay=0.25)
            if retry is _CLIENT_DEFAULT else retry
        )
        # Per-forward cap when the caller sent NO deadline and no
        # x-tdn-timeout-ms hint: a replica that accepts TCP but never
        # answers (SIGSTOP, blackhole) must not hold a router worker
        # thread forever — 32 such requests would wedge the whole
        # front door. Deadline-carrying requests keep their own budget
        # (the engine path bounds these the same way: the batcher's
        # submit_timeout defaults to 120s). None disables the cap.
        self._forward_timeout = forward_timeout

    # ----------------------------------------------------------- serve

    def handle(self, method: str, payload: bytes, context) -> bytes:
        span, budget, md = _request_span(context, f"{method}")
        session = md.get(SESSION_HEADER)
        t0 = time.monotonic()
        try:
            return self._route(method, payload, context, span, budget,
                               session)
        finally:
            # Observed on EVERY outcome (abort raises through here):
            # an SLO over this family must see the slow failures, not
            # just the successes.
            ROUTER_LATENCY.labels(method=method).observe(
                time.monotonic() - t0
            )
            span.end()

    def _abort(self, context, replica: str, code, message: str):
        ROUTER_REQUESTS.labels(
            replica=replica, outcome=_code_name(code)
        ).inc()
        context.abort(code, message)

    def _route(self, method: str, payload: bytes, context, span, budget,
               session: str | None) -> bytes:
        policy = self._retry
        deadline = time.monotonic() + budget if budget is not None else None
        attempt = 0
        tried: set[str] = set()
        last: grpc.RpcError | None = None
        prev_failed: str | None = None
        while True:
            attempt += 1
            t0 = time.monotonic()
            rep = self.pool.place(session_key=session, exclude=tried)
            if rep is None and tried:
                # Every placeable replica failed this request once:
                # widen back to the full set for the next pass.
                tried.clear()
                rep = self.pool.place(session_key=session)
            ROUTER_PLACEMENT.observe(time.monotonic() - t0)
            if rep is None:
                span.annotate("no placeable replica")
                if last is not None:
                    self._abort(
                        context, "none", _status_of(last),
                        f"no replica left to fail over to: "
                        f"{_details_of(last)}",
                    )
                self._abort(
                    context, "none", grpc.StatusCode.UNAVAILABLE,
                    "no healthy replica available (pool empty, all "
                    "draining, or all breakers open)",
                )
            remaining = None
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0.001:
                    # Label "none": this replica never saw the request
                    # — the budget died on earlier attempts elsewhere.
                    span.annotate("budget exhausted before forward")
                    self._abort(
                        context, "none",
                        grpc.StatusCode.DEADLINE_EXCEEDED,
                        "request budget exhausted during failover",
                    )
            metadata = [(_trace.TRACE_HEADER, span.ctx.header())]
            if remaining is not None:
                metadata.append(
                    (_trace.TIMEOUT_HEADER,
                     str(max(0, int(remaining * 1000))))
                )
            if session is not None:
                metadata.append((SESSION_HEADER, session))
            if prev_failed is not None and rep.target != prev_failed:
                # Only an actual re-placement onto ANOTHER replica is a
                # failover — a same-replica retry (single-replica pool,
                # or the widened pass landing back) is not the fleet
                # absorbing anything.
                ROUTER_FAILOVERS.inc()
            self.pool.begin(rep)
            err: grpc.RpcError | None = None
            t_fwd = time.monotonic()
            try:
                reply = rep.call(
                    method, payload,
                    timeout=(remaining if remaining is not None
                             else self._forward_timeout),
                    metadata=metadata,
                )
            except grpc.RpcError as e:
                err = e
            finally:
                self.pool.done(rep)
                _trace.TRACER.record_span(
                    "router.forward", span.ctx, t_fwd,
                    time.monotonic() - t_fwd,
                    attrs={"replica": rep.target, "attempt": attempt,
                           "ok": err is None},
                )
            if err is None:
                rep.breaker.record_success()
                ROUTER_REQUESTS.labels(
                    replica=rep.target, outcome="ok"
                ).inc()
                if session is not None:
                    self.pool.pin(session, rep.target)
                if attempt > 1:
                    span.annotate(
                        f"served by {rep.target} on attempt {attempt}"
                    )
                return reply
            code = _status_of(err)
            transient = (
                policy.retryable(code) if policy is not None
                else _code_name(code) in RETRYABLE_CODES
            )
            if transient:
                rep.breaker.record_failure()
            else:
                # The replica ANSWERED (reachability): close a probe
                # instead of wedging it, exactly like GrpcClient.
                rep.breaker.record_success()
            ROUTER_REQUESTS.labels(
                replica=rep.target, outcome=_code_name(code)
            ).inc()
            if not transient:
                # Deterministic verdicts propagate verbatim — another
                # replica would say the same thing.
                span.annotate(
                    f"{_code_name(code)} from {rep.target}: propagated"
                )
                context.abort(code, _details_of(err))
            last = err
            tried.add(rep.target)
            # A fresh replica is tried immediately; the backoff only
            # paces a renewed pass once every PLACEABLE replica has
            # failed. Draining / breaker-open replicas don't count —
            # place() will never return them, and letting them mask
            # the pacing would hammer the one struggling replica
            # back-to-back with zero delay.
            placeable = {
                r.target for r in self.pool.replicas()
                if r.state == ACTIVE
                and r.breaker.state == CircuitBreaker.CLOSED
            }
            retry_same_set = not (placeable - tried)
            # The attempt cap scales with the fleet: policy.max_attempts
            # is a client-oriented default (3) — on a 5-replica pool
            # where 3 died together (their breakers still closed, and
            # dead-fast failures make p2c PREFER them), a fixed cap
            # aborts with healthy replicas never tried. Every replica
            # in this request's view gets at least one shot.
            out_of_attempts = (
                policy is None
                or attempt >= max(policy.max_attempts,
                                  len(placeable | tried))
            )
            delay = (
                policy.backoff(attempt)
                if not out_of_attempts and retry_same_set else 0.0
            )
            out_of_budget = (
                deadline is not None
                and time.monotonic() + delay >= deadline
            )
            if out_of_attempts or out_of_budget:
                why = ("attempts exhausted" if out_of_attempts
                       else "budget exhausted")
                span.annotate(
                    f"failover stopped after attempt {attempt} ({why})"
                )
                slog.warning(
                    "router.request_failed", method=method,
                    replica=rep.target, code=_code_name(code),
                    attempts=attempt, why=why,
                )
                context.abort(code, _details_of(err))
            prev_failed = rep.target
            span.annotate(
                f"failover after {_code_name(code)} from {rep.target}"
            )
            if delay:
                policy.sleep(delay)


def _status_of(e: grpc.RpcError):
    try:
        code = e.code()
    except Exception:  # noqa: BLE001 — in-process fakes
        code = None
    return code if code is not None else grpc.StatusCode.UNKNOWN


def _details_of(e: grpc.RpcError) -> str:
    try:
        return e.details() or str(e)
    except Exception:  # noqa: BLE001
        return str(e)


def _make_router_handler(router: Router):
    def bind(method: str):
        def handle(request_bytes: bytes, context) -> bytes:
            return router.handle(method, request_bytes, context)

        return grpc.unary_unary_rpc_method_handler(
            handle, request_deserializer=bytes, response_serializer=bytes
        )

    return grpc.method_handlers_generic_handler(
        SERVICE_NAME,
        {"Process": bind("Process"), "Generate": bind("Generate")},
    )


def serve_router(pool: ReplicaPool, port: int, *,
                 host: str = "0.0.0.0", max_workers: int = 32,
                 retry=_CLIENT_DEFAULT, interceptors=(),
                 forward_timeout: float | None = 120.0):
    """Start the router on ``host:port``; returns ``(server,
    bound_port)``. ``server.router`` / ``server.pool`` expose the
    internals; ``port=0`` picks an ephemeral port (printed by ``tdn
    router`` as a JSON line). ``retry=None`` disables failover (one
    attempt per request — the A/B control arm); ``interceptors`` is
    the fault-injection seam, same as the engine servers;
    ``forward_timeout`` caps each forward for deadline-less callers
    (a wedged replica must not hold worker threads forever)."""
    router = Router(pool, retry=retry, forward_timeout=forward_timeout)
    server = _new_grpc_server(max_workers, interceptors)
    server.add_generic_rpc_handlers((_make_router_handler(router),))
    bound = server.add_insecure_port(f"{host}:{port}")
    if bound == 0:
        raise OSError(f"could not bind router to port {port}")
    server.router = router
    server.pool = pool
    server.start()
    slog.info("router.start", port=bound, replicas=pool.targets())
    return server, bound


def router_health(pool: ReplicaPool):
    """A ``/healthz`` closure for the router's metrics endpoint: ready
    while at least one replica is placeable (the condition under which
    the router can serve anything)."""

    def health():
        snap = pool.snapshot()
        placeable = [
            s for s in snap
            if s["state"] == "active" and s["breaker"] != "open"
        ]
        return {
            "ready": bool(placeable),
            "role": "router",
            "replicas": len(snap),
            "placeable": len(placeable),
        }

    return health


def admin_routes(pool: ReplicaPool, recorder=None) -> dict:
    """The rolling-restart admin surface, mounted on the router's
    metrics endpoint (:class:`~tpu_dist_nn.obs.exposition.MetricsServer`
    ``routes=``): fleet introspection for ``tdn metrics --aggregate``,
    the drain choreography for ``tdn router --drain-replica``, and the
    server-side stitched fleet trace (``GET /trace/fleet`` — the
    router's own spans merged with every replica's ``/trace`` pull,
    one lane per process; ``tdn trace --aggregate`` is the client-side
    twin).

    ``recorder`` (a :class:`~tpu_dist_nn.obs.incident.FlightRecorder`
    fronting this pool) additionally mounts the incident surface —
    ``/incidents``, ``/incidents/get``, and a ``/debug/bundle`` that
    captures the WHOLE fleet (every replica's bundle pulled and the
    traces stitched) instead of the endpoint's process-local default."""

    def replicas(query: str):
        return 200, "application/json", (
            json.dumps(pool.snapshot()).encode() + b"\n"
        )

    def _one_target(query: str) -> str | None:
        q = urllib.parse.parse_qs(query)
        vals = q.get("replica")
        return vals[0] if vals else None

    def drain(query: str):
        target = _one_target(query)
        if target is None:
            return 400, "application/json", \
                b'{"error": "replica= query parameter required"}\n'
        ok = pool.drain(target)
        status = 200 if ok else 404
        return status, "application/json", json.dumps(
            {"replica": target, "draining": ok}
        ).encode() + b"\n"

    def undrain(query: str):
        target = _one_target(query)
        if target is None:
            return 400, "application/json", \
                b'{"error": "replica= query parameter required"}\n'
        ok = pool.undrain(target)
        status = 200 if ok else 404
        return status, "application/json", json.dumps(
            {"replica": target, "active": ok}
        ).encode() + b"\n"

    from tpu_dist_nn.obs.collect import fleet_trace_route

    routes = {
        "/router/replicas": replicas,
        "/router/drain": drain,
        "/router/undrain": undrain,
        "/trace/fleet": fleet_trace_route(pool),
    }
    if recorder is not None:
        from tpu_dist_nn.obs.incident import incident_routes

        routes.update(incident_routes(recorder))
    return routes
