"""Shared scheduling core: the admission/shed/close/drain contract
both request schedulers speak, extracted once.

``_Batcher`` (serving/server.py) and ``ContinuousScheduler``
(serving/continuous.py) each grew the same machinery across PRs 4, 5
and 7 — a pending queue under a condition variable, a pending-rows
admission ledger with a ``max_pending_rows`` shed watermark, bounded
submit waits with abandoned-entry discard, and a close-time failover
sweep that fails still-queued waiters over as UNAVAILABLE. Two copies
meant every admission fix landed twice (or once). This module is the
ONE implementation, plus the degradation ladder the freedom pays for
(docs/ROBUSTNESS.md "Degradation ladder"):

* **SLO classes** — every entry carries a class (``critical`` /
  ``standard`` / ``best_effort``, the ``x-tdn-class`` header
  end-to-end). The queue is CLASS-PRIORITY FIFO: critical pops first,
  best_effort last, FIFO within a class — under backlog a
  latency-critical request no longer convoys behind batch backfill.
* **Class-aware shedding** — each class sheds at its own fraction of
  the ``max_pending_rows`` watermark (``class_watermarks``; default
  best_effort 0.5, standard/critical 1.0), so best_effort absorbs the
  overload first while the headroom above its fraction stays reserved
  for the classes that page. The legacy edge is preserved per class:
  an oversized request against an EMPTY queue is always admitted (the
  watermark bounds backlog, not request size).
* **Burn-rate tightening** — an :class:`AdmissionGovernor` on the
  runtime-sampler tick maps the existing
  :class:`~tpu_dist_nn.obs.slo.SLOTracker` fast-window verdict to a
  pressure level, ONE CLASS AT A TIME: sustained fast burn > 1 first
  sheds all best_effort admissions (level 1), then standard too
  (level 2); critical admission is never tightened. Sustained calm
  steps the pressure back down.
* **Deadline-aware expiry** — an entry whose caller budget (gRPC
  deadline / ``x-tdn-timeout-ms`` hint) is already exhausted when the
  scheduler would stage/bind it is failed DEADLINE_EXCEEDED on the
  spot instead of being launched: today that entry rides a full device
  launch nobody is waiting for (the waiter's own timer and the pop
  race by construction fire within the same instant — the expiry
  check closes the window where the pop wins the race and burns a
  launch; ``tdn_batcher_expired_total{method,class}`` counts them).
* **Backoff hints** — every shed carries ``retry_after_ms`` derived
  from the CURRENT drain rate (rows completed per second over a short
  window): the server tells its clients how long the backlog actually
  needs, and :class:`~tpu_dist_nn.serving.resilience.RetryPolicy`
  honors it as the backoff FLOOR (``x-tdn-retry-after-ms`` trailing
  metadata), so a shed storm cannot re-synchronize into a hot-retry
  storm.

The core owns the queue and the contract; the schedulers own their
device loops. Their counter surface (``pending_rows``,
``requests_total``, ``shed_total`` ...) is preserved via delegation so
the runtime sampler, drain plumbing, and every existing resilience
test read the same names unchanged.
"""

from __future__ import annotations

import collections
import threading
import time

from tpu_dist_nn.obs.log import get_logger
from tpu_dist_nn.obs.registry import REGISTRY

slog = get_logger(__name__)

# The service classes, best first. Rank is the pop order (critical
# pops first) AND the tightening order reversed (best_effort tightens
# first).
SLO_CLASSES = ("critical", "standard", "best_effort")
CLASS_RANK = {cls: i for i, cls in enumerate(SLO_CLASSES)}

# Watermark fraction per class: a class sheds when admitting would push
# pending_rows past fraction * max_pending_rows. standard/critical at
# 1.0 preserve the legacy single-watermark behavior bit-for-bit;
# best_effort sheds first at half the queue.
DEFAULT_CLASS_WATERMARKS = {
    "critical": 1.0,
    "standard": 1.0,
    "best_effort": 0.5,
}

# Bounds on the shed reply's backoff hint. With no drain observed in
# the window (a wedged device) the hint is the cap — the backlog is
# not moving, retrying sooner cannot help.
RETRY_AFTER_MIN_MS = 50
RETRY_AFTER_MAX_MS = 5000
_DRAIN_WINDOW_S = 10.0

# Shared scheduler metric families (docs/OBSERVABILITY.md catalog).
# tdn_batcher_shed_total / tdn_batch_wait_seconds moved here from the
# two schedulers (same names, same labels — dashboards unchanged); the
# class-labeled families are new.
SHED = REGISTRY.counter(
    "tdn_batcher_shed_total",
    "submits fast-failed RESOURCE_EXHAUSTED at the pending-rows "
    "watermark (admission control)",
    labels=("method",),
)
WAIT = REGISTRY.histogram(
    "tdn_batch_wait_seconds",
    "time a request spent in the batcher (submit to result)",
    labels=("method",),
)
CLASS_SHED = REGISTRY.counter(
    "tdn_sched_class_shed_total",
    "admission sheds per SLO class (best_effort absorbing the "
    "overload is the degradation ladder working; critical sheds page)",
    labels=("method", "slo_class"),
)
CLASS_WAIT = REGISTRY.histogram(
    "tdn_sched_class_wait_seconds",
    "request time in the scheduler (submit to result) per SLO class "
    "— the per-class latency family the mixed-class A/B gates",
    labels=("method", "slo_class"),
)
EXPIRED = REGISTRY.counter(
    "tdn_batcher_expired_total",
    "queued entries failed DEADLINE_EXCEEDED at stage/bind time "
    "because their caller budget was already exhausted (work the "
    "device never burned a launch on)",
    labels=("method", "slo_class"),
)
PRESSURE = REGISTRY.gauge(
    "tdn_sched_pressure",
    "admission tightening level from the burn-rate governor (0 = "
    "none, 1 = best_effort shed, 2 = standard shed too; critical "
    "admission is never tightened)",
)
# tdn_sched_class_pending_rows is sampled by the runtime sampler
# (obs/runtime.py) off SchedCore.pending_by_class().


def normalize_class(value) -> str:
    """Map a wire value to a known class: missing/unknown -> standard
    (forward-compatible — a typo'd class must degrade to the default,
    not fail the RPC)."""
    if isinstance(value, str):
        v = value.strip().lower()
        if v in CLASS_RANK:
            return v
    return "standard"


def slide_stream_deadline(item: dict, gap: float | None) -> None:
    """Stream-aware deadline semantics (PR 16, docs/ROBUSTNESS.md).

    A unary entry's ``deadline`` bounds submit-to-RETIREMENT — the
    caller is blocked until the whole result exists. A STREAMING entry
    delivers incrementally, so the same absolute deadline would expire
    a perfectly healthy long generation mid-stream; what the client
    actually needs bounded is the NEXT-TOKEN gap. The scheduler calls
    this after every published token: the deadline slides forward by
    ``gap`` (the original caller budget), so :meth:`SchedCore._expire`
    / the preemption victim picker only ever kill a stream that has
    genuinely STALLED for a full budget — queued too long before its
    first token (the un-slid admission deadline covers that), or
    silent for ``gap`` seconds while preempted/wedged.

    Plain dict write, GIL-atomic: the scheduler loop is the only
    writer after admission, and readers (:meth:`SchedCore._dead`, the
    victim picker) tolerate either the old or new value.
    """
    if gap is not None:
        item["deadline"] = time.monotonic() + gap


def validate_class_watermarks(fractions: dict) -> dict:
    """Fail-fast validation for ``--class-watermarks``: known classes,
    fractions in [0, 1], returned as a full table over DEFAULTS."""
    table = dict(DEFAULT_CLASS_WATERMARKS)
    for cls, frac in (fractions or {}).items():
        if cls not in CLASS_RANK:
            raise ValueError(
                f"unknown SLO class {cls!r} (choose from "
                f"{', '.join(SLO_CLASSES)})"
            )
        f = float(frac)
        if not 0.0 <= f <= 1.0:
            raise ValueError(
                f"class watermark fraction for {cls} must be in "
                f"[0, 1], got {frac}"
            )
        table[cls] = f
    return table


class SchedCore:
    """The shared queue + admission + close contract for one scheduler.

    The scheduler's device loop holds ``self.cond`` exactly where it
    held its own condition before; the ``caller-holds`` methods below
    document which side of the lock each operation runs on. Expired
    entries are finalized (err + done) under the lock — cheap flag
    flips — while their structured log evidence is deferred to
    :meth:`drain_deferred`, called by the loops OUTSIDE the lock (one
    stalled log consumer must never wedge admission).
    """

    def __init__(self, method: str, *,
                 max_pending_rows: int | None = None,
                 submit_timeout: float | None = 120.0,
                 class_watermarks: dict | None = None):
        self.method = method
        self._max_pending_rows = (
            int(max_pending_rows) if max_pending_rows is not None else None
        )
        self._submit_timeout = submit_timeout
        self._fractions = validate_class_watermarks(class_watermarks)
        self.cond = threading.Condition()
        # One FIFO per class, popped in rank order (critical first).
        self._queues: dict[str, collections.deque] = {
            cls: collections.deque() for cls in SLO_CLASSES
        }  # guarded-by: cond
        self.pending_rows = 0  # guarded-by: cond
        self.closed = False  # guarded-by: cond
        self.requests_total = 0  # guarded-by: cond
        self.shed_total = 0  # guarded-by: cond
        self.expired_total = 0  # guarded-by: cond
        # Burn-rate tightening level (0..2), written by the governor's
        # sampler tick, read at admission. Plain int store/load.
        self.pressure = 0
        # Drain-rate window for the shed replies' retry-after hint:
        # (monotonic, rows) completions over the last _DRAIN_WINDOW_S.
        self._drained: collections.deque = collections.deque()  # guarded-by: _drain_lock
        self._drain_lock = threading.Lock()
        # Deferred expiry log events: (slo_class, rows, waited_s).
        self._deferred: list[tuple] = []  # guarded-by: cond
        self._m_shed = SHED.labels(method=method)
        self._m_wait = WAIT.labels(method=method)
        self._m_class_shed = {
            cls: CLASS_SHED.labels(method=method, slo_class=cls)
            for cls in SLO_CLASSES
        }
        self._m_class_wait = {
            cls: CLASS_WAIT.labels(method=method, slo_class=cls)
            for cls in SLO_CLASSES
        }
        self._m_expired = {
            cls: EXPIRED.labels(method=method, slo_class=cls)
            for cls in SLO_CLASSES
        }

    # ------------------------------------------------------------ admit

    def tightened(self, slo_class: str) -> bool:
        """Is this class's admission shut by the burn-rate governor?
        Pressure closes one class at a time from the bottom of the
        ladder (level 1: best_effort, level 2: standard; critical is
        never tightened). A tightened class sheds UNCONDITIONALLY —
        the empty-queue exemption below is for the row watermark, and
        honoring it here would re-admit most traffic between launches
        (the dispatch loop drains the whole queue per pop) while the
        SLO is actively burning."""
        rank = CLASS_RANK.get(slo_class, 1)
        return (self.pressure >= 1
                and rank >= len(SLO_CLASSES) - self.pressure)

    def effective_watermark(self, slo_class: str) -> float | None:
        """The class's shed threshold (None = unbounded); tightening
        is :meth:`tightened`, checked separately at admission."""
        if self.tightened(slo_class):
            return 0.0
        if self._max_pending_rows is None:
            return None
        return self._fractions[slo_class] * self._max_pending_rows

    def has_pending(self) -> bool:  # caller-holds: cond
        return any(self._queues[cls] for cls in SLO_CLASSES)

    def queue_depth(self) -> int:
        """Entries queued (lock-free snapshot for the sampler)."""
        return sum(len(q) for q in self._queues.values())

    def pending_items(self) -> list:
        """Flattened queue snapshot in pop order (test/debug surface;
        also what the schedulers' legacy ``_pending`` attribute now
        returns)."""
        with self.cond:
            return [
                item for cls in SLO_CLASSES for item in self._queues[cls]
            ]

    def pending_by_class(self) -> dict[str, int]:
        """Rows pending per class (sampled into
        tdn_sched_class_pending_rows)."""
        with self.cond:
            return {
                cls: sum(
                    len(it["x"]) - it.get("next_row", 0)
                    for it in self._queues[cls]
                )
                for cls in SLO_CLASSES
            }

    def admit(self, item: dict, timeout: float | None = None) -> None:
        """Admit one entry or shed it. ``item`` must carry ``x`` (the
        rows), ``done``/``err``/``abandoned``, ``t_submit`` and
        ``slo_class``; this sets ``item["deadline"]`` (absolute
        monotonic expiry of the caller's budget — the wait bound and
        the stage-time expiry check read the same number) and
        ``item["_wait"]``. Raises
        :class:`~tpu_dist_nn.utils.errors.UnavailableError` after
        close and :class:`~tpu_dist_nn.utils.errors
        .ResourceExhaustedError` (with ``retry_after_ms``) at the
        class watermark."""
        from tpu_dist_nn.utils.errors import (
            ResourceExhaustedError,
            UnavailableError,
        )

        cls = item.setdefault("slo_class", "standard")
        if cls not in CLASS_RANK:
            cls = item["slo_class"] = normalize_class(cls)
        n = len(item["x"]) - item.get("next_row", 0)
        bounds = [
            t for t in (self._submit_timeout, timeout) if t is not None
        ]
        item["_wait"] = min(bounds) if bounds else None
        # Expiry tracks the CALLER's budget only: submit_timeout is the
        # server's bound on holding a worker thread, not evidence the
        # client stopped waiting.
        item["deadline"] = (
            item["t_submit"] + timeout if timeout is not None else None
        )
        shed_pending = None
        with self.cond:
            if self.closed:
                raise UnavailableError("server is shutting down")
            watermark = self.effective_watermark(cls)
            # Admission control: past the class watermark, shed NOW
            # with a back-off signal instead of queueing work the
            # device is already minutes behind on. An oversized request
            # against an EMPTY queue is admitted — it could otherwise
            # never run; the watermark bounds backlog, not batch size.
            # A pressure-TIGHTENED class has no such exemption: the
            # burn governor shut its admission outright.
            if self.tightened(cls) or (
                    watermark is not None and self.has_pending()
                    and self.pending_rows + n > watermark):
                self.shed_total += 1
                self._m_shed.inc()
                self._m_class_shed[cls].inc()
                shed_pending = self.pending_rows
            else:
                self._queues[cls].append(item)
                self.pending_rows += n
                self.requests_total += 1
                self.cond.notify()
        if shed_pending is not None:
            retry_after = self.retry_after_ms()
            # Emitted OUTSIDE cond: the record write blocks on stderr,
            # and one stalled log consumer holding the admission lock
            # would wedge every submit and the device loop behind it.
            slog.warning(
                "batcher.shed", method=self.method, slo_class=cls,
                pending_rows=shed_pending, rows=n,
                watermark=watermark, pressure=self.pressure,
                retry_after_ms=retry_after,
            )
            e = ResourceExhaustedError(
                f"serving queue at capacity for class {cls} "
                f"({shed_pending} rows pending, watermark "
                f"{watermark:g}); back off and retry"
            )
            # The backoff hint rides the exception to
            # _abort_for_exception, which turns it into
            # x-tdn-retry-after-ms trailing metadata.
            e.retry_after_ms = retry_after
            raise e

    def wait(self, item: dict, what: str = "batch") -> None:
        """Block the submitting thread on ``item["done"]`` under the
        bound computed at admit; marks the entry abandoned and raises
        DEADLINE_EXCEEDED on expiry, re-raises a recorded error, and
        observes the wait histograms (method + class) on success."""
        wait = item["_wait"]
        # Bounded wait: if the engine wedges mid-batch, the gRPC
        # worker thread must get back to the client with
        # DEADLINE_EXCEEDED instead of blocking forever — an unbounded
        # wait would eventually strand every worker thread.
        if not item["done"].wait(wait):
            from tpu_dist_nn.utils.errors import DeadlineExceededError

            # Mark abandoned under the lock so the consumer discards
            # it at pop time: without this, a long wedge accumulates
            # dead requests unboundedly and the recovered engine burns
            # its first launches computing rows nobody is waiting for.
            with self.cond:
                item["abandoned"] = True
            raise DeadlineExceededError(
                f"{what} did not complete within {wait}s "
                "(engine wedged or request backlogged?)"
            )
        # Observed before the error re-raise (the legacy order): a
        # served-with-error entry still spent its time in the queue.
        waited = time.monotonic() - item["t_submit"]
        self._m_wait.observe(waited)
        self._m_class_wait[item["slo_class"]].observe(waited)
        if item["err"] is not None:
            raise item["err"]

    # ------------------------------------------------------------- pop

    def _expire(self, item: dict, now: float) -> None:  # caller-holds: cond
        """Finalize one entry whose caller budget ran out while it
        queued: DEADLINE_EXCEEDED without a launch."""
        from tpu_dist_nn.utils.errors import DeadlineExceededError

        cls = item["slo_class"]
        self.expired_total += 1
        self._m_expired[cls].inc()
        if item["err"] is None:
            item["err"] = DeadlineExceededError(
                "request budget exhausted while queued "
                f"(waited {now - item['t_submit']:.3f}s); not launched"
            )
            item["done"].set()
        self._deferred.append(
            (cls, len(item["x"]) - item.get("next_row", 0),
             now - item["t_submit"])
        )

    def _dead(self, item: dict, now: float) -> bool:  # caller-holds: cond
        """Is this popped entry not worth launching? Abandoned/errored
        entries are discarded silently (the waiter already raised);
        budget-expired ones are failed over via :meth:`_expire`."""
        if item["abandoned"] or item["err"] is not None:
            return True
        if item["deadline"] is not None and now >= item["deadline"]:
            self._expire(item, now)
            return True
        return False

    def _head_class(self):  # caller-holds: cond
        for cls in SLO_CLASSES:
            if self._queues[cls]:
                return cls
        return None

    def peek_rank(self) -> int | None:  # caller-holds: cond
        """Rank of the first non-empty class queue (liveness of the
        head entry is only known at pop time)."""
        cls = self._head_class()
        return None if cls is None else CLASS_RANK[cls]

    def queued(self, slo_class: str) -> int:  # caller-holds: cond
        return len(self._queues[slo_class])

    def pop_group(self, max_rows: int) -> tuple[list, int]:  # caller-holds: cond
        """Batcher-style pop: whole entries up to ``max_rows`` rows in
        class-priority order (the first entry is always taken even if
        oversized). Dead entries leave the ledger without joining the
        batch."""
        now = time.monotonic()
        batch: list = []
        rows = 0
        while True:
            cls = self._head_class()
            if cls is None:
                break
            head = self._queues[cls][0]
            n = len(head["x"])
            if batch and rows + n > max_rows:
                break
            self._queues[cls].popleft()
            # Popped (computed OR dropped): either way these rows
            # leave the admission ledger.
            self.pending_rows -= n
            if self._dead(head, now):
                continue
            rows += n
            batch.append(head)
        return batch, rows

    def pop_row(self, max_rank: int | None = None):  # caller-holds: cond
        """Row-granular pop (the continuous scheduler's admission
        unit): the next ``(item, row_index)`` in class-priority order,
        or None. ``max_rank`` restricts to classes at least that good
        (0 = critical only — the preemption path's pop)."""
        now = time.monotonic()
        while True:
            cls = self._head_class()
            if cls is None or (
                max_rank is not None and CLASS_RANK[cls] > max_rank
            ):
                return None
            item = self._queues[cls][0]
            if self._dead(item, now):
                self._queues[cls].popleft()
                self.pending_rows -= len(item["x"]) - item.get("next_row", 0)
                continue
            row = item.get("next_row", 0)
            item["next_row"] = row + 1
            self.pending_rows -= 1
            if item["next_row"] >= len(item["x"]):
                self._queues[cls].popleft()
            return item, row

    def drain_deferred(self) -> None:
        """Emit the expiry evidence accumulated under the lock (called
        by the device loops after releasing it; rate-limited by the
        structured-log channel)."""
        with self.cond:
            events, self._deferred = self._deferred, []
        for cls, rows, waited in events:
            slog.warning(
                "batcher.expired", method=self.method, slo_class=cls,
                rows=rows, waited_s=round(waited, 3),
            )

    # ----------------------------------------------------------- close

    def close_begin(self) -> None:
        with self.cond:
            self.closed = True
            self.cond.notify_all()

    def sweep_leftovers(self) -> None:
        """Fail everything STILL queued over as UNAVAILABLE (a wedged
        loop never popped it): its waiters would otherwise sit out
        their full submit timeout against a scheduler that is already
        gone. Pops under the lock, so a still-alive loop thread and
        this sweep never double-serve an entry."""
        from tpu_dist_nn.utils.errors import UnavailableError

        leftovers = []
        with self.cond:
            for cls in SLO_CLASSES:
                q = self._queues[cls]
                while q:
                    item = q.popleft()
                    self.pending_rows -= (
                        len(item["x"]) - item.get("next_row", 0)
                    )
                    if not item["abandoned"] and item["err"] is None:
                        leftovers.append(item)
        for item in leftovers:
            item["err"] = UnavailableError(
                "server shut down before this request was served"
            )
            item["done"].set()

    # ------------------------------------------------------ retry-after

    def note_drained(self, rows: int) -> None:
        """Record ``rows`` completions (drain fan-out / slot retire):
        the drain-rate window behind the shed replies' backoff hint."""
        now = time.monotonic()
        with self._drain_lock:
            self._drained.append((now, int(rows)))
            cutoff = now - _DRAIN_WINDOW_S
            while self._drained and self._drained[0][0] < cutoff:
                self._drained.popleft()

    def retry_after_ms(self) -> int:
        """The shed reply's backoff hint: how long the CURRENT backlog
        needs at the CURRENT drain rate, clamped to
        [RETRY_AFTER_MIN_MS, RETRY_AFTER_MAX_MS]. No drain observed in
        the window (wedged or idle-then-burst device) pins the cap —
        the backlog is not moving, retrying sooner cannot help."""
        now = time.monotonic()
        with self._drain_lock:
            cutoff = now - _DRAIN_WINDOW_S
            while self._drained and self._drained[0][0] < cutoff:
                self._drained.popleft()
            drained = sum(r for _, r in self._drained)
            oldest = self._drained[0][0] if self._drained else None
        if not drained:
            return RETRY_AFTER_MAX_MS
        with self.cond:
            backlog = self.pending_rows
        span = max(now - oldest, 0.25)
        rate = drained / span  # rows per second
        ms = int(backlog / rate * 1000.0)
        return max(RETRY_AFTER_MIN_MS, min(RETRY_AFTER_MAX_MS, ms))


class AdmissionGovernor:
    """Maps the SLO tracker's fast-window burn verdict to the cores'
    admission pressure, one class at a time (docs/ROBUSTNESS.md).

    Ticked by the runtime sampler AFTER the SLO trackers evaluate
    (``RuntimeSampler.add_admission_governor``), so the verdict it
    reads is this tick's. Tick-pure: it reads the tracker's cached
    ``status()`` (never recomputes windows) and flips an int.

    ``raise_after`` consecutive breaching ticks tighten one more
    class; ``lower_after`` consecutive calm ticks release one — the
    asymmetry keeps a flapping burn from strobing best_effort
    admission open and shut.
    """

    def __init__(self, tracker, cores, *, raise_after: int = 2,
                 lower_after: int = 6, max_level: int = 2):
        self.tracker = tracker
        self.cores = list(cores)
        self.raise_after = int(raise_after)
        self.lower_after = int(lower_after)
        self.max_level = min(int(max_level), len(SLO_CLASSES) - 1)
        self.level = 0
        self._hot = 0
        self._calm = 0

    def tick(self) -> int:
        doc = self.tracker.status()
        burning = any(
            o.get("burning") for o in doc.get("objectives", ())
        )
        if burning:
            self._hot += 1
            self._calm = 0
            if self.level < self.max_level and self._hot >= self.raise_after:
                self.level += 1
                self._hot = 0
                slog.warning(
                    "sched.tightened", level=self.level,
                    shedding=list(SLO_CLASSES[len(SLO_CLASSES)
                                              - self.level:]),
                )
        else:
            self._calm += 1
            self._hot = 0
            if self.level > 0 and self._calm >= self.lower_after:
                self.level -= 1
                self._calm = 0
                slog.info("sched.loosened", level=self.level)
        for core in self.cores:
            core.pressure = self.level
        PRESSURE.set(float(self.level))
        return self.level
