"""Fleet manifest generator: docker-compose / k8s specs from a pool.

``tdn router --spawn N`` fleets get the full lifecycle automation for
free — supervised children, SIGTERM → GracefulDrain rolling restarts,
ready-scrape rejoin — because the pool owns the processes. Remote
fleets (one ``tdn up`` per host/container) historically had to
recreate that choreography by hand. This module writes it down ONCE,
as orchestrator config generated from the same parameters a local
fleet runs with (docs/SCALING.md "Fleet manifests"):

* **docker-compose** — one service per replica plus the router.
  ``healthcheck`` polls the replica's ``/healthz`` (the exact probe
  the pool's scraper speaks), ``stop_grace_period`` covers the
  replica's ``--drain-grace-seconds`` so ``docker compose restart``
  IS the zero-downtime rolling restart, and ``restart:
  unless-stopped`` is the crash-respawn supervisor.
* **k8s** — a headless Service + StatefulSet for the replicas (stable
  per-replica DNS names, which the router's ``--replicas`` list and
  session affinity need) and a Deployment + Service for the router.
  ``readinessProbe`` hits ``/healthz`` (503 while draining unplaces
  the pod from the k8s Service AND the pool's scraper view at once)
  and ``terminationGracePeriodSeconds`` covers the drain window, so a
  pod delete runs the same SIGTERM choreography a local drain does.

Everything is emitted as plain YAML text by string templating —
stdlib only, nothing to install, and the output is a starting point
an operator audits rather than an abstraction they fight.
"""

from __future__ import annotations

import json


def build_spec(replicas: int, *, config: str = "model.json",
               image: str = "tpu-dist-nn:latest",
               grpc_base_port: int = 5101,
               metrics_base_port: int = 9101,
               router_port: int = 5100,
               router_metrics_port: int = 9100,
               drain_grace_seconds: float = 10.0,
               warm_rows: int = 64,
               autoscale: dict | None = None,
               hedge_after_p99_ratio: float | None = None,
               replica_name: str = "tdn-replica",
               router_name: str = "tdn-router") -> dict:
    """Normalize one fleet description; both emitters consume this.
    ``autoscale`` is ``{"min": .., "max": .., "target_occupancy": ..}``
    or None. Port layout: compose services each get the SAME ports
    (per-container netns); the k8s StatefulSet uses the base ports on
    every pod (per-pod DNS)."""
    if replicas < 1:
        raise ValueError(f"replicas must be >= 1, got {replicas}")
    if autoscale is not None:
        missing = {"min", "max"} - set(autoscale)
        if missing:
            raise ValueError(
                f"autoscale spec needs min/max, missing {sorted(missing)}"
            )
        # The same envelope Autoscaler enforces at construction: an
        # invalid manifest must fail HERE, not crash-loop the deployed
        # router container on every start.
        amin, amax = int(autoscale["min"]), int(autoscale["max"])
        if not 1 <= amin <= amax:
            raise ValueError(
                f"autoscale needs 1 <= min <= max, got {amin}..{amax}"
            )
        target = autoscale.get("target_occupancy")
        if target is not None and not 0.0 < float(target) <= 1.5:
            raise ValueError(
                f"autoscale target_occupancy must be in (0, 1.5], got "
                f"{target}"
            )
    return {
        "replicas": int(replicas),
        "config": config,
        "image": image,
        "grpc_port": int(grpc_base_port),
        "metrics_port": int(metrics_base_port),
        "router_port": int(router_port),
        "router_metrics_port": int(router_metrics_port),
        "drain_grace_seconds": float(drain_grace_seconds),
        "warm_rows": int(warm_rows),
        "autoscale": dict(autoscale) if autoscale else None,
        "hedge_after_p99_ratio": hedge_after_p99_ratio,
        "replica_name": replica_name,
        "router_name": router_name,
    }


def spec_from_snapshot(snapshot: list, **overrides) -> dict:
    """A spec sized from a RUNNING pool's ``/router/replicas``
    snapshot (``tdn fleet manifest --admin``): the replica count is
    the fleet's current non-removed membership, everything else comes
    from flags/defaults — the generated manifest reproduces the
    running fleet's shape, not its ephemeral local ports."""
    n = sum(1 for s in snapshot if s.get("state") != "removed")
    if n < 1:
        raise ValueError("running pool reports zero replicas")
    return build_spec(n, **overrides)


def _replica_command(spec: dict) -> list[str]:
    return [
        "tdn", "up", "--config", f"/model/{_config_name(spec)}",
        "--grpc-port", str(spec["grpc_port"]),
        "--metrics-port", str(spec["metrics_port"]),
        "--serve-warm-rows", str(spec["warm_rows"]),
        "--drain-grace-seconds", str(spec["drain_grace_seconds"]),
    ]


def _router_command(spec: dict, replica_hosts: list[str]) -> list[str]:
    cmd = [
        "tdn", "router",
        "--port", str(spec["router_port"]),
        "--metrics-port", str(spec["router_metrics_port"]),
        "--replicas",
        ",".join(f"{h}:{spec['grpc_port']}" for h in replica_hosts),
        "--replica-metrics",
        ",".join(f"{h}:{spec['metrics_port']}" for h in replica_hosts),
        "--drain-grace-seconds", str(spec["drain_grace_seconds"]),
    ]
    auto = spec["autoscale"]
    if auto:
        # The router's autoscaler actuates through pool.spawn_local —
        # LOCAL subprocesses. Under an external orchestrator the
        # replicas are containers/pods the pool cannot create, so the
        # emitted range is CLAMPED to the emitted fleet size: within
        # it, scale-down parks and scale-up un-parks (both work on a
        # static fleet); growth past the membership is the
        # orchestrator's job (compose --scale / kubectl scale / HPA),
        # and POST /router/scale?replicas=N remains the manual lever.
        # An unclamped max would just make the deployed router want
        # spawns it can never perform.
        amax = min(int(auto["max"]), spec["replicas"])
        amin = min(int(auto["min"]), amax)
        cmd += [
            "--autoscale-min", str(amin),
            "--autoscale-max", str(amax),
        ]
        if auto.get("target_occupancy") is not None:
            cmd += ["--autoscale-target-occupancy",
                    str(auto["target_occupancy"])]
    if spec["hedge_after_p99_ratio"] is not None:
        cmd += ["--hedge-after-p99-ratio",
                str(spec["hedge_after_p99_ratio"])]
    return cmd


def _config_name(spec: dict) -> str:
    return spec["config"].rstrip("/").rsplit("/", 1)[-1] or "model.json"


def _yaml_list(items: list[str]) -> str:
    """A flow-style YAML string list (json.dumps of each element is a
    valid YAML double-quoted scalar)."""
    return "[" + ", ".join(json.dumps(i) for i in items) + "]"


# ------------------------------------------------------- docker-compose


def compose_manifest(spec: dict) -> str:
    """One docker-compose document for the whole fleet. ``docker
    compose up -d`` brings it up; ``docker compose restart
    tdn-replica-0`` is a zero-downtime rolling restart of that replica
    (SIGTERM → its GracefulDrain → healthcheck flips → the router
    unplaces it → restart → ready → rejoin)."""
    stop_grace = int(spec["drain_grace_seconds"]) + 5
    hosts = [f"{spec['replica_name']}-{i}"
             for i in range(spec["replicas"])]
    out = [
        "# Generated by `tdn fleet manifest --format compose` "
        "(docs/SCALING.md).",
        "# The healthcheck speaks the same /healthz the router's "
        "scraper does;",
        "# stop_grace_period covers --drain-grace-seconds so a "
        "restart drains, never drops.",
        "services:",
    ]
    for host in hosts:
        out += [
            f"  {host}:",
            f"    image: {json.dumps(spec['image'])}",
            f"    command: {_yaml_list(_replica_command(spec))}",
            "    volumes:",
            f"      - ./{_config_name(spec)}:/model/"
            f"{_config_name(spec)}:ro",
            "    healthcheck:",
            "      test: [\"CMD-SHELL\", \"python -c \\\"import "
            "urllib.request,sys; "
            "sys.exit(0 if urllib.request.urlopen('http://127.0.0.1:"
            f"{spec['metrics_port']}/healthz', timeout=2).status==200 "
            "else 1)\\\"\"]",
            "      interval: 5s",
            "      timeout: 3s",
            "      retries: 3",
            f"    stop_grace_period: {stop_grace}s",
            "    restart: unless-stopped",
        ]
    out += [
        f"  {spec['router_name']}:",
        f"    image: {json.dumps(spec['image'])}",
        f"    command: {_yaml_list(_router_command(spec, hosts))}",
        "    ports:",
        f"      - \"{spec['router_port']}:{spec['router_port']}\"",
        f"      - \"{spec['router_metrics_port']}:"
        f"{spec['router_metrics_port']}\"",
        "    depends_on:",
    ]
    for host in hosts:
        out += [
            f"      {host}:",
            "        condition: service_healthy",
        ]
    out += [
        f"    stop_grace_period: {stop_grace}s",
        "    restart: unless-stopped",
    ]
    return "\n".join(out) + "\n"


# ---------------------------------------------------------------- k8s


def k8s_manifest(spec: dict) -> str:
    """A k8s multi-document manifest: headless Service + StatefulSet
    for the replicas (stable DNS so ``--replicas`` lists and session
    affinity survive pod churn), Deployment + Service for the router.
    The model JSON is expected in a ConfigMap named ``tdn-model``
    (``kubectl create configmap tdn-model --from-file=model.json``)."""
    name = spec["replica_name"]
    rname = spec["router_name"]
    grace = int(spec["drain_grace_seconds"]) + 5
    hosts = [f"{name}-{i}.{name}" for i in range(spec["replicas"])]
    replica_cmd = _yaml_list(_replica_command(spec))
    router_cmd = _yaml_list(_router_command(spec, hosts))
    return f"""# Generated by `tdn fleet manifest --format k8s` (docs/SCALING.md).
# Replica pods get stable DNS ({name}-0.{name} ...) via the headless
# Service, so the router's --replicas list and session affinity
# survive pod churn. readinessProbe speaks the same /healthz the
# router's scraper does: 503-while-draining unplaces the pod from the
# k8s Service and the pool view at once, and
# terminationGracePeriodSeconds covers the GracefulDrain window —
# `kubectl rollout restart statefulset/{name}` IS the zero-downtime
# rolling restart.
apiVersion: v1
kind: Service
metadata:
  name: {name}
spec:
  clusterIP: None
  selector:
    app: {name}
  ports:
    - name: grpc
      port: {spec['grpc_port']}
    - name: metrics
      port: {spec['metrics_port']}
---
apiVersion: apps/v1
kind: StatefulSet
metadata:
  name: {name}
spec:
  serviceName: {name}
  replicas: {spec['replicas']}
  selector:
    matchLabels:
      app: {name}
  template:
    metadata:
      labels:
        app: {name}
    spec:
      terminationGracePeriodSeconds: {grace}
      containers:
        - name: engine
          image: {json.dumps(spec['image'])}
          command: {replica_cmd}
          ports:
            - containerPort: {spec['grpc_port']}
              name: grpc
            - containerPort: {spec['metrics_port']}
              name: metrics
          readinessProbe:
            httpGet:
              path: /healthz
              port: {spec['metrics_port']}
            periodSeconds: 5
            timeoutSeconds: 3
          volumeMounts:
            - name: model
              mountPath: /model
              readOnly: true
      volumes:
        - name: model
          configMap:
            name: tdn-model
---
apiVersion: apps/v1
kind: Deployment
metadata:
  name: {rname}
spec:
  replicas: 1
  selector:
    matchLabels:
      app: {rname}
  template:
    metadata:
      labels:
        app: {rname}
    spec:
      terminationGracePeriodSeconds: {grace}
      containers:
        - name: router
          image: {json.dumps(spec['image'])}
          command: {router_cmd}
          ports:
            - containerPort: {spec['router_port']}
              name: grpc
            - containerPort: {spec['router_metrics_port']}
              name: metrics
          readinessProbe:
            httpGet:
              path: /healthz
              port: {spec['router_metrics_port']}
            periodSeconds: 5
            timeoutSeconds: 3
---
apiVersion: v1
kind: Service
metadata:
  name: {rname}
spec:
  selector:
    app: {rname}
  ports:
    - name: grpc
      port: {spec['router_port']}
    - name: metrics
      port: {spec['router_metrics_port']}
"""
