"""Replica pool for the multi-replica data plane (docs/SCALING.md).

The reference's orchestrator spawns N nodes and chains them into ONE
linear pipeline (``run_grpc_fcnn.py``); PRs 1-7 made a single engine
process fast, resilient, and observable, and this module is the lift
from "one pipeline" to "a fleet": a :class:`ReplicaPool` manages N
backend engine endpoints for the gRPC front door
(:mod:`tpu_dist_nn.serving.router`), owning the three things a router
must know about a replica —

* **Load.** Power-of-two-choices (Mitzenmacher 2001) needs a load
  signal: the pool scrapes each replica's existing
  ``tdn_batcher_pending_rows`` / ``tdn_gen_slot_occupancy_ratio``
  gauges from its ``--metrics-port`` endpoint on an interval, and
  blends them with the router's own live outstanding-request count.
  Gauge data is STALENESS-BOUNDED: past ``load_staleness`` seconds the
  score degrades to least-outstanding-requests (the signal the router
  can always trust because it produced it).
* **Health.** Each replica reuses the per-target
  :class:`~tpu_dist_nn.serving.resilience.CircuitBreaker`
  (``for_target``) the client stack already speaks — the router
  records outcomes, the pool stops placing onto open breakers and
  lets the post-cooldown probe through. ``remove()`` / respawn call
  ``CircuitBreaker.evict`` so a NEW server on a reused address never
  inherits its predecessor's open breaker (the registry is
  process-global and was never pruned before this).
* **Membership + drain.** ``drain()`` marks a replica not-placeable
  and (for pool-spawned local replicas) SIGTERMs it so its own
  :class:`~tpu_dist_nn.serving.resilience.GracefulDrain` runs the
  zero-downtime sequence — ``/healthz`` flips ``draining: true``, the
  pool's scraper observes it, in-flight work finishes, the process
  exits and is respawned on the SAME address, and the scraper
  re-admits it the moment ``/healthz`` reports ready again. Remote
  replicas follow the identical choreography with the operator (or
  their init system) doing the SIGTERM/restart.

Session affinity: ``place(session_key=...)`` pins a session to the
replica that served it last (the replica holding its KV/prefix-cache
state — Orca-style continuous batching makes that state valuable),
re-pinning only when the pinned replica stops being placeable. A
session's FIRST placement uses p2c when any load data exists, else
rendezvous (highest-random-weight) hashing so a cold pool still
spreads sessions consistently.

Everything here is stdlib + the in-repo obs/resilience modules; the
scraper uses ``urllib`` against the same ``/metrics`` + ``/healthz``
endpoints operators already curl.
"""

from __future__ import annotations

import collections
import concurrent.futures
import hashlib
import json
import logging
import random
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import grpc

from tpu_dist_nn.obs.log import get_logger
from tpu_dist_nn.obs.registry import REGISTRY
from tpu_dist_nn.serving.resilience import CircuitBreaker
from tpu_dist_nn.serving.wire import SERVICE_NAME

log = logging.getLogger(__name__)
slog = get_logger(__name__)

# 1 while the pool will place new requests on this replica (ACTIVE and
# last health scrape did not say draining), 0 otherwise — the
# per-replica availability view of the fleet (docs/OBSERVABILITY.md).
REPLICA_HEALTHY = REGISTRY.gauge(
    "tdn_router_replica_healthy",
    "1 while the router pool will place new requests on this replica "
    "(0 = draining, removed, or breaker-open)",
    labels=("replica",),
)

# 1 while a replica sits in integrity quarantine (docs/ROBUSTNESS.md
# "Silent corruption & quarantine") — distinct from healthy=0, which a
# drain also produces: quarantined means "answered WRONG", not "away".
REPLICA_QUARANTINED = REGISTRY.gauge(
    "tdn_router_replica_quarantined",
    "1 while the replica is quarantined by the integrity plane "
    "(canary/spot-check/guard/fingerprint verdict)",
    labels=("replica",),
)

QUARANTINES = REGISTRY.counter(
    "tdn_quarantines_total",
    "replicas moved to QUARANTINED by the integrity plane, by detector",
    labels=("reason",),
)

ACTIVE, DRAINING, REMOVED = "active", "draining", "removed"
# Integrity quarantine: the replica answered WRONG (canary mismatch,
# spot-check arbitration, repeated INTEGRITY errors, or a weights
# fingerprint disagreeing with the fleet). Not placeable, and —
# unlike DRAINING — never auto-rejoined by a mere ready scrape, and
# unlike an open breaker never half-open-probed back in: re-admission
# requires the fingerprint AND canary checks to pass (unquarantine).
QUARANTINED = "quarantined"


def _sum_series(parsed: dict, family: str) -> float | None:
    """Sum every labeled series of ``family`` in a parsed /metrics
    scrape (None when the family is absent — a replica that never
    served keeps 'no data' distinct from 'zero load')."""
    total, seen = 0.0, False
    for k, v in parsed.items():
        if k == family or (isinstance(k, str) and k.startswith(family + "{")):
            total += float(v)
            seen = True
    return total if seen else None


class Replica:
    """One backend endpoint: gRPC target, optional metrics endpoint,
    breaker, live load view, and (for pool-spawned replicas) the
    subprocess handle."""

    def __init__(self, target: str, metrics_target: str | None = None,
                 weight: float | None = None):
        self.target = target
        self.metrics_target = metrics_target
        self.state = ACTIVE
        self.breaker = CircuitBreaker.for_target(target)
        # Explicit capacity weight (--replica-weights): scales the p2c
        # load score so a replica that can absorb k x the rows of a
        # baseline one compares as 1/k as loaded at equal backlog —
        # heterogeneous fleets (TPU replica + CPU spillover) mix
        # without starving the fast one. None = derive from the
        # scraped warm-bucket ladder, else 1.0 (homogeneous).
        self.weight = float(weight) if weight is not None else None
        # Last scraped tdn_engine_warm_buckets value: the implicit
        # capacity signal when no explicit weight was configured (a
        # replica with a deeper precompiled bucket ladder is
        # provisioned for more concurrent rows).
        self.warm_buckets: float | None = None
        # Scale-down in progress (serving/autoscale.py): the replica is
        # draining toward REMOVAL, so the supervisor must not respawn
        # its exited child and the ready-scrape must not re-admit it.
        self.decommissioning = False
        # Requests this router currently has in flight on the replica —
        # the always-available load signal (and the drain barrier).
        self.outstanding = 0
        # Last scraped gauge view (None until a successful scrape).
        self.pending_rows: float | None = None
        self.occupancy: float | None = None
        self.scraped_at: float | None = None
        # /healthz said draining: the replica is mid-rolling-restart.
        self.reported_draining = False
        # The drain was OBSERVED (healthz said draining, or the replica
        # went unreachable while DRAINING): the gate for auto-rejoin. A
        # ready scrape alone must NOT undrain an admin-drained replica
        # that never began restarting — that would revert the
        # operator's `--drain-replica` within one scrape tick.
        self.drain_observed = False
        # Consecutive scrape ticks with /healthz unreachable. One blown
        # probe (GC pause, host load, transient timeout) on a DRAINING
        # replica is indistinguishable from "old process exited
        # mid-restart" — only repeated loss counts as drain observation.
        self.unreachable_ticks = 0
        # Last boot_id /healthz reported (None until one is seen). A
        # DRAINING replica answering ready with a DIFFERENT boot_id was
        # restarted — even when the whole restart fell between two
        # scrape ticks and neither timing detector could see it.
        self.boot_id: str | None = None
        # Integrity plane (serving/integrity.py). fingerprint is the
        # whole-model weights fingerprint /healthz last reported;
        # quarantine_boot_id records which process incarnation was
        # indicted, so only a RESPAWNED replica (different boot_id) is
        # eligible for automatic reverify-readmission.
        self.fingerprint: str | None = None
        self.canary_at: float = 0.0
        self.quarantine_reason: str | None = None
        self.quarantine_evidence: dict | None = None
        self.quarantine_boot_id: str | None = None
        self.quarantined_at: float | None = None
        # Cumulative INTEGRITY (DATA_LOSS) errors the router observed
        # from this replica — the numeric-guard verdict counter.
        self.integrity_strikes = 0
        # Pool-spawned local replica bookkeeping (tdn router --spawn).
        self.proc: subprocess.Popen | None = None
        self.spawn_argv: list[str] | None = None
        # A respawn is in flight (scraper auto-respawn or an explicit
        # restart_replica) — the other path must not double-spawn.
        self.respawning = False
        # Minimum spacing between auto-respawn attempts: claimed at
        # the START of every attempt, so neither a spawn that fails
        # outright NOR a child that boots, reports ports, then crashes
        # can turn the scrape loop into a hot spawn loop (each cycle
        # burns an engine compile/warmup).
        self.respawn_backoff_until = 0.0
        # (The mutable fields above are guarded by the POOL's lock —
        # cross-object guarding the lock-discipline rule cannot
        # express; only the channel state below is this object's own.)
        self._channel = None  # guarded-by: _lock
        self._stubs: dict[str, object] = {}  # guarded-by: _lock
        self._stream_stubs: dict[str, object] = {}  # guarded-by: _lock
        self._lock = threading.Lock()

    # ------------------------------------------------------------ wire

    def _stub(self, method: str):
        with self._lock:
            if self._channel is None:
                self._channel = grpc.insecure_channel(
                    self.target,
                    options=[
                        ("grpc.max_send_message_length", -1),
                        ("grpc.max_receive_message_length", -1),
                    ],
                )
            stub = self._stubs.get(method)
            if stub is None:
                stub = self._channel.unary_unary(
                    f"/{SERVICE_NAME}/{method}",
                    request_serializer=bytes,
                    response_deserializer=bytes,
                )
                self._stubs[method] = stub
        return stub

    def call(self, method: str, payload: bytes, *, timeout=None,
             metadata=()):
        """Forward raw request bytes to this replica (one persistent
        channel per replica, stubs cached per method)."""
        return self._stub(method)(payload, timeout=timeout,
                                  metadata=tuple(metadata))

    def call_future(self, method: str, payload: bytes, *, timeout=None,
                    metadata=()):
        """The non-blocking twin of :meth:`call`: returns the grpc
        future so the router's hedging path can race two replicas and
        ``cancel()`` the loser (a blocking call cannot be abandoned
        without leaking its worker thread for the full timeout)."""
        return self._stub(method).future(payload, timeout=timeout,
                                         metadata=tuple(metadata))

    def call_stream(self, method: str, payload: bytes, *, timeout=None,
                    metadata=()):
        """Server-streaming forward (GenerateStream): returns the grpc
        response iterator over raw frame bytes — the router relays them
        without decoding (serving/wire.py owns the frame format)."""
        with self._lock:
            if self._channel is None:
                self._channel = grpc.insecure_channel(
                    self.target,
                    options=[
                        ("grpc.max_send_message_length", -1),
                        ("grpc.max_receive_message_length", -1),
                    ],
                )
            stub = self._stream_stubs.get(method)
            if stub is None:
                stub = self._channel.unary_stream(
                    f"/{SERVICE_NAME}/{method}",
                    request_serializer=bytes,
                    response_deserializer=bytes,
                )
                self._stream_stubs[method] = stub
        return stub(payload, timeout=timeout, metadata=tuple(metadata))

    def close_channel(self) -> None:
        with self._lock:
            if self._channel is not None:
                self._channel.close()
            self._channel = None
            self._stubs = {}
            self._stream_stubs = {}

    # ------------------------------------------------------------ load

    def fresh(self, now: float, staleness: float) -> bool:
        return (
            self.scraped_at is not None
            and now - self.scraped_at <= staleness
            and self.pending_rows is not None
        )

    @property
    def capacity_weight(self) -> float:
        """Relative capacity for weighted p2c: the explicit
        ``--replica-weights`` value when configured, else the scraped
        warm-bucket ladder depth (a replica precompiled for more
        buckets is provisioned for more concurrent rows), else 1.0."""
        if self.weight is not None:
            return max(self.weight, 1e-6)
        if self.warm_buckets is not None and self.warm_buckets >= 1.0:
            return float(self.warm_buckets)
        return 1.0

    def load_score(self, now: float, staleness: float,
                   occupancy_weight: float) -> float:
        """The p2c comparison key: the router's own outstanding count,
        plus the scraped backlog while it is fresh. ``occupancy_weight``
        converts the slot-occupancy RATIO into row-equivalents (one
        full continuous-decode ladder ~ a gen_slots-sized backlog).
        The blend is divided by :attr:`capacity_weight`, so a 4x
        replica at backlog 8 ties a 1x replica at backlog 2 instead of
        losing every comparison the moment it absorbs its fair share."""
        score = float(self.outstanding)
        if self.fresh(now, staleness):
            score += float(self.pending_rows or 0.0)
            score += occupancy_weight * float(self.occupancy or 0.0)
        return score / self.capacity_weight

    def snapshot(self) -> dict:
        snap = {
            "target": self.target,
            "metrics_target": self.metrics_target,
            "state": self.state,
            "outstanding": self.outstanding,
            "pending_rows": self.pending_rows,
            "occupancy": self.occupancy,
            "breaker": self.breaker.state,
            "draining_reported": self.reported_draining,
            "spawned": self.proc is not None,
            "weight": self.capacity_weight,
            "decommissioning": self.decommissioning,
        }
        if self.fingerprint is not None:
            snap["fingerprint"] = self.fingerprint
        if self.state == QUARANTINED:
            snap["quarantine_reason"] = self.quarantine_reason
            snap["quarantined_at"] = self.quarantined_at
        if self.integrity_strikes:
            snap["integrity_strikes"] = self.integrity_strikes
        return snap


class ReplicaPool:
    """N engine replicas + the placement policy over them.

    ``place()`` implements power-of-two-choices over
    :meth:`Replica.load_score` (two uniform candidates, route to the
    less loaded — the classic exponential improvement over random
    placement without the herding of always-least-loaded), with:

    * session affinity — a ``session_key`` that placed before goes
      back to the same replica while it remains placeable;
    * a rendezvous-hash fallback for session FIRST placements when no
      replica has any load data (cold pool, no metrics endpoints);
    * breaker gating — open-breaker replicas are skipped until their
      cooldown, then exactly one request probes them.

    Thread-safe; the scrape loop (``start()``) refreshes load and
    health on ``scrape_interval``. Tests drive ``scrape_once()``
    directly.
    """

    def __init__(self, targets=(), metrics_targets=None, weights=None, *,
                 load_staleness: float = 5.0,
                 occupancy_weight: float = 32.0,
                 scrape_interval: float = 1.0,
                 scrape_timeout: float = 1.0,
                 session_capacity: int = 8192,
                 seed: int | None = None):
        self._lock = threading.RLock()
        self._replicas: dict[str, Replica] = {}  # guarded-by: _lock
        # guarded-by: _lock
        self._sessions: collections.OrderedDict[str, str] = (
            collections.OrderedDict()
        )
        self._session_capacity = int(session_capacity)
        self.load_staleness = float(load_staleness)
        self.occupancy_weight = float(occupancy_weight)
        self.scrape_interval = float(scrape_interval)
        self.scrape_timeout = float(scrape_timeout)
        self._rng = random.Random(seed)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # Cumulative membership/drain state transitions (drain, undrain,
        # remove, crash-respawn, scrape-observed drains): the flight
        # recorder's drain/failover detector fires on the DELTA, so the
        # choreography itself is an incident trigger without the
        # detector having to diff per-replica states.
        self.transitions_total = 0  # guarded-by: _lock
        # Integrity plane (serving/integrity.py). canary: a
        # CanaryProber ridden on the scrape loop (None = probing off).
        # on_quarantine(target, reason, evidence): the incident hook —
        # serve_router wires it to the flight recorder so every verdict
        # freezes a bundle naming the evidence. fleet_fingerprint: the
        # golden whole-model weights fingerprint, established from the
        # first ACTIVE ready replica that reports one; any replica
        # reporting a DIFFERENT fingerprint is refused admission
        # (quarantined) while fingerprint_gate is on.
        self.canary = None
        self.on_quarantine = None
        self.fleet_fingerprint: str | None = None  # guarded-by: _lock
        self.fingerprint_gate = True
        # INTEGRITY (DATA_LOSS) replies from one replica before the
        # router's guard verdict quarantines it. 3, not 1: one launch
        # can fail rows for a transiently absurd input; a replica that
        # keeps producing non-finite activations is corrupt.
        self.guard_quarantine_threshold = 3
        self._scrape_pool: concurrent.futures.ThreadPoolExecutor | None \
            = None
        metrics_targets = list(metrics_targets or ())
        weights = list(weights or ())
        for i, t in enumerate(targets):
            self.add(t, metrics_targets[i] if i < len(metrics_targets)
                     else None,
                     weight=weights[i] if i < len(weights) else None)

    # ------------------------------------------------------ membership

    def add(self, target: str, metrics_target: str | None = None, *,
            weight: float | None = None) -> Replica:
        with self._lock:
            existing = self._replicas.get(target)
            if existing is not None and existing.state != REMOVED:
                if metrics_target is not None:
                    existing.metrics_target = metrics_target
                if weight is not None:
                    existing.weight = float(weight)
                return existing
            rep = Replica(target, metrics_target, weight)
            self._replicas[target] = rep
            REPLICA_HEALTHY.labels(replica=target).set(1.0)
            slog.info("router.replica_added", replica=target,
                      metrics_target=metrics_target)
            return rep

    def remove(self, target: str) -> None:
        """Take a replica out of the pool for good: stop placing, drop
        its channel AND its process-global breaker registration — a
        future server on the reused address must start with a closed
        breaker, not the dead incumbent's open one."""
        with self._lock:
            rep = self._replicas.pop(target, None)
            if rep is None:
                return
            rep.state = REMOVED
            self.transitions_total += 1
            # Unpin every session that pointed here; their next request
            # re-places (their KV state died with the replica anyway).
            for k in [k for k, v in self._sessions.items() if v == target]:
                del self._sessions[k]
            # Retire the series, don't pin it at 0: a replica that left
            # the pool for good has no health to report, and membership
            # churn must not grow the label set unboundedly.
            _retire_replica_series(target)
        rep.close_channel()
        CircuitBreaker.evict(target)
        # A pool-spawned child is OWNED by the pool: removal must not
        # leave the live engine serving on its ports forever — and once
        # the entry is popped, close()'s sweep can no longer reach it.
        if rep.proc is not None:
            _terminate_child(rep.proc)
        slog.info("router.replica_removed", replica=target)

    def drain(self, target: str, *, signal_process: bool = True) -> bool:
        """Begin the rolling-restart drain of one replica: stop placing
        new requests on it; for a pool-spawned replica also SIGTERM the
        process so its own GracefulDrain finishes in-flight work and
        exits. Returns False for an unknown target. The scrape loop
        re-admits the replica (fresh breaker) once its /healthz reports
        ready again — restart → rejoin needs no second command."""
        with self._lock:
            rep = self._replicas.get(target)
            if rep is None or rep.state == REMOVED:
                return False
            if rep.state == QUARANTINED:
                # Quarantine dominates: a drain would re-route the
                # replica onto the ready-scrape auto-rejoin path,
                # bypassing the fingerprint + canary reverify that
                # quarantine exists to enforce.
                return False
            if rep.state != DRAINING:
                self.transitions_total += 1
            rep.state = DRAINING
            REPLICA_HEALTHY.labels(replica=target).set(0.0)
        if signal_process and rep.proc is not None \
                and rep.proc.poll() is None:
            rep.proc.terminate()
        slog.info("router.replica_draining", replica=target,
                  spawned=rep.proc is not None)
        return True

    def undrain(self, target: str) -> bool:
        """Re-admit a drained replica (the restarted server on the
        reused address): evict the old breaker so the first requests
        are not fail-fasted by stale history. No-op (False) unless the
        replica is actually DRAINING — undrain on an ACTIVE replica
        would silently wipe a live breaker's state and load view (a
        hard-down replica the breaker correctly opened on would
        re-enter rotation off a typo'd admin call)."""
        with self._lock:
            rep = self._replicas.get(target)
            if rep is None or rep.state != DRAINING:
                return False
            rep.state = ACTIVE
            self.transitions_total += 1
            rep.reported_draining = False
            rep.drain_observed = False
            # An operator undrain cancels an autoscaler scale-down in
            # flight: the replica is back in service, not on its way
            # out (the autoscaler's next tick re-decides from signals).
            rep.decommissioning = False
            # Reused address: the OLD server's failure history must not
            # greet the new one.
            CircuitBreaker.evict(target)
            rep.breaker = CircuitBreaker.for_target(target)
            rep.scraped_at = None  # stale gauges are the old server's
            REPLICA_HEALTHY.labels(replica=target).set(1.0)
        slog.info("router.replica_undrained", replica=target)
        return True

    # ------------------------------------------------------ quarantine

    def quarantine(self, target: str, *, reason: str,
                   evidence: dict | None = None) -> bool:
        """Move a replica to QUARANTINED on an integrity verdict: stop
        placement, sever its channel so in-flight forwards fail over
        NOW (its in-flight answers are as suspect as its future ones),
        unpin its sessions, fire the incident hook with the evidence,
        and — for a pool-spawned child — SIGTERM it so the supervisor
        respawns a fresh process for reverify-readmission.

        Deliberately NOT the drain path: a drained replica auto-rejoins
        on the next ready scrape, and a breaker-opened one half-open
        probes back in. A wrong replica answers ready and serves probes
        perfectly — it re-enters only through :meth:`unquarantine`'s
        fingerprint + canary checks. Returns False for unknown/removed
        targets and no-ops (False) when already quarantined."""
        with self._lock:
            rep = self._replicas.get(target)
            if rep is None or rep.state in (REMOVED, QUARANTINED):
                return False
            rep.state = QUARANTINED
            self.transitions_total += 1
            rep.quarantine_reason = reason
            rep.quarantine_evidence = dict(evidence or {})
            rep.quarantine_boot_id = rep.boot_id
            rep.quarantined_at = time.monotonic()
            REPLICA_HEALTHY.labels(replica=target).set(0.0)
            REPLICA_QUARANTINED.labels(replica=target).set(1.0)
            QUARANTINES.labels(reason=reason).inc()
            # Unpin every session here: their next request re-places
            # (affinity to a corrupt replica is affinity to wrong
            # answers, and its KV state cannot be trusted either).
            for k in [k for k, v in self._sessions.items() if v == target]:
                del self._sessions[k]
        # Outside the lock: sever the channel so the router's in-flight
        # forwards fail immediately and ride the normal failover loop
        # to a healthy replica (clean in-flight failover, no waiting
        # for suspect answers to finish).
        rep.close_channel()
        hook = self.on_quarantine
        if hook is not None:
            try:
                hook(target, reason, dict(evidence or {}))
            except Exception:  # noqa: BLE001 — evidence capture is best-effort
                log.exception("on_quarantine hook failed for %s", target)
        if rep.proc is not None and rep.proc.poll() is None:
            # Respawn-with-reverify for spawned replicas: the exit
            # routes through _maybe_respawn (which preserves the
            # QUARANTINED state), and the fresh process re-admits only
            # via unquarantine's checks.
            rep.proc.terminate()
        slog.warning("router.replica_quarantined", replica=target,
                     reason=reason,
                     spawned=rep.proc is not None)
        return True

    def unquarantine(self, target: str, *, force: bool = False) -> dict:
        """Re-admission with reverify: the replica re-enters rotation
        only if its /healthz weights fingerprint agrees with the
        fleet's AND a fresh canary probe answers on-golden (each check
        skipped when unconfigured; ``force=True`` skips both — the
        operator's break-glass). Returns a structured result with the
        individual check outcomes; ``{"ok": True}`` means re-admitted."""
        with self._lock:
            rep = self._replicas.get(target)
            if rep is None or rep.state != QUARANTINED:
                return {"ok": False, "error": "not quarantined",
                        "target": target}
            golden = self.fleet_fingerprint
        checks: dict = {}
        if not force:
            if golden is not None and rep.fingerprint is not None \
                    and rep.fingerprint != golden:
                checks["fingerprint"] = {
                    "ok": False, "fingerprint": rep.fingerprint,
                    "fleet": golden,
                }
                return {"ok": False, "target": target, "checks": checks}
            if golden is not None and rep.fingerprint is not None:
                checks["fingerprint"] = {"ok": True}
            if self.canary is not None:
                verdict, ev = self.canary.probe(rep)
                checks["canary"] = {"ok": bool(verdict), **(
                    {} if verdict else {"evidence": ev}
                )}
                if not verdict:
                    # None (unreachable) also refuses: re-admitting a
                    # replica the prober cannot even reach proves
                    # nothing about its answers.
                    return {"ok": False, "target": target,
                            "checks": checks}
        with self._lock:
            rep = self._replicas.get(target)
            if rep is None or rep.state != QUARANTINED:
                return {"ok": False, "error": "not quarantined",
                        "target": target}
            rep.state = ACTIVE
            self.transitions_total += 1
            rep.quarantine_reason = None
            rep.quarantine_evidence = None
            rep.quarantine_boot_id = None
            rep.quarantined_at = None
            rep.integrity_strikes = 0
            rep.reported_draining = False
            rep.drain_observed = False
            # The quarantined incumbent's failure history must not
            # greet the re-verified (usually respawned) server.
            CircuitBreaker.evict(target)
            rep.breaker = CircuitBreaker.for_target(target)
            rep.scraped_at = None
            REPLICA_HEALTHY.labels(replica=target).set(1.0)
            REPLICA_QUARANTINED.labels(replica=target).set(0.0)
        slog.info("router.replica_unquarantined", replica=target,
                  forced=force, checks=list(checks) or None)
        return {"ok": True, "target": target, "checks": checks,
                "forced": force}

    def note_integrity_error(self, target: str) -> None:
        """Record one INTEGRITY (DATA_LOSS) reply the router observed
        from a replica — the numeric-guard verdict path. At
        ``guard_quarantine_threshold`` strikes the replica is
        quarantined (a healthy replica's guard essentially never
        fires; repeated firing means corrupt weights or a bad core)."""
        with self._lock:
            rep = self._replicas.get(target)
            if rep is None or rep.state != ACTIVE:
                return
            rep.integrity_strikes += 1
            strikes = rep.integrity_strikes
        if strikes >= self.guard_quarantine_threshold:
            self.quarantine(
                target, reason="guard",
                evidence={"integrity_errors": strikes,
                          "threshold": self.guard_quarantine_threshold},
            )

    def decommission(self, target: str) -> bool:
        """Begin a SCALE-DOWN drain (serving/autoscale.py): like
        :meth:`drain`, but toward permanent removal — the supervisor
        will not respawn a pool-spawned child's exit, and the ready
        scrape will not re-admit the replica. The caller removes it
        once :meth:`drained_for_removal` says the drain was observed
        (zero dropped in-flight requests is the whole point of going
        through the choreography instead of calling remove() cold)."""
        with self._lock:
            rep = self._replicas.get(target)
            if rep is None or rep.state == REMOVED:
                return False
            rep.decommissioning = True
        return self.drain(target)

    def drained_for_removal(self, target: str) -> bool:
        """True once a decommissioning replica can be removed with
        nothing in flight: the router holds zero outstanding forwards
        on it and — for a pool-spawned child — the process has exited
        (its own GracefulDrain finished). Unknown target = already
        gone = removable."""
        with self._lock:
            rep = self._replicas.get(target)
            if rep is None or rep.state == REMOVED:
                return True
            if rep.outstanding > 0:
                return False
            return rep.proc is None or rep.proc.poll() is not None

    def wait_drained(self, target: str, timeout: float = 30.0) -> bool:
        """Block until the router has zero outstanding requests on a
        draining replica (the point it is safe to restart)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                rep = self._replicas.get(target)
                if rep is None or rep.outstanding == 0:
                    return True
            time.sleep(0.005)
        return False

    def replicas(self) -> list[Replica]:
        with self._lock:
            return list(self._replicas.values())

    def targets(self) -> list[str]:
        with self._lock:
            return list(self._replicas)

    def snapshot(self) -> list[dict]:
        with self._lock:
            return [r.snapshot() for r in self._replicas.values()]

    # ------------------------------------------------------- placement

    def begin(self, rep: Replica) -> None:
        with self._lock:
            rep.outstanding += 1

    def done(self, rep: Replica) -> None:
        with self._lock:
            rep.outstanding = max(0, rep.outstanding - 1)

    def pin(self, session_key: str, target: str) -> None:
        with self._lock:
            self._sessions[session_key] = target
            self._sessions.move_to_end(session_key)
            while len(self._sessions) > self._session_capacity:
                self._sessions.popitem(last=False)

    def pinned(self, session_key: str) -> str | None:
        with self._lock:
            return self._sessions.get(session_key)

    @staticmethod
    def _rendezvous(session_key: str, cands: list[Replica]) -> Replica:
        """Highest-random-weight hash: stable per (session, target), so
        membership changes only move the sessions that must move."""
        return max(
            cands,
            key=lambda r: hashlib.sha1(
                f"{session_key}|{r.target}".encode()
            ).digest(),
        )

    def place(self, session_key: str | None = None,
              exclude=frozenset()) -> Replica | None:
        """Pick the replica for one request (None = nothing placeable).

        Order of precedence: a still-placeable session pin; a replica
        whose open breaker is due its half-open probe (exactly one
        request per cooldown rides this); p2c over the blended load
        score; rendezvous hashing for a session's first placement on a
        pool with no load data at all.
        """
        with self._lock:
            now = time.monotonic()
            cands = [
                r for r in self._replicas.values()
                if r.state == ACTIVE and r.target not in exclude
            ]
            if not cands:
                return None
            if session_key is not None:
                t = self._sessions.get(session_key)
                if t is not None:
                    rep = self._replicas.get(t)
                    if (rep is not None and rep.state == ACTIVE
                            and t not in exclude
                            and rep.breaker.state == CircuitBreaker.CLOSED):
                        self._sessions.move_to_end(session_key)
                        return rep
            closed = [
                r for r in cands
                if r.breaker.state == CircuitBreaker.CLOSED
            ]
            if len(closed) < len(cands):
                # A non-closed breaker that allows a call right now is
                # the due half-open probe — route THIS request to it
                # (its outcome closes or re-opens the breaker).
                for r in cands:
                    if (r.breaker.state != CircuitBreaker.CLOSED
                            and r.breaker.allow()):
                        return r
            if not closed:
                return None
            if len(closed) == 1:
                return closed[0]
            if session_key is not None and not any(
                r.fresh(now, self.load_staleness) for r in closed
            ) and all(r.outstanding == 0 for r in closed):
                # Cold pool, no load signal of any kind: spread session
                # first-placements consistently instead of randomly.
                return self._rendezvous(session_key, closed)
            a, b = self._rng.sample(closed, 2)
            sa = a.load_score(now, self.load_staleness,
                              self.occupancy_weight)
            sb = b.load_score(now, self.load_staleness,
                              self.occupancy_weight)
            return a if sa <= sb else b

    # --------------------------------------------------------- scrape

    def _scrape_one(self, rep: Replica) -> None:
        """Refresh one replica's gauge load + health view (no pool lock
        held during HTTP). Failures leave the last view to age out
        through the staleness bound."""
        from tpu_dist_nn.obs.exposition import parse_prometheus_text

        base = rep.metrics_target
        if base is None:
            return
        if "://" not in base:
            base = f"http://{base}"
        base = base.rstrip("/")
        pending = occupancy = warm = None
        metrics_ok = False
        try:
            with urllib.request.urlopen(
                base + "/metrics", timeout=self.scrape_timeout
            ) as resp:
                parsed = parse_prometheus_text(resp.read().decode())
            pending = _sum_series(parsed, "tdn_batcher_pending_rows")
            occupancy = _sum_series(parsed, "tdn_gen_slot_occupancy_ratio")
            warm = _sum_series(parsed, "tdn_engine_warm_buckets")
            metrics_ok = True
        except (urllib.error.URLError, OSError, ValueError):
            # Stale view ages out; the breaker covers hard-down. NOT a
            # drain-observation signal by itself: one blown fetch (GC
            # pause, garbled body) on an admin-drained STATIC replica
            # must not read as "the process exited" — the very next
            # ready scrape would then auto-undrain the replica the
            # operator just drained. /healthz below is the arbiter.
            pass
        draining = None
        ready = None
        boot_id = None
        fingerprint = None
        reachable = False
        try:
            req = urllib.request.urlopen(
                base + "/healthz", timeout=self.scrape_timeout
            )
            with req as resp:
                body = resp.read()
            reachable = True
            try:
                # json.loads takes the raw bytes: a non-UTF-8 body
                # raises UnicodeDecodeError, a ValueError subclass —
                # decoding OUTSIDE this try let a binary proxy error
                # page crash the whole scrape tick.
                health = json.loads(body)
                ready = bool(health.get("ready"))
                draining = bool(health.get("draining"))
                boot_id = health.get("boot_id")
                fingerprint = health.get("fingerprint")
            except (ValueError, AttributeError):
                # 200 with a garbled or non-dict body (proxy error
                # page, misconfigured port): something answered, so
                # this is neither a drain observation nor a rejoin
                # signal — health stays unknown for this tick.
                pass
        except urllib.error.HTTPError as e:
            # 503 carries the health JSON (not-ready / draining).
            reachable = True
            try:
                health = json.loads(e.read().decode())
                ready = bool(health.get("ready"))
                draining = bool(health.get("draining"))
                boot_id = health.get("boot_id")
                fingerprint = health.get("fingerprint")
            except (ValueError, AttributeError, OSError):
                pass
        except (urllib.error.URLError, OSError):
            pass
        with self._lock:
            if rep.state == REMOVED:
                return
            if metrics_ok:
                rep.pending_rows = pending
                rep.occupancy = occupancy
                if warm is not None:
                    # Capacity signal for weighted p2c: sticky (not
                    # aged by staleness) — a ladder already compiled
                    # does not un-compile when a scrape is missed.
                    rep.warm_buckets = warm
                rep.scraped_at = time.monotonic()
            if not reachable:
                # The health endpoint itself is gone: for a DRAINING
                # replica that IS the drain being observed (the old
                # process exited mid-rolling-restart) — record it so
                # the restarted server's ready scrape rejoins. Gated on
                # TWO consecutive lost ticks: a single blown probe on a
                # still-running admin-drained replica must not read as
                # "the process exited", or the next ready scrape would
                # undo the operator's --drain-replica. (A real restart
                # is observed via draining:true first anyway; this path
                # only covers an exit that fell between ticks.)
                rep.unreachable_ticks += 1
                if rep.state == DRAINING and rep.unreachable_ticks >= 2:
                    rep.drain_observed = True
                return
            rep.unreachable_ticks = 0
            if boot_id is not None:
                if (rep.state == DRAINING and rep.boot_id is not None
                        and boot_id != rep.boot_id):
                    # A DIFFERENT process answers on the address: the
                    # restart fell entirely between two ticks (downtime
                    # AND draining window each shorter than one scrape
                    # interval), so neither timing detector could see
                    # it — but the identity change IS the drain having
                    # completed.
                    rep.drain_observed = True
                rep.boot_id = boot_id
            if draining is not None:
                rep.reported_draining = draining
            if draining:
                rep.drain_observed = True
            if draining and rep.state == ACTIVE:
                # The replica began its own drain (operator SIGTERM):
                # stop placing — the other half of the choreography.
                rep.state = DRAINING
                self.transitions_total += 1
                REPLICA_HEALTHY.labels(replica=rep.target).set(0.0)
                slog.info("router.replica_draining", replica=rep.target,
                          source="healthz")
            fingerprint_mismatch = None
            if fingerprint is not None:
                rep.fingerprint = str(fingerprint)
                if self.fingerprint_gate:
                    if self.fleet_fingerprint is None and ready \
                            and rep.state == ACTIVE:
                        # First ACTIVE ready replica to report one
                        # establishes the fleet golden fingerprint.
                        self.fleet_fingerprint = rep.fingerprint
                        slog.info("integrity.fleet_fingerprint",
                                  source=rep.target,
                                  fingerprint=rep.fingerprint[:12])
                    elif (self.fleet_fingerprint is not None
                          and rep.fingerprint != self.fleet_fingerprint
                          and rep.state == ACTIVE):
                        fingerprint_mismatch = {
                            "fingerprint": rep.fingerprint,
                            "fleet_fingerprint": self.fleet_fingerprint,
                        }
        if fingerprint_mismatch is not None:
            # Outside the pool lock (quarantine takes it): the replica
            # loaded weights the rest of the fleet disagrees with —
            # refuse to keep serving from it.
            self.quarantine(rep.target, reason="fingerprint",
                            evidence=fingerprint_mismatch)
            return
        if ready and not draining and rep.state == QUARANTINED:
            # Reverify-readmission for a RESPAWNED quarantined replica:
            # a different boot_id proves the indicted process is gone
            # and a fresh one answers — run the fingerprint + canary
            # checks and re-admit only on a clean pass. The SAME
            # process incarnation never auto-readmits (its weights are
            # the ones that answered wrong); that path is the
            # operator's explicit unquarantine.
            if boot_id is not None and rep.quarantine_boot_id is not None \
                    and boot_id != rep.quarantine_boot_id:
                self.unquarantine(rep.target)
            return
        if rep.state == ACTIVE and ready and self.canary is not None:
            # Canary probing rides the scrape: at most one probe per
            # replica per canary interval, off the request path (this
            # runs on the scrape fan-out pool). A False verdict is a
            # corruption conviction; None (transport) is the breaker's
            # territory.
            now = time.monotonic()
            if now - rep.canary_at >= self.canary.interval:
                rep.canary_at = now
                verdict, evidence = self.canary.probe(rep)
                if verdict is False:
                    self.quarantine(rep.target, reason="canary",
                                    evidence=evidence)
                    return
        if ready and not draining and rep.state == DRAINING \
                and rep.drain_observed and not rep.decommissioning:
            # (decommissioning replicas never auto-rejoin: the drain is
            # toward removal, and re-admitting one that still answers
            # ready — a static replica being scaled down — would undo
            # the autoscaler's decision one scrape tick later.)
            # The restarted server answers ready on the reused address:
            # rejoin with a fresh breaker. Gated on the drain having
            # been OBSERVED (draining:true scraped, the replica
            # unreachable 2+ ticks while draining, or its boot_id
            # changed) — a still-ready replica that never began
            # restarting stays out of rotation, so an admin
            # `--drain-replica` on a static fleet is not undone by the
            # very next scrape.
            self.undrain(rep.target)

    def _maybe_respawn(self, rep: Replica) -> None:
        """Complete the drain choreography for a POOL-SPAWNED replica
        whose process has exited: respawn it on the same address so the
        next ready scrape rejoins it. Without this, an admin
        ``--drain-replica`` on a spawned fleet would SIGTERM the child
        and leave the fleet at N-1 forever — the drain is only half of
        the rolling restart the flag promises."""
        with self._lock:
            if (rep.state == REMOVED or rep.spawn_argv is None
                    or rep.respawning or rep.decommissioning
                    or time.monotonic() < rep.respawn_backoff_until
                    or rep.proc is None or rep.proc.poll() is None):
                # decommissioning: the exit IS the scale-down drain
                # completing — respawning it would undo the autoscaler
                # (and re-burn an engine compile for a replica that is
                # being removed on purpose).
                return
            if rep.state == DRAINING:
                # The exit IS the drain completing (GracefulDrain ran).
                rep.drain_observed = True
            elif rep.state == QUARANTINED:
                # Quarantine terminated the child on purpose: respawn a
                # fresh process but KEEP the quarantined state — the
                # new boot re-admits only through unquarantine's
                # fingerprint + canary reverify (the scrape's
                # boot_id-change path), never the drain auto-rejoin.
                pass
            else:
                # The child exited OUTSIDE any drain (crash, or an
                # undrain racing a child the drain already SIGTERMed):
                # --spawn promises a supervised fleet, not N-1 forever.
                # Route it through the same drain-rejoin choreography —
                # stop placing now, respawn, let the ready scrape
                # re-admit it with a fresh breaker.
                rep.state = DRAINING
                self.transitions_total += 1
                rep.drain_observed = True
                REPLICA_HEALTHY.labels(replica=rep.target).set(0.0)
                slog.warning("router.replica_exited_unexpectedly",
                             replica=rep.target,
                             returncode=rep.proc.poll())
            rep.respawning = True
            rep.respawn_backoff_until = time.monotonic() + 5.0
            argv = list(rep.spawn_argv)
        # The boot can take minutes (engine compile/warmup); it must
        # not run on the scrape thread, or health/load scraping — and
        # drain observation — for every OTHER replica freezes until
        # this one is up. `respawning` keeps the next ticks out.
        threading.Thread(
            target=self._respawn, args=(rep, argv),
            name=f"tdn-respawn-{rep.target}", daemon=True,
        ).start()

    def _respawn(self, rep: Replica, argv: list[str]) -> None:
        # Let forwards that raced the exit finish on the old channel
        # first: close_channel() turns in-flight RPCs into CANCELLED,
        # which the router classifies non-transient and propagates to
        # a client that never cancelled anything — the exact loss the
        # failover machinery exists to absorb (they fail UNAVAILABLE
        # on their own against the dead process, which DOES fail
        # over). Bounded wait: the process is gone, they fail fast.
        self.wait_drained(rep.target, 5.0)
        rep.close_channel()
        try:
            if self._stop.is_set() or rep.state == REMOVED:
                # The pool began shutting down — or remove() took this
                # replica out — while this thread was in its pre-spawn
                # window: a child spawned NOW would be born after
                # cleanup already terminated rep.proc (the OLD exited
                # process) and be orphaned on the reused ports.
                return
            proc = subprocess.Popen(
                argv, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
                text=True,
            )
            # Visible on rep BEFORE the (possibly minutes-long) port
            # wait: router shutdown mid-boot must find and terminate
            # this child, not orphan it holding the reused ports.
            # Re-check shutdown/removal under the lock: close() or
            # remove() may have run entirely between the pre-spawn
            # check and this assignment, in which case their proc
            # sweep saw the OLD exited process and nothing else will
            # ever terminate this child.
            with self._lock:
                if self._stop.is_set() or rep.state == REMOVED:
                    stillborn = proc
                else:
                    rep.proc = proc
                    stillborn = None
            if stillborn is not None:
                _terminate_child(stillborn)
                return
            _read_child_ports(proc, 180.0)
            slog.info("router.replica_respawned", replica=rep.target)
        except (OSError, RuntimeError):
            log.exception("respawn of drained replica %s failed",
                          rep.target)
        finally:
            with self._lock:
                rep.respawning = False

    def scrape_once(self) -> None:
        reps = [r for r in self.replicas() if r.state != REMOVED]
        for rep in reps:
            self._maybe_respawn(rep)
        # Fan the HTTP out: each unreachable replica blocks for up to
        # 2x scrape_timeout, and scraping serially would let a few
        # wedged hosts age EVERY healthy replica's gauges past the
        # staleness bound (p2c degrades fleet-wide) and delay drain
        # observation. One tick costs max(replica), not sum(replica).
        futs = []
        if len(reps) > 1:
            if self._scrape_pool is None:
                self._scrape_pool = (
                    concurrent.futures.ThreadPoolExecutor(
                        max_workers=16, thread_name_prefix="tdn-scrape"
                    )
                )
            futs = [self._scrape_pool.submit(self._scrape_one, rep)
                    for rep in reps[1:]]
        if reps:
            self._scrape_one(reps[0])
        for f in futs:
            f.result()
        # Reconcile the availability gauge with the breaker: membership
        # changes set it eagerly, but a breaker opening/closing happens
        # at request time in the router — without this tick a hard-down
        # replica the breaker already un-placed would keep reporting
        # healthy=1. Under the pool lock so a concurrent remove() (which
        # retires the series) cannot be resurrected by this write.
        with self._lock:
            for rep in reps:
                if rep.state != REMOVED:
                    REPLICA_HEALTHY.labels(replica=rep.target).set(
                        1.0 if (rep.state == ACTIVE
                                and rep.breaker.state
                                == CircuitBreaker.CLOSED)
                        else 0.0
                    )

    def start(self) -> "ReplicaPool":
        if self._thread is not None:
            return self
        self.scrape_once()
        self._thread = threading.Thread(
            target=self._run, name="tdn-router-scrape", daemon=True
        )
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.scrape_interval):
            try:
                self.scrape_once()
            except Exception:  # noqa: BLE001 — scraping must never kill routing
                log.exception("replica scrape failed")

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def close(self, *, grace: float = 10.0) -> None:
        self.stop()
        if self._scrape_pool is not None:
            self._scrape_pool.shutdown(wait=False)
            self._scrape_pool = None
        reps = self.replicas()
        for rep in reps:
            rep.close_channel()
            # Release the per-target PROCESS-GLOBAL state the pool
            # claimed: the breaker registry entry (+ its
            # tdn_breaker_state series) and the healthy series. A
            # long-lived process cycling pools over ephemeral-port
            # replicas (bench, tests) must not accumulate dead series
            # forever, and a later pool reusing an address must not
            # inherit this one's breaker history.
            _retire_replica_series(rep.target)
            CircuitBreaker.evict(rep.target)
        # Pool-spawned children are OWNED by the pool: a library caller
        # closing it must not orphan live engines holding their ports.
        # SIGTERM runs each child's own GracefulDrain; ``grace`` bounds
        # the wait before the hard kill (the CLI passes its
        # --drain-grace-seconds budget through). Defensive per-proc:
        # tests park duck-typed fakes on rep.proc.
        procs = [r.proc for r in reps if r.proc is not None]
        for p in procs:
            try:
                if p.poll() is None:
                    p.terminate()
            except Exception:  # noqa: BLE001 — best-effort teardown
                continue
        for p in procs:
            try:
                p.wait(timeout=grace)
            except Exception:  # noqa: BLE001 — last resort
                try:
                    p.kill()
                except Exception:  # noqa: BLE001
                    pass

    # ----------------------------------------------------- local spawn

    def spawn_local(self, config: str, *, grpc_port: int = 0,
                    metrics_port: int = 0, extra_args=(),
                    startup_timeout: float = 180.0) -> Replica:
        """Spawn one local engine replica (``tdn up --grpc-port``) as a
        subprocess and add it to the pool. Ports default to ephemeral;
        the child prints its bound ports as JSON lines (the CLI's
        port-in-stdout convention) and this blocks until both appear.
        """
        if self._stop.is_set():
            raise RuntimeError("pool is closed; refusing to spawn a replica")
        argv = [
            sys.executable, "-m", "tpu_dist_nn.cli", "up",
            "--config", config,
            "--grpc-port", str(grpc_port),
            "--metrics-port", str(metrics_port),
            *extra_args,
        ]
        proc = subprocess.Popen(
            argv, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            text=True,
        )
        ports = _read_child_ports(proc, startup_timeout)
        target = f"127.0.0.1:{ports['grpc_port']}"
        rep = self.add(target, f"127.0.0.1:{ports['metrics_port']}")
        with self._lock:
            rep.proc = proc
            # Remember the exact argv WITH the now-known ports so a
            # rolling restart respawns on the same (reused) addresses.
            rep.spawn_argv = [
                sys.executable, "-m", "tpu_dist_nn.cli", "up",
                "--config", config,
                "--grpc-port", str(ports["grpc_port"]),
                "--metrics-port", str(ports["metrics_port"]),
                *extra_args,
            ]
            closing = self._stop.is_set()
        if closing:
            # close() swept the pool while this child was booting (the
            # proc landed on rep only now, and the membership entry
            # after the sweep's snapshot): tear both down ourselves —
            # same bug class _respawn/restart_replica guard against.
            self.remove(target)
            raise RuntimeError("pool closed during spawn_local")
        return rep

    def restart_replica(self, target: str, *, grace: float = 30.0,
                        startup_timeout: float = 180.0) -> bool:
        """The full zero-downtime rolling-restart of one POOL-SPAWNED
        replica: drain (SIGTERM → its GracefulDrain) → wait for the
        router's outstanding work AND the process to finish → respawn
        on the same address → rejoin with a fresh breaker."""
        with self._lock:
            rep = self._replicas.get(target)
            if rep is None or rep.spawn_argv is None or rep.respawning:
                return False
            # Claim the respawn so the scrape loop's auto-respawn does
            # not race this explicit restart into a double spawn.
            rep.respawning = True
        try:
            self.drain(target)
            self.wait_drained(target, grace)
            if rep.proc is not None:
                try:
                    rep.proc.wait(timeout=grace)
                except subprocess.TimeoutExpired:
                    rep.proc.kill()
                    rep.proc.wait(timeout=5.0)
            rep.close_channel()
            proc = subprocess.Popen(
                rep.spawn_argv, stdout=subprocess.PIPE,
                stderr=subprocess.DEVNULL, text=True,
            )
            # Same rule as _respawn: the child rides rep.proc through
            # the (possibly minutes-long) port wait so shutdown cleanup
            # terminates it instead of orphaning it on the reused
            # ports — and a concurrent close()/remove() that already
            # swept the OLD proc means this child is ours to kill.
            with self._lock:
                if self._stop.is_set() or rep.state == REMOVED:
                    stillborn = proc
                else:
                    rep.proc = proc
                    stillborn = None
            if stillborn is not None:
                _terminate_child(stillborn)
                return False
            _read_child_ports(proc, startup_timeout)
        finally:
            with self._lock:
                rep.respawning = False
        if self.undrain(target):
            return True
        # The scrape loop's auto-rejoin may have undrained the
        # restarted server before we got here (undrain refuses
        # non-DRAINING replicas, so ours returns False) — a replica
        # that ended up ACTIVE is a SUCCESSFUL restart either way.
        with self._lock:
            rep2 = self._replicas.get(target)
            return rep2 is not None and rep2.state == ACTIVE


def _retire_replica_series(target: str) -> None:
    """Retire every per-replica metric series a departed target owned:
    the healthy gauge plus the router's request counters (looked up by
    name — the router module imports this one, not vice versa). The
    sampler's outstanding/pending gauges retire via its own churn
    handling."""
    REPLICA_HEALTHY.remove(replica=target)
    REPLICA_QUARANTINED.remove(replica=target)
    requests = REGISTRY.get("tdn_router_requests_total")
    if requests is not None:
        requests.remove_matching(replica=target)


def _terminate_child(proc) -> None:
    """Best-effort SIGTERM (the child's own GracefulDrain) → bounded
    wait → SIGKILL. Duck-typed: tests park fakes on ``rep.proc``."""
    try:
        if proc.poll() is None:
            proc.terminate()
        proc.wait(timeout=10.0)
    except Exception:  # noqa: BLE001 — best-effort teardown
        try:
            proc.kill()
        except Exception:  # noqa: BLE001
            pass


def _read_child_ports(proc: subprocess.Popen,
                      timeout: float) -> dict[str, int]:
    """Read a spawned replica's JSON stdout lines until both its
    metrics and gRPC ports are known (a reader thread bounds the wait —
    a wedged child must raise, not hang the router bring-up)."""
    ports: dict[str, int] = {}
    done = threading.Event()
    err: list[str] = []

    def reader():
        try:
            for line in proc.stdout:  # type: ignore[union-attr]
                line = line.strip()
                if not line.startswith("{"):
                    continue
                try:
                    doc = json.loads(line)
                except ValueError:
                    continue
                for key in ("metrics_port", "grpc_port"):
                    if key in doc:
                        ports[key] = int(doc[key])
                if "metrics_port" in ports and "grpc_port" in ports:
                    done.set()
                    return
            err.append("child exited before printing its ports")
        except Exception as e:  # noqa: BLE001 — surfaced to the waiter
            err.append(repr(e))
        finally:
            done.set()

    threading.Thread(target=reader, daemon=True).start()
    if not done.wait(timeout) or "grpc_port" not in ports:
        _terminate_child(proc)
        raise RuntimeError(
            "spawned replica did not report its ports within "
            f"{timeout}s" + (f": {err[0]}" if err else "")
        )
    return ports
