"""Wire codec for the reference's gRPC protocol, vectorized with numpy.

The reference's only message types are ``Row { repeated double values }``
and ``Matrix { repeated Row rows }`` (``src/proto/dist_nn.proto:5-11``),
proto3. This module speaks that exact wire format without protobuf
codegen: a Matrix is a sequence of field-1 length-delimited Row
messages, and a Row's values are field-1 packed little-endian doubles
(proto3 packs repeated scalars by default — the reference's generated
stubs produce exactly this). The decoder additionally accepts the
unpacked encoding (one fixed64 per value) that proto2-style writers
emit, so any conforming client interoperates.

Hand-rolling buys two things: zero dependence on protoc/codegen version
skew, and numpy-vectorized pack/unpack — the reference's stubs cross
the Python<->C++ protobuf boundary per row (``grpc_node.py:107,126``).

Fast lane (docs/PERF.md "Host data path"): every row of an ``(N, D)``
matrix our encoder (or any packed-proto3 writer with a deterministic
varint encoder — protoc included) emits has BYTE-IDENTICAL headers at a
fixed stride, so the whole message is one periodic byte pattern:

    [0x0A varint(row_msg_len) 0x0A varint(8*D) <8*D payload bytes>] * N

* :func:`encode_matrix` writes the message as ONE preallocated uint8
  buffer: a broadcast header write plus a single strided cast-copy of
  the payload. It accepts ANY input dtype — the cast to the wire's
  float64 lands per-stripe into the output buffer, so the caller never
  materializes an (N, D) float64 intermediate.
* :func:`decode_matrix` probes the FIRST row's structure, verifies the
  remaining headers match at stride with one vectorized view compare,
  then decodes all payload doubles through one strided view — falling
  back to the general per-row parser on ANY mismatch (unpacked
  encoding, unknown fields, non-uniform varints, ragged rows,
  truncation), so conformance is exactly the general parser's.
* :class:`WireMatrix` / :func:`decode_matrix_lazy` defer even that one
  payload copy: the serving batcher lands wire rows DIRECTLY in its
  per-bucket staging buffer (:func:`decode_matrix_into`), so a
  coalesced batch is assembled from each member's raw bytes with
  exactly one cast-copy end-to-end.

Fast-vs-fallback traffic is observable (``tdn_wire_decode_fast_total``
/ ``tdn_wire_decode_fallback_total`` + the rate-limited
``wire.fallback`` structured event — docs/OBSERVABILITY.md): a client
silently knocking a server off the fast path is a scrape away, not a
profile-archaeology find.

Round-trip parity against real protoc-generated stubs is tested when a
``protoc`` binary is available (tests/test_serving.py); scalar-vs-
vectorized equivalence is fuzzed in tests/test_wire_codec.py.
"""

from __future__ import annotations

import threading

import numpy as np

from tpu_dist_nn.obs.log import get_logger
from tpu_dist_nn.obs.registry import REGISTRY

_TAG_ROW = 0x0A          # field 1, wire type 2 (LEN): Matrix.rows / Row.values
_WT_LEN = 2
_WT_FIXED64 = 1
_WT_VARINT = 0
_WT_FIXED32 = 5

slog = get_logger(__name__)

# Fast-path vs fallback decode traffic (docs/OBSERVABILITY.md). The
# fallback counter ticking on a production server means some client's
# encoder is NOT the packed uniform layout — the decode stage silently
# runs ~10-100x slower for those requests; the wire.fallback event
# (rate-limited) names why.
_DECODE_FAST = REGISTRY.counter(
    "tdn_wire_decode_fast_total",
    "Matrix decodes served by the vectorized zero-copy fast path",
)
_DECODE_FALLBACK = REGISTRY.counter(
    "tdn_wire_decode_fallback_total",
    "Matrix decodes that fell back to the general per-row parser "
    "(unpacked rows, unknown fields, ragged widths, malformed bytes)",
)


def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _read_varint(buf, pos: int) -> tuple[int, int]:
    result = 0
    shift = 0
    while True:
        if pos >= len(buf):
            raise ValueError("truncated varint")
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 63:
            raise ValueError("varint too long")


# Per-thread reusable encode buffer. A fresh np.empty per encode sits
# above glibc's mmap threshold for any real batch, so every call paid
# map + page-fault-on-write + unmap for the whole message (~2 ms/MB
# measured — 30x the actual byte work). One warm scratch per thread
# amortizes that to zero; the returned bytes object is the single copy
# out. Capped so a one-off huge reply can't pin 8 MB per worker thread
# forever (above the cap: fresh alloc, still one strided cast-copy).
_SCRATCH_MAX = 1 << 23
_scratch_tls = threading.local()


def _encode_scratch(nbytes: int) -> np.ndarray:
    if nbytes > _SCRATCH_MAX:
        return np.empty(nbytes, dtype=np.uint8)
    buf = getattr(_scratch_tls, "buf", None)
    if buf is None or buf.size < nbytes:
        buf = np.empty(1 << max(16, (nbytes - 1).bit_length()),
                       dtype=np.uint8)
        _scratch_tls.buf = buf
    return buf[:nbytes]


def _headers(d: int) -> tuple[bytes, int, int]:
    """(matrix_header + row_header, header_len, stride) for width ``d``
    — the per-row byte prefix every row of a packed (N, d) matrix
    shares, and the full per-row period."""
    payload_len = 8 * d
    row_header = b"\x0a" + _varint(payload_len)
    matrix_header = b"\x0a" + _varint(len(row_header) + payload_len)
    header = matrix_header + row_header
    return header, len(header), len(header) + payload_len


def encode_matrix(x) -> bytes:
    """``(N, D) array -> Matrix`` bytes (rows of packed doubles).

    Accepts ANY real dtype: the cast to the wire's little-endian
    float64 happens per-stripe into the preallocated output buffer (one
    strided cast-copy), so callers hand over their engine-dtype arrays
    directly instead of materializing an (N, D) float64 copy first.
    Byte-for-byte identical to the legacy per-row encoder
    (:func:`encode_matrix_scalar`) for every input.
    """
    x = np.asarray(x)
    if x.ndim != 2:
        raise ValueError(f"matrix must be 2-D, got shape {x.shape}")
    n, d = x.shape
    if n == 0:
        return b""
    header, h, stride = _headers(d)
    if n == 1:
        # One row has nothing to broadcast: the message is the shared
        # header plus one payload cast-copy.
        return header + np.ascontiguousarray(x[0], "<f8").tobytes()
    out = _encode_scratch(n * stride)
    mat = out.reshape(n, stride)
    # Broadcast header write: every row's 0x0A/len/0x0A/len prefix is
    # the same few bytes at a fixed period.
    mat[:, :h] = np.frombuffer(header, dtype=np.uint8)
    if d:
        # ONE strided cast-copy of the whole payload: the f64 view of
        # the payload stripes is written straight from x (numpy casts
        # per-stripe; x is never materialized as float64).
        mat[:, h:].view("<f8")[...] = x
    return out.tobytes()


def encode_matrix_scalar(x: np.ndarray) -> bytes:
    """The legacy per-row encoder (3·N list parts + join), kept as the
    equivalence oracle for tests and the ``bench.py --wire`` A/B
    control arm. Semantics identical to :func:`encode_matrix`."""
    x = np.ascontiguousarray(np.asarray(x, dtype="<f8"))
    if x.ndim != 2:
        raise ValueError(f"matrix must be 2-D, got shape {x.shape}")
    n, d = x.shape
    payload_len = 8 * d
    row_header = _TAG_ROW.to_bytes(1, "little") + _varint(payload_len)
    row_msg_len = len(row_header) + payload_len
    matrix_header = _TAG_ROW.to_bytes(1, "little") + _varint(row_msg_len)
    parts = []
    for i in range(n):
        parts.append(matrix_header)
        parts.append(row_header)
        parts.append(x[i].tobytes())
    return b"".join(parts)


def _bounded(buf, pos: int, need: int) -> int:
    """Advance past ``need`` bytes, rejecting overruns — a truncated
    length-delimited field must raise like real protobuf parsers do,
    not silently decode a short slice."""
    end = pos + need
    if end > len(buf):
        raise ValueError("truncated message")
    return end


def _skip_field(buf, pos: int, wire_type: int) -> int:
    if wire_type == _WT_VARINT:
        _, pos = _read_varint(buf, pos)
        return pos
    if wire_type == _WT_FIXED64:
        return _bounded(buf, pos, 8)
    if wire_type == _WT_LEN:
        ln, pos = _read_varint(buf, pos)
        return _bounded(buf, pos, ln)
    if wire_type == _WT_FIXED32:
        return _bounded(buf, pos, 4)
    raise ValueError(f"unsupported wire type {wire_type}")


def _decode_row(buf: memoryview) -> np.ndarray:
    values: list[np.ndarray] = []
    pos = 0
    while pos < len(buf):
        key, pos = _read_varint(buf, pos)
        field, wt = key >> 3, key & 7
        if field == 1 and wt == _WT_LEN:        # packed doubles
            ln, pos = _read_varint(buf, pos)
            end = _bounded(buf, pos, ln)
            if ln % 8:
                raise ValueError("packed double payload not a multiple of 8")
            values.append(np.frombuffer(buf[pos:end], dtype="<f8"))
            pos = end
        elif field == 1 and wt == _WT_FIXED64:  # unpacked double
            end = _bounded(buf, pos, 8)
            values.append(np.frombuffer(buf[pos:end], dtype="<f8"))
            pos = end
        else:
            pos = _skip_field(buf, pos, wt)
    if not values:
        return np.empty((0,), dtype=np.float64)
    return np.concatenate(values)


def decode_matrix_scalar(data: bytes, dtype=np.float64) -> np.ndarray:
    """The general per-row parser: full protobuf conformance (packed OR
    unpacked values, unknown fields skipped, ragged rows rejected — the
    reference's per-layer dim check, grpc_node.py:83-84, applies to
    whole matrices). The fast path's fallback arm AND its behavioral
    oracle: whatever bytes the fast path declines must decode (or
    raise) identically here."""
    buf = memoryview(data)
    rows: list[np.ndarray] = []
    pos = 0
    while pos < len(buf):
        key, pos = _read_varint(buf, pos)
        field, wt = key >> 3, key & 7
        if field == 1 and wt == _WT_LEN:
            ln, pos = _read_varint(buf, pos)
            end = _bounded(buf, pos, ln)
            rows.append(_decode_row(buf[pos:end]))
            pos = end
        else:
            pos = _skip_field(buf, pos, wt)
    if not rows:
        return np.empty((0, 0), dtype=dtype)
    width = {r.shape[0] for r in rows}
    if len(width) != 1:
        raise ValueError(f"ragged matrix rows: widths {sorted(width)}")
    out = np.empty((len(rows), width.pop()), dtype=dtype)
    for i, r in enumerate(rows):
        out[i] = r  # casts the f8 row view on assignment, no f64 matrix
    return out


class _FastLayout:
    """Probed structure of a uniform packed Matrix: ``n`` rows of width
    ``d``, payload at byte ``h`` of each ``stride``-byte period."""

    __slots__ = ("n", "d", "h", "stride")

    def __init__(self, n: int, d: int, h: int, stride: int):
        self.n, self.d, self.h, self.stride = n, d, h, stride


def _probe_fast(data) -> "_FastLayout | str":
    """Validate the first row's header and the periodic structure of
    the rest; returns a :class:`_FastLayout` on success, else a short
    reason string (the fallback observability breadcrumb). Never
    raises: anything suspicious is the general parser's job, so the
    fast path can only ever decline, not diverge."""
    buf = data if isinstance(data, (bytes, bytearray)) else bytes(data)
    total = len(buf)
    try:
        if buf[0] != _TAG_ROW:
            return "first field is not Matrix.rows"
        row_len, pos = _read_varint(buf, 1)
        row_end = pos + row_len
        if row_end > total:
            return "first row truncated"
        if row_len == 0:
            # An empty Row message decodes to width 0; the general
            # parser handles the (legal, never-emitted-by-us) shape.
            return "empty first row"
        if buf[pos] != _TAG_ROW:
            return "first row value field not packed"
        payload_len, payload_start = _read_varint(buf, pos + 1)
        if payload_len % 8:
            return "payload not a multiple of 8"
        if payload_start + payload_len != row_end:
            return "extra fields in first row"
    except ValueError as e:
        return str(e)  # general parser raises the identical error
    stride = row_end
    if total % stride:
        return "trailing bytes break the row period"
    n = total // stride
    if n > 1:
        # ONE vectorized compare: every row's header must be byte-
        # identical to the first row's (same keys, same minimal-varint
        # lengths) — the check that makes the strided payload view
        # valid by construction.
        arr = np.frombuffer(buf, dtype=np.uint8)
        mat = arr.reshape(n, stride)
        if not (mat[:, :payload_start] == mat[0, :payload_start]).all():
            return "row headers not uniform at stride"
    return _FastLayout(n, payload_len // 8, payload_start, stride)


def _fast_payload_view(data, layout: _FastLayout) -> np.ndarray:
    """The ``(n, d) <f8`` strided read-only view over the raw wire
    bytes — the zero-copy half of the fast path. Consumers copy-cast
    out of it exactly once, into their own dtype/buffer. (A single
    row's payload is contiguous, so it is one offset frombuffer; the
    (d,) view broadcasts into every (1, d) consumer slot.)"""
    raw = data if isinstance(data, (bytes, bytearray)) else bytes(data)
    if layout.n == 1:
        return np.frombuffer(raw, dtype="<f8", count=layout.d,
                             offset=layout.h)
    arr = np.frombuffer(raw, dtype=np.uint8)
    return arr.reshape(layout.n, layout.stride)[:, layout.h:].view("<f8")


def _note_fallback(reason: str, nbytes: int) -> None:
    _DECODE_FALLBACK.inc()
    # Rate-limited (obs/log.py token bucket): a chatty nonconforming
    # client logs its first occurrences then ~1/s, not one line per RPC.
    slog.warning("wire.fallback", reason=reason, bytes=nbytes,
                 hint="client encoder is off the packed uniform layout; "
                      "decode runs the slow general parser")


def decode_matrix(data: bytes, dtype=np.float64) -> np.ndarray:
    """``Matrix`` bytes -> ``(N, D) dtype`` array (ragged rows rejected
    — the reference's per-layer dim check, grpc_node.py:83-84, applies
    to whole matrices).

    ``dtype`` lands rows DIRECTLY in the consumer's dtype: the serving
    path decodes into the engine's compute dtype, so the only float64
    in the process is the zero-copy f8 view of the wire bytes — the
    (N, D) float64 staging matrix the old decode-then-cast pipeline
    materialized never exists. The wire format itself stays the
    reference's packed float64 contract.

    Fast path: one structure probe + one strided view cast-copy
    (module docstring); any non-uniform/unknown/ragged/truncated input
    falls back to :func:`decode_matrix_scalar` with identical results
    and identical errors.
    """
    if len(data) == 0:
        return np.empty((0, 0), dtype=dtype)
    layout = _probe_fast(data)
    if isinstance(layout, _FastLayout):
        _DECODE_FAST.inc()
        out = np.empty((layout.n, layout.d), dtype=dtype)
        if layout.d:
            out[...] = _fast_payload_view(data, layout)
        return out
    out = decode_matrix_scalar(data, dtype=dtype)
    # Count/log AFTER the general parse: malformed bytes raise out of
    # it (the server's INVALID_ARGUMENT funnel already counts those);
    # the fallback series means "valid message, slow layout".
    _note_fallback(layout, len(data))
    return out


def decode_matrix_into(data: bytes, out: np.ndarray,
                       row_offset: int = 0) -> int:
    """Decode ``Matrix`` bytes DIRECTLY into ``out[row_offset:]`` and
    return the number of rows landed.

    The decode-into-staging half of the one-copy pipeline: the serving
    batcher hands its per-bucket staging buffer here, so a request's
    payload goes wire bytes -> device-feed buffer in ONE cast-copy —
    no intermediate (N, D) matrix, no second copy at stage time.
    Raises ``ValueError`` on a width mismatch with ``out`` (the
    caller validated the width at decode-probe time, so this firing
    means a bug, not a client error) and on overflow past ``len(out)``.
    """
    if len(data) == 0:
        return 0
    layout = _probe_fast(data)
    if isinstance(layout, _FastLayout):
        _DECODE_FAST.inc()
        # One bounds/copy contract: WireMatrix.read_into is the same
        # code the batcher's staging stage runs.
        return WireMatrix(data, layout, out.dtype).read_into(out, row_offset)
    x = decode_matrix_scalar(data)
    _note_fallback(layout, len(data))
    n, d = x.shape
    if d != out.shape[1]:
        raise ValueError(
            f"matrix width {d} does not match staging width {out.shape[1]}"
        )
    if row_offset + n > len(out):
        raise ValueError(
            f"{n} rows at offset {row_offset} overflow staging buffer "
            f"of {len(out)} rows"
        )
    out[row_offset:row_offset + n] = x
    return n


class WireMatrix:
    """A probed-but-undecoded fast-path Matrix.

    Ducks enough of the ndarray surface for the serving batcher
    (``len``, ``shape``, ``dtype``, ``ndim``) while deferring the one
    payload cast-copy until :meth:`read_into` lands the rows in the
    batcher's staging buffer — or :meth:`__array__` materializes them
    for the non-coalescing paths (``np.asarray`` just works).
    """

    __slots__ = ("_data", "_layout", "dtype")

    def __init__(self, data: bytes, layout: _FastLayout, dtype):
        self._data = data
        self._layout = layout
        self.dtype = np.dtype(dtype)

    @property
    def shape(self) -> tuple[int, int]:
        return (self._layout.n, self._layout.d)

    @property
    def ndim(self) -> int:
        return 2

    def __len__(self) -> int:
        return self._layout.n

    def read_into(self, out: np.ndarray, row_offset: int = 0) -> int:
        """Land this matrix's rows in ``out[row_offset:]`` (one strided
        cast-copy straight off the wire bytes); returns the row
        count."""
        lo = self._layout
        if lo.d != out.shape[1]:
            raise ValueError(
                f"matrix width {lo.d} does not match staging width "
                f"{out.shape[1]}"
            )
        if row_offset + lo.n > len(out):
            raise ValueError(
                f"{lo.n} rows at offset {row_offset} overflow staging "
                f"buffer of {len(out)} rows"
            )
        if lo.d:
            out[row_offset:row_offset + lo.n] = _fast_payload_view(
                self._data, lo
            )
        return lo.n

    def __array__(self, dtype=None, copy=None):
        lo = self._layout
        out = np.empty((lo.n, lo.d), dtype=dtype or self.dtype)
        if lo.d:
            out[...] = _fast_payload_view(self._data, lo)
        return out


def decode_matrix_lazy(data: bytes, dtype=np.float64):
    """Probe ``Matrix`` bytes; return a :class:`WireMatrix` (fast
    layout — payload untouched until the consumer lands it) or a fully
    decoded ndarray (fallback/general layout). The serving handler's
    entry point: shape/width validation needs only the probe, and the
    payload's single cast-copy moves to the batcher's staging stage.
    Raises the general parser's ``ValueError`` on malformed bytes."""
    if len(data) == 0:
        return np.empty((0, 0), dtype=dtype)
    layout = _probe_fast(data)
    if isinstance(layout, _FastLayout):
        _DECODE_FAST.inc()
        return WireMatrix(data, layout, dtype)
    out = decode_matrix_scalar(data, dtype=dtype)
    _note_fallback(layout, len(data))
    return out


# ----------------------------------------------------- stream frames
#
# GenerateStream (serving/stream.py, docs/SCALING.md "Streaming
# failover") speaks a tiny frame codec ON TOP of gRPC server-streaming:
# each gRPC stream message is exactly ONE frame (gRPC already
# length-delimits messages, so frames need no outer envelope). Byte 0
# is the frame type; varints reuse the protobuf encoder above.
#
#   TOKENS frame: 0x01 varint(count) varint(token_id) * count
#     — a delta of newly produced token ids, in order.
#   END frame:    0x02 varint(len) reason_utf8 varint(len) code_utf8
#                 varint(len) message_utf8
#     — the terminal status: ``reason`` is "eos" / "max_tokens" for a
#       normal finish (code/message empty), else "error" with the
#       canonical error code name + message. Exactly one END frame
#       closes every well-formed stream.
#
# The router forwards these bytes VERBATIM (it never decodes matrices),
# but shallow-parses TOKENS frames to keep its delivered-token ledger —
# the resume state it replays into a fallback replica on mid-stream
# failover. Keeping the codec here (not serving/stream.py) preserves
# the layering: wire.py owns every byte format, stream.py owns the
# channel semantics.

FRAME_TOKENS = 1
FRAME_END = 2


def encode_token_frame(tokens) -> bytes:
    """``[token ids] -> TOKENS frame`` bytes (a non-empty delta)."""
    out = bytearray((FRAME_TOKENS,))
    out += _varint(len(tokens))
    for t in tokens:
        out += _varint(int(t))
    return bytes(out)


def encode_end_frame(reason: str, code: str = "",
                     message: str = "") -> bytes:
    """Terminal frame: ``reason`` ("eos" / "max_tokens" / "error"),
    plus the canonical error code name + message when reason is
    "error"."""
    out = bytearray((FRAME_END,))
    for s in (reason, code, message):
        b = s.encode("utf-8")
        out += _varint(len(b))
        out += b
    return bytes(out)


def decode_frame(data: bytes):
    """One stream frame -> ``("tokens", [ids])`` or
    ``("end", {"reason", "code", "message"})``. Raises ``ValueError``
    on malformed bytes (unknown type, truncation)."""
    if not data:
        raise ValueError("empty stream frame")
    kind = data[0]
    if kind == FRAME_TOKENS:
        count, pos = _read_varint(data, 1)
        toks = []
        for _ in range(count):
            t, pos = _read_varint(data, pos)
            toks.append(t)
        if pos != len(data):
            raise ValueError("trailing bytes after TOKENS frame")
        return "tokens", toks
    if kind == FRAME_END:
        fields = []
        pos = 1
        for _ in range(3):
            ln, pos = _read_varint(data, pos)
            end = _bounded(data, pos, ln)
            fields.append(bytes(data[pos:end]).decode("utf-8"))
            pos = end
        if pos != len(data):
            raise ValueError("trailing bytes after END frame")
        return "end", {"reason": fields[0], "code": fields[1],
                       "message": fields[2]}
    raise ValueError(f"unknown stream frame type {kind}")


#: The fully-qualified method the reference's stubs call — the proto
#: package is ``grpc_dist_nn`` (``src/proto/dist_nn.proto:3``), so
#: LayerServiceStub targets exactly this path.
PROCESS_METHOD = "/grpc_dist_nn.LayerService/Process"
# Generation rides the SAME Matrix wire format (token ids as doubles —
# exact for ids < 2^53): prompts (N, T) in, (N, T + max_new_tokens)
# out. A second method on the reference's service, not a new protocol.
GENERATE_METHOD = "/grpc_dist_nn.LayerService/Generate"
# Server-streaming generation (PR 16): same prompt Matrix in (exactly
# one row), a stream of TOKENS/END frames out (codec above). The
# router forwards the frames verbatim and owns mid-stream failover.
GENERATE_STREAM_METHOD = "/grpc_dist_nn.LayerService/GenerateStream"
SERVICE_NAME = "grpc_dist_nn.LayerService"
# Client -> server session key (serving/router.py): pins a session's
# follow-up Generate requests to the replica already holding its
# KV/prefix-cache state. Engine servers ignore it; the router reads it.
SESSION_HEADER = "x-tdn-session"
# Client -> server SLO class (serving/sched_core.py): critical /
# standard / best_effort. Queue priority + shed watermark at the
# scheduler; the router forwards it and exempts best_effort from
# hedging. Missing/unknown values degrade to "standard".
CLASS_HEADER = "x-tdn-class"
# Server -> client trailing metadata on RESOURCE_EXHAUSTED sheds: the
# drain-rate-derived backoff floor in milliseconds (RetryPolicy honors
# it so a shed storm cannot re-synchronize into a hot-retry storm).
RETRY_AFTER_HEADER = "x-tdn-retry-after-ms"
# Router -> replica request metadata on a GenerateStream failover
# re-placement: the comma-separated token ids the client ALREADY
# received. The fallback replica replays them as forced tokens
# (serving/continuous.py resume path) and streams only what follows —
# exactly-once delivery across the replica switch. Bounded by gRPC's
# ~8 KB default metadata budget, which comfortably holds any
# max_new_tokens this engine is configured for.
STREAM_RESUME_HEADER = "x-tdn-stream-resume"
# Hard cap on how many delivered tokens the resume header may carry
# (ISSUE 18). Bit-exact resume needs EVERY delivered token to reach
# the fallback replica — a clamped suffix would replay against KV
# state the fallback does not have — so past this bound the failover
# fails with OUT_OF_RANGE + a counter instead of an opaque gRPC
# metadata error. 1024 ids x ~6 chars comma-separated ~= 7 KB, safely
# under the ~8 KB default metadata budget; moving the ledger into the
# request body is the ROADMAP follow-on for longer streams.
STREAM_RESUME_MAX_TOKENS = 1024
