"""Wire codec for the reference's gRPC protocol, vectorized with numpy.

The reference's only message types are ``Row { repeated double values }``
and ``Matrix { repeated Row rows }`` (``src/proto/dist_nn.proto:5-11``),
proto3. This module speaks that exact wire format without protobuf
codegen: a Matrix is a sequence of field-1 length-delimited Row
messages, and a Row's values are field-1 packed little-endian doubles
(proto3 packs repeated scalars by default — the reference's generated
stubs produce exactly this). The decoder additionally accepts the
unpacked encoding (one fixed64 per value) that proto2-style writers
emit, so any conforming client interoperates.

Hand-rolling buys two things: zero dependence on protoc/codegen version
skew, and numpy-vectorized pack/unpack (``tobytes``/``frombuffer``) —
the reference's stubs cross the Python<->C++ protobuf boundary per row
(``grpc_node.py:107,126``).

Round-trip parity against real protoc-generated stubs is tested when a
``protoc`` binary is available (tests/test_serving.py).
"""

from __future__ import annotations

import numpy as np

_TAG_ROW = 0x0A          # field 1, wire type 2 (LEN): Matrix.rows / Row.values
_WT_LEN = 2
_WT_FIXED64 = 1
_WT_VARINT = 0
_WT_FIXED32 = 5


def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _read_varint(buf: memoryview, pos: int) -> tuple[int, int]:
    result = 0
    shift = 0
    while True:
        if pos >= len(buf):
            raise ValueError("truncated varint")
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 63:
            raise ValueError("varint too long")


def encode_matrix(x: np.ndarray) -> bytes:
    """``(N, D) float64 -> Matrix`` bytes (rows of packed doubles)."""
    x = np.ascontiguousarray(np.asarray(x, dtype="<f8"))
    if x.ndim != 2:
        raise ValueError(f"matrix must be 2-D, got shape {x.shape}")
    n, d = x.shape
    payload_len = 8 * d
    row_header = _TAG_ROW.to_bytes(1, "little") + _varint(payload_len)
    row_msg_len = len(row_header) + payload_len
    matrix_header = _TAG_ROW.to_bytes(1, "little") + _varint(row_msg_len)
    parts = []
    for i in range(n):
        parts.append(matrix_header)
        parts.append(row_header)
        parts.append(x[i].tobytes())
    return b"".join(parts)


def _bounded(buf: memoryview, pos: int, need: int) -> int:
    """Advance past ``need`` bytes, rejecting overruns — a truncated
    length-delimited field must raise like real protobuf parsers do,
    not silently decode a short slice."""
    end = pos + need
    if end > len(buf):
        raise ValueError("truncated message")
    return end


def _skip_field(buf: memoryview, pos: int, wire_type: int) -> int:
    if wire_type == _WT_VARINT:
        _, pos = _read_varint(buf, pos)
        return pos
    if wire_type == _WT_FIXED64:
        return _bounded(buf, pos, 8)
    if wire_type == _WT_LEN:
        ln, pos = _read_varint(buf, pos)
        return _bounded(buf, pos, ln)
    if wire_type == _WT_FIXED32:
        return _bounded(buf, pos, 4)
    raise ValueError(f"unsupported wire type {wire_type}")


def _decode_row(buf: memoryview) -> np.ndarray:
    values: list[np.ndarray] = []
    pos = 0
    while pos < len(buf):
        key, pos = _read_varint(buf, pos)
        field, wt = key >> 3, key & 7
        if field == 1 and wt == _WT_LEN:        # packed doubles
            ln, pos = _read_varint(buf, pos)
            end = _bounded(buf, pos, ln)
            if ln % 8:
                raise ValueError("packed double payload not a multiple of 8")
            values.append(np.frombuffer(buf[pos:end], dtype="<f8"))
            pos = end
        elif field == 1 and wt == _WT_FIXED64:  # unpacked double
            end = _bounded(buf, pos, 8)
            values.append(np.frombuffer(buf[pos:end], dtype="<f8"))
            pos = end
        else:
            pos = _skip_field(buf, pos, wt)
    if not values:
        return np.empty((0,), dtype=np.float64)
    return np.concatenate(values)


def decode_matrix(data: bytes, dtype=np.float64) -> np.ndarray:
    """``Matrix`` bytes -> ``(N, D) dtype`` array (ragged rows rejected
    — the reference's per-layer dim check, grpc_node.py:83-84, applies
    to whole matrices).

    ``dtype`` lands rows DIRECTLY in the consumer's dtype: the serving
    path decodes into the engine's compute dtype, so the only float64
    in the process is the per-row zero-copy ``frombuffer`` view of the
    wire bytes — the (N, D) float64 staging matrix the old
    decode-then-cast pipeline materialized never exists. The wire
    format itself stays the reference's packed float64 contract.
    """
    buf = memoryview(data)
    rows: list[np.ndarray] = []
    pos = 0
    while pos < len(buf):
        key, pos = _read_varint(buf, pos)
        field, wt = key >> 3, key & 7
        if field == 1 and wt == _WT_LEN:
            ln, pos = _read_varint(buf, pos)
            end = _bounded(buf, pos, ln)
            rows.append(_decode_row(buf[pos:end]))
            pos = end
        else:
            pos = _skip_field(buf, pos, wt)
    if not rows:
        return np.empty((0, 0), dtype=dtype)
    width = {r.shape[0] for r in rows}
    if len(width) != 1:
        raise ValueError(f"ragged matrix rows: widths {sorted(width)}")
    out = np.empty((len(rows), width.pop()), dtype=dtype)
    for i, r in enumerate(rows):
        out[i] = r  # casts the f8 row view on assignment, no f64 matrix
    return out


#: The fully-qualified method the reference's stubs call — the proto
#: package is ``grpc_dist_nn`` (``src/proto/dist_nn.proto:3``), so
#: LayerServiceStub targets exactly this path.
PROCESS_METHOD = "/grpc_dist_nn.LayerService/Process"
# Generation rides the SAME Matrix wire format (token ids as doubles —
# exact for ids < 2^53): prompts (N, T) in, (N, T + max_new_tokens)
# out. A second method on the reference's service, not a new protocol.
GENERATE_METHOD = "/grpc_dist_nn.LayerService/Generate"
SERVICE_NAME = "grpc_dist_nn.LayerService"
# Client -> server session key (serving/router.py): pins a session's
# follow-up Generate requests to the replica already holding its
# KV/prefix-cache state. Engine servers ignore it; the router reads it.
SESSION_HEADER = "x-tdn-session"
