"""gRPC serving endpoint, wire-compatible with the reference's client.

Runs the reference's one RPC — ``LayerService.Process(Matrix) ->
Matrix`` (``src/proto/dist_nn.proto:13-15``) — in front of an
:class:`~tpu_dist_nn.api.engine.Engine`, so a user of docker-dist-nn
can point their EXISTING client (``run_grpc_inference.py``) at
``tdn serve`` unchanged. The difference is behind the socket: the
reference answers by chaining nested gRPC hops through one container
per stage (``grpc_node.py:120-147``); here the whole pipeline is one
SPMD program on the mesh, so the request crosses exactly one
serialization boundary instead of ``2 x num_stages``.

Concurrency: the reference overlaps concurrent requests only through
its 10-thread server pool, each request traversing the whole pipeline
alone (``grpc_node.py:169``). Here concurrent requests COALESCE: a
:class:`_Batcher` thread owns the device, and every request that
arrives while a batch is in flight joins the next one — rows from many
clients fuse into one padded device batch and split on reply. Under
load the device sees a few large launches instead of many one-row
launches (aggregate throughput scales with the coalesced batch size);
an idle server dispatches immediately, adding zero latency.

Error parity (``grpc_node.py:149-158``): a wrong input width returns
``INVALID_ARGUMENT`` with the dim message — validated per request
BEFORE coalescing so one bad client cannot poison a shared batch;
unexpected failures return ``INTERNAL``.
"""

from __future__ import annotations

import logging
import threading
from concurrent import futures

import grpc
import numpy as np

from tpu_dist_nn.serving.wire import (
    PROCESS_METHOD,
    SERVICE_NAME,
    decode_matrix,
    encode_matrix,
)

log = logging.getLogger(__name__)


class _Batcher:
    """Single-consumer micro-batching queue in front of one engine.

    ``submit(x)`` blocks the calling (gRPC worker) thread until its
    rows' results are ready. One daemon thread drains the queue: it
    grabs EVERYTHING pending (up to ``max_batch_rows`` rows), runs one
    ``engine.infer`` on the concatenation, and slices the result back
    per request. Arrival during an in-flight batch is the coalescing
    window — no artificial delay is ever inserted.
    """

    def __init__(self, engine, max_batch_rows: int = 65536,
                 submit_timeout: float | None = 120.0):
        self._engine = engine
        self._max_rows = int(max_batch_rows)
        self._submit_timeout = submit_timeout
        self._cond = threading.Condition()
        self._pending: list[dict] = []
        self._closed = False
        # Observability: served totals let tests/operators confirm
        # coalescing actually happens (batches < requests under load).
        self.requests_total = 0
        self.batches_total = 0
        self.rows_total = 0
        self._thread = threading.Thread(
            target=self._loop, name="tdn-serve-batcher", daemon=True
        )
        self._thread.start()

    def submit(self, x: np.ndarray,
               timeout: float | None = None) -> np.ndarray:
        """Block until this request's rows are served.

        ``timeout`` is the CALLER's remaining budget (the RPC deadline);
        the effective wait is ``min(timeout, submit_timeout)`` — there
        is no point holding a worker thread past the moment its client
        gave up.
        """
        from tpu_dist_nn.utils.errors import UnavailableError

        item = {"x": x, "done": threading.Event(), "out": None, "err": None,
                "abandoned": False}
        with self._cond:
            if self._closed:
                raise UnavailableError("server is shutting down")
            self._pending.append(item)
            self.requests_total += 1
            self._cond.notify()
        bounds = [t for t in (self._submit_timeout, timeout) if t is not None]
        wait = min(bounds) if bounds else None
        # Bounded wait: if the engine wedges mid-batch (the tunneled-TPU
        # hang mode), the gRPC worker thread must get back to the client
        # with DEADLINE_EXCEEDED instead of blocking forever — an
        # unbounded wait here would eventually strand every worker
        # thread and leave the server unable even to return errors.
        if not item["done"].wait(wait):
            from tpu_dist_nn.utils.errors import DeadlineExceededError

            # Mark abandoned under the lock so the consumer discards it
            # at pop time: without this, a long wedge accumulates dead
            # requests unboundedly and the recovered engine burns its
            # first launches computing rows nobody is waiting for.
            with self._cond:
                item["abandoned"] = True
            raise DeadlineExceededError(
                f"coalesced batch did not complete within {wait}s "
                "(engine wedged or request backlogged?)"
            )
        if item["err"] is not None:
            raise item["err"]
        return item["out"]

    def _loop(self) -> None:
        while True:
            with self._cond:
                while not self._pending and not self._closed:
                    self._cond.wait()
                if not self._pending and self._closed:
                    return
                batch, rows = [], 0
                while self._pending and (
                    not batch
                    or rows + len(self._pending[0]["x"]) <= self._max_rows
                ):
                    it = self._pending.pop(0)
                    if it["abandoned"]:  # caller timed out; don't compute
                        continue
                    rows += len(it["x"])
                    batch.append(it)
                if not batch:
                    continue
                self.rows_total += rows
            # Group by feature width: engines without a declared
            # input_dim cannot be pre-validated in the handler, and a
            # mixed-width concatenation would fail EVERY request in the
            # batch. One launch per width keeps each group's fate its
            # own — a wrong-width group gets the engine's dim error.
            groups: dict[tuple, list[dict]] = {}
            for it in batch:
                groups.setdefault(it["x"].shape[1:], []).append(it)
            for group in groups.values():
                self.batches_total += 1
                try:
                    xs = (
                        group[0]["x"]
                        if len(group) == 1
                        else np.concatenate([it["x"] for it in group], axis=0)
                    )
                    # Pad rows up to a power-of-two bucket: every
                    # distinct row count is a distinct jit shape, so
                    # unbucketed coalescing would recompile on nearly
                    # every batch (compile costs dwarf the launch
                    # overhead saved). Buckets cap the compiled-program
                    # set at log2(max_rows).
                    n = len(xs)
                    n_pad = 1 << (n - 1).bit_length() if n > 1 else 1
                    if n_pad != n:
                        xs = np.concatenate(
                            [xs, np.zeros((n_pad - n, *xs.shape[1:]), xs.dtype)]
                        )
                    out = np.asarray(self._engine.infer(xs))
                    ofs = 0
                    for it in group:
                        k = len(it["x"])
                        it["out"] = out[ofs:ofs + k]
                        ofs += k
                except Exception as e:  # noqa: BLE001 — per request
                    for it in group:
                        it["err"] = e
                finally:
                    for it in group:
                        it["done"].set()

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self._thread.join(timeout=10)


def _make_handler(engine, batcher: _Batcher | None):
    lock = threading.Lock()
    # Per-request width validation BEFORE coalescing: a bad request must
    # fail alone, not poison the shared batch it would have joined.
    expected_dim = getattr(getattr(engine, "model", None), "input_dim", None)

    def process(request_bytes: bytes, context) -> bytes:
        try:
            x = decode_matrix(request_bytes)
        except ValueError as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, f"bad Matrix: {e}")
        if (
            batcher is not None
            and expected_dim is not None
            and x.shape[1] != expected_dim
        ):
            # The reference's dim-check path (grpc_node.py:149-153),
            # message shape matching pipeline.pad_batch's error.
            context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                f"expected input of shape (N, {expected_dim}), got "
                f"{tuple(x.shape)}",
            )
        try:
            if batcher is not None:
                # Pass the RPC's remaining deadline so the worker never
                # waits for a client that already gave up.
                out = batcher.submit(x, timeout=context.time_remaining())
            else:
                with lock:
                    out = engine.infer(x)
        except Exception as e:  # noqa: BLE001 — map to status codes
            from tpu_dist_nn.utils.errors import (
                DeadlineExceededError,
                InvalidArgumentError,
                UnavailableError,
            )

            if isinstance(e, InvalidArgumentError):
                # The reference's dim-check path (grpc_node.py:149-153).
                context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
            if isinstance(e, DeadlineExceededError):
                # Batcher wait expired (wedged engine): the reference's
                # per-RPC timeout semantics (grpc_node.py:133).
                context.abort(grpc.StatusCode.DEADLINE_EXCEEDED, str(e))
            if isinstance(e, UnavailableError):
                # Engine torn down mid-flight: the reference's
                # dead-channel semantics (clients may retry elsewhere).
                context.abort(grpc.StatusCode.UNAVAILABLE, str(e))
            log.exception("inference failed")
            context.abort(grpc.StatusCode.INTERNAL, f"inference failed: {e}")
        return encode_matrix(np.asarray(out, np.float64))

    rpc = grpc.unary_unary_rpc_method_handler(
        process,
        request_deserializer=bytes,   # raw bytes in, our codec decodes
        response_serializer=bytes,
    )
    service = grpc.method_handlers_generic_handler(
        SERVICE_NAME, {"Process": rpc}
    )
    return service


def serve_engine(engine, port: int, *, max_workers: int = 10,
                 host: str = "0.0.0.0", coalesce: bool = True,
                 max_batch_rows: int = 65536, warm_rows: int = 0,
                 submit_timeout: float | None = 120.0):
    """Start a gRPC server bound to ``host:port``; returns
    ``(server, bound_port)`` (``port=0`` picks an ephemeral port;
    ``host="127.0.0.1"`` keeps self-checks off the network).

    ``max_workers=10`` is the reference's thread-pool size
    (``grpc_node.py:169``); unlimited message sizes match its client
    channel options (``run_grpc_inference.py:124-127``).

    ``coalesce=True`` (default) batches concurrent requests into shared
    device launches (:class:`_Batcher`; ``server.batcher`` exposes its
    counters); ``False`` restores the serialized one-request-at-a-time
    engine lock. ``server.stop()`` also shuts the batcher down.

    ``warm_rows > 0`` precompiles the coalescing bucket shapes (powers
    of two up to ``warm_rows``) before the port opens: each bucket is a
    distinct XLA program, and an unwarmed bucket pays its compile on
    the first unlucky request mix (~hundreds of ms) instead of at
    startup.

    ``submit_timeout`` bounds how long a coalescing gRPC worker waits
    for its batch (``None`` = forever): a wedged engine turns into
    DEADLINE_EXCEEDED for the affected requests instead of stranding
    every worker thread.
    """
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers),
        options=[
            ("grpc.max_send_message_length", -1),
            ("grpc.max_receive_message_length", -1),
        ],
    )
    batcher = (
        _Batcher(engine, max_batch_rows, submit_timeout) if coalesce else None
    )
    if coalesce and warm_rows > 0:
        # Bucket shapes only exist on the coalescing path; the lock
        # path forwards raw client shapes and would never hit them.
        dim = getattr(getattr(engine, "model", None), "input_dim", None)
        if dim is not None:
            n = 1
            while n <= warm_rows:
                engine.infer(np.zeros((n, dim)))
                n *= 2
    server.add_generic_rpc_handlers((_make_handler(engine, batcher),))
    bound = server.add_insecure_port(f"{host}:{port}")
    if bound == 0:
        if batcher is not None:
            batcher.close()
        raise OSError(f"could not bind gRPC server to port {port}")
    server.batcher = batcher
    if batcher is not None:
        # server.stop() must also stop the batcher thread (tests and
        # tdn up --serve call stop(), not a separate teardown hook) —
        # but only AFTER the grace drain: closing immediately would
        # turn in-flight RPCs that haven't reached submit() yet into
        # UNAVAILABLE during the window the caller asked to protect.
        inner_stop = server.stop

        def stop(grace=None):
            ev = inner_stop(grace)
            if grace:
                def _close_after_drain():
                    ev.wait()
                    batcher.close()

                threading.Thread(
                    target=_close_after_drain, daemon=True
                ).start()
            else:
                batcher.close()
            return ev

        server.stop = stop
    server.start()
    log.info("gRPC LayerService serving on :%d (wire-compatible with "
             "run_grpc_inference.py)%s", bound,
             " with request coalescing" if coalesce else "")
    return server, bound


class GrpcClient:
    """Minimal client for the Process RPC — the ``tdn infer --target``
    transport (the reference client's ``run_batch_inference`` analogue,
    ``run_grpc_inference.py:112-158``: one persistent channel, unlimited
    message sizes, float64 rows)."""

    def __init__(self, target: str, timeout: float = 30.0):
        self.target = target
        self.timeout = timeout
        self._channel = grpc.insecure_channel(
            target,
            options=[
                ("grpc.max_send_message_length", -1),
                ("grpc.max_receive_message_length", -1),
            ],
        )
        self._call = self._channel.unary_unary(
            PROCESS_METHOD,
            request_serializer=bytes,
            response_deserializer=bytes,
        )

    def process(self, x: np.ndarray) -> np.ndarray:
        reply = self._call(encode_matrix(np.asarray(x, np.float64)),
                           timeout=self.timeout)
        return decode_matrix(reply)

    def close(self) -> None:
        self._channel.close()
