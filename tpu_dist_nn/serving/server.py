"""gRPC serving endpoint, wire-compatible with the reference's client.

Runs the reference's one RPC — ``LayerService.Process(Matrix) ->
Matrix`` (``src/proto/dist_nn.proto:13-15``) — in front of an
:class:`~tpu_dist_nn.api.engine.Engine`, so a user of docker-dist-nn
can point their EXISTING client (``run_grpc_inference.py``) at
``tdn serve`` unchanged. The difference is behind the socket: the
reference answers by chaining nested gRPC hops through one container
per stage (``grpc_node.py:120-147``); here the whole pipeline is one
SPMD program on the mesh, so the request crosses exactly one
serialization boundary instead of ``2 x num_stages``.

Error parity (``grpc_node.py:149-158``): a wrong input width returns
``INVALID_ARGUMENT`` with the dim message; unexpected failures return
``INTERNAL``. gRPC concurrency mirrors the reference's 10-thread server
(``grpc_node.py:169``); compute itself serializes through the engine
(one mesh, one program — concurrent REQUESTS queue, exactly like the
reference's per-stage GIL-bound numpy).
"""

from __future__ import annotations

import logging
from concurrent import futures

import grpc
import numpy as np

from tpu_dist_nn.serving.wire import (
    PROCESS_METHOD,
    SERVICE_NAME,
    decode_matrix,
    encode_matrix,
)

log = logging.getLogger(__name__)


def _make_handler(engine):
    import threading

    lock = threading.Lock()

    def process(request_bytes: bytes, context) -> bytes:
        try:
            x = decode_matrix(request_bytes)
        except ValueError as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, f"bad Matrix: {e}")
        try:
            with lock:
                out = engine.infer(x)
        except Exception as e:  # noqa: BLE001 — map to status codes
            from tpu_dist_nn.utils.errors import InvalidArgumentError, UnavailableError

            if isinstance(e, InvalidArgumentError):
                # The reference's dim-check path (grpc_node.py:149-153).
                context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
            if isinstance(e, UnavailableError):
                # Engine torn down mid-flight: the reference's
                # dead-channel semantics (clients may retry elsewhere).
                context.abort(grpc.StatusCode.UNAVAILABLE, str(e))
            log.exception("inference failed")
            context.abort(grpc.StatusCode.INTERNAL, f"inference failed: {e}")
        return encode_matrix(np.asarray(out, np.float64))

    rpc = grpc.unary_unary_rpc_method_handler(
        process,
        request_deserializer=bytes,   # raw bytes in, our codec decodes
        response_serializer=bytes,
    )
    service = grpc.method_handlers_generic_handler(
        SERVICE_NAME, {"Process": rpc}
    )
    return service


def serve_engine(engine, port: int, *, max_workers: int = 10,
                 host: str = "0.0.0.0"):
    """Start a gRPC server bound to ``host:port``; returns
    ``(server, bound_port)`` (``port=0`` picks an ephemeral port;
    ``host="127.0.0.1"`` keeps self-checks off the network).

    ``max_workers=10`` is the reference's thread-pool size
    (``grpc_node.py:169``); unlimited message sizes match its client
    channel options (``run_grpc_inference.py:124-127``).
    """
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers),
        options=[
            ("grpc.max_send_message_length", -1),
            ("grpc.max_receive_message_length", -1),
        ],
    )
    server.add_generic_rpc_handlers((_make_handler(engine),))
    bound = server.add_insecure_port(f"{host}:{port}")
    if bound == 0:
        raise OSError(f"could not bind gRPC server to port {port}")
    server.start()
    log.info("gRPC LayerService serving on :%d (wire-compatible with "
             "run_grpc_inference.py)", bound)
    return server, bound


class GrpcClient:
    """Minimal client for the Process RPC — the ``tdn infer --target``
    transport (the reference client's ``run_batch_inference`` analogue,
    ``run_grpc_inference.py:112-158``: one persistent channel, unlimited
    message sizes, float64 rows)."""

    def __init__(self, target: str, timeout: float = 30.0):
        self.target = target
        self.timeout = timeout
        self._channel = grpc.insecure_channel(
            target,
            options=[
                ("grpc.max_send_message_length", -1),
                ("grpc.max_receive_message_length", -1),
            ],
        )
        self._call = self._channel.unary_unary(
            PROCESS_METHOD,
            request_serializer=bytes,
            response_deserializer=bytes,
        )

    def process(self, x: np.ndarray) -> np.ndarray:
        reply = self._call(encode_matrix(np.asarray(x, np.float64)),
                           timeout=self.timeout)
        return decode_matrix(reply)

    def close(self) -> None:
        self._channel.close()
