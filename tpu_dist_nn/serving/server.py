"""gRPC serving endpoint, wire-compatible with the reference's client.

Runs the reference's one RPC — ``LayerService.Process(Matrix) ->
Matrix`` (``src/proto/dist_nn.proto:13-15``) — in front of an
:class:`~tpu_dist_nn.api.engine.Engine`, so a user of docker-dist-nn
can point their EXISTING client (``run_grpc_inference.py``) at
``tdn serve`` unchanged. The difference is behind the socket: the
reference answers by chaining nested gRPC hops through one container
per stage (``grpc_node.py:120-147``); here the whole pipeline is one
SPMD program on the mesh, so the request crosses exactly one
serialization boundary instead of ``2 x num_stages``.

Concurrency: the reference overlaps concurrent requests only through
its 10-thread server pool, each request traversing the whole pipeline
alone (``grpc_node.py:169``). Here concurrent requests COALESCE: a
:class:`_Batcher` thread owns the device, and every request that
arrives while a batch is in flight joins the next one — rows from many
clients fuse into one padded device batch and split on reply. Under
load the device sees a few large launches instead of many one-row
launches (aggregate throughput scales with the coalesced batch size);
an idle server dispatches immediately, adding zero latency.

Error parity (``grpc_node.py:149-158``): a wrong input width returns
``INVALID_ARGUMENT`` with the dim message — validated per request
BEFORE coalescing so one bad client cannot poison a shared batch;
unexpected failures return ``INTERNAL``.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from concurrent import futures

import grpc
import numpy as np

from tpu_dist_nn.obs import trace as _trace
from tpu_dist_nn.obs.log import get_logger
from tpu_dist_nn.obs.registry import POW2_BUCKETS, REGISTRY
from tpu_dist_nn.serving.sched_core import SchedCore, normalize_class
from tpu_dist_nn.serving.stream import note_stream_resumed
from tpu_dist_nn.serving.wire import (
    CLASS_HEADER,
    GENERATE_METHOD,
    GENERATE_STREAM_METHOD,
    PROCESS_METHOD,
    RETRY_AFTER_HEADER,
    SERVICE_NAME,
    SESSION_HEADER,
    STREAM_RESUME_HEADER,
    STREAM_RESUME_MAX_TOKENS,
    WireMatrix,
    decode_frame,
    decode_matrix,
    decode_matrix_lazy,
    encode_matrix,
    encode_end_frame,
    encode_token_frame,
)

log = logging.getLogger(__name__)
# Structured channel for the operational events a log pipeline matches
# on (server.start, client.rpc_failed, ...): trace-correlated JSON
# records under `tdn --log-json`, readable key=value lines otherwise.
slog = get_logger(__name__)

# Serving metric families (docs/OBSERVABILITY.md catalog). All updates
# are host-side float adds — never a device touch on the hot path.
_RPC_REQUESTS = REGISTRY.counter(
    "tdn_rpc_requests_total", "RPCs received, per method",
    labels=("method",),
)
_RPC_ERRORS = REGISTRY.counter(
    "tdn_rpc_errors_total", "RPCs aborted, per method and status code",
    labels=("method", "code"),
)
_BATCH_ROWS = REGISTRY.histogram(
    "tdn_batch_rows", "coalesced rows per device launch (pre-padding)",
    labels=("method",), buckets=POW2_BUCKETS,
)
_SUBMITS = REGISTRY.counter(
    "tdn_batcher_submits_total", "requests entering the coalescing queue",
    labels=("method",),
)
_ABANDONED = REGISTRY.counter(
    "tdn_batcher_abandoned_total",
    "requests that timed out waiting for their batch",
    labels=("method",),
)
_LAUNCHES = REGISTRY.counter(
    "tdn_batch_launches_total", "device launches issued by the batcher",
    labels=("method",),
)
# tdn_batcher_shed_total / tdn_batch_wait_seconds and the class-labeled
# admission families moved to serving/sched_core.py — the ONE
# admission/shed/close implementation both schedulers rebase on.


class _Batcher:
    """Two-stage (double-buffered) micro-batching pipeline in front of
    one engine.

    ``submit(x)`` blocks the calling (gRPC worker) thread until its
    rows' results are ready. Two daemon threads own the device path:

    * **dispatch** grabs everything pending (up to ``max_batch_rows``
      rows), stages it into a reusable per-bucket host buffer (rows
      copied in, pad tail zeroed in place — no per-batch
      ``np.concatenate`` + ``np.zeros`` allocation), and LAUNCHES it
      (``engine.infer_async`` where the engine has one — JAX async
      dispatch returns a device handle without a host sync).
    * **drain** materializes launched batches in order (the one host
      sync per batch), slices the result back per request, and fans
      out to the waiting workers.

    So batch N+1 is assembled, padded, and launched while batch N's
    device result is still materializing — host serialization overlaps
    device execution instead of extending the launch critical section.
    ``pipeline_depth=1`` collapses to the old strictly-serial loop
    (dispatch fetches inline; the A/B arm ``bench.py --overlap``
    measures against). Arrival during an in-flight batch remains the
    coalescing window — no artificial delay is ever inserted.
    """

    def __init__(self, engine, max_batch_rows: int = 65536,
                 submit_timeout: float | None = 120.0, run_fn=None,
                 method: str = "Process", pipeline_depth: int = 2,
                 max_pending_rows: int | None = None, account_fn=None,
                 class_watermarks: dict | None = None):
        self._engine = engine
        # The device launch the batcher owns, split into the dispatch
        # half (launch, ideally non-blocking) and the fetch half (the
        # host sync). engine.infer_async/fetch when available; any
        # ``rows (n, ...) -> rows (n, ...)`` closure otherwise (the LM
        # generation endpoint passes its decode runner — returning a
        # device array from it buys the same overlap) — coalescing,
        # bucketing, abandonment, and error fan-out are identical.
        # An engine whose infer_async takes ``useful_rows`` gets the
        # pre-padding row count declared per launch, so the goodput
        # plane (obs/goodput.py) books bucket pad exactly; fakes with a
        # plain one-arg infer_async keep working (signature-probed).
        self._useful_aware = False
        if run_fn is not None:
            self._dispatch_fn, self._fetch_fn = run_fn, np.asarray
        elif hasattr(engine, "infer_async") and hasattr(engine, "fetch"):
            self._dispatch_fn, self._fetch_fn = engine.infer_async, engine.fetch
            try:
                import inspect

                self._useful_aware = "useful_rows" in inspect.signature(
                    engine.infer_async
                ).parameters
            except (TypeError, ValueError):
                pass
        else:
            self._dispatch_fn, self._fetch_fn = engine.infer, np.asarray
        # Post-fetch accounting seam: called with (materialized output,
        # useful_rows, launched_rows) after each successful drain — the
        # static Generate path's goodput hook (EOS positions are only
        # visible in the materialized sequences). Must never fail a
        # request; exceptions are swallowed to a log line.
        self._account_fn = account_fn
        # Whether the accounting seam takes the dead-waiter row count
        # (rows whose caller abandoned mid-flight — goodput books them
        # as pad, not useful). Signature-probed so older account fakes
        # keep working.
        self._account_dead_aware = False
        if account_fn is not None:
            try:
                import inspect

                self._account_dead_aware = "dead_rows" in inspect.signature(
                    account_fn
                ).parameters
            except (TypeError, ValueError):
                pass
        self._max_rows = int(max_batch_rows)
        # The admission/shed/close/drain contract lives in the shared
        # scheduling core (serving/sched_core.py): pending queue +
        # rows ledger under core.cond, class watermarks, deadline
        # expiry, close-failover sweep. The dispatch loop below holds
        # core.cond exactly where it held its own condition before.
        self._core = SchedCore(
            method, max_pending_rows=max_pending_rows,
            submit_timeout=submit_timeout,
            class_watermarks=class_watermarks,
        )
        self._cond = self._core.cond
        self._serial = pipeline_depth <= 1
        # Launched-but-not-drained hand-off. The SEMAPHORE is the
        # launch-ahead bound — dispatch takes a slot BEFORE staging or
        # launching, drain returns it after the fetch, so at most
        # pipeline_depth batches of device work (and staging buffers)
        # are ever outstanding (depth 2 = classic double buffering).
        # Bounding the queue instead would be off by one: dispatch
        # would launch, THEN block on put.
        self._launched: queue.Queue = queue.Queue()
        self._slots = threading.Semaphore(max(1, pipeline_depth))
        # Reusable staging buffers, keyed (bucket, feature-shape,
        # dtype) -> free list. Dispatch pops (sole consumer), drain
        # returns a buffer only AFTER its batch's fetch completed —
        # so a backend that zero-copy-aliases host memory into device
        # buffers can never see a staging buffer mutate mid-flight.
        self._staging: dict[tuple, list[np.ndarray]] = {}
        self._staging_keep = max(2, pipeline_depth)
        # Observability: served totals let tests/operators confirm
        # coalescing actually happens (batches < requests under load).
        # requests/shed/pending ride the core (delegating properties
        # below keep the legacy attribute names the sampler and tests
        # read).
        self.batches_total = 0
        self.rows_total = 0
        # Launches issued while a previously launched batch had not
        # finished draining — the overlap evidence
        # (tdn_batcher_overlap_ratio = overlapped_total/batches_total).
        self.overlapped_total = 0
        # Rows launched and not yet drained (the runtime sampler's
        # in-flight gauge reads this attribute); with pipelining this
        # can span up to pipeline_depth batches.
        self.inflight_rows = 0
        self.inflight_batches = 0
        self._stats_lock = threading.Lock()
        self.method = method
        # Pre-bound registry children: the hot path does a float add,
        # not a label lookup.
        self._m_submits = _SUBMITS.labels(method=method)
        self._m_abandoned = _ABANDONED.labels(method=method)
        self._m_launches = _LAUNCHES.labels(method=method)
        self._m_rows = _BATCH_ROWS.labels(method=method)
        self._dispatch_thread = threading.Thread(
            target=self._dispatch_loop, name="tdn-serve-dispatch", daemon=True
        )
        self._drain_thread = None
        if not self._serial:
            self._drain_thread = threading.Thread(
                target=self._drain_loop, name="tdn-serve-drain", daemon=True
            )
            self._drain_thread.start()
        self._dispatch_thread.start()

    # Legacy counter/queue surface, now owned by the shared core (the
    # runtime sampler, drain plumbing, and the resilience tests read
    # these names).
    @property
    def pending_rows(self) -> int:
        return self._core.pending_rows

    @property
    def requests_total(self) -> int:
        return self._core.requests_total

    @property
    def shed_total(self) -> int:
        return self._core.shed_total

    @property
    def expired_total(self) -> int:
        return self._core.expired_total

    @property
    def _pending(self) -> list:
        return self._core.pending_items()

    @property
    def _closed(self) -> bool:
        return self._core.closed

    def queue_depth(self) -> int:
        """Entries queued (lock-free; the runtime sampler's per-tick
        read — the `_pending` property above copies the whole queue
        under the admission lock and exists for tests)."""
        return self._core.queue_depth()

    def pending_by_class(self) -> dict:
        return self._core.pending_by_class()

    def submit(self, x: np.ndarray,
               timeout: float | None = None,
               ctx=None, slo_class: str = "standard") -> np.ndarray:
        """Block until this request's rows are served.

        ``timeout`` is the CALLER's remaining budget (the RPC deadline);
        the effective wait is ``min(timeout, submit_timeout)`` — there
        is no point holding a worker thread past the moment its client
        gave up. The same budget is the entry's queue DEADLINE: if it
        expires before dispatch stages the entry, the entry fails
        DEADLINE_EXCEEDED without riding a launch.

        ``slo_class`` (``critical``/``standard``/``best_effort``, the
        ``x-tdn-class`` header) sets the entry's queue priority and
        shed watermark (docs/ROBUSTNESS.md "Degradation ladder").

        ``ctx`` is the request's :class:`~tpu_dist_nn.obs.trace
        .SpanContext`: when sampled, this entry's passage through the
        pipeline is recorded as queue_wait / stage / launch / fetch
        spans under it (each batch-level stage appears once per member
        request, so every trace tree is complete on its own).
        """
        item = {"x": x, "done": threading.Event(), "out": None, "err": None,
                "abandoned": False, "slo_class": slo_class,
                "t_submit": time.monotonic(),
                # Only a SAMPLED context is worth carrying: the per-item
                # skip below is then one None check.
                "ctx": ctx if ctx is not None and ctx.sampled else None}
        self._core.admit(item, timeout)
        self._m_submits.inc()
        try:
            self._core.wait(item, what="coalesced batch")
        except Exception:
            if item["abandoned"]:
                self._m_abandoned.inc()
            raise
        return item["out"]

    def _stage(self, group: list[dict]):
        """Assemble a width-group into a pow2-bucket staging buffer.

        Pads rows up to a power-of-two bucket: every distinct row count
        is a distinct jit shape, so unbucketed coalescing would
        recompile on nearly every batch (compile costs dwarf the launch
        overhead saved). Buckets cap the compiled-program set at
        log2(max_rows). Returns ``(xs, key, buf)``; ``buf`` is None on
        the zero-copy single-request fast path (a lone request already
        ON a bucket boundary launches the caller's array directly).
        """
        n = sum(len(it["x"]) for it in group)
        n_pad = 1 << (n - 1).bit_length() if n > 1 else 1
        if (len(group) == 1 and n == n_pad
                and not isinstance(group[0]["x"], WireMatrix)):
            return group[0]["x"], None, None
        feat = tuple(group[0]["x"].shape[1:])
        dtype = group[0]["x"].dtype
        key = (n_pad, feat, str(dtype))
        pool = self._staging.get(key)
        buf = pool.pop() if pool else None
        if buf is None:
            buf = np.empty((n_pad, *feat), dtype)
        ofs = 0
        for it in group:
            x = it["x"]
            k = len(x)
            if isinstance(x, WireMatrix):
                # Decode-into-staging: the request's payload goes wire
                # bytes -> this bucket buffer in ONE cast-copy (the
                # handler only probed the structure; nothing was
                # materialized in between).
                x.read_into(buf, ofs)
            else:
                buf[ofs:ofs + k] = x
            ofs += k
        if ofs < n_pad:
            buf[ofs:] = 0  # zero the pad tail in place
        return buf, key, buf

    def _release(self, key, buf) -> None:
        """Drain-side buffer return (after the fetch — the batch's
        device input can no longer alias it). Single producer (drain) /
        single consumer (dispatch) per list, so GIL-atomic list ops
        suffice; the pool keeps at most pipeline_depth buffers per
        bucket, the steady-state working set."""
        if buf is None:
            return
        pool = self._staging.setdefault(key, [])
        if len(pool) < self._staging_keep:
            pool.append(buf)

    def _drain_one(self, group, handle, key, buf, launched_rows) -> None:
        """Fetch one launched batch and fan results out per request."""
        t_fetch = time.monotonic()
        err = None
        notes: list = []
        traced = any(it["ctx"] is not None for it in group)
        try:
            if traced:
                with _trace.annotation_sink() as notes:
                    out = self._fetch_fn(handle)
            else:
                out = self._fetch_fn(handle)
            # Per-row integrity verdict (engine.fetch stashes a bad-row
            # mask on the launch handle when the numeric guard tripped):
            # only the requests whose rows are corrupt fail — with
            # INTEGRITY, not INTERNAL — and every other request in the
            # same coalesced launch ships its slice bit-identical.
            bad = getattr(handle, "bad_rows", None)
            ofs = 0
            for it in group:
                k = len(it["x"])
                if bad is not None and bad[ofs:ofs + k].any():
                    from tpu_dist_nn.utils.errors import IntegrityError

                    it["err"] = IntegrityError(
                        f"numeric guard: {int(bad[ofs:ofs + k].sum())} "
                        f"of this request's {k} rows carried non-finite "
                        f"or out-of-magnitude activations"
                    )
                else:
                    it["out"] = out[ofs:ofs + k]
                ofs += k
            if self._account_fn is not None:
                # Post-fetch goodput accounting (static Generate path:
                # EOS-frozen positions only exist in the materialized
                # sequences). Best-effort — accounting must never fail
                # a request that already has its result. Rows whose
                # waiter abandoned AFTER dispatch popped them (the one
                # window deadline expiry cannot close) are declared as
                # dead: goodput books the launch they rode as pad, not
                # useful (reason dead_waiter).
                try:
                    if self._account_dead_aware:
                        dead = sum(
                            len(it["x"]) for it in group if it["abandoned"]
                        )
                        self._account_fn(out, ofs, launched_rows,
                                         dead_rows=dead)
                    else:
                        self._account_fn(out, ofs, launched_rows)
                except Exception:  # noqa: BLE001 — accounting only
                    log.exception("goodput accounting failed")
        except Exception as e:  # noqa: BLE001 — per request
            err = e
            for it in group:
                it["err"] = e
        finally:
            dur = time.monotonic() - t_fetch
            if err is not None:
                notes = notes + [
                    (time.monotonic(), f"error: {type(err).__name__}: {err}")
                ]
            for it in group:
                if it["ctx"] is not None:
                    # The one host sync of the request's batch — the
                    # span that separates "device was slow" from "queue
                    # was long" in a trace.
                    _trace.TRACER.record_span(
                        "fetch", it["ctx"], t_fetch, dur,
                        attrs={"rows": len(it["x"]),
                               "batch_rows": launched_rows},
                        annotations=notes,
                    )
            with self._stats_lock:
                self.inflight_batches -= 1
                self.inflight_rows -= launched_rows
            if err is None:
                # Completions feed the drain-rate window behind the
                # shed replies' x-tdn-retry-after-ms hint.
                self._core.note_drained(
                    sum(len(it["x"]) for it in group)
                )
            self._release(key, buf)
            self._slots.release()
            for it in group:
                it["done"].set()

    def _dispatch_loop(self) -> None:
        core = self._core
        while True:
            with core.cond:
                while not core.has_pending() and not core.closed:
                    core.cond.wait()
                if not core.has_pending() and core.closed:
                    if not self._serial:
                        self._launched.put(None)  # drain's shutdown pill
                    return
                # Class-priority pop (critical first, FIFO within a
                # class); abandoned entries are discarded and
                # budget-expired ones failed DEADLINE_EXCEEDED here —
                # neither rides the launch.
                batch, rows = core.pop_group(self._max_rows)
                self.rows_total += rows
            core.drain_deferred()
            if not batch:
                continue
            # Queue wait ends the moment the dispatch stage owns the
            # request (recorded outside the condition lock — tracing
            # must not extend the producers' critical section).
            t_pop = time.monotonic()
            for it in batch:
                if it["ctx"] is not None:
                    _trace.TRACER.record_span(
                        "queue_wait", it["ctx"], it["t_submit"],
                        t_pop - it["t_submit"],
                    )
            # Group by feature width: engines without a declared
            # input_dim cannot be pre-validated in the handler, and a
            # mixed-width concatenation would fail EVERY request in the
            # batch. One launch per width keeps each group's fate its
            # own — a wrong-width group gets the engine's dim error.
            groups: dict[tuple, list[dict]] = {}
            for it in batch:
                groups.setdefault(
                    (it["x"].shape[1:], str(it["x"].dtype)), []
                ).append(it)
            for group in groups.values():
                # Take the launch-ahead slot BEFORE staging/launching:
                # the back-pressure that keeps dispatch honest (blocks
                # here when pipeline_depth batches are outstanding).
                self._slots.acquire()
                key = buf = None
                traced = [it for it in group if it["ctx"] is not None]
                group_rows = sum(len(it["x"]) for it in group)

                def _launch(xs):
                    # Goodput declaration: the engine books this
                    # launch's bucket-pad rows (bucket - useful) as pad
                    # FLOPs under path="batcher" (obs/goodput.py).
                    if self._useful_aware:
                        return self._dispatch_fn(
                            xs, useful_rows=group_rows
                        )
                    return self._dispatch_fn(xs)

                try:
                    t_stage = time.monotonic()
                    xs, key, buf = self._stage(group)
                    t_launch = time.monotonic()
                    if traced:
                        # Collect engine-side annotations (async
                        # dispatch, compile-cache misses) emitted while
                        # the launch runs; they attach to every member
                        # request's launch span below.
                        with _trace.annotation_sink() as notes:
                            handle = _launch(xs)
                    else:
                        handle = _launch(xs)
                    t_launched = time.monotonic()
                    for it in traced:
                        _trace.TRACER.record_span(
                            "stage", it["ctx"], t_stage, t_launch - t_stage,
                            attrs={"rows": len(it["x"]),
                                   "batch_rows": len(xs),
                                   "zero_copy": buf is None},
                        )
                        _trace.TRACER.record_span(
                            "launch", it["ctx"], t_launch,
                            t_launched - t_launch,
                            attrs={"batch_rows": len(xs)},
                            annotations=notes,
                        )
                except Exception as e:  # noqa: BLE001 — per request
                    # Dispatch-time failure (validation, trace error):
                    # fail the group here — it never reached the device,
                    # so the launch counters do NOT tick (a down engine
                    # must not render as healthy launch activity on the
                    # exact scrape diagnosing it).
                    self._release(key, buf)
                    self._slots.release()
                    for it in group:
                        it["err"] = e
                        it["done"].set()
                    continue
                self.batches_total += 1
                self._m_launches.inc()
                # tdn_batch_rows keeps the pre-padding count — the
                # useful-rows view; inflight_rows below reports what
                # the device is actually running.
                self._m_rows.observe(group_rows)
                with self._stats_lock:
                    if self.inflight_batches:
                        # A prior batch is still materializing while
                        # this one launched: that IS the overlap.
                        self.overlapped_total += 1
                    self.inflight_batches += 1
                    self.inflight_rows += len(xs)
                if self._serial:
                    self._drain_one(group, handle, key, buf, len(xs))
                else:
                    self._launched.put((group, handle, key, buf, len(xs)))

    def _drain_loop(self) -> None:
        while True:
            item = self._launched.get()
            if item is None:
                return
            self._drain_one(*item)

    def close(self, timeout: float = 10.0) -> None:
        self._core.close_begin()
        # Dispatch drains the queue then pills the drain queue; drain
        # finishes every launched batch before exiting — both stages
        # empty by the time close returns. Anything STILL pending (a
        # wedged dispatch never popped it) is failed over UNAVAILABLE
        # by the core's sweep, so its waiters don't sit out their full
        # submit timeout against a batcher that is already gone.
        self._dispatch_thread.join(timeout=timeout)
        if self._drain_thread is not None:
            self._drain_thread.join(timeout=timeout)
        self._core.sweep_leftovers()


def _request_span(context, method: str):
    """Begin the handler span for one RPC and derive its wait budget.

    Honors an inbound ``x-tdn-trace`` header (the remote parent makes
    this handler a child in the caller's trace — and inherits the
    caller's sampling decision); without one this is a new locally
    sampled root. Always names the trace back to the caller in
    trailing metadata so a failed RPC tells the client which trace to
    pull from ``/trace``. Returns ``(span, budget_seconds, metadata)``
    where the budget is ``min(grpc deadline remaining, x-tdn-timeout-ms
    hint)`` — whichever bounds exist — and ``metadata`` is the parsed
    invocation-metadata dict (the router reads ``x-tdn-session`` from
    it; engine handlers ignore it).
    """
    md = {}
    try:
        for k, v in context.invocation_metadata() or ():
            md[k] = v
    except Exception:  # noqa: BLE001 — tracing must never fail an RPC
        pass
    parent = _trace.SpanContext.from_header(md.get(_trace.TRACE_HEADER))
    span = _trace.TRACER.start(f"rpc.{method}", parent=parent)
    base_trailing = ((_trace.TRACE_ID_HEADER, span.ctx.trace_id),)
    try:
        # Stashed so a later abort path (shed replies' retry-after
        # hint) can EXTEND the trailing metadata instead of replacing
        # the trace id — set_trailing_metadata's last call wins.
        context._tdn_trailing = base_trailing
        context.set_trailing_metadata(base_trailing)
    except Exception:  # noqa: BLE001 — in-process fakes may not have it
        pass
    bounds = []
    try:
        rem = context.time_remaining()
        # Deadline-less calls can report a far-future sentinel (~1e10 s)
        # instead of None; a "budget" measured in centuries is no bound
        # at all and overflows condition waits downstream.
        if rem is not None and rem < 1e9:
            bounds.append(rem)
    except Exception:  # noqa: BLE001
        pass
    hint = md.get(_trace.TIMEOUT_HEADER)
    if hint is not None:
        try:
            bounds.append(float(hint) / 1000.0)
        except ValueError:
            pass  # a garbled hint must not fail the RPC
    return span, (min(bounds) if bounds else None), md


def _abort(context, method: str, code, message: str):
    """Count, then abort: context.abort raises, so the error counter
    must tick first (one funnel for every handler's abort)."""
    _RPC_ERRORS.labels(method=method, code=code.name).inc()
    context.abort(code, message)


def _abort_for_exception(context, e, what: str, method: str = "Process"):
    """Map framework exceptions to the reference's gRPC status taxonomy
    (grpc_node.py:149-158) — ONE mapping for every method so a new
    status cannot land in Process and miss Generate."""
    from tpu_dist_nn.utils.errors import (
        DeadlineExceededError,
        IntegrityError,
        InvalidArgumentError,
        ResourceExhaustedError,
        UnavailableError,
    )

    if isinstance(e, InvalidArgumentError):
        # The reference's dim-check path (grpc_node.py:149-153).
        _abort(context, method, grpc.StatusCode.INVALID_ARGUMENT, str(e))
    if isinstance(e, IntegrityError):
        # A correctness check refused to ship the answer: DATA_LOSS —
        # deliberately NOT in the transient-retry set, so a direct
        # client never retries the same weights; the router gives it
        # failover-to-a-DIFFERENT-replica semantics plus an integrity
        # strike toward quarantine (docs/ROBUSTNESS.md).
        _abort(context, method, grpc.StatusCode.DATA_LOSS, str(e))
    if isinstance(e, DeadlineExceededError):
        # Batcher wait expired (wedged engine): the reference's
        # per-RPC timeout semantics (grpc_node.py:133).
        _abort(context, method, grpc.StatusCode.DEADLINE_EXCEEDED, str(e))
    if isinstance(e, ResourceExhaustedError):
        # Admission-control shed: the queue is at its watermark — the
        # server is healthy and asking this client to back off. The
        # reply names HOW LONG in x-tdn-retry-after-ms (derived from
        # the current drain rate — serving/sched_core.py), which
        # RetryPolicy honors as its backoff floor so a shed storm
        # cannot re-synchronize into a hot-retry storm.
        retry_after = getattr(e, "retry_after_ms", None)
        if retry_after is not None:
            try:
                context.set_trailing_metadata(
                    tuple(getattr(context, "_tdn_trailing", ()))
                    + ((RETRY_AFTER_HEADER, str(int(retry_after))),)
                )
            except Exception:  # noqa: BLE001 — fakes without metadata
                pass
        _abort(context, method, grpc.StatusCode.RESOURCE_EXHAUSTED, str(e))
    if isinstance(e, UnavailableError):
        # Engine torn down mid-flight: the reference's dead-channel
        # semantics (clients may retry elsewhere).
        _abort(context, method, grpc.StatusCode.UNAVAILABLE, str(e))
    slog.exception("rpc.internal_error", method=method, what=what,
                   error=f"{type(e).__name__}: {e}")
    _abort(context, method, grpc.StatusCode.INTERNAL, f"{what} failed: {e}")


def _new_grpc_server(max_workers: int, interceptors=()):
    """The reference's server shape: thread pool + unlimited messages
    (grpc_node.py:169, run_grpc_inference.py:124-127). ``interceptors``
    is the fault-injection seam (testing/faults.FaultInterceptor) —
    empty in production."""
    return grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers),
        options=[
            ("grpc.max_send_message_length", -1),
            ("grpc.max_receive_message_length", -1),
        ],
        interceptors=tuple(interceptors),
    )


def _bind_or_close(server, host: str, port: int, batcher) -> int:
    bound = server.add_insecure_port(f"{host}:{port}")
    if bound == 0:
        if batcher is not None:
            batcher.close()
        raise OSError(f"could not bind gRPC server to port {port}")
    return bound


def _wrap_server_stop(server, batcher) -> None:
    """server.stop() must also stop the batcher thread (tests and the
    CLI call stop(), not a separate teardown hook) — but only AFTER the
    grace drain: closing immediately would turn in-flight RPCs that
    haven't reached submit() yet into UNAVAILABLE during the window the
    caller asked to protect."""
    if batcher is None:
        return
    inner_stop = server.stop

    def stop(grace=None):
        ev = inner_stop(grace)
        if grace:
            def _close_after_drain():
                ev.wait()
                batcher.close()

            threading.Thread(target=_close_after_drain, daemon=True).start()
        else:
            batcher.close()
        return ev

    server.stop = stop


def _engine_wire_dtype(engine):
    """The dtype the decoder should land rows in: the engine's own
    compute dtype where it declares one (the float64 wire contract
    stops at the socket — decoding straight to the engine dtype kills
    the (N, D) float64 intermediate), float64 otherwise."""
    dt = getattr(engine, "dtype", None)
    if dt is None:
        return np.float64
    try:
        return np.dtype(dt)
    except TypeError:
        return np.float64


def _make_handler(engine, batcher: _Batcher | None):
    lock = threading.Lock()
    # Per-request width validation BEFORE coalescing: a bad request must
    # fail alone, not poison the shared batch it would have joined.
    expected_dim = getattr(getattr(engine, "model", None), "input_dim", None)
    wire_dtype = _engine_wire_dtype(engine)

    def process(request_bytes: bytes, context) -> bytes:
        _RPC_REQUESTS.labels(method="Process").inc()
        span, budget, md = _request_span(context, "Process")
        # SLO class rides x-tdn-class (missing/unknown -> standard):
        # queue priority + shed watermark in the scheduling core.
        slo_class = normalize_class(md.get(CLASS_HEADER))
        try:
            try:
                # Structure probe only on the fast path: a WireMatrix
                # carries shape/width for validation while the payload
                # stays untouched until the batcher lands it directly
                # in a staging buffer (one cast-copy end-to-end). The
                # fallback (non-uniform layout) decodes fully here.
                with _trace.TRACER.span("decode", span.ctx):
                    x = decode_matrix_lazy(request_bytes, dtype=wire_dtype)
            except ValueError as e:
                span.annotate(f"abort INVALID_ARGUMENT: bad Matrix: {e}")
                _abort(context, "Process", grpc.StatusCode.INVALID_ARGUMENT,
                       f"bad Matrix: {e}")
            span.set("rows", len(x))
            # Capture-completeness attrs (ISSUE 18): a bundle's root
            # span alone must be a replayable request.
            _annotate_capture_attrs(span, md, slo_class, budget)
            span.set("dim", int(x.shape[1]))
            if (
                batcher is not None
                and expected_dim is not None
                and x.shape[1] != expected_dim
            ):
                # The reference's dim-check path (grpc_node.py:149-153),
                # message shape matching pipeline.pad_batch's error.
                span.annotate("abort INVALID_ARGUMENT: width mismatch")
                _abort(
                    context, "Process", grpc.StatusCode.INVALID_ARGUMENT,
                    f"expected input of shape (N, {expected_dim}), got "
                    f"{tuple(x.shape)}",
                )
            try:
                if batcher is not None:
                    # Pass the RPC's remaining budget (deadline and/or
                    # client hint) so the worker never waits for a
                    # client that already gave up; the span context
                    # rides the pending entry through the pipeline.
                    out = batcher.submit(x, timeout=budget, ctx=span.ctx,
                                         slo_class=slo_class)
                else:
                    with lock, _trace.TRACER.activate(span):
                        out = engine.infer(x)
            except Exception as e:  # noqa: BLE001 — map to status codes
                span.annotate(f"error: {type(e).__name__}: {e}")
                _abort_for_exception(context, e, "inference", "Process")
            with _trace.TRACER.span("encode", span.ctx):
                # Engine-dtype result straight into the codec: the cast
                # to wire float64 lands per-stripe in the encode buffer
                # (the old np.asarray(out, np.float64) full-matrix
                # materialization is gone).
                return encode_matrix(out)
        finally:
            span.end()

    rpc = grpc.unary_unary_rpc_method_handler(
        process,
        request_deserializer=bytes,   # raw bytes in, our codec decodes
        response_serializer=bytes,
    )
    service = grpc.method_handlers_generic_handler(
        SERVICE_NAME, {"Process": rpc}
    )
    return service


def serve_engine(engine, port: int, *, max_workers: int = 10,
                 host: str = "0.0.0.0", coalesce: bool = True,
                 max_batch_rows: int = 65536, warm_rows: int = 0,
                 submit_timeout: float | None = 120.0,
                 pipeline_depth: int = 2,
                 max_pending_rows: int | None = None,
                 class_watermarks: dict | None = None,
                 interceptors=()):
    """Start a gRPC server bound to ``host:port``; returns
    ``(server, bound_port)`` (``port=0`` picks an ephemeral port;
    ``host="127.0.0.1"`` keeps self-checks off the network).

    ``max_workers=10`` is the reference's thread-pool size
    (``grpc_node.py:169``); unlimited message sizes match its client
    channel options (``run_grpc_inference.py:124-127``).

    ``coalesce=True`` (default) batches concurrent requests into shared
    device launches (:class:`_Batcher`; ``server.batcher`` exposes its
    counters); ``False`` restores the serialized one-request-at-a-time
    engine lock. ``server.stop()`` also shuts the batcher down.

    ``warm_rows > 0`` precompiles the coalescing bucket shapes (powers
    of two up to ``warm_rows``) before the port opens: each bucket is a
    distinct XLA program, and an unwarmed bucket pays its compile on
    the first unlucky request mix (~hundreds of ms) instead of at
    startup.

    ``submit_timeout`` bounds how long a coalescing gRPC worker waits
    for its batch (``None`` = forever): a wedged engine turns into
    DEADLINE_EXCEEDED for the affected requests instead of stranding
    every worker thread.

    ``pipeline_depth`` sets the batcher's launch-ahead window (2 =
    double-buffered default: batch N+1 stages and launches while batch
    N materializes; 1 = the strictly serial legacy loop, kept as the
    A/B control arm for ``bench.py --overlap``).

    ``max_pending_rows`` is the admission-control watermark (``tdn up
    --max-pending-rows``): a submit that would queue past it is shed
    with RESOURCE_EXHAUSTED instead of joining an unbounded backlog
    (None = unbounded, the legacy behavior). ``class_watermarks``
    overrides the per-SLO-class shed fractions of that watermark
    (``tdn up --class-watermarks``; docs/ROBUSTNESS.md "Degradation
    ladder"). ``interceptors`` are gRPC server interceptors — the
    fault-injection seam (:mod:`tpu_dist_nn.testing.faults`).
    """
    server = _new_grpc_server(max_workers, interceptors)
    batcher = (
        _Batcher(engine, max_batch_rows, submit_timeout,
                 pipeline_depth=pipeline_depth,
                 max_pending_rows=max_pending_rows,
                 class_watermarks=class_watermarks)
        if coalesce else None
    )
    if coalesce and warm_rows > 0:
        # Bucket shapes only exist on the coalescing path; the lock
        # path forwards raw client shapes and would never hit them.
        if hasattr(engine, "warm_buckets"):
            engine.warm_buckets(warm_rows)
        else:
            dim = getattr(getattr(engine, "model", None), "input_dim", None)
            if dim is not None:
                n = 1
                while n <= warm_rows:
                    engine.infer(np.zeros((n, dim)))
                    n *= 2
    server.add_generic_rpc_handlers((_make_handler(engine, batcher),))
    bound = _bind_or_close(server, host, port, batcher)
    server.batcher = batcher
    _wrap_server_stop(server, batcher)
    server.start()
    slog.info("server.start", method="Process", port=bound,
              coalesce=coalesce, pipeline_depth=pipeline_depth,
              warm_rows=warm_rows,
              max_pending_rows=max_pending_rows)
    return server, bound


def _make_generate_handler(run_submit, prompt_len: int, vocab_size: int,
                           max_new_tokens: int | None = None):
    """The Generate method: Matrix of token ids (N, prompt_len) ->
    Matrix (N, prompt_len + max_new_tokens). Same wire format, same
    status taxonomy as Process."""

    def generate(request_bytes: bytes, context) -> bytes:
        _RPC_REQUESTS.labels(method="Generate").inc()
        span, budget, md = _request_span(context, "Generate")
        slo_class = normalize_class(md.get(CLASS_HEADER))
        _annotate_capture_attrs(span, md, slo_class, budget,
                                prompt_len=prompt_len,
                                max_new_tokens=max_new_tokens)
        try:
            try:
                with _trace.TRACER.span("decode", span.ctx):
                    x = decode_matrix(request_bytes)
            except ValueError as e:
                span.annotate(f"abort INVALID_ARGUMENT: bad Matrix: {e}")
                _abort(context, "Generate", grpc.StatusCode.INVALID_ARGUMENT,
                       f"bad Matrix: {e}")
            span.set("rows", len(x))
            if x.ndim != 2 or x.shape[1] != prompt_len:
                # The decode program is compiled for ONE static prompt
                # length per endpoint (static shapes under jit); clients
                # pad/pack to it.
                span.annotate("abort INVALID_ARGUMENT: prompt shape")
                _abort(
                    context, "Generate", grpc.StatusCode.INVALID_ARGUMENT,
                    f"expected prompts of shape (N, {prompt_len}), got "
                    f"{tuple(x.shape)}",
                )
            ids = x.astype(np.int64)
            if (ids != x).any() or (ids < 0).any() or (ids >= vocab_size).any():
                span.annotate("abort INVALID_ARGUMENT: token id range")
                _abort(
                    context, "Generate", grpc.StatusCode.INVALID_ARGUMENT,
                    f"prompts must be integer token ids in [0, {vocab_size})",
                )
            try:
                out = run_submit(ids.astype(np.int32), budget, span.ctx,
                                 slo_class)
            except Exception as e:  # noqa: BLE001 — map to status codes
                span.annotate(f"error: {type(e).__name__}: {e}")
                _abort_for_exception(context, e, "generation", "Generate")
            with _trace.TRACER.span("encode", span.ctx):
                # Token ids encode straight from the decoder's int32
                # output — the per-stripe cast to wire float64 happens
                # inside the codec's one preallocated buffer.
                return encode_matrix(out)
        finally:
            span.end()

    rpc = grpc.unary_unary_rpc_method_handler(
        generate, request_deserializer=bytes, response_serializer=bytes
    )
    return grpc.method_handlers_generic_handler(
        SERVICE_NAME, {"Generate": rpc}
    )


def _status_from_code(name: str):
    """Stream END-frame / FrameworkError code name -> gRPC status (the
    stream-side twin of _abort_for_exception's isinstance ladder — by
    the time an error reaches a TokenStream terminal it is a string)."""
    if name == "INTEGRITY":
        # IntegrityError.code is the framework taxonomy name; its wire
        # status is DATA_LOSS (same mapping as _abort_for_exception).
        return grpc.StatusCode.DATA_LOSS
    try:
        return grpc.StatusCode[name]
    except KeyError:
        return grpc.StatusCode.INTERNAL


def _annotate_capture_attrs(span, md, slo_class, budget, *,
                            prompt_len=None, max_new_tokens=None,
                            stream=False):
    """Capture-completeness attrs (ISSUE 18): the handler root span
    carries every request attribute :mod:`tpu_dist_nn.obs.replay`
    needs, so an incident bundle's trace.json alone is a replayable
    workload. Attrs ride ``Span.set`` -> chrome ``args`` and survive
    ``stitch_chrome_traces`` (which passes args through verbatim)."""
    span.set("slo_class", slo_class)
    sess = md.get(SESSION_HEADER)
    if sess:
        span.set("session", sess)
    if prompt_len is not None:
        span.set("prompt_len", int(prompt_len))
    if max_new_tokens is not None:
        span.set("max_new_tokens", int(max_new_tokens))
    if budget is not None:
        span.set("budget_ms", int(budget * 1000))
    if stream:
        span.set("stream", True)


def _make_generate_stream_handler(run_submit_stream, prompt_len: int,
                                  vocab_size: int,
                                  max_new_tokens: int | None = None):
    """The GenerateStream method (PR 16): ONE prompt row in, a stream
    of wire frames out — TOKENS deltas as the continuous scheduler
    publishes them (serving/stream.py), then exactly one END frame
    naming the terminal (eos / max_tokens). Same Matrix request wire
    and status taxonomy as Generate; frames per serving/wire.py.

    Continuous-scheduler only: the static run-to-completion decode has
    no step-granular tokens to stream, so a static endpoint leaves the
    method unregistered (UNIMPLEMENTED — the honest answer).
    """

    def generate_stream(request_bytes: bytes, context):
        _RPC_REQUESTS.labels(method="GenerateStream").inc()
        span, budget, md = _request_span(context, "GenerateStream")
        slo_class = normalize_class(md.get(CLASS_HEADER))
        _annotate_capture_attrs(span, md, slo_class, budget,
                                prompt_len=prompt_len,
                                max_new_tokens=max_new_tokens,
                                stream=True)
        stream = None
        try:
            try:
                with _trace.TRACER.span("decode", span.ctx):
                    x = decode_matrix(request_bytes)
            except ValueError as e:
                span.annotate(f"abort INVALID_ARGUMENT: bad Matrix: {e}")
                _abort(context, "GenerateStream",
                       grpc.StatusCode.INVALID_ARGUMENT, f"bad Matrix: {e}")
            if x.ndim != 2 or x.shape != (1, prompt_len):
                # One stream = one sequence: frame order and failover
                # resume are per-sequence concepts. A client streams N
                # prompts over N concurrent RPCs.
                span.annotate("abort INVALID_ARGUMENT: prompt shape")
                _abort(
                    context, "GenerateStream",
                    grpc.StatusCode.INVALID_ARGUMENT,
                    f"GenerateStream takes ONE prompt of shape "
                    f"(1, {prompt_len}), got {tuple(x.shape)}",
                )
            ids = x.astype(np.int64)
            if (ids != x).any() or (ids < 0).any() or (ids >= vocab_size).any():
                span.annotate("abort INVALID_ARGUMENT: token id range")
                _abort(
                    context, "GenerateStream",
                    grpc.StatusCode.INVALID_ARGUMENT,
                    f"prompts must be integer token ids in [0, {vocab_size})",
                )
            resume = None
            raw = md.get(STREAM_RESUME_HEADER)
            if raw:
                # The router's mid-stream-failover prefix: tokens the
                # client already received from the dead replica. Rides
                # the preemption-resume path (forced-token replay) so
                # the stream continues bit-identically at temperature 0.
                try:
                    resume = [int(t) for t in raw.split(",")]
                except ValueError:
                    span.annotate("abort INVALID_ARGUMENT: resume header")
                    _abort(
                        context, "GenerateStream",
                        grpc.StatusCode.INVALID_ARGUMENT,
                        f"bad {STREAM_RESUME_HEADER}: expected "
                        "comma-separated token ids",
                    )
                if len(resume) > STREAM_RESUME_MAX_TOKENS:
                    # Bit-exact resume needs EVERY delivered token; a
                    # clamped suffix would replay against KV state this
                    # replica does not have. Fail loudly (the router
                    # refuses to even attempt it — this is the backstop
                    # for hand-rolled clients).
                    span.annotate("abort OUT_OF_RANGE: resume too long")
                    _abort(
                        context, "GenerateStream",
                        grpc.StatusCode.OUT_OF_RANGE,
                        f"{STREAM_RESUME_HEADER} carries {len(resume)} "
                        f"tokens; the metadata-borne resume path is "
                        f"bounded at {STREAM_RESUME_MAX_TOKENS}",
                    )
            # Streams surface the trace id in INITIAL metadata (ISSUE
            # 16 satellite): trailing only lands at stream end — useless
            # while debugging a stream that is wedged mid-flight. Unary
            # methods keep the trailing-only contract (_request_span).
            try:
                context.send_initial_metadata(
                    ((_trace.TRACE_ID_HEADER, span.ctx.trace_id),)
                )
            except Exception:  # noqa: BLE001 — in-process fakes
                pass
            try:
                stream = run_submit_stream(
                    x.astype(np.int32), budget, span.ctx, slo_class, resume
                )
            except Exception as e:  # noqa: BLE001 — map to status codes
                span.annotate(f"error: {type(e).__name__}: {e}")
                _abort_for_exception(context, e, "stream admission",
                                     "GenerateStream")
            if resume:
                note_stream_resumed()
                span.set("resume_tokens", len(resume))
            # Client disconnect / gRPC cancellation must free the decode
            # slot: the callback flips the channel, the next publish
            # returns False, and the scheduler's reap pass releases the
            # slot + prefix-cache refs on its next iteration.
            try:
                context.add_callback(stream.cancel)
            except Exception:  # noqa: BLE001 — in-process fakes
                pass
            ntok = 0
            while True:
                # The budget is the STREAM deadline (docs/ROBUSTNESS.md):
                # it bounds each next-token gap — admission + prefill
                # before the first frame, decode cadence after — not
                # total stream duration. None = wait for the scheduler's
                # own terminal (every exit path reaches finish()).
                ev = stream.next_event(budget)
                if ev is None:
                    stream.cancel()
                    span.annotate("abort DEADLINE_EXCEEDED: token gap")
                    _abort(
                        context, "GenerateStream",
                        grpc.StatusCode.DEADLINE_EXCEEDED,
                        f"no token within the {budget:.3f}s stream gap "
                        "budget",
                    )
                kind, data = ev
                if kind == "tokens":
                    ntok += len(data)
                    yield encode_token_frame(data)
                    continue
                if data["reason"] == "error":
                    span.annotate(
                        f"stream error {data['code']}: {data['message']}"
                    )
                    _abort(context, "GenerateStream",
                           _status_from_code(data["code"]),
                           data["message"] or "stream failed")
                span.set("tokens", ntok)
                yield encode_end_frame(data["reason"], data["code"],
                                       data["message"])
                return
        finally:
            if stream is not None:
                stream.cancel()  # no-op after a clean terminal
            span.end()

    rpc = grpc.unary_stream_rpc_method_handler(
        generate_stream, request_deserializer=bytes,
        response_serializer=bytes,
    )
    return grpc.method_handlers_generic_handler(
        SERVICE_NAME, {"GenerateStream": rpc}
    )


def serve_lm_generate(params, cfg, port: int, *, max_new_tokens: int,
                      prompt_len: int, num_stages: int = 1,
                      num_groups: int | None = None,
                      temperature: float = 0.0, top_k: int | None = None,
                      top_p: float | None = None, seed: int = 0,
                      host: str = "0.0.0.0", max_workers: int = 10,
                      coalesce: bool = True, warm_rows: int = 0,
                      submit_timeout: float | None = 120.0,
                      pipeline_depth: int = 2,
                      max_pending_rows: int | None = None,
                      scheduler: str = "auto", gen_slots: int = 8,
                      eos_id: int | None = None,
                      prefix_cache_blocks: int = 0,
                      prefill_chunk: int | None = None,
                      class_watermarks: dict | None = None,
                      interceptors=()):
    """Serve LM GENERATION over the reference wire.

    ``scheduler`` picks the decode scheduling policy:

    * ``"continuous"`` — iteration-level continuous batching
      (:class:`~tpu_dist_nn.serving.continuous.ContinuousScheduler`):
      a fixed ladder of ``gen_slots`` KV-cache slots, requests admitted
      at decode-STEP granularity and retired early on ``eos_id`` or
      their token budget, so a short request never pays for a long
      neighbor and late arrivals don't convoy behind a full batch.
      Single-chip only (``num_stages == 1``).
    * ``"static"`` — the legacy run-to-completion coalescing batcher in
      front of :func:`~tpu_dist_nn.models.generate.generate` (kept as
      the A/B control arm, exactly like ``pipeline_depth=1`` for the
      Process path; ``bench.py --gen-ab`` measures against it).
    * ``"auto"`` (default) — continuous when ``num_stages == 1`` and
      ``coalesce`` is on; static for the pipelined placement (whose
      overlapped round-robin decoder schedules groups itself) and for
      ``coalesce=False`` (the lock-serialized legacy arm, which keeps
      its ``server.batcher is None`` contract). ``pipeline_depth``
      applies to the static batcher only — the continuous scheduler's
      loop has no launch-ahead analogue.

    ``num_stages > 1`` decodes IN the pipeline placement with the
    OVERLAPPED round-robin decoder
    (:func:`~tpu_dist_nn.parallel.pp_generate.make_pipeline_generate_overlapped`):
    ``num_groups`` (default ``max(num_stages, 2)``) request groups ride
    the stage ring so every stage does useful work every tick — the
    batcher's coalesced rows are exactly the decoder's group slots
    (rows pad to a ``(G, Bg)`` grid, ``Bg`` power-of-two bucketed).

    One endpoint = one decode config (prompt_len, max_new_tokens,
    sampling knobs are compile-time static). Sampling at
    ``temperature > 0`` folds a per-batch counter into the key so
    repeated identical prompts draw fresh continuations. ``eos_id``
    enables stop-token semantics on BOTH schedulers (same freeze/pad
    rule, so their ``temperature == 0`` outputs are identical).

    ``prefix_cache_blocks > 0`` enables the continuous scheduler's
    shared-prefix KV reuse (ref-counted pool blocks, copy-on-write
    admission) and ``prefill_chunk`` bounds tokens per prefill launch
    so long prompts interleave with resident decodes — both continuous-
    scheduler features (docs/PERF.md "Prefix caching & chunked
    prefill"); requesting either with a static resolution is an error
    rather than a silently-ignored perf flag.

    Returns ``(server, bound_port)``; ``server.batcher`` exposes the
    scheduling counters (the continuous scheduler satisfies the
    batcher counter contract; ``server.scheduler`` names it
    explicitly, None on the static path). ``warm_rows > 0``
    precompiles the continuous prefill-at-slot + step kernels, or the
    static bucket ladder, before the port opens.
    """
    import itertools

    import jax

    from tpu_dist_nn.models.generate import validate_generate_args

    if scheduler not in ("auto", "static", "continuous"):
        raise ValueError(
            f"scheduler must be 'auto', 'static' or 'continuous', "
            f"got {scheduler!r}"
        )
    if scheduler == "continuous" and num_stages > 1:
        raise ValueError(
            "scheduler='continuous' is single-chip (its slot cache "
            "lives on one device); the pipelined placement's overlapped "
            "round-robin decoder already schedules groups — use "
            "scheduler='static' (or 'auto') with num_stages > 1"
        )
    if scheduler == "continuous" and not coalesce:
        raise ValueError(
            "coalesce=False is the lock-serialized legacy arm of the "
            "STATIC scheduler; the continuous scheduler owns the device "
            "by construction — drop coalesce=False or use "
            "scheduler='static'"
        )
    if scheduler == "auto":
        # coalesce=False keeps its documented meaning (the serialized
        # static lock path, server.batcher is None) rather than being
        # silently consumed by the continuous default.
        scheduler = (
            "static" if num_stages > 1 or not coalesce else "continuous"
        )
    if scheduler != "continuous" and (
        prefix_cache_blocks or prefill_chunk is not None
    ):
        raise ValueError(
            "prefix_cache_blocks / prefill_chunk are continuous-"
            "scheduler features (the static run-to-completion decode "
            "has no slot cache to reuse or chunk into); drop them or "
            "serve scheduler='continuous'"
        )
    params = cfg.cast_params(params)
    N = int(max_new_tokens)
    T = int(prompt_len)
    counter = itertools.count()
    base_key = jax.random.key(seed)
    # Validate the WHOLE decode contract (lengths, causality, sampling
    # ranges, greedy-vs-top_k conflicts) ONCE at construction: a bad
    # combination must fail fast here, not surface as a per-RPC
    # INTERNAL from inside the decode runner (ADVICE r5).
    validate_generate_args(
        cfg, T, N, temperature, top_k, top_p,
        base_key if temperature > 0 else None, eos_id,
    )

    if scheduler == "continuous":
        from tpu_dist_nn.serving.continuous import ContinuousScheduler

        sched = ContinuousScheduler(
            params, cfg, slots=gen_slots, prompt_len=T, max_new_tokens=N,
            temperature=temperature, top_k=top_k, top_p=top_p,
            eos_id=eos_id, seed=seed, submit_timeout=submit_timeout,
            max_pending_rows=max_pending_rows,
            prefix_cache_blocks=prefix_cache_blocks,
            prefill_chunk=prefill_chunk,
            class_watermarks=class_watermarks,
        )
        if warm_rows > 0:
            sched.warm()

        def run_submit(ids: np.ndarray, time_remaining, ctx=None,
                       slo_class: str = "standard"):
            return sched.submit(ids, timeout=time_remaining, ctx=ctx,
                                slo_class=slo_class)

        def run_submit_stream(ids: np.ndarray, time_remaining, ctx=None,
                              slo_class: str = "standard", resume=None):
            return sched.submit_stream(
                ids, timeout=time_remaining, ctx=ctx, slo_class=slo_class,
                resume_tokens=resume,
            )

        server = _new_grpc_server(max_workers, interceptors)
        server.add_generic_rpc_handlers((
            _make_generate_handler(run_submit, T, cfg.vocab_size,
                                   max_new_tokens=N),
            _make_generate_stream_handler(
                run_submit_stream, T, cfg.vocab_size, max_new_tokens=N
            ),
        ))
        bound = _bind_or_close(server, host, port, sched)
        # The scheduler fulfils the batcher counter/close contract, so
        # stop-wrapping, GracefulDrain, and the runtime sampler work
        # unchanged; `scheduler` is the explicit handle.
        server.batcher = sched
        server.scheduler = sched
        _wrap_server_stop(server, sched)
        server.start()
        slog.info("server.start", method="Generate",
                  scheduler="continuous", port=bound, gen_slots=gen_slots,
                  prompt_len=T, max_new_tokens=N, eos_id=eos_id,
                  prefix_cache_blocks=prefix_cache_blocks,
                  prefill_chunk=prefill_chunk)
        return server, bound

    if num_stages > 1:
        if eos_id is not None:
            raise ValueError(
                "eos_id is not supported by the pipelined overlapped "
                "decoder (its round-robin loop has no done-mask); "
                "serve num_stages == 1 for stop-token semantics"
            )
        from tpu_dist_nn.parallel.mesh import MeshSpec, build_mesh
        from tpu_dist_nn.parallel.pp_generate import (
            make_pipeline_generate_overlapped,
        )
        from tpu_dist_nn.parallel.transformer_pipeline import shard_blocks

        S = int(num_stages)
        G = int(num_groups) if num_groups is not None else max(S, 2)
        mesh = build_mesh(MeshSpec(stage=S))
        params_served = dict(
            params, blocks=shard_blocks(params["blocks"], S)
        )
        fn = make_pipeline_generate_overlapped(
            mesh, cfg, S, N, G, temperature=temperature, top_k=top_k,
            top_p=top_p,
        )

        def run(rows: np.ndarray):
            n = len(rows)
            bg = -(-n // G)  # ceil: the batcher's bucket already padded
            grid = n if n == bg * G else bg * G
            if grid != n:
                rows = np.concatenate(
                    [rows, np.zeros((grid - n, T), rows.dtype)]
                )
            prompts = rows.reshape(G, -1, T)
            key = (
                jax.random.fold_in(base_key, next(counter))
                if temperature > 0 else None
            )
            # Return the DEVICE array (reshape/slice are lazy jax ops):
            # the batcher's drain stage pays the one host sync, so the
            # dispatch stage can stage+launch the next decode batch
            # while this one runs.
            out = fn(params_served, prompts, key=key)
            return out.reshape(-1, T + N)[:n]
    else:
        import jax.numpy as jnp

        from tpu_dist_nn.models.generate import generate

        params_served = params

        def run(rows: np.ndarray):
            key = (
                jax.random.fold_in(base_key, next(counter))
                if temperature > 0 else None
            )
            out = generate(
                params_served, cfg, rows, N, temperature=temperature,
                top_k=top_k, top_p=top_p, key=key, eos_id=eos_id,
            )
            # Device-side concat keeps the handle un-materialized for
            # the batcher's drain stage (same overlap contract as the
            # pipelined runner above).
            return jnp.concatenate([jnp.asarray(rows, out.dtype), out], axis=1)

    server = _new_grpc_server(max_workers, interceptors)
    # Goodput accounting for the run-to-completion decode: one record
    # per coalesced launch AT DRAIN (EOS-frozen positions only exist in
    # the materialized sequences). The coalesce=False lock path is the
    # legacy A/B control arm and stays unaccounted; the num_stages>1
    # grid pad beyond the bucket is invisible here (named model
    # simplification — docs/OBSERVABILITY.md "Goodput & MFU").
    from tpu_dist_nn.obs.goodput import GOODPUT, LMFlopModel

    gp_model = LMFlopModel.from_config(cfg, T + N - 1 if N > 1 else T)
    # The pipelined placement decodes over num_stages devices; the
    # single-chip path over one — the peak must match the footprint.
    GOODPUT.ensure_peak(device_count=max(int(num_stages), 1))

    def account(out, useful_rows, launched_rows, dead_rows=0):
        GOODPUT.record_static_generate(
            gp_model, out, useful_rows, launched_rows, T, eos_id,
            dead_rows=dead_rows,
        )

    batcher = (
        _Batcher(None, 65536, submit_timeout, run_fn=run, method="Generate",
                 pipeline_depth=pipeline_depth,
                 max_pending_rows=max_pending_rows, account_fn=account,
                 class_watermarks=class_watermarks)
        if coalesce else None
    )
    lock = threading.Lock()

    def run_submit(ids: np.ndarray, time_remaining, ctx=None,
                   slo_class: str = "standard"):
        if batcher is not None:
            return batcher.submit(ids, timeout=time_remaining, ctx=ctx,
                                  slo_class=slo_class)
        with lock:
            return run(ids)

    if warm_rows > 0:
        n = 1
        while n <= warm_rows:
            # np.asarray forces the decode so the compile really lands
            # before the port opens (run returns a lazy device array).
            np.asarray(run(np.zeros((n, T), np.int32)))
            n *= 2
    server.add_generic_rpc_handlers(
        (_make_generate_handler(run_submit, T, cfg.vocab_size,
                                max_new_tokens=N),)
    )
    bound = _bind_or_close(server, host, port, batcher)
    server.batcher = batcher
    server.scheduler = None  # continuous-mode handle; static here
    _wrap_server_stop(server, batcher)
    server.start()
    slog.info("server.start", method="Generate", scheduler="static",
              port=bound, num_stages=num_stages, prompt_len=T,
              max_new_tokens=N, coalesce=coalesce)
    return server, bound


_CLIENT_DEFAULT = object()  # "use the built-in default" sentinel


class StreamReply:
    """One streamed generation (``GrpcClient.generate_stream``).

    Iterate to receive token ids as the server publishes them; when
    iteration ends normally, ``finish`` holds the terminal frame
    (``{"reason": "eos" | "max_tokens", ...}``). ``trace_id`` carries
    the server's trace id from INITIAL metadata — available as soon as
    the stream opens, so a wedged stream can be debugged (``tdn trace``)
    before it ever terminates. ``cancel()`` tears the RPC down; the
    server frees the decode slot on its next scheduler iteration.

    A broken stream raises ``grpc.RpcError`` (enriched with
    ``server_trace_id``) from the iterator. There is deliberately NO
    client-side retry: a mid-stream failure is not idempotent from here
    (tokens were already delivered) — failover is the ROUTER's job,
    which resumes the stream on another replica via forced-token replay
    (docs/SCALING.md "Streaming failover").
    """

    def __init__(self, call, span):
        self._call = call
        self._span = span
        self._ended = False
        self.finish: dict | None = None
        self.trace_id: str | None = None

    def cancel(self) -> None:
        self._call.cancel()

    def _end_span(self) -> None:
        if not self._ended:
            self._ended = True
            self._span.end()

    def __iter__(self):
        try:
            try:
                for k, v in self._call.initial_metadata() or ():
                    if k == _trace.TRACE_ID_HEADER:
                        self.trace_id = v
            except Exception:  # noqa: BLE001 — metadata is best-effort
                pass
            for frame in self._call:
                kind, data = decode_frame(frame)
                if kind == "tokens":
                    yield from data
                else:
                    self.finish = data
                    self._span.annotate(f"end: {data['reason']}")
                    return
            # Stream closed OK without an END frame: a server that died
            # between its last TOKENS flush and the terminal. Surface it
            # rather than pretend the generation completed.
            raise grpc.RpcError(
                "stream closed without a terminal END frame"
            )
        except grpc.RpcError as e:
            code, trace_id = GrpcClient._enrich(e, self._span)
            if trace_id is not None:
                self.trace_id = trace_id
            self._span.annotate(
                f"stream failed {code}: server trace {trace_id}"
            )
            raise
        finally:
            self._end_span()


class GrpcClient:
    """Minimal client for the Process RPC — the ``tdn infer --target``
    transport (the reference client's ``run_batch_inference`` analogue,
    ``run_grpc_inference.py:112-158``: one persistent channel, unlimited
    message sizes, float64 rows).

    Resilient by default (docs/ROBUSTNESS.md): a transient failure
    (UNAVAILABLE / DEADLINE_EXCEEDED) is retried under a
    :class:`~tpu_dist_nn.serving.resilience.RetryPolicy` with capped
    jittered backoff, every attempt's deadline carved from the
    REMAINING ``timeout`` (a retried call never exceeds the budget of
    the original); a per-target
    :class:`~tpu_dist_nn.serving.resilience.CircuitBreaker` fails fast
    with :class:`~tpu_dist_nn.utils.errors.UnavailableError` while the
    target is known-dead. Pass ``retry=None`` / ``breaker=None`` to
    opt out (the reference's one-attempt behavior).

    ``wait_for_ready=True`` blocks construction on channel readiness
    (the reference orchestrator's TCP poll, run_grpc_fcnn.py:157-172)
    for up to ``ready_timeout`` seconds, raising ``UnavailableError``
    on expiry — instead of the first RPC silently eating the connect
    latency or failing with an opaque UNAVAILABLE.

    ``session_key`` rides every call as ``x-tdn-session`` metadata:
    against the multi-replica router (docs/SCALING.md) it pins this
    client's follow-up Generate requests to the replica holding their
    KV/prefix-cache state; a single engine server ignores it. Per-call
    override via ``process(..., session_key=)`` / ``generate(...,
    session_key=)`` for clients multiplexing many sessions over one
    channel.
    """

    def __init__(self, target: str, timeout: float = 30.0, *,
                 retry=_CLIENT_DEFAULT, breaker=_CLIENT_DEFAULT,
                 wait_for_ready: bool = False, ready_timeout: float = 5.0,
                 session_key: str | None = None,
                 slo_class: str | None = None):
        from tpu_dist_nn.serving.resilience import CircuitBreaker, RetryPolicy

        self.target = target
        self.timeout = timeout
        self.session_key = session_key
        # SLO class rides every call as x-tdn-class (None = send no
        # header — the server defaults to "standard"): queue priority,
        # shed watermark, and — behind a router — the hedging
        # exemption for best_effort (docs/ROBUSTNESS.md "Degradation
        # ladder"). Per-call override via process/generate(slo_class=).
        self.slo_class = slo_class
        self._retry = RetryPolicy() if retry is _CLIENT_DEFAULT else retry
        self._breaker = (
            CircuitBreaker.for_target(target)
            if breaker is _CLIENT_DEFAULT else breaker
        )
        self._channel = grpc.insecure_channel(
            target,
            options=[
                ("grpc.max_send_message_length", -1),
                ("grpc.max_receive_message_length", -1),
            ],
        )
        if wait_for_ready:
            from tpu_dist_nn.utils.errors import UnavailableError

            fut = grpc.channel_ready_future(self._channel)
            try:
                fut.result(timeout=ready_timeout)
            except grpc.FutureTimeoutError:
                fut.cancel()
                self._channel.close()
                raise UnavailableError(
                    f"server at {target} not ready within {ready_timeout}s "
                    "(readiness poll timed out; is it up?)"
                ) from None
        self._call = self._channel.unary_unary(
            PROCESS_METHOD,
            request_serializer=bytes,
            response_deserializer=bytes,
        )
        self._call_generate = self._channel.unary_unary(
            GENERATE_METHOD,
            request_serializer=bytes,
            response_deserializer=bytes,
        )
        self._call_generate_stream = self._channel.unary_stream(
            GENERATE_STREAM_METHOD,
            request_serializer=bytes,
            response_deserializer=bytes,
        )

    @staticmethod
    def _enrich(e, span) -> tuple:
        """Attach ``server_trace_id`` / ``retry_after_ms`` + extract
        the status code from a failed RPC (best-effort — in-process
        fakes may lack both)."""
        trace_id = span.ctx.trace_id  # the id we propagated
        retry_after = None
        try:
            for k, v in e.trailing_metadata() or ():
                if k == _trace.TRACE_ID_HEADER:
                    trace_id = v  # the server's own root, if any
                elif k == RETRY_AFTER_HEADER:
                    try:
                        retry_after = int(v)
                    except (TypeError, ValueError):
                        pass  # a garbled hint is no hint
        except Exception:  # noqa: BLE001 — best-effort enrichment
            pass
        e.server_trace_id = trace_id
        # The shed reply's backoff hint (x-tdn-retry-after-ms): the
        # server's drain-rate-derived floor for the next attempt.
        e.retry_after_ms = retry_after
        code = None
        try:
            code = e.code()
        except Exception:  # noqa: BLE001
            pass
        return code, trace_id

    def _traced_call(self, call, method: str, payload: bytes,
                     session_key=_CLIENT_DEFAULT,
                     slo_class=_CLIENT_DEFAULT) -> bytes:
        """One LOGICAL call (original attempt + bounded retries) under
        one client span: the trace context and the remaining-budget
        hint ride the metadata out on every attempt; a final failure
        comes back NAMING the server-side trace (``e.server_trace_id``)
        so the operator pulls exactly the right span tree from
        ``/trace`` instead of guessing from timestamps. Retried
        attempts are annotated onto the span and counted in
        ``tdn_client_retries_total``."""
        from tpu_dist_nn.serving.resilience import CLIENT_RETRIES
        from tpu_dist_nn.utils.errors import UnavailableError

        policy, breaker = self._retry, self._breaker
        session = (
            self.session_key if session_key is _CLIENT_DEFAULT
            else session_key
        )
        cls = (
            self.slo_class if slo_class is _CLIENT_DEFAULT else slo_class
        )
        span = _trace.TRACER.start(f"client.{method}")
        deadline = (
            time.monotonic() + self.timeout if self.timeout is not None
            else None
        )
        attempt = 0
        last_err = None
        try:
            while True:
                attempt += 1
                if breaker is not None and not breaker.allow():
                    span.annotate(f"breaker open for {self.target}: fail-fast")
                    raise UnavailableError(
                        f"circuit breaker open for {self.target} (too many "
                        "consecutive failures; cooling down)"
                    )
                remaining = None
                if deadline is not None:
                    # Budget carving: this attempt gets whatever the
                    # ORIGINAL call has left, never a fresh window.
                    remaining = deadline - time.monotonic()
                    if last_err is not None and remaining <= 0.001:
                        # A backoff sleep overshot the budget: re-raise
                        # the last REAL outcome instead of issuing a
                        # ~0ms attempt that fails client-side and
                        # counts a phantom failure against the breaker.
                        span.annotate(
                            f"retry budget exhausted before attempt {attempt}"
                        )
                        raise last_err
                metadata = ((_trace.TRACE_HEADER, span.ctx.header()),)
                if session is not None:
                    # Session affinity key for the router; an engine
                    # server just never reads it.
                    metadata += ((SESSION_HEADER, session),)
                if cls is not None:
                    # SLO class: admission priority + shed watermark
                    # at the scheduler, hedging exemption at the
                    # router (best_effort).
                    metadata += ((CLASS_HEADER, cls),)
                if remaining is not None:
                    # Remaining-budget hint (the grpc-timeout analogue,
                    # readable by the batcher even where a proxy
                    # rewrites deadlines).
                    metadata += (
                        (_trace.TIMEOUT_HEADER,
                         str(max(0, int(remaining * 1000)))),
                    )
                try:
                    reply = call(payload, timeout=remaining,
                                 metadata=metadata)
                    if breaker is not None:
                        breaker.record_success()
                    if attempt > 1:
                        span.annotate(f"succeeded on attempt {attempt}")
                    return reply
                except grpc.RpcError as e:
                    from tpu_dist_nn.serving.resilience import (
                        RETRYABLE_CODES,
                        _code_name,
                    )

                    code, trace_id = self._enrich(e, span)
                    last_err = e
                    # Transience classification feeds the breaker even
                    # with retries disabled (a no-retry client still
                    # learns the target is down); only TRANSIENT
                    # statuses say anything about target health —
                    # INVALID_ARGUMENT must not trip the breaker.
                    transient = (
                        policy.retryable(code) if policy is not None
                        else _code_name(code) in RETRYABLE_CODES
                    )
                    if breaker is not None:
                        if transient:
                            breaker.record_failure()
                        else:
                            # A non-transient status means the target
                            # RESPONDED — reachability evidence. This
                            # also closes the half-open probe instead
                            # of leaving it wedged (a probe answered
                            # INVALID_ARGUMENT proves the server is
                            # back even though the request was bad).
                            breaker.record_success()
                    # A shed (RESOURCE_EXHAUSTED) is retryable too —
                    # the server is healthy and explicitly asked for a
                    # paced retry — but stays NON-transient for the
                    # breaker above: a shed storm must never open
                    # breakers to a healthy server.
                    shed = _code_name(code) == "RESOURCE_EXHAUSTED"
                    retryable = policy is not None and (transient or shed)
                    out_of_attempts = (
                        policy is None or attempt >= policy.max_attempts
                    )
                    # The server's drain-rate hint is the backoff
                    # FLOOR: jitter still de-synchronizes the herd
                    # above it, but nobody retries before the backlog
                    # can have moved.
                    floor = (
                        e.retry_after_ms / 1000.0
                        if getattr(e, "retry_after_ms", None) else None
                    )
                    delay = (
                        0.0 if out_of_attempts
                        else policy.backoff(attempt, floor=floor)
                    )
                    out_of_budget = (
                        deadline is not None
                        and time.monotonic() + delay >= deadline
                    )
                    if not retryable or out_of_attempts or out_of_budget:
                        why = (
                            "not retryable" if not retryable
                            else "attempts exhausted" if out_of_attempts
                            else "retry budget exhausted"
                        )
                        span.annotate(
                            f"rpc error {code} on attempt {attempt} ({why}): "
                            f"server trace {trace_id}"
                        )
                        # Rate-limited: a dead target under a client
                        # loop logs its first occurrences then 1/s, not
                        # one line per failed RPC.
                        slog.warning(
                            "client.rpc_failed", method=method,
                            target=self.target, code=str(code),
                            attempt=attempt, why=why, trace_id=trace_id,
                            hint="pull the server span tree with "
                                 "`tdn trace --target <metrics-port>`",
                        )
                        raise
                    CLIENT_RETRIES.labels(method=method).inc()
                    span.annotate(
                        f"retry {attempt} after {code}: backoff {delay:.4f}s"
                    )
                    policy.sleep(delay)
        finally:
            span.end()

    def process(self, x: np.ndarray,
                session_key=_CLIENT_DEFAULT,
                slo_class=_CLIENT_DEFAULT) -> np.ndarray:
        # The codec owns the ONE cast to wire float64 (per-stripe into
        # its output buffer) — pre-casting here would materialize a
        # float64 copy just for encode_matrix to walk.
        reply = self._traced_call(
            self._call, "Process", encode_matrix(x),
            session_key=session_key, slo_class=slo_class,
        )
        return decode_matrix(reply)

    def generate(self, prompts: np.ndarray,
                 session_key=_CLIENT_DEFAULT,
                 slo_class=_CLIENT_DEFAULT) -> np.ndarray:
        """Token-id prompts ``(N, prompt_len)`` -> full sequences
        ``(N, prompt_len + max_new_tokens)`` (ids ride the Matrix wire
        as doubles — exact). ``session_key`` / ``slo_class`` override
        the client-level values for this call (None = send no such
        header)."""
        reply = self._traced_call(
            self._call_generate, "Generate", encode_matrix(prompts),
            session_key=session_key, slo_class=slo_class,
        )
        # Decode lands token ids straight in int64 — the wire doubles
        # are exact for ids < 2^53, so the cast-on-decode is lossless.
        return decode_matrix(reply, dtype=np.int64)

    def generate_stream(self, prompt: np.ndarray, *,
                        session_key=_CLIENT_DEFAULT,
                        slo_class=_CLIENT_DEFAULT,
                        timeout: float | None = None,
                        gap_timeout: float | None = None) -> StreamReply:
        """Stream ONE prompt's tokens as the server produces them.

        ``prompt`` is one sequence of token ids — ``(prompt_len,)`` or
        ``(1, prompt_len)``. Returns a :class:`StreamReply`; iterate it
        for token ids at decode-step granularity (first token at ~TTFT,
        not retirement).

        ``timeout`` bounds the WHOLE stream (gRPC deadline; None =
        unbounded — the streaming default, a long generation is not an
        error). ``gap_timeout`` is the stream-aware deadline
        (docs/ROBUSTNESS.md): the server bounds admission + prefill to
        first token and then every next-token gap by it, so a stalled
        stream dies fast while a steadily-producing one never expires.
        """
        x = np.asarray(prompt)
        if x.ndim == 1:
            x = x[None, :]
        session = (
            self.session_key if session_key is _CLIENT_DEFAULT
            else session_key
        )
        cls = (
            self.slo_class if slo_class is _CLIENT_DEFAULT else slo_class
        )
        span = _trace.TRACER.start("client.GenerateStream")
        metadata = ((_trace.TRACE_HEADER, span.ctx.header()),)
        if session is not None:
            metadata += ((SESSION_HEADER, session),)
        if cls is not None:
            metadata += ((CLASS_HEADER, cls),)
        if gap_timeout is not None:
            metadata += (
                (_trace.TIMEOUT_HEADER,
                 str(max(0, int(gap_timeout * 1000)))),
            )
        call = self._call_generate_stream(
            encode_matrix(x), timeout=timeout, metadata=metadata
        )
        return StreamReply(call, span)

    def close(self) -> None:
        self._channel.close()
