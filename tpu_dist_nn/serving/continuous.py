"""Continuous batching for LM generation: the iteration-level decode
scheduler (Orca, OSDI '22) on a slot-based KV cache, with
cross-request KV REUSE (RadixAttention-style shared-prefix caching,
exact-match tiers) and CHUNKED PREFILL (Sarathi-Serve).

The static Generate path (``serving/server.py``'s ``_Batcher`` over
``models.generate.generate``) is run-to-completion batching: a batch is
admitted, decodes ALL ``max_new_tokens`` steps, and only then does the
next batch start — a 4-token request pays for its 32-token neighbor,
and late arrivals convoy behind the whole batch. This module schedules
at DECODE-STEP granularity instead:

* One fixed ``(L, S + P, max_len, H, Dh)`` slot KV cache
  (:func:`~tpu_dist_nn.models.generate.init_slot_cache`) holds ``S``
  independent request slots plus ``P`` reserved PREFIX-POOL blocks
  (``--prefix-cache-blocks``). Shapes never change — admission and
  retirement only flip entries of a per-slot active mask, the
  TPU-friendly static-shape answer to vLLM-style paged KV (one request
  = one slot = one contiguous ``max_len`` extent; no block tables, no
  gathers on the hot path — trade-off discussion in docs/PERF.md).
* **Prefix caching**: most production Generate traffic shares a long
  common prefix (system prompt, few-shot header). The pool caches K/V
  for chunk-aligned token prefixes, keyed on the exact prefix bytes
  (exact-match tiers — no radix tree; rationale in docs/PERF.md). A
  hit admits by COPYING the block into the request's slot
  (:func:`~tpu_dist_nn.models.generate.copy_cache_slot` — copy-on-
  write: the request then decodes into its own slot and can never
  mutate the shared block) and prefilling only the SUFFIX. Blocks are
  ref-counted (held admission -> retire), evicted LRU at refcount 0,
  with hit/miss/evict accounting (``tdn_prefix_cache_*``).
* **Chunked prefill**: prefills longer than ``--prefill-chunk`` tokens
  are split across scheduler iterations — each iteration runs at most
  ONE chunk (:func:`~tpu_dist_nn.models.generate.
  prefill_chunk_into_cache`) alongside the resident decode step, so a
  4k-token prompt no longer freezes every live decode stream. The
  per-slot ``pos`` vector already supports the resulting staggered
  positions. Every admission routes through the chunk kernel (a
  monolithic prefill is just one whole-prompt chunk), so cache-on and
  cache-off share ONE numeric path and greedy outputs stay
  bit-identical — the correctness anchor
  (test_prefix_cache_greedy_bit_parity).
* **Admission at step granularity**: whenever a slot is free and a
  request is pending, it binds to that slot and starts chunking; the
  request starts decoding on the step after its last chunk — no
  waiting for the current "batch" to finish, because there is no
  batch.
* **One compiled step kernel**
  (:func:`~tpu_dist_nn.models.generate.decode_step_slots`) advances
  every slot at its OWN position (per-slot ``pos`` vector + active
  mask) — mixed-age requests share each device launch.
* **Early retirement**: a slot frees on EOS
  (:func:`~tpu_dist_nn.models.generate.generate`'s stop-token
  semantics, so the two schedulers are output-comparable) or its
  per-request ``max_new_tokens`` — and the freed slot is refilled on
  the same scheduler iteration while the remaining slots keep
  decoding. Finished rows stream back to their waiters immediately.

Resilience contract (docs/ROBUSTNESS.md): the admission/shed/close/
drain machinery is the SHARED scheduling core
(:mod:`~tpu_dist_nn.serving.sched_core` — one implementation with the
Process batcher): class-priority admission with per-class shed
watermarks, deadline-aware expiry at bind time, ``close(timeout)``
letting resident rows — INCLUDING half-prefilled slots — finish before
failing still-pending waiters over as UNAVAILABLE (the ``_Batcher``
drain contract, so ``GracefulDrain`` works unchanged), and first-class
fault hook points — ``launch_hook`` fires before every step-kernel
dispatch, ``fetch_hook`` before its token fetch, and ``prefill_hook``
before every prefill-chunk dispatch (a mid-prefill fault fails that
request over, frees its slot, and releases its prefix-block ref).
Assign a ``testing/faults.py`` plan's ``fire`` directly (the
``inject_engine_faults`` helper covers only engine hooks).

**Decode-slot preemption** (docs/ROBUSTNESS.md "Degradation ladder"):
a ``critical``-class request that cannot bind evicts the best victim
(dead-waiters first, then lowest class, then fewest generated tokens)
and binds into the freed slot the same iteration; the victim
re-queues with its generated prefix and resumes via prompt re-prefill
(prefix-cache hits make it cheap) + forced-token REPLAY through the
shared step kernel — the exact original computation, so greedy output
is bit-identical to an unpreempted run and sampled runs keep their
original stream.
"""

from __future__ import annotations

import collections
import functools
import itertools
import logging
import threading
import time

import numpy as np

from tpu_dist_nn.obs import trace as _trace
from tpu_dist_nn.obs.goodput import GOODPUT, LMFlopModel
from tpu_dist_nn.obs.log import get_logger
from tpu_dist_nn.obs.registry import POW2_BUCKETS, REGISTRY
from tpu_dist_nn.serving import integrity as _integrity
from tpu_dist_nn.serving.sched_core import (
    CLASS_RANK,
    SchedCore,
    slide_stream_deadline,
)
from tpu_dist_nn.serving.stream import StreamDone, TokenStream

log = logging.getLogger(__name__)  # plain channel (kept for debug use)
slog = get_logger(__name__)

# Generation metric families (docs/OBSERVABILITY.md catalog). Pushed by
# the scheduler loop; the slot gauges are sampled by obs/runtime.py.
_TTFT = REGISTRY.histogram(
    "tdn_gen_ttft_seconds",
    "time to first token: request submit to its first sampled token "
    "(prefill complete), continuous scheduler",
)
_TOKENS = REGISTRY.counter(
    "tdn_gen_tokens_total",
    "tokens emitted by the continuous decode scheduler",
)
_RETIRED = REGISTRY.counter(
    "tdn_gen_requests_retired_total",
    "request rows retired from a decode slot, by reason",
    labels=("reason",),
)
# tdn_batcher_shed_total / tdn_batch_wait_seconds moved to
# serving/sched_core.py — the shared admission/shed/close contract.
_PREEMPTED = REGISTRY.counter(
    "tdn_gen_preemptions_total",
    "decode-slot preemptions: a resident row evicted mid-stream so a "
    "critical-class request could bind, re-queued with its generated "
    "prefix for replay (by the VICTIM's class)",
    labels=("slo_class",),
)
# Same family (and meaning — rows per device launch) as the static
# batcher's, so dashboards read the Generate series unchanged across
# schedulers: here a "launch" is one slot step and its rows are the
# active slots it advanced.
_BATCH_ROWS = REGISTRY.histogram(
    "tdn_batch_rows", "coalesced rows per device launch (pre-padding)",
    labels=("method",), buckets=POW2_BUCKETS,
)
# Prefix-cache accounting (docs/OBSERVABILITY.md catalog; the
# tdn_prefix_cache_blocks_used gauge rides the runtime sampler).
_PREFIX_HITS = REGISTRY.counter(
    "tdn_prefix_cache_hits_total",
    "admissions served from a cached prefix block (copy-on-write "
    "block copy + suffix-only prefill)",
)
_PREFIX_MISSES = REGISTRY.counter(
    "tdn_prefix_cache_misses_total",
    "admissions whose prompt matched no cached prefix tier "
    "(full prefill)",
)
_PREFIX_EVICTIONS = REGISTRY.counter(
    "tdn_prefix_cache_evictions_total",
    "refcount-0 prefix blocks evicted (LRU) to admit a new prefix",
)


class PrefixCachePool:
    """Host-side bookkeeping for the reserved prefix region of the slot
    cache: which pool block holds which token-prefix, with refcounts
    and LRU eviction. Exact-match only — the key IS the prefix bytes,
    so there are no collisions and no radix tree (docs/PERF.md
    "exact-match vs radix").

    Single-threaded by design: the scheduler loop thread is the only
    caller (lookups/inserts happen at admission and chunk boundaries,
    releases at retirement — all loop-side events), so no lock.

    A block is REFERENCED from the admission that hit it until that
    request retires (or fails): a referenced block is never evicted, so
    a hot shared header cannot be thrashed out from under the requests
    using it. Eviction picks the least-recently-USED block among
    refcount-0 blocks; with every block referenced, insertion is simply
    skipped (caching is an optimization, never a correctness gate).
    """

    def __init__(self, blocks: int):
        if blocks < 1:
            raise ValueError(f"pool needs >= 1 block, got {blocks}")
        self.blocks = int(blocks)
        self._key: list[bytes | None] = [None] * self.blocks
        self._len = [0] * self.blocks
        self._refs = [0] * self.blocks
        self._last_use = [0] * self.blocks
        self._by_key: dict[bytes, int] = {}
        self._tick = itertools.count(1)
        self.hits_total = 0
        self.misses_total = 0
        self.evictions_total = 0

    @property
    def used(self) -> int:
        """Blocks currently holding a cached prefix."""
        return len(self._by_key)

    def refs(self, block: int) -> int:
        return self._refs[block]

    def block_len(self, block: int) -> int:
        return self._len[block]

    def lookup(self, candidates) -> tuple[int, int] | None:
        """The longest cached prefix among ``candidates`` (``(length,
        key_bytes)`` pairs, longest FIRST). A hit takes a reference and
        bumps recency, returning ``(block, length)``; a full miss
        returns None. Exactly one hit-or-miss is accounted per call
        (per admission)."""
        for length, key in candidates:
            b = self._by_key.get(key)
            if b is not None:
                self._refs[b] += 1
                self._last_use[b] = next(self._tick)
                self.hits_total += 1
                return b, length
        self.misses_total += 1
        return None

    def release(self, block: int) -> None:
        """Drop one reference (the request that held it retired)."""
        if self._refs[block] <= 0:
            raise AssertionError(
                f"release of unreferenced prefix block {block}"
            )
        self._refs[block] -= 1

    def clear(self) -> None:
        """Drop every cached block — the backing cache was rebuilt
        after a device fault, so the K/V the blocks pointed at is gone.
        Lifetime counters survive (they are totals, not state). The
        caller fails/releases every resident first, so no block can
        still be referenced."""
        if any(self._refs):
            raise AssertionError(
                "clear() with live references — release residents first"
            )
        self._key = [None] * self.blocks
        self._len = [0] * self.blocks
        self._last_use = [0] * self.blocks
        self._by_key.clear()

    def insert(self, key: bytes, length: int) -> tuple[int | None, bool]:
        """Reserve a block for a new prefix: a free block, else the LRU
        refcount-0 block (eviction), else None — all blocks referenced,
        insertion skipped. Returns ``(block, evicted)``; ``(None,
        False)`` when skipped or the key is already cached."""
        if key in self._by_key:
            return None, False
        free = next(
            (b for b in range(self.blocks) if self._key[b] is None), None
        )
        evicted = False
        if free is None:
            idle = [b for b in range(self.blocks) if self._refs[b] == 0]
            if not idle:
                return None, False
            free = min(idle, key=lambda b: self._last_use[b])
            del self._by_key[self._key[free]]
            self.evictions_total += 1
            evicted = True
        self._key[free] = key
        self._len[free] = int(length)
        self._refs[free] = 0
        self._last_use[free] = next(self._tick)
        self._by_key[key] = free
        return free, evicted


class ContinuousScheduler:
    """Iteration-level decode scheduler over a slot-based KV cache.

    ``submit(rows)`` blocks the calling (gRPC worker) thread until every
    row's sequence is finished, exactly like ``_Batcher.submit`` — the
    difference is behind the call: one daemon loop thread owns the
    device, interleaving per-iteration prefill CHUNKS (at most one per
    iteration, so no prompt ever stalls the decode frontier for more
    than one chunk) with single-token steps over all decoding slots,
    retiring each row the moment it hits EOS or its token budget.

    ``prefix_cache_blocks > 0`` reserves that many pool blocks at the
    tail of the slot cache and enables shared-prefix reuse: admission
    looks the prompt's chunk-aligned prefixes up (longest tier first),
    copies a hit's block into the request slot, and prefills only the
    suffix. ``prefill_chunk`` bounds tokens per prefill launch (None =
    whole prompt/suffix in one chunk) and doubles as the prefix tier
    granularity. Tuning guide: docs/PERF.md "Prefix caching & chunked
    prefill".

    Construction compiles nothing; :meth:`warm` precompiles the
    chunk-prefill, slot-copy, and step kernels so a port can open hot
    (``serve_lm_generate(warm_rows=...)`` / ``tdn warmup --lm``).

    Counter attributes mirror ``_Batcher`` (``requests_total``,
    ``batches_total`` = step-kernel launches, ``rows_total``,
    ``pending_rows``, ``inflight_rows`` = rows resident in slots,
    ``shed_total``) so the runtime sampler and drain plumbing work
    unchanged; generation-specific state (``slots_active``,
    ``steps_total``, ``slot_steps_total``, ``ttft_recent``, the
    ``prefix_*`` accessors) feeds the ``tdn_gen_*`` /
    ``tdn_prefix_cache_*`` families.

    ``prefill_fn`` / ``step_fn`` / ``copy_fn`` are testing seams (the
    bench CI smokes inject deterministic cost models); production
    always builds the real jitted kernels from ``params``/``cfg``.
    """

    method = "Generate"

    def __init__(self, params, cfg, *, slots: int, prompt_len: int,
                 max_new_tokens: int, temperature: float = 0.0,
                 top_k: int | None = None, top_p: float | None = None,
                 eos_id: int | None = None, seed: int = 0,
                 submit_timeout: float | None = 120.0,
                 max_pending_rows: int | None = None,
                 prefix_cache_blocks: int = 0,
                 prefill_chunk: int | None = None,
                 class_watermarks: dict | None = None,
                 preemption: bool = True,
                 prefill_fn=None, step_fn=None, copy_fn=None):
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        self._S = int(slots)
        self._T = int(prompt_len)
        self._N = int(max_new_tokens)
        self._eos = None if eos_id is None else int(eos_id)
        # submit_timeout / max_pending_rows / class_watermarks live in
        # the shared scheduling core constructed below.
        self._counter = itertools.count()
        if prefill_chunk is not None and prefill_chunk < 1:
            raise ValueError(
                f"prefill_chunk must be >= 1, got {prefill_chunk}"
            )
        self._chunk = None if prefill_chunk is None else int(prefill_chunk)
        self._P = int(prefix_cache_blocks)
        if self._P < 0:
            raise ValueError(
                f"prefix_cache_blocks must be >= 0, got {prefix_cache_blocks}"
            )
        # Prefix tiers: the cacheable prefix lengths, chunk-aligned so a
        # hit resumes exactly at a chunk boundary. Without chunking the
        # single tier is the whole-prompt-but-last-token prefix (repeat
        # / retry traffic); capped at T-1 so a hit always leaves >= 1
        # suffix token to produce the last-position logits from.
        grain = self._chunk if self._chunk is not None else self._T - 1
        self._tiers: tuple[int, ...] = tuple(
            sorted(
                (k * grain for k in range(1, self._T)
                 if 1 <= k * grain <= self._T - 1),
                reverse=True,
            )
        ) if self._P else ()
        if self._P and not self._tiers:
            raise ValueError(
                f"prefix_cache_blocks={self._P} has no cacheable tier: "
                f"need a prefix length in [1, prompt_len - 1 = "
                f"{self._T - 1}] — lower prefill_chunk (got "
                f"{self._chunk}) or raise prompt_len"
            )
        self._pool = PrefixCachePool(self._P) if self._P else None
        if prefill_fn is not None or step_fn is not None:
            if prefill_fn is None or step_fn is None:
                raise ValueError(
                    "prefill_fn and step_fn must be injected together"
                )
            # The public step_fn seam keeps its (toks, cache) contract;
            # normalize to the internal 3-tuple with ok=None — injected
            # kernels carry no logits for the in-launch numeric guard.
            def _step_no_guard(*a, _fn=step_fn):
                toks, cache = _fn(*a)
                return toks, None, cache

            self._prefill, self._step = prefill_fn, _step_no_guard
            # Fake caches have no block storage; the default injected
            # copy is the identity (pool bookkeeping still exercises).
            self._copy = (
                copy_fn if copy_fn is not None
                else (lambda cache, src, dst: cache)
            )
            self._params = params
            self._cache = None
            self._make_cache = None
            self._key = None
            self._temperature = float(temperature)
            # Injected fake kernels carry no architecture: the goodput
            # plane has no FLOP model to apply, so accounting is off.
            self._gp_model = None
        else:
            if copy_fn is not None:
                raise ValueError(
                    "copy_fn is an injection seam: pass it together "
                    "with prefill_fn/step_fn"
                )
            import jax

            from tpu_dist_nn.models.generate import validate_generate_args

            self._key = jax.random.key(int(seed))
            validate_generate_args(
                cfg, self._T, self._N, temperature, top_k, top_p,
                self._key if temperature > 0 else None, eos_id,
            )
            self._params = cfg.cast_params(params)
            self._temperature = float(temperature)
            self._build_kernels(
                cfg, float(temperature), top_k, top_p,
            )
        # Host-side slot state: the loop thread is the only writer.
        # _active marks DECODING slots; a bound slot whose prefill is
        # still chunking has an occupant but is not yet active.
        self._pos = np.zeros(self._S, np.int32)
        self._active = np.zeros(self._S, bool)
        self._tok = np.zeros(self._S, np.int32)
        self._occupant: list[dict | None] = [None] * self._S
        self._prefill_rr = 0  # round-robin fairness over chunking slots
        # Fault-injection hook points (testing/faults.py): called at
        # the top of every step-kernel dispatch / token fetch /
        # prefill-chunk dispatch.
        self.launch_hook = None
        self.fetch_hook = None
        self.prefill_hook = None
        # Pending queue + admission ledger: the shared scheduling core
        # (serving/sched_core.py) — class-priority queue, watermark
        # sheds, deadline expiry, close-failover sweep. The loop holds
        # core.cond exactly where it held its own condition before.
        self._sched_core = SchedCore(
            self.method, max_pending_rows=max_pending_rows,
            submit_timeout=submit_timeout,
            class_watermarks=class_watermarks,
        )
        self._cond = self._sched_core.cond
        # Preempted rows awaiting re-bind: class-annotated resume
        # entries carrying the generated prefix for replay. Mutated
        # under _cond (the loop pops there already; appends and the
        # close sweep take it too, so a wedged-loop close can never
        # race a pop and strand an entry's waiter).
        self._resume: collections.deque[dict] = collections.deque()  # guarded-by: _cond
        self._preemption = bool(preemption)
        # _Batcher-compatible counters (runtime sampler contract;
        # requests/shed/pending ride the core via properties below).
        self.rows_total = 0        # rows that entered a slot
        self.batches_total = 0     # step-kernel launches (steps_total
        #                            is a read alias — one source of truth)
        self.preempted_total = 0   # rows evicted for a critical bind
        self.overlapped_total = 0  # N/A here; kept for sampler parity
        # Generation-specific stats.
        self.slot_steps_total = 0  # active slots summed over steps
        self.retired_total = 0     # rows retired (eos + max_tokens)
        self.prefill_chunks_total = 0  # chunk-kernel launches
        self.ttft_recent: collections.deque[float] = collections.deque(
            maxlen=1024
        )
        self._m_rows = _BATCH_ROWS.labels(method=self.method)
        self._thread = threading.Thread(
            target=self._loop, name="tdn-gen-continuous", daemon=True
        )
        self._thread.start()

    # ------------------------------------------------------------ kernels

    def _build_kernels(self, cfg, temperature, top_k, top_p) -> None:
        import jax
        import jax.numpy as jnp

        from tpu_dist_nn.models.generate import (
            _truncate_logits,
            copy_cache_slot,
            decode_step_slots,
            init_slot_cache,
            prefill_chunk_into_cache,
        )

        # The last decode writes position T + N - 2 (generate()'s cache
        # sizing rule), so the slot extent is total - 1. The prefix
        # pool rides the SAME cache as P extra slots past the request
        # region — one allocation, one shape, one copy kernel.
        M = self._T + self._N - 1 if self._N > 1 else self._T
        self._make_cache = lambda: init_slot_cache(cfg, self._S + self._P, M)
        self._cache = self._make_cache()
        # Goodput FLOP model at the kernels' static shapes: the decode
        # step runs the REQUEST region only (pool blocks are sliced out
        # — decode_step_slots sees S slots, extent M), so the model's
        # extent is M regardless of prefix_cache_blocks. Peak resolves
        # here, at configure time, never on a sampler tick.
        self._gp_model = LMFlopModel.from_config(cfg, M)
        GOODPUT.ensure_peak(device_count=1)  # slot cache is single-chip
        top_k = None if top_k is None else int(top_k)
        top_p = None if top_p is None else float(top_p)

        def sample(logits, key):
            if temperature == 0:
                return jnp.argmax(logits, axis=-1).astype(jnp.int32)
            logits = _truncate_logits(logits, top_k, top_p)
            return jax.random.categorical(
                key, logits / temperature, axis=-1
            ).astype(jnp.int32)

        # The cache is LINEAR through the scheduler (one owner, always
        # rebound to the kernel's output), so its buffer is DONATED to
        # every kernel: XLA updates it in place instead of copying the
        # whole (L, S+P, M, H, Dh) pytree per launch — per-launch cost
        # that would otherwise dwarf a small chunk's compute.
        @functools.partial(jax.jit, donate_argnums=(1,))
        def prefill_chunk(params, cache, slot, tokens, start, key):
            logits, cache = prefill_chunk_into_cache(
                params, cfg, cache, slot, tokens, start
            )
            return sample(logits, key)[0], cache

        self._prefill = prefill_chunk
        self._copy = jax.jit(copy_cache_slot, donate_argnums=(0,))
        S, P = self._S, self._P

        @functools.partial(jax.jit, donate_argnums=(1,))
        def step(params, cache, pos, active, tok, key):
            # Decode advances the REQUEST region only: the pool blocks
            # past slot S hold cached prefixes, not decoding sequences
            # — running them through the step kernel would burn FLOPs
            # on dead slots every token.
            if P:
                head = {"k": cache["k"][:, :S], "v": cache["v"][:, :S]}
                logits, head = decode_step_slots(
                    params, head, pos, tok, cfg, active=active
                )
                cache = {
                    "k": cache["k"].at[:, :S].set(head["k"]),
                    "v": cache["v"].at[:, :S].set(head["v"]),
                }
            else:
                logits, cache = decode_step_slots(
                    params, cache, pos, tok, cfg, active=active
                )
            # Numeric guard folded into the SAME launch: one fused
            # isfinite reduction over the logits per slot (an (S,) bool
            # riding the step's existing device->host sync — always
            # computed so the compiled kernel never depends on the
            # runtime GUARD toggle; acting on it is a host decision).
            ok = jnp.isfinite(logits).all(axis=-1)
            return sample(logits, key), ok, cache

        self._step = step

    def _next_key(self):
        """A fresh fold of the base key per sampling event (prefill or
        step): repeated identical prompts draw fresh continuations, the
        serving endpoint's existing contract."""
        if self._key is None:
            return None
        if self._temperature == 0:
            return self._key  # unused inside the greedy kernels
        import jax

        return jax.random.fold_in(self._key, next(self._counter))

    def _chunk_lengths(self) -> list[int]:
        """Every chunk length the scheduler can launch (the compile
        set): walking from each possible start position — 0, or any
        prefix tier a hit can resume at — in ``prefill_chunk`` strides.
        Small by construction: {chunk, T mod chunk} in the common case.
        """
        starts = {0, *self._tiers}
        lengths: set[int] = set()
        for s in starts:
            pos = s
            while pos < self._T:
                c = (
                    self._T - pos if self._chunk is None
                    else min(self._chunk, self._T - pos)
                )
                lengths.add(c)
                pos += c
        return sorted(lengths, reverse=True)

    def warm(self) -> list[str]:
        """Precompile every kernel the loop can launch — the
        chunk-prefill kernel at each chunk LENGTH the configuration can
        produce, the slot-copy kernel (prefix pool on), and the step
        kernel — so the port opens hot (with JAX_COMPILATION_CACHE_DIR
        the compiles also land on disk for later processes). Runs
        against slot 0 of the real cache with zero prompts — the slot
        is free, so the junk K/V is masked and the next real occupant's
        prefill overwrites it."""
        key = self._next_key()
        cache = self._cache
        for c in self._chunk_lengths():
            zeros = np.zeros((1, c), np.int32)
            _, cache = self._prefill(
                self._params, cache, np.int32(0), zeros, np.int32(0), key
            )
        warmed = ["prefill_chunk_into_cache"]
        if self._P:
            # Self-copy of free slot 0: compiles the (src, dst)-traced
            # kernel without touching live state.
            cache = self._copy(cache, np.int32(0), np.int32(0))
            warmed.append("copy_cache_slot")
        toks, _ok, cache = self._step(
            self._params, cache,
            np.zeros(self._S, np.int32), np.zeros(self._S, bool),
            np.zeros(self._S, np.int32), key,
        )
        np.asarray(toks)  # force the compile + execution to finish
        self._cache = cache
        warmed.append("decode_step_slots")
        return warmed

    # ------------------------------------------------------------ submit

    @property
    def inflight_rows(self) -> int:
        """Rows resident in slots — decoding OR mid-prefill."""
        return sum(1 for o in self._occupant if o is not None)

    # Legacy counter/queue surface, owned by the shared core (the
    # runtime sampler, drain plumbing, and resilience tests read these
    # names on both schedulers).
    @property
    def pending_rows(self) -> int:
        """Rows awaiting a slot: queued fresh rows plus preempted rows
        awaiting re-bind. Deliberately lock-free (GIL-atomic int read
        + deque len): the runtime sampler's gauge read must never
        queue behind admission."""
        return (self._sched_core.pending_rows
                + len(self._resume))  # tdnlint: disable=lock-discipline

    @property
    def requests_total(self) -> int:
        return self._sched_core.requests_total

    @property
    def shed_total(self) -> int:
        return self._sched_core.shed_total

    @property
    def expired_total(self) -> int:
        return self._sched_core.expired_total

    @property
    def _pending(self) -> list:
        return self._sched_core.pending_items()

    @property
    def _closed(self) -> bool:
        return self._sched_core.closed

    def queue_depth(self) -> int:
        """Entries awaiting a slot (deliberately lock-free — the
        runtime sampler's per-tick read): queued fresh items plus
        preempted rows awaiting resume."""
        return (self._sched_core.queue_depth()
                + len(self._resume))  # tdnlint: disable=lock-discipline

    def pending_by_class(self) -> dict:
        return self._sched_core.pending_by_class()

    @property
    def slots(self) -> int:
        return self._S

    @property
    def slots_active(self) -> int:
        """Alias of :attr:`inflight_rows` under its generation name."""
        return self.inflight_rows

    @property
    def steps_total(self) -> int:
        """Step-kernel launches, under the name the occupancy ratio
        reads naturally (alias of ``batches_total`` — a device launch
        IS a decode step here)."""
        return self.batches_total

    # Prefix-cache accounting (None-safe: 0 with the pool off, so the
    # sampler/bench read one shape regardless of configuration).
    @property
    def prefix_blocks(self) -> int:
        return self._P

    @property
    def prefix_blocks_used(self) -> int:
        return self._pool.used if self._pool is not None else 0

    @property
    def prefix_hits_total(self) -> int:
        return self._pool.hits_total if self._pool is not None else 0

    @property
    def prefix_misses_total(self) -> int:
        return self._pool.misses_total if self._pool is not None else 0

    @property
    def prefix_evictions_total(self) -> int:
        return self._pool.evictions_total if self._pool is not None else 0

    @property
    def prefix_hit_ratio(self) -> float:
        n = self.prefix_hits_total + self.prefix_misses_total
        return self.prefix_hits_total / n if n else 0.0

    def submit(self, x: np.ndarray, *, max_new_tokens: int | None = None,
               timeout: float | None = None, ctx=None,
               slo_class: str = "standard") -> np.ndarray:
        """Block until every row of ``x (N, prompt_len)`` has finished
        generating; returns ``(N, prompt_len + max_new_tokens)`` int64
        (prompt included, post-retirement positions padded with
        ``eos_id``, or with token id 0 when no ``eos_id`` is configured
        — identical row semantics to the static scheduler, whose only
        retire reason without an eos is the full budget, so the 0-pad
        case is reachable only via per-request ``max_new_tokens``).

        ``max_new_tokens`` caps THIS request below the endpoint budget
        (iteration-level scheduling makes per-request budgets free:
        the row simply retires earlier); the output width stays the
        endpoint's. ``timeout``/``ctx`` follow ``_Batcher.submit``.
        ``slo_class`` sets queue priority and the shed watermark; a
        ``critical`` row that cannot bind may PREEMPT a lower-class
        resident (docs/ROBUSTNESS.md "Degradation ladder").
        """
        x = np.asarray(x, np.int32)
        if x.ndim != 2 or x.shape[1] != self._T:
            raise ValueError(
                f"expected prompts of shape (N, {self._T}), got "
                f"{tuple(x.shape)}"
            )
        budget = self._N if max_new_tokens is None else int(max_new_tokens)
        if not 1 <= budget <= self._N:
            raise ValueError(
                f"max_new_tokens must be in [1, {self._N}], got {budget}"
            )
        n = len(x)
        out = np.full(
            (n, self._T + self._N),
            self._eos if self._eos is not None else 0, np.int64,
        )
        out[:, :self._T] = x
        if n == 0:
            # Nothing to decode: answer immediately (the static batcher
            # round-trips an empty matrix too). Queueing it would hand
            # the loop a rowless item whose bogus occupant corrupts the
            # ledger.
            return out
        item = {
            "x": x, "budget": budget, "out": out, "next_row": 0,
            "remaining": n, "done": threading.Event(), "err": None,
            "abandoned": False, "t_submit": time.monotonic(),
            "slo_class": slo_class,
            "ctx": ctx if ctx is not None and ctx.sampled else None,
        }
        # Admission (class watermark, close check, deadline stamp) and
        # the bounded wait are the shared core's contract — identical
        # to _Batcher by construction. Abandoned rows already decoding
        # finish their (bounded) budget and are discarded; rows still
        # pending are skipped at bind.
        self._sched_core.admit(item, timeout)
        self._sched_core.wait(item, what="generation")
        return item["out"]

    def submit_stream(self, x: np.ndarray, *,
                      max_new_tokens: int | None = None,
                      timeout: float | None = None, ctx=None,
                      slo_class: str = "standard",
                      resume_tokens=None,
                      max_buffer: int = 4096) -> TokenStream:
        """Admit ONE prompt row ``(1, prompt_len)`` for streaming
        generation and return its :class:`TokenStream` immediately (the
        GenerateStream handler drains it; nothing blocks here beyond
        admission itself, which can shed). Single-row by contract:
        frame ordering and failover resume are per-sequence concepts —
        a client streams N prompts over N streams.

        ``timeout`` is STREAM-aware (docs/ROBUSTNESS.md): it bounds the
        submit-to-first-token wait (queue + prefill) and then each
        NEXT-TOKEN gap — the deadline slides forward at every published
        token — instead of total retirement time, so a long generation
        that is steadily producing tokens never expires mid-stream.

        ``resume_tokens`` is the router's mid-stream-failover prefix:
        tokens the CLIENT already holds. The row binds through the
        preemption-resume path (prompt re-prefill + forced-token
        replay, bit-identical at temperature 0) and the stream's sent
        cursor swallows the replayed prefix, so the client receives
        each token exactly once across the replica switch.
        """
        x = np.asarray(x, np.int32)
        if x.ndim != 2 or x.shape != (1, self._T):
            raise ValueError(
                f"streaming expects ONE prompt of shape (1, {self._T}), "
                f"got {tuple(x.shape)}"
            )
        budget = self._N if max_new_tokens is None else int(max_new_tokens)
        if not 1 <= budget <= self._N:
            raise ValueError(
                f"max_new_tokens must be in [1, {self._N}], got {budget}"
            )
        resume = [int(t) for t in resume_tokens] if resume_tokens else None
        stream = TokenStream(max_buffer)
        if resume is not None:
            # The client already holds the whole replayed prefix.
            stream.seed(len(resume))
            # Degenerate resumes — the stream actually FINISHED on the
            # dead replica (terminal frame lost in the failover): there
            # is nothing left to generate, so answer the terminal
            # without burning a slot on a full replay.
            if self._eos is not None and self._eos in resume:
                stream.finish("eos")
                return stream
            if len(resume) >= budget:
                stream.finish("max_tokens")
                return stream
        out = np.full(
            (1, self._T + self._N),
            self._eos if self._eos is not None else 0, np.int64,
        )
        out[:, :self._T] = x
        item = {
            "x": x, "budget": budget, "out": out, "next_row": 0,
            "remaining": 1, "err": None,
            "abandoned": False, "t_submit": time.monotonic(),
            "slo_class": slo_class,
            "ctx": ctx if ctx is not None and ctx.sampled else None,
            "stream": stream,
            # Per-token-gap budget: _publish slides item["deadline"]
            # forward by this much at every published token.
            "gap_budget": timeout,
            # Consumed at bind: routes the row through the preemption-
            # resume path (forced-token replay).
            "resume_tokens": resume,
        }
        # The done Event is the terminal seam: every existing exit path
        # (_retire, _free_slot_on_error, queue expiry, close sweeps)
        # already stamps err/finish_reason then calls done.set() — the
        # StreamDone subclass turns that into the END frame.
        item["done"] = StreamDone(item, stream)
        self._sched_core.admit(item, timeout)
        return stream

    # ------------------------------------------------------------ loop

    def _publish(self, occ: dict) -> None:
        """Flush the occupant's known-token list into its stream, if it
        has one (called after every ``occ["tokens"]`` append). A dead
        stream (client gone / buffer overflow) marks the item abandoned
        — the loop's reap pass frees the slot next iteration. A live
        publish slides the stream's next-token-gap deadline."""
        item = occ["item"]
        stream = item.get("stream")
        if stream is None:
            return
        if not stream.publish(occ["tokens"]):
            item["abandoned"] = True
            return
        slide_stream_deadline(item, item.get("gap_budget"))

    def _reap_cancelled(self) -> None:
        """Free resident slots whose STREAM item died — client abandon,
        gRPC cancellation, or backpressure overflow (satellite 2: the
        cancel-propagation half of the streaming contract). Unary items
        keep their documented semantics: abandoned rows already
        decoding finish their bounded budget and are discarded."""
        for s in range(self._S):
            occ = self._occupant[s]
            if occ is None:
                continue
            item = occ["item"]
            if item.get("stream") is None:
                continue
            if not (item["abandoned"] or item["err"] is not None):
                continue
            self._occupant[s] = None
            self._active[s] = False
            self._release_block(occ)
            self.retired_total += 1
            _RETIRED.labels(reason="cancelled").inc()
            _TOKENS.inc(len(occ["tokens"]))
            self._sched_core.note_drained(1)
            item["remaining"] -= 1
            slog.info(
                "gen.stream_cancelled", slot=s,
                tokens_generated=len(occ["tokens"]),
            )

    def _release_block(self, occ: dict) -> None:
        """Drop the occupant's prefix-block reference, if it holds one
        (once — retire, fault, and drain paths all funnel here)."""
        block = occ.pop("block", None)
        if block is not None and self._pool is not None:
            self._pool.release(block)

    def _free_slot_on_error(self, slot: int, e: Exception) -> None:
        """Fail ONE occupant's item over (a mid-prefill or per-request
        fault) and free its slot + prefix ref so the scheduler keeps
        serving later arrivals."""
        occ = self._occupant[slot]
        self._occupant[slot] = None
        self._active[slot] = False
        self._release_block(occ)
        item = occ["item"]
        if item["err"] is None:
            item["err"] = e
            item["done"].set()

    def _fail_occupants(self, e: Exception) -> None:
        """A step-kernel fault leaves the shared cache pytree in an
        unknown state, so it hits every resident row — decoding AND
        mid-prefill: fail their items over (a row cannot be replayed —
        its sampling position in the stream is gone) and free the
        slots so the scheduler keeps serving later arrivals."""
        for s in range(self._S):
            if self._occupant[s] is not None:
                self._free_slot_on_error(s, e)

    def _device_fault(self, e: Exception) -> None:
        """A REAL kernel call raised (not an injected hook fault, which
        fires before the dispatch): the cache buffer was DONATED to
        that call and may already be consumed, so per-slot recovery is
        impossible — fail every resident over, rebuild a fresh zeroed
        cache (every slot is free after the fan-out, so zeroes are the
        correct contents), and drop the prefix pool, whose blocks lived
        in the dead cache. The scheduler then keeps serving later
        arrivals — the same contract as before, paid for with a cold
        prefix pool."""
        self._fail_occupants(e)
        if self._make_cache is not None:
            try:
                self._cache = self._make_cache()
            except Exception:  # noqa: BLE001 — backend fully down
                log.exception("cache rebuild after device fault failed")
        if self._pool is not None:
            self._pool.clear()

    def _retire(self, slot: int, reason: str) -> None:
        occ = self._occupant[slot]
        item, row = occ["item"], occ["row"]
        toks = occ["tokens"]
        item["out"][row, self._T:self._T + len(toks)] = toks
        # Terminal state BEFORE done.set(): a streaming item's
        # StreamDone reads it to build the END frame.
        item["finish_reason"] = reason
        self._active[slot] = False
        self._occupant[slot] = None
        self._release_block(occ)
        self.retired_total += 1
        _RETIRED.labels(reason=reason).inc()
        _TOKENS.inc(len(toks))
        # Completions feed the drain-rate window behind the shed
        # replies' x-tdn-retry-after-ms hint.
        self._sched_core.note_drained(1)
        if item["ctx"] is not None:
            _trace.TRACER.record_span(
                "decode", item["ctx"], occ["t_first"],
                time.monotonic() - occ["t_first"],
                attrs={"slot": slot, "steps": len(toks), "reason": reason},
            )
        item["remaining"] -= 1
        if item["remaining"] == 0 and not item["abandoned"]:
            item["done"].set()

    def _tier_keys(self, row: np.ndarray):
        """The prompt's cacheable-prefix candidates, longest first —
        the exact-match lookup/insert keys (the raw prefix bytes: no
        hash collisions to reason about). Lazy: ``lookup`` early-exits
        on the first (longest) hit, so a warm-pool deepest-tier hit
        copies exactly one prefix instead of materializing every tier
        of a long prompt on the scheduler loop thread."""
        return ((ln, row[:ln].tobytes()) for ln in self._tiers)

    def _bind_slot(self, item: dict, row: int,
                   resume: list | None = None) -> None:
        """Bind one pending row to a free slot (there is one — the
        caller checked): prefix-pool lookup, copy-on-write block copy
        on a hit, and the slot enters its chunked-prefill phase. No
        prompt tokens run here — chunks are the loop's per-iteration
        work, so binding never stalls the decode frontier.

        ``resume`` is a PREEMPTED row's generated token prefix: the
        slot re-prefills the prompt (prefix-cache hits make that
        cheap), then REPLAYS the prefix through the shared decode-step
        kernel with forced tokens — the exact computation the original
        run performed, so the resumed K/V and every subsequent greedy
        token are bit-identical to an unpreempted run (and a sampled
        run resumes its ORIGINAL stream instead of redrawing)."""
        slot = int(
            next(s for s in range(self._S) if self._occupant[s] is None)
        )
        now = time.monotonic()
        occ = {
            "item": item, "row": row, "tokens": [],
            "budget": item["budget"], "t_first": None,
            "t_bind": now, "fill": 0, "block": None,
            # Generated tokens to replay after the prompt re-prefill
            # (preemption resume); None on a fresh bind.
            "resume": list(resume) if resume else None,
        }
        self._occupant[slot] = occ
        self.rows_total += 1
        if item["ctx"] is not None and resume is None:
            _trace.TRACER.record_span(
                "queue_wait", item["ctx"], item["t_submit"],
                now - item["t_submit"],
            )
        if self._pool is None:
            return
        hit = self._pool.lookup(self._tier_keys(item["x"][row]))
        if hit is None:
            _PREFIX_MISSES.inc()
            return
        block, length = hit
        # Counted at lookup, BEFORE the copy, so this counter can never
        # diverge from the pool's own hits_total (which lookup() just
        # bumped) — a hit whose COW copy then faults is still a hit in
        # both ledgers.
        _PREFIX_HITS.inc()
        try:
            self._cache = self._copy(
                self._cache, np.int32(self._S + block), np.int32(slot)
            )
        except Exception as e:  # noqa: BLE001 — donated cache: global fault
            occ["block"] = block
            self._device_fault(e)
            return
        occ["fill"] = length
        occ["block"] = block
        if self._gp_model is not None:
            # The hit's savings: the chunk launches that will never run
            # for positions [0, length) (counted as savings, never as
            # useful work — the work was NOT done).
            GOODPUT.record_prefix_saved(
                self._gp_model.prefill_chunks_flops(0, length, self._chunk)
            )
        slog.info(
            "gen.prefix_hit", slot=slot, block=block, prefix_len=length,
            suffix_len=self._T - length,
        )

    def _next_prefill_slot(self) -> int | None:
        """The next slot with prefill work, round-robin so concurrent
        long prompts chunk fairly instead of head-of-line blocking each
        other."""
        for i in range(self._S):
            s = (self._prefill_rr + i) % self._S
            occ = self._occupant[s]
            if occ is not None and not self._active[s] \
                    and occ["fill"] < self._T:
                self._prefill_rr = (s + 1) % self._S
                return s
        return None

    def _maybe_insert_tiers(self, slot: int, occ: dict, start: int) -> None:
        """After a chunk lands, publish any newly-completed prefix tier
        in ``(start, fill]`` into the pool (slot -> block copy). Failure
        to insert — pool full of referenced blocks, or a copy fault —
        skips silently: caching is an optimization, never load-bearing."""
        row = occ["item"]["x"][occ["row"]]
        for length in reversed(self._tiers):  # ascending
            if not start < length <= occ["fill"]:
                continue
            block, evicted = self._pool.insert(row[:length].tobytes(), length)
            if evicted:
                _PREFIX_EVICTIONS.inc()
            if block is None:
                continue
            try:
                self._cache = self._copy(
                    self._cache, np.int32(slot), np.int32(self._S + block)
                )
            except Exception as e:  # noqa: BLE001 — donated cache: global
                log.warning("prefix-block insert copy failed: %s", e)
                self._device_fault(e)
                return

    def _prefill_chunk_once(self, slot: int) -> None:
        """Run ONE chunk of ``slot``'s pending prefill — the at-most-
        one-chunk-per-iteration budget that keeps a long prompt from
        freezing the resident decode streams. The final chunk yields
        the prompt's last-position sample: the request's first token
        (TTFT), after which the slot joins the decode frontier."""
        occ = self._occupant[slot]
        item = occ["item"]
        start = occ["fill"]
        size = (
            self._T - start if self._chunk is None
            else min(self._chunk, self._T - start)
        )
        tokens = item["x"][occ["row"]:occ["row"] + 1, start:start + size]
        t0 = time.monotonic()
        if self.prefill_hook is not None:
            # Hook faults fire BEFORE the dispatch: the cache is still
            # intact, so only THIS request fails over — the mid-prefill
            # chaos contract (slot freed, prefix ref released).
            try:
                self.prefill_hook(tokens)
            except Exception as e:  # noqa: BLE001 — per item
                self._free_slot_on_error(slot, e)
                return
        try:
            tok, cache = self._prefill(
                self._params, self._cache, np.int32(slot), tokens,
                np.int32(start), self._next_key(),
            )
        except Exception as e:  # noqa: BLE001 — donated cache: global
            self._device_fault(e)
            return
        self._cache = cache
        try:
            tok = int(tok)  # the token fetch (host sync)
        except Exception as e:  # noqa: BLE001 — donated cache: global
            # On async backends a failed LAUNCH surfaces here, at the
            # first host sync of its results — the rebound cache is the
            # poisoned donated output, so this is a device fault, not a
            # per-item one (on the sync CPU backend a post-return fetch
            # failure is unreachable, so nothing is lost by escalating).
            self._device_fault(e)
            return
        occ["fill"] = start + size
        self.prefill_chunks_total += 1
        if self._gp_model is not None:
            # A resume re-prefill's last-position logits are DISCARDED
            # (the first generated token is already known), so its
            # final chunk carries no sampled-unembed useful work.
            GOODPUT.record_prefill_chunk(
                self._gp_model, start, size,
                final=occ["fill"] >= self._T and occ["resume"] is None,
            )
        now = time.monotonic()
        if item["ctx"] is not None:
            _trace.TRACER.record_span(
                "prefill.chunk", item["ctx"], t0, now - t0,
                attrs={"slot": slot, "start": start, "tokens": size},
            )
        if self._pool is not None:
            self._maybe_insert_tiers(slot, occ, start)
            if self._occupant[slot] is not occ:
                return  # an insert-copy fault failed the slot over
        if occ["fill"] < self._T:
            return
        if occ["resume"] is not None:
            # Preemption resume: the first generated token is KNOWN —
            # the prefill's last-position sample is discarded, the
            # remaining prefix replays through the shared step kernel
            # with forced tokens (bit-identical K/V to the original
            # run; TTFT was observed on the first pass and is not
            # re-counted).
            known = occ["resume"]
            occ["resume"] = None
            occ["replay"] = known[1:]
            first = int(known[0])
            occ["t_first"] = now
            if item["ctx"] is not None:
                _trace.TRACER.record_span(
                    "prefill", item["ctx"], occ["t_bind"],
                    now - occ["t_bind"],
                    attrs={
                        "slot": slot, "prompt_len": self._T,
                        "prefix_hit": occ["block"] is not None,
                        "resume_tokens": len(known),
                    },
                )
            occ["tokens"].append(first)
            self._publish(occ)
            self._active[slot] = True
            self._pos[slot] = self._T
            self._tok[slot] = first
            return
        # Prefill complete: `tok` is the sample from the prompt's last
        # position — the first generated token.
        ttft = now - item["t_submit"]
        _TTFT.observe(ttft)
        self.ttft_recent.append(ttft)
        occ["t_first"] = now
        if item["ctx"] is not None:
            _trace.TRACER.record_span(
                "prefill", item["ctx"], occ["t_bind"], now - occ["t_bind"],
                attrs={
                    "slot": slot, "prompt_len": self._T,
                    "prefix_hit": occ["block"] is not None,
                },
            )
        occ["tokens"].append(tok)
        self._publish(occ)
        self._active[slot] = True
        self._pos[slot] = self._T
        self._tok[slot] = tok
        if self._eos is not None and tok == self._eos:
            self._retire(slot, "eos")
        elif len(occ["tokens"]) >= occ["budget"]:
            self._retire(slot, "max_tokens")

    def _step_once(self) -> None:
        """One compiled step over every decoding slot; retire/refill
        happens on the host between steps (the iteration-level
        boundary)."""
        t0 = time.monotonic()
        traced = [
            self._occupant[s] for s in range(self._S)
            if self._active[s] and self._occupant[s]["item"]["ctx"] is not None
        ]
        def fail(e: Exception, kernel: bool) -> None:
            # Rate-limited: a wedged backend fails every subsequent
            # step too — the first few stack traces are the signal,
            # thousands more per minute are noise.
            slog.exception(
                "gen.step_failed", error=f"{type(e).__name__}: {e}",
                active_slots=int(self._active.sum()),
                steps_total=self.batches_total,
            )
            # A raise from the kernel call itself may have consumed
            # the donated cache; hook/fetch faults leave it intact.
            self._device_fault(e) if kernel else self._fail_occupants(e)

        if self.launch_hook is not None:
            try:
                self.launch_hook(self._tok)
            except Exception as e:  # noqa: BLE001 — fan out to occupants
                fail(e, kernel=False)
                return
        try:
            toks, ok, cache = self._step(
                self._params, self._cache, self._pos, self._active,
                self._tok, self._next_key(),
            )
        except Exception as e:  # noqa: BLE001 — fan out to occupants
            fail(e, kernel=True)
            return
        self._cache = cache
        if self.fetch_hook is not None:
            try:
                self.fetch_hook(toks)
            except Exception as e:  # noqa: BLE001 — fan out to occupants
                fail(e, kernel=False)
                return
        try:
            toks = np.asarray(toks)
            ok = np.asarray(ok) if ok is not None else None
        except Exception as e:  # noqa: BLE001 — fan out to occupants
            # Async backends surface a failed launch at this first host
            # sync: the rebound cache is the poisoned donated output,
            # so recover as a device fault (kernel=True), unlike the
            # pre-sync hook fault above which leaves the cache intact.
            fail(e, kernel=True)
            return
        # Act on the in-kernel numeric guard (host decision — the
        # runtime opt-out never reshapes the compiled kernel): a slot
        # whose logits went non-finite fails over ALONE with INTEGRITY
        # before its garbage token ships; every other slot's stream is
        # untouched (bit-parity preserved).
        bad_slots: list[int] = []
        if ok is not None and _integrity.GUARD.enabled:
            bad_slots = [
                s for s in range(self._S)
                if self._active[s] and not ok[s]
            ]
        if bad_slots:
            _integrity.GUARD_ROWS_FAILED.inc(len(bad_slots))
            _integrity.GUARD_LAUNCHES.inc()
            from tpu_dist_nn.utils.errors import IntegrityError

            for s in bad_slots:
                slog.warning(
                    "gen.integrity_guard_tripped", slot=s,
                    tokens_generated=len(self._occupant[s]["tokens"]),
                )
                self._free_slot_on_error(s, IntegrityError(
                    f"numeric guard: decode step produced non-finite "
                    f"logits for slot {s} — failing this row instead "
                    f"of shipping a garbage token"
                ))
        self.batches_total += 1
        active = int(self._active.sum())
        self.slot_steps_total += active
        self._m_rows.observe(active)
        if self._gp_model is not None:
            # Goodput split of this launch at slot granularity (Orca's
            # waste taxonomy): active lanes are useful up to their live
            # attention frontier (launch-time pos — read BEFORE the
            # retire loop advances it), occupied-but-chunking lanes are
            # mid_prefill pad, empty lanes idle pad.
            active_pos = []
            idle = mid = replay = 0
            for s in range(self._S):
                if self._active[s]:
                    if self._occupant[s].get("replay"):
                        # Re-doing work the preemption threw away:
                        # booked as pad (reason preempt_replay), never
                        # as useful.
                        replay += 1
                    else:
                        active_pos.append(int(self._pos[s]))
                elif self._occupant[s] is None:
                    idle += 1
                else:
                    mid += 1
            GOODPUT.record_decode_step(
                self._gp_model, active_pos, idle, mid,
                replay_slots=replay,
            )
        dur = time.monotonic() - t0
        for occ in traced:
            if occ["item"]["err"] is not None:
                continue
            _trace.TRACER.record_span(
                "decode.step", occ["item"]["ctx"], t0, dur,
                attrs={"active_slots": active},
            )
        for s in range(self._S):
            if not self._active[s]:
                continue
            occ = self._occupant[s]
            if occ.get("replay"):
                # Preemption replay: the step WROTE this position's
                # K/V from the forced token (the same computation the
                # original run performed); its sample is discarded —
                # the next token is already known. No retire checks:
                # the replayed stream was mid-decode when preempted.
                forced = int(occ["replay"].pop(0))
                occ["tokens"].append(forced)
                self._publish(occ)
                self._pos[s] += 1
                self._tok[s] = forced
                continue
            tok = int(toks[s])
            occ["tokens"].append(tok)
            self._publish(occ)
            self._pos[s] += 1
            self._tok[s] = tok
            if self._eos is not None and tok == self._eos:
                self._retire(s, "eos")
            elif len(occ["tokens"]) >= occ["budget"]:
                self._retire(s, "max_tokens")

    def _resident(self) -> bool:
        """Any slot occupied — decoding or mid-prefill (both must drain
        before close() may stop the loop)."""
        return any(o is not None for o in self._occupant)

    def _next_bindable(self, max_rank: int | None = None):  # caller-holds: _cond
        """The next row to bind, in class-priority order across BOTH
        sources — preempted rows awaiting resume and the fresh queue
        (a tie goes to the resume row: it was admitted earlier).
        ``max_rank=0`` restricts to critical (the preemption pop).
        Returns ``("resume", entry)`` / ``("fresh", (item, row))`` /
        None."""
        core = self._sched_core
        while True:
            # Best-ranked resume entry, FIFO within rank: _resume is
            # one deque in preemption order, so a head-only peek would
            # let an earlier best_effort eviction shadow a later
            # standard one.
            entry = idx = None
            e_rank = 99
            for i, cand in enumerate(self._resume):
                r = CLASS_RANK.get(cand["slo_class"], 1)
                if max_rank is not None and r > max_rank:
                    continue
                if r < e_rank:
                    entry, idx, e_rank = cand, i, r
                    if r == 0:
                        break  # nothing outranks critical
            f_rank = core.peek_rank()
            if (f_rank is not None and max_rank is not None
                    and f_rank > max_rank):
                f_rank = None
            if entry is not None and (f_rank is None or e_rank <= f_rank):
                del self._resume[idx]
                item = entry["item"]
                if item["abandoned"] or item["err"] is not None:
                    continue  # waiter gone while awaiting resume
                dl = item.get("deadline")
                if dl is not None and time.monotonic() >= dl:
                    # Budget died while the row waited to resume: same
                    # expiry contract as a queued entry.
                    core._expire(item, time.monotonic())
                    continue
                return "resume", entry
            got = core.pop_row(max_rank=max_rank)
            if got is not None:
                return "fresh", got
            if entry is None:
                return None
            # Fresh queue exhausted (or all dead): retry the resume
            # head on the next pass.

    def _bind(self, bindable) -> None:
        kind, data = bindable
        if kind == "resume":
            self._bind_slot(data["item"], data["row"],
                            resume=data["tokens"])
        else:
            item, row = data
            # A streaming failover resume (submit_stream's
            # resume_tokens) rides the SAME replay path a preemption
            # victim uses: re-prefill the prompt, force-replay the
            # already-delivered tokens, continue bit-identically.
            self._bind_slot(item, row,
                            resume=item.pop("resume_tokens", None))

    def _pick_victim(self) -> int | None:
        """The slot to preempt for a critical bind: never a critical
        resident; prefer occupants whose waiter is already gone
        (abandoned / budget-expired — evicting them costs nothing),
        then the LOWEST class, then the fewest generated tokens (the
        cheapest replay). None when every resident is critical."""
        now = time.monotonic()
        best = best_key = None
        for s in range(self._S):
            occ = self._occupant[s]
            if occ is None:
                continue
            item = occ["item"]
            rank = CLASS_RANK.get(item.get("slo_class", "standard"), 1)
            if rank == 0:
                continue
            dl = item.get("deadline")
            dead = item["abandoned"] or (dl is not None and now >= dl)
            key = (0 if dead else 1, -rank, len(occ["tokens"]))
            if best_key is None or key < best_key:
                best_key, best = key, s
        return best

    def _preempt_slot(self, slot: int) -> None:
        """Evict one resident so a critical row can bind: the victim's
        prompt + generated prefix re-queue for resume (re-prefill +
        forced-token replay — bit-identical continuation), its slot
        and prefix-block reference free immediately."""
        now = time.monotonic()
        occ = self._occupant[slot]
        item = occ["item"]
        cls = item.get("slo_class", "standard")
        # The full known generated stream, whatever phase the victim
        # was in: mid-resume-prefill (resume holds it all), mid-replay
        # (tokens + the un-replayed remainder), or plain decoding.
        if occ.get("resume"):
            prefix = list(occ["resume"])
        else:
            prefix = list(occ["tokens"]) + list(occ.get("replay") or ())
        self._occupant[slot] = None
        self._active[slot] = False
        self._release_block(occ)
        self.preempted_total += 1
        _PREEMPTED.labels(slo_class=cls).inc()
        if item["ctx"] is not None and occ["t_first"] is not None:
            _trace.TRACER.record_span(
                "decode", item["ctx"], occ["t_first"],
                now - occ["t_first"],
                attrs={"slot": slot, "steps": len(occ["tokens"]),
                       "reason": "preempted"},
            )
        slog.info(
            "gen.preempted", slot=slot, slo_class=cls,
            tokens_generated=len(prefix),
        )
        if item["abandoned"] or item["err"] is not None:
            return  # nobody is waiting: evicted work is simply dropped
        with self._cond:
            self._resume.append({
                "item": item, "row": occ["row"], "tokens": prefix,
                "slo_class": cls,
            })

    def _preempt_for_critical(self) -> None:
        """While a critical row is queued with no free slot, evict the
        best victim and bind the critical row INTO the freed slot —
        same scheduler iteration, so the class the SLO pages on never
        waits out a lower-class resident's full decode."""
        while True:
            victim = self._pick_victim()
            if victim is None:
                return
            with self._cond:
                got = self._next_bindable(max_rank=0)
            if got is None:
                return
            self._preempt_slot(victim)
            self._bind(got)

    def _loop(self) -> None:
        core = self._sched_core
        while True:
            # Cancel propagation first: slots freed by dead streams are
            # bindable THIS iteration (satellite 2 — a cancel storm must
            # not strand slots for even one extra step).
            self._reap_cancelled()
            admits = []
            with self._cond:
                while (not core.closed and not core.has_pending()
                       and not self._resume and not self._resident()):
                    self._cond.wait()
                if core.closed and not self._resident():
                    return  # close() sweeps whatever is still pending
                if not core.closed:
                    free = sum(1 for o in self._occupant if o is None)
                    while len(admits) < free:
                        got = self._next_bindable()
                        if got is None:
                            break
                        admits.append(got)
            core.drain_deferred()
            # Device work OUTSIDE the lock: submitters must never block
            # behind a block copy, a prefill chunk, or a step.
            for bindable in admits:
                self._bind(bindable)
            if self._preemption and not core.closed:
                self._preempt_for_critical()
            slot = self._next_prefill_slot()
            if slot is not None:
                self._prefill_chunk_once(slot)
            if self._active.any():
                self._step_once()

    # ------------------------------------------------------------ close

    def close(self, timeout: float = 10.0) -> None:
        """Stop admitting, let resident rows — including half-prefilled
        slots, which finish their remaining chunks — complete their
        (bounded) decodes, then fail still-pending waiters over as
        UNAVAILABLE (preempted rows awaiting resume included) — the
        ``_Batcher.close`` contract ``GracefulDrain`` relies on, now
        one implementation in the shared core."""
        from tpu_dist_nn.utils.errors import UnavailableError

        self._sched_core.close_begin()
        self._thread.join(timeout=timeout)
        # Preempted rows still awaiting a resume slot are pending too:
        # their waiters fail over like any queued entry's. Popped
        # under _cond, so a still-alive (wedged past the join timeout)
        # loop thread and this sweep can never double-serve or strand
        # an entry.
        leftovers = []
        with self._cond:
            while self._resume:
                leftovers.append(self._resume.popleft())
        for entry in leftovers:
            item = entry["item"]
            if not item["abandoned"] and item["err"] is None:
                item["err"] = UnavailableError(
                    "server shut down before this request was served"
                )
                item["done"].set()
        self._sched_core.sweep_leftovers()
