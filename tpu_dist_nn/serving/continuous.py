"""Continuous batching for LM generation: the iteration-level decode
scheduler (Orca, OSDI '22) on a slot-based KV cache.

The static Generate path (``serving/server.py``'s ``_Batcher`` over
``models.generate.generate``) is run-to-completion batching: a batch is
admitted, decodes ALL ``max_new_tokens`` steps, and only then does the
next batch start — a 4-token request pays for its 32-token neighbor,
and late arrivals convoy behind the whole batch. This module schedules
at DECODE-STEP granularity instead:

* One fixed ``(L, S, max_len, H, Dh)`` slot KV cache
  (:func:`~tpu_dist_nn.models.generate.init_slot_cache`) holds ``S``
  independent requests. Shapes never change — admission and retirement
  only flip entries of a per-slot active mask, the TPU-friendly
  static-shape answer to vLLM-style paged KV (one request = one slot =
  one contiguous ``max_len`` extent; no block tables, no gathers on
  the hot path — trade-off discussion in docs/PERF.md).
* **Admission at step granularity**: whenever a slot is free and a
  request is pending, its prompt prefills INTO that slot
  (:func:`~tpu_dist_nn.models.generate.prefill_into_cache`,
  ``lax.dynamic_update_slice`` at the traced slot index) and the
  request starts decoding on the very next step — no waiting for the
  current "batch" to finish, because there is no batch.
* **One compiled step kernel**
  (:func:`~tpu_dist_nn.models.generate.decode_step_slots`) advances
  every slot at its OWN position (per-slot ``pos`` vector + active
  mask) — mixed-age requests share each device launch.
* **Early retirement**: a slot frees on EOS
  (:func:`~tpu_dist_nn.models.generate.generate`'s stop-token
  semantics, so the two schedulers are output-comparable) or its
  per-request ``max_new_tokens`` — and the freed slot is refilled on
  the same scheduler iteration while the remaining slots keep
  decoding. Finished rows stream back to their waiters immediately.

Resilience contract (docs/ROBUSTNESS.md): ``max_pending_rows``
admission shedding (``tdn_batcher_shed_total``), ``close(timeout)``
failing still-pending waiters over as UNAVAILABLE (the ``_Batcher``
drain contract, so ``GracefulDrain`` works unchanged), and the
``testing/faults.py`` hook points — ``launch_hook`` fires before every
step-kernel dispatch, ``fetch_hook`` before its token fetch.
"""

from __future__ import annotations

import collections
import itertools
import logging
import threading
import time

import numpy as np

from tpu_dist_nn.obs import trace as _trace
from tpu_dist_nn.obs.log import get_logger
from tpu_dist_nn.obs.registry import POW2_BUCKETS, REGISTRY

log = logging.getLogger(__name__)  # plain channel (kept for debug use)
slog = get_logger(__name__)

# Generation metric families (docs/OBSERVABILITY.md catalog). Pushed by
# the scheduler loop; the slot gauges are sampled by obs/runtime.py.
_TTFT = REGISTRY.histogram(
    "tdn_gen_ttft_seconds",
    "time to first token: request submit to its first sampled token "
    "(prefill complete), continuous scheduler",
)
_TOKENS = REGISTRY.counter(
    "tdn_gen_tokens_total",
    "tokens emitted by the continuous decode scheduler",
)
_RETIRED = REGISTRY.counter(
    "tdn_gen_requests_retired_total",
    "request rows retired from a decode slot, by reason",
    labels=("reason",),
)
_SHED = REGISTRY.counter(
    "tdn_batcher_shed_total",
    "submits fast-failed RESOURCE_EXHAUSTED at the pending-rows "
    "watermark (admission control)",
    labels=("method",),
)
_WAIT = REGISTRY.histogram(
    "tdn_batch_wait_seconds",
    "time a request spent in the batcher (submit to result)",
    labels=("method",),
)
# Same family (and meaning — rows per device launch) as the static
# batcher's, so dashboards read the Generate series unchanged across
# schedulers: here a "launch" is one slot step and its rows are the
# active slots it advanced.
_BATCH_ROWS = REGISTRY.histogram(
    "tdn_batch_rows", "coalesced rows per device launch (pre-padding)",
    labels=("method",), buckets=POW2_BUCKETS,
)


class ContinuousScheduler:
    """Iteration-level decode scheduler over a slot-based KV cache.

    ``submit(rows)`` blocks the calling (gRPC worker) thread until every
    row's sequence is finished, exactly like ``_Batcher.submit`` — the
    difference is behind the call: one daemon loop thread owns the
    device, interleaving slot admission (prefill) with single-token
    steps over all active slots, retiring each row the moment it hits
    EOS or its token budget.

    Construction compiles nothing; :meth:`warm` precompiles the
    prefill-at-slot and step kernels so a port can open hot
    (``serve_lm_generate(warm_rows=...)`` / ``tdn warmup --lm``).

    Counter attributes mirror ``_Batcher`` (``requests_total``,
    ``batches_total`` = step-kernel launches, ``rows_total``,
    ``pending_rows``, ``inflight_rows`` = rows resident in slots,
    ``shed_total``) so the runtime sampler and drain plumbing work
    unchanged; generation-specific state (``slots_active``,
    ``steps_total``, ``slot_steps_total``, ``ttft_recent``) feeds the
    ``tdn_gen_*`` families.

    ``prefill_fn`` / ``step_fn`` are testing seams (the bench CI smoke
    injects a deterministic cost model); production always builds the
    real jitted kernels from ``params``/``cfg``.
    """

    method = "Generate"

    def __init__(self, params, cfg, *, slots: int, prompt_len: int,
                 max_new_tokens: int, temperature: float = 0.0,
                 top_k: int | None = None, top_p: float | None = None,
                 eos_id: int | None = None, seed: int = 0,
                 submit_timeout: float | None = 120.0,
                 max_pending_rows: int | None = None,
                 prefill_fn=None, step_fn=None):
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        self._S = int(slots)
        self._T = int(prompt_len)
        self._N = int(max_new_tokens)
        self._eos = None if eos_id is None else int(eos_id)
        self._submit_timeout = submit_timeout
        self._max_pending_rows = (
            int(max_pending_rows) if max_pending_rows is not None else None
        )
        self._counter = itertools.count()
        if prefill_fn is not None or step_fn is not None:
            if prefill_fn is None or step_fn is None:
                raise ValueError(
                    "prefill_fn and step_fn must be injected together"
                )
            self._prefill, self._step = prefill_fn, step_fn
            self._params = params
            self._cache = None
            self._key = None
            self._temperature = float(temperature)
        else:
            import jax

            from tpu_dist_nn.models.generate import validate_generate_args

            self._key = jax.random.key(int(seed))
            validate_generate_args(
                cfg, self._T, self._N, temperature, top_k, top_p,
                self._key if temperature > 0 else None, eos_id,
            )
            self._params = cfg.cast_params(params)
            self._temperature = float(temperature)
            self._build_kernels(
                cfg, float(temperature), top_k, top_p,
            )
        # Host-side slot state: the loop thread is the only writer.
        self._pos = np.zeros(self._S, np.int32)
        self._active = np.zeros(self._S, bool)
        self._tok = np.zeros(self._S, np.int32)
        self._occupant: list[dict | None] = [None] * self._S
        # Fault-injection hook points (testing/faults.py): called at
        # the top of every step-kernel dispatch / token fetch.
        self.launch_hook = None
        self.fetch_hook = None
        # Pending queue + admission ledger (same shape as _Batcher).
        self._cond = threading.Condition()
        self._pending: collections.deque[dict] = collections.deque()
        self.pending_rows = 0
        self._closed = False
        # _Batcher-compatible counters (runtime sampler contract).
        self.requests_total = 0    # submit() calls admitted to the queue
        self.rows_total = 0        # rows that entered a slot
        self.batches_total = 0     # step-kernel launches (steps_total
        #                            is a read alias — one source of truth)
        self.shed_total = 0
        self.overlapped_total = 0  # N/A here; kept for sampler parity
        # Generation-specific stats.
        self.slot_steps_total = 0  # active slots summed over steps
        self.retired_total = 0     # rows retired (eos + max_tokens)
        self.ttft_recent: collections.deque[float] = collections.deque(
            maxlen=1024
        )
        self._m_shed = _SHED.labels(method=self.method)
        self._m_wait = _WAIT.labels(method=self.method)
        self._m_rows = _BATCH_ROWS.labels(method=self.method)
        self._thread = threading.Thread(
            target=self._loop, name="tdn-gen-continuous", daemon=True
        )
        self._thread.start()

    # ------------------------------------------------------------ kernels

    def _build_kernels(self, cfg, temperature, top_k, top_p) -> None:
        import jax
        import jax.numpy as jnp

        from tpu_dist_nn.models.generate import (
            _truncate_logits,
            decode_step_slots,
            init_slot_cache,
            prefill_into_cache,
        )

        # The last decode writes position T + N - 2 (generate()'s cache
        # sizing rule), so the slot extent is total - 1.
        M = self._T + self._N - 1 if self._N > 1 else self._T
        self._cache = init_slot_cache(cfg, self._S, M)
        top_k = None if top_k is None else int(top_k)
        top_p = None if top_p is None else float(top_p)

        def sample(logits, key):
            if temperature == 0:
                return jnp.argmax(logits, axis=-1).astype(jnp.int32)
            logits = _truncate_logits(logits, top_k, top_p)
            return jax.random.categorical(
                key, logits / temperature, axis=-1
            ).astype(jnp.int32)

        @jax.jit
        def prefill_at(params, cache, slot, tokens, key):
            logits, cache = prefill_into_cache(
                params, cfg, cache, slot, tokens
            )
            return sample(logits, key)[0], cache

        @jax.jit
        def step(params, cache, pos, active, tok, key):
            logits, cache = decode_step_slots(
                params, cache, pos, tok, cfg, active=active
            )
            return sample(logits, key), cache

        self._prefill = prefill_at
        self._step = step

    def _next_key(self):
        """A fresh fold of the base key per sampling event (prefill or
        step): repeated identical prompts draw fresh continuations, the
        serving endpoint's existing contract."""
        if self._key is None:
            return None
        if self._temperature == 0:
            return self._key  # unused inside the greedy kernels
        import jax

        return jax.random.fold_in(self._key, next(self._counter))

    def warm(self) -> list[str]:
        """Precompile the prefill-at-slot and step kernels (the port
        opens hot; with JAX_COMPILATION_CACHE_DIR the compiles also
        land on disk for later processes). Runs against slot 0 of the
        real cache with a zero prompt — the slot is free, so the junk
        K/V is masked and the next real occupant's prefill overwrites
        it."""
        zeros = np.zeros((1, self._T), np.int32)
        key = self._next_key()
        _, cache = self._prefill(
            self._params, self._cache, np.int32(0), zeros, key
        )
        toks, cache = self._step(
            self._params, cache,
            np.zeros(self._S, np.int32), np.zeros(self._S, bool),
            np.zeros(self._S, np.int32), key,
        )
        np.asarray(toks)  # force the compile + execution to finish
        self._cache = cache
        return ["prefill_into_cache", "decode_step_slots"]

    # ------------------------------------------------------------ submit

    @property
    def inflight_rows(self) -> int:
        return int(self._active.sum())

    @property
    def slots(self) -> int:
        return self._S

    @property
    def slots_active(self) -> int:
        """Alias of :attr:`inflight_rows` under its generation name."""
        return self.inflight_rows

    @property
    def steps_total(self) -> int:
        """Step-kernel launches, under the name the occupancy ratio
        reads naturally (alias of ``batches_total`` — a device launch
        IS a decode step here)."""
        return self.batches_total

    def submit(self, x: np.ndarray, *, max_new_tokens: int | None = None,
               timeout: float | None = None, ctx=None) -> np.ndarray:
        """Block until every row of ``x (N, prompt_len)`` has finished
        generating; returns ``(N, prompt_len + max_new_tokens)`` int64
        (prompt included, post-retirement positions padded with
        ``eos_id``, or with token id 0 when no ``eos_id`` is configured
        — identical row semantics to the static scheduler, whose only
        retire reason without an eos is the full budget, so the 0-pad
        case is reachable only via per-request ``max_new_tokens``).

        ``max_new_tokens`` caps THIS request below the endpoint budget
        (iteration-level scheduling makes per-request budgets free:
        the row simply retires earlier); the output width stays the
        endpoint's. ``timeout``/``ctx`` follow ``_Batcher.submit``.
        """
        from tpu_dist_nn.utils.errors import (
            DeadlineExceededError,
            ResourceExhaustedError,
            UnavailableError,
        )

        x = np.asarray(x, np.int32)
        if x.ndim != 2 or x.shape[1] != self._T:
            raise ValueError(
                f"expected prompts of shape (N, {self._T}), got "
                f"{tuple(x.shape)}"
            )
        budget = self._N if max_new_tokens is None else int(max_new_tokens)
        if not 1 <= budget <= self._N:
            raise ValueError(
                f"max_new_tokens must be in [1, {self._N}], got {budget}"
            )
        n = len(x)
        out = np.full(
            (n, self._T + self._N),
            self._eos if self._eos is not None else 0, np.int64,
        )
        out[:, :self._T] = x
        if n == 0:
            # Nothing to decode: answer immediately (the static batcher
            # round-trips an empty matrix too). Queueing it would hand
            # the loop a rowless item whose bogus occupant corrupts the
            # ledger.
            return out
        item = {
            "x": x, "budget": budget, "out": out, "next_row": 0,
            "remaining": n, "done": threading.Event(), "err": None,
            "abandoned": False, "t_submit": time.monotonic(),
            "ctx": ctx if ctx is not None and ctx.sampled else None,
        }
        with self._cond:
            if self._closed:
                raise UnavailableError("server is shutting down")
            # Admission control: same watermark semantics as _Batcher
            # (an oversized request against an empty queue is admitted;
            # the watermark bounds backlog, not request size).
            if (self._max_pending_rows is not None and self._pending
                    and self.pending_rows + n > self._max_pending_rows):
                self.shed_total += 1
                self._m_shed.inc()
                raise ResourceExhaustedError(
                    f"generation queue at capacity ({self.pending_rows} "
                    f"rows pending, watermark {self._max_pending_rows}); "
                    "back off and retry"
                )
            self._pending.append(item)
            self.pending_rows += n
            self.requests_total += 1
            self._cond.notify()
        bounds = [
            t for t in (self._submit_timeout, timeout) if t is not None
        ]
        wait = min(bounds) if bounds else None
        if not item["done"].wait(wait):
            # Abandoned rows already decoding finish their (bounded)
            # budget and are discarded; rows still pending are skipped
            # at admission. Either way nobody computes for a caller
            # that is gone for longer than one residual decode.
            with self._cond:
                item["abandoned"] = True
            raise DeadlineExceededError(
                f"generation did not complete within {wait}s "
                "(decode wedged or request backlogged?)"
            )
        self._m_wait.observe(time.monotonic() - item["t_submit"])
        if item["err"] is not None:
            raise item["err"]
        return item["out"]

    # ------------------------------------------------------------ loop

    def _pop_admittable(self):
        """Under ``_cond``: the next (item, row_index) to admit, or
        None. Drops abandoned/failed items from the queue, returning
        their rows to the ledger."""
        while self._pending:
            item = self._pending[0]
            if item["abandoned"] or item["err"] is not None:
                self._pending.popleft()
                self.pending_rows -= len(item["x"]) - item["next_row"]
                continue
            row = item["next_row"]
            item["next_row"] += 1
            self.pending_rows -= 1
            if item["next_row"] >= len(item["x"]):
                self._pending.popleft()
            return item, row
        return None

    def _fail_occupants(self, e: Exception) -> None:
        """A step-kernel fault hits every resident row: fail their
        items over (a row cannot be replayed — its sampling position
        in the stream is gone) and free the slots so the scheduler
        keeps serving later arrivals."""
        for s in range(self._S):
            occ = self._occupant[s]
            if occ is None:
                continue
            self._occupant[s] = None
            self._active[s] = False
            item = occ["item"]
            if item["err"] is None:
                item["err"] = e
                item["done"].set()

    def _retire(self, slot: int, reason: str) -> None:
        occ = self._occupant[slot]
        item, row = occ["item"], occ["row"]
        toks = occ["tokens"]
        item["out"][row, self._T:self._T + len(toks)] = toks
        self._active[slot] = False
        self._occupant[slot] = None
        self.retired_total += 1
        _RETIRED.labels(reason=reason).inc()
        _TOKENS.inc(len(toks))
        if item["ctx"] is not None:
            _trace.TRACER.record_span(
                "decode", item["ctx"], occ["t_first"],
                time.monotonic() - occ["t_first"],
                attrs={"slot": slot, "steps": len(toks), "reason": reason},
            )
        item["remaining"] -= 1
        if item["remaining"] == 0 and not item["abandoned"]:
            item["done"].set()

    def _admit_one(self, item: dict, row: int) -> None:
        """Prefill one row into a free slot (there is one — the caller
        checked) and start it decoding; a first token that already
        satisfies EOS/budget retires without ever occupying the slot
        across a step."""
        slot = int(np.flatnonzero(~self._active)[0])
        t0 = time.monotonic()
        try:
            first, cache = self._prefill(
                self._params, self._cache, np.int32(slot),
                item["x"][row:row + 1], self._next_key(),
            )
            first = int(first)
        except Exception as e:  # noqa: BLE001 — per item
            if item["err"] is None:
                item["err"] = e
                item["done"].set()
            return
        self._cache = cache
        now = time.monotonic()
        ttft = now - item["t_submit"]
        _TTFT.observe(ttft)
        self.ttft_recent.append(ttft)
        self.rows_total += 1
        if item["ctx"] is not None:
            _trace.TRACER.record_span(
                "queue_wait", item["ctx"], item["t_submit"],
                t0 - item["t_submit"],
            )
            _trace.TRACER.record_span(
                "prefill", item["ctx"], t0, now - t0,
                attrs={"slot": slot, "prompt_len": self._T},
            )
        occ = {"item": item, "row": row, "tokens": [first],
               "budget": item["budget"], "t_first": now}
        self._occupant[slot] = occ
        self._active[slot] = True
        self._pos[slot] = self._T
        self._tok[slot] = first
        if self._eos is not None and first == self._eos:
            self._retire(slot, "eos")
        elif len(occ["tokens"]) >= occ["budget"]:
            self._retire(slot, "max_tokens")

    def _step_once(self) -> None:
        """One compiled step over every slot; retire/refill happens on
        the host between steps (the iteration-level boundary)."""
        t0 = time.monotonic()
        traced = [
            self._occupant[s] for s in range(self._S)
            if self._active[s] and self._occupant[s]["item"]["ctx"] is not None
        ]
        try:
            if self.launch_hook is not None:
                self.launch_hook(self._tok)
            toks, cache = self._step(
                self._params, self._cache, self._pos, self._active,
                self._tok, self._next_key(),
            )
            if self.fetch_hook is not None:
                self.fetch_hook(toks)
            toks = np.asarray(toks)
        except Exception as e:  # noqa: BLE001 — fan out to occupants
            # Rate-limited: a wedged backend fails every subsequent
            # step too — the first few stack traces are the signal,
            # thousands more per minute are noise.
            slog.exception(
                "gen.step_failed", error=f"{type(e).__name__}: {e}",
                active_slots=int(self._active.sum()),
                steps_total=self.batches_total,
            )
            self._fail_occupants(e)
            return
        self._cache = cache
        self.batches_total += 1
        active = int(self._active.sum())
        self.slot_steps_total += active
        self._m_rows.observe(active)
        dur = time.monotonic() - t0
        for occ in traced:
            if occ["item"]["err"] is not None:
                continue
            _trace.TRACER.record_span(
                "decode.step", occ["item"]["ctx"], t0, dur,
                attrs={"active_slots": active},
            )
        for s in range(self._S):
            if not self._active[s]:
                continue
            occ = self._occupant[s]
            tok = int(toks[s])
            occ["tokens"].append(tok)
            self._pos[s] += 1
            self._tok[s] = tok
            if self._eos is not None and tok == self._eos:
                self._retire(s, "eos")
            elif len(occ["tokens"]) >= occ["budget"]:
                self._retire(s, "max_tokens")

    def _loop(self) -> None:
        while True:
            admits = []
            with self._cond:
                while (not self._closed and not self._pending
                       and not self._active.any()):
                    self._cond.wait()
                if (self._closed and not self._active.any()):
                    return  # close() sweeps whatever is still pending
                if not self._closed:
                    while self._active.sum() + len(admits) < self._S:
                        got = self._pop_admittable()
                        if got is None:
                            break
                        admits.append(got)
            # Device work OUTSIDE the lock: submitters must never block
            # behind a prefill or a step.
            for item, row in admits:
                self._admit_one(item, row)
            if self._active.any():
                self._step_once()

    # ------------------------------------------------------------ close

    def close(self, timeout: float = 10.0) -> None:
        """Stop admitting, let resident rows finish their (bounded)
        decodes, then fail still-pending waiters over as UNAVAILABLE —
        the ``_Batcher.close`` contract ``GracefulDrain`` relies on."""
        from tpu_dist_nn.utils.errors import UnavailableError

        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self._thread.join(timeout=timeout)
        leftovers = []
        with self._cond:
            while self._pending:
                item = self._pending.popleft()
                self.pending_rows -= len(item["x"]) - item["next_row"]
                if not item["abandoned"] and item["err"] is None:
                    leftovers.append(item)
        for item in leftovers:
            item["err"] = UnavailableError(
                "server shut down before this request was served"
            )
            item["done"].set()
