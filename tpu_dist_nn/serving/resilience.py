"""Client/server resilience: retries, circuit breaking, graceful drain.

The reference's whole failure story is status propagation plus "the
channel is dead, clients may retry elsewhere" (``grpc_node.py:136-158``)
— the retrying itself was left to the reader. This module is that
reader: the pieces a serving stack needs so a transient fault (engine
relaunch, rolling restart, dropped connection) costs a client one
backoff instead of a failed request, and a persistent fault costs one
fast-failed probe per cooldown instead of a timeout per call.

* :class:`RetryPolicy` — capped exponential backoff with FULL jitter
  (AWS-style: ``uniform(0, min(cap, base * 2^attempt))``), applied only
  to errors whose status classifies as transient (``UNAVAILABLE``,
  ``DEADLINE_EXCEEDED``). Budget-aware: every attempt's deadline is
  carved from the caller's REMAINING timeout, so a retried call can
  never exceed the budget the original call declared.
* :class:`CircuitBreaker` — per-target closed → open after N
  consecutive retryable failures, half-open probe after a cooldown.
  While open, calls fail fast with
  :class:`~tpu_dist_nn.utils.errors.UnavailableError` instead of
  burning a timeout each.
* :class:`GracefulDrain` — the rolling-restart shutdown sequence:
  SIGTERM → ``/healthz`` flips NOT_SERVING (load balancer stops
  routing) → gRPC stops accepting new calls → in-flight RPCs drain
  within the grace window → process exits. Without it, a restart turns
  every in-flight RPC into an INTERNAL/UNAVAILABLE surprise.

Determinism: the policy's jitter RNG is seedable and its sleep is
injectable, so tests (``tests/test_resilience.py``) drive the whole
retry schedule with no sleeps over a few ms; the breaker's clock is
injectable for the same reason. Observability: every decision lands in
a ``tdn_`` metric (docs/OBSERVABILITY.md) and as span annotations on
the retried client call (docs/ROBUSTNESS.md has the tuning guide).
"""

from __future__ import annotations

import dataclasses
import logging
import random
import signal
import threading
import time
import uuid

from tpu_dist_nn.obs.registry import REGISTRY

log = logging.getLogger(__name__)

# Stable for the life of THIS process, different every boot: /healthz
# carries it (wrap_health) so a poller can distinguish a restarted
# server on a reused address from the same process still answering.
BOOT_ID = uuid.uuid4().hex

# Retries the CLIENT issued, per method — the acceptance signal that a
# faulty run recovered through the policy rather than by luck.
CLIENT_RETRIES = REGISTRY.counter(
    "tdn_client_retries_total",
    "retry attempts issued by GrpcClient after a retryable status",
    labels=("method",),
)
# Breaker state per target: 0 closed, 1 half-open, 2 open (higher =
# less traffic flows). Alert on ==2 sustained.
BREAKER_STATE = REGISTRY.gauge(
    "tdn_breaker_state",
    "circuit breaker state per target (0=closed, 1=half-open, 2=open)",
    labels=("target",),
)
# 1 while this process is draining (SIGTERM received, /healthz already
# NOT_SERVING, in-flight work finishing) — the scrape that explains a
# refusing-but-alive server.
SERVER_DRAINING = REGISTRY.gauge(
    "tdn_server_draining",
    "1 while graceful drain is in progress (new work refused)",
)

# Status names the policy treats as transient. DEADLINE_EXCEEDED is
# retryable because the server carves it from a bounded submit wait (a
# wedged batch), which a fresh attempt may miss; INVALID_ARGUMENT /
# INTERNAL are deterministic and retrying them only doubles the damage.
RETRYABLE_CODES = frozenset({"UNAVAILABLE", "DEADLINE_EXCEEDED"})


def _code_name(code) -> str:
    """Accept a grpc.StatusCode, a FrameworkError code string, or an
    exception carrying ``.code`` — one classifier for every caller."""
    name = getattr(code, "name", None)
    if name is not None:
        return name
    if isinstance(code, str):
        return code
    inner = getattr(code, "code", None)
    if inner is not None and not callable(inner):
        return _code_name(inner)
    return "UNKNOWN"


@dataclasses.dataclass
class RetryPolicy:
    """Capped exponential backoff with full jitter, budget-aware.

    ``backoff(attempt)`` draws ``uniform(0, min(max_delay, base_delay *
    2^(attempt-1)))`` from a seedable RNG — full jitter, so a burst of
    clients that failed together does not retry together (the thundering
    herd the deterministic schedule would re-create). ``max_attempts``
    counts the ORIGINAL call: 3 means at most 2 retries; 1 disables
    retrying while keeping the classification/enrichment path.

    The caller (``GrpcClient._traced_call``) owns the total budget:
    each attempt's RPC deadline is the caller's remaining timeout, and
    a backoff that would sleep past the budget raises the last error
    instead — retries never extend the original deadline.
    """

    max_attempts: int = 3
    base_delay: float = 0.05
    max_delay: float = 2.0
    retryable_codes: frozenset = RETRYABLE_CODES
    seed: int | None = None
    sleep: object = time.sleep  # injectable for deterministic tests

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError(
                f"max_attempts must be >= 1, got {self.max_attempts}"
            )
        if self.base_delay < 0 or self.max_delay < 0:
            raise ValueError("delays must be >= 0")
        self._rng = random.Random(self.seed)

    def retryable(self, code) -> bool:
        return _code_name(code) in self.retryable_codes

    def backoff(self, attempt: int, floor: float | None = None) -> float:
        """Jittered delay BEFORE retry number ``attempt`` (1-based:
        attempt 1 is the delay after the first failed call).

        ``floor`` is a server-provided minimum (the shed replies'
        ``x-tdn-retry-after-ms`` hint, in seconds): the draw is
        clamped UP to it — jitter still spreads the herd above the
        floor, but nobody retries before the server said the backlog
        could have moved. The floor may exceed ``max_delay`` (the
        server knows its own drain rate better than the client's cap).
        """
        cap = min(self.max_delay, self.base_delay * (2 ** max(0, attempt - 1)))
        delay = self._rng.uniform(0.0, cap)
        if floor is not None and floor > 0:
            # Full jitter ON TOP of the floor (up to 25%): a uniform
            # clamp would stack every shed client on the exact floor
            # tick — the synchronized storm the hint exists to break.
            return max(delay, floor * self._rng.uniform(1.0, 1.25))
        return delay


class CircuitBreaker:
    """Per-target closed → open → half-open breaker.

    ``record_failure`` counts CONSECUTIVE retryable failures (the
    caller classifies; deterministic errors like INVALID_ARGUMENT must
    not trip the breaker — they say nothing about target health). At
    ``failure_threshold`` the breaker opens: ``allow()`` returns False
    (callers fail fast) until ``cooldown_seconds`` elapse, then exactly
    ONE probe call is let through half-open. The probe's outcome
    decides: success closes the breaker, failure re-opens it for
    another cooldown.

    Thread-safe; ``clock`` is injectable so tests drive the cooldown
    without sleeping. State is published to ``tdn_breaker_state``
    (0 closed / 1 half-open / 2 open) per target.
    """

    CLOSED, HALF_OPEN, OPEN = "closed", "half_open", "open"
    _STATE_VALUE = {CLOSED: 0.0, HALF_OPEN: 1.0, OPEN: 2.0}

    # Shared per-target instances: every GrpcClient to the same target
    # in this process sees the same breaker (the point — N clients must
    # not each pay the full failure run before backing off).
    _registry: dict[str, "CircuitBreaker"] = {}  # guarded-by: _registry_lock
    _registry_lock = threading.Lock()

    def __init__(self, target: str = "", *, failure_threshold: int = 10,
                 cooldown_seconds: float = 1.0, clock=time.monotonic):
        if failure_threshold < 1:
            raise ValueError(
                f"failure_threshold must be >= 1, got {failure_threshold}"
            )
        self.target = target
        self.failure_threshold = int(failure_threshold)
        self.cooldown_seconds = float(cooldown_seconds)
        self._clock = clock
        self._lock = threading.Lock()
        self._state = self.CLOSED  # guarded-by: _lock
        self._consecutive = 0  # guarded-by: _lock
        self._opened_at = 0.0  # guarded-by: _lock
        self._probing = False  # guarded-by: _lock
        self._probe_started = 0.0  # guarded-by: _lock
        self._gauge = BREAKER_STATE.labels(target=target)
        self._gauge.set(0.0)

    @classmethod
    def for_target(cls, target: str, **kwargs) -> "CircuitBreaker":
        """The process-wide breaker for ``target`` (first caller's
        config wins). Construct directly for a private instance with
        guaranteed tuning — a registry hit cannot honor ``kwargs``."""
        with cls._registry_lock:
            br = cls._registry.get(target)
            if br is None:
                br = cls._registry[target] = cls(target, **kwargs)
            elif kwargs:
                mismatched = {
                    k: v for k, v in kwargs.items()
                    if k != "clock" and getattr(br, k, None) != v
                }
                if mismatched:
                    log.warning(
                        "breaker for %s already registered; ignoring "
                        "differing config %s (pass a CircuitBreaker "
                        "instance for per-client tuning)",
                        target, mismatched,
                    )
            return br

    @classmethod
    def evict(cls, target: str) -> None:
        """Drop the shared breaker for ``target`` (long-lived processes
        dialing many ephemeral targets, or a reused address whose OLD
        incumbent's open state should not greet the new server — the
        cooldown bounds that window anyway, this removes it). Also
        retires the target's ``tdn_breaker_state`` series: a departed
        target's stale last value must not sit on /metrics forever,
        and replica churn must not grow the label set unboundedly
        (``for_target`` on the reused address recreates it)."""
        with cls._registry_lock:
            cls._registry.pop(target, None)
        BREAKER_STATE.remove(target=target)

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def _set_state(self, state: str) -> None:  # caller-holds: _lock
        self._state = state
        self._gauge.set(self._STATE_VALUE[state])

    def allow(self) -> bool:
        """May a call proceed right now? Transitions open → half-open
        when the cooldown has elapsed (this caller becomes the probe)."""
        with self._lock:
            if self._state == self.CLOSED:
                return True
            now = self._clock()
            if self._state == self.OPEN:
                if now - self._opened_at >= self.cooldown_seconds:
                    self._set_state(self.HALF_OPEN)
                    self._probing = True
                    self._probe_started = now
                    return True
                return False
            # HALF_OPEN: one probe in flight at a time — but a probe
            # slot AGES OUT after a cooldown. A prober that vanished
            # without recording its outcome (process bug, an exception
            # between allow() and the call) must not wedge the breaker
            # into fail-fast forever.
            if (self._probing
                    and now - self._probe_started < self.cooldown_seconds):
                return False
            self._probing = True
            self._probe_started = now
            return True

    def record_success(self) -> None:
        with self._lock:
            self._consecutive = 0
            self._probing = False
            if self._state != self.CLOSED:
                log.info("breaker %s: probe succeeded, closing", self.target)
            self._set_state(self.CLOSED)

    def record_failure(self) -> None:
        """Count one RETRYABLE failure (caller classifies first)."""
        with self._lock:
            self._probing = False
            if self._state == self.HALF_OPEN:
                # The probe failed: back to open for a fresh cooldown.
                self._opened_at = self._clock()
                self._set_state(self.OPEN)
                return
            self._consecutive += 1
            if (self._state == self.CLOSED
                    and self._consecutive >= self.failure_threshold):
                log.warning(
                    "breaker %s: %d consecutive retryable failures, "
                    "opening for %.1fs", self.target, self._consecutive,
                    self.cooldown_seconds,
                )
                self._opened_at = self._clock()
                self._set_state(self.OPEN)


class GracefulDrain:
    """The rolling-restart drain sequence for one serving process.

    Wire-up (``cmd_up`` / ``cmd_lm`` do exactly this):

    1. construct BEFORE the metrics endpoint, so ``wrap_health`` can
       gate ``/healthz``;
    2. ``add_server(server)`` for each gRPC server (whose wrapped
       ``stop`` already closes its batcher after the grace window —
       :func:`~tpu_dist_nn.serving.server._wrap_server_stop`);
    3. ``install_signal_handler()`` (best-effort: signal handlers only
       install from the main thread; tests call :meth:`begin` directly).

    On SIGTERM / ``begin()``: ``tdn_server_draining`` → 1 and
    ``/healthz`` flips NOT_SERVING *first* (the load balancer must stop
    routing before the port refuses), then every server stops accepting
    new RPCs while in-flight calls get ``grace_seconds`` to finish;
    ``drained`` is set when they have. ``begin`` is idempotent — the
    signal handler and the teardown path can both call it.
    """

    def __init__(self, grace_seconds: float = 5.0):
        self.grace_seconds = float(grace_seconds)
        self.draining = threading.Event()
        self.drained = threading.Event()
        self._servers: list = []  # guarded-by: _lock
        # RLock: the SIGTERM handler runs ON the main thread — if the
        # signal lands while that thread is already inside begin()'s
        # critical section, a plain Lock would self-deadlock the whole
        # drain. Reentrancy + the _begun latch make the interrupted
        # case collapse to a no-op instead.
        self._lock = threading.RLock()
        self._begun = False  # guarded-by: _lock

    def add_server(self, server) -> None:
        with self._lock:
            self._servers.append(server)

    def wrap_health(self, health_fn=None):
        """Wrap a ``/healthz`` closure: while draining, ``ready`` is
        forced False (HTTP 503 — NOT_SERVING) and ``draining: true``
        names why, whatever the engine underneath reports. Every
        payload also carries this process's ``boot_id``, so a poller
        (the router's scraper) can tell a RESTARTED server on a reused
        address from the same process still answering — a restart fast
        enough to fall entirely between two polls is otherwise
        invisible."""

        def health():
            if self.draining.is_set():
                # Draining is the headline; a probe failing mid-drain
                # (the engine may already be down) must not erase it.
                base = {}
                try:
                    if health_fn is not None:
                        base = dict(health_fn())
                except Exception as e:  # noqa: BLE001 — drain wins
                    base = {"error": repr(e)}
                base["ready"] = False
                base["draining"] = True
                base.setdefault("boot_id", BOOT_ID)
                return base
            base = dict(health_fn()) if health_fn is not None else {"ready": True}
            base.setdefault("draining", False)
            base.setdefault("boot_id", BOOT_ID)
            return base

        return health

    def install_signal_handler(self, signals=(signal.SIGTERM,)) -> bool:
        """Route SIGTERM (by default) to :meth:`begin`. Best-effort:
        only the main thread may install handlers — in-process callers
        (tests, embedding apps) call ``begin()`` themselves."""
        try:
            for s in signals:
                signal.signal(s, lambda *_: self.begin())
            return True
        except ValueError:
            log.warning(
                "not in the main thread: graceful-drain signal handler "
                "not installed; call GracefulDrain.begin() to drain"
            )
            return False

    def begin(self) -> threading.Event:
        """Start (or join) the drain; returns the ``drained`` event.
        Idempotent and signal-safe: the teardown path and the SIGTERM
        handler may both call it (even nested on one thread)."""
        # fast path, no lock: signal-handler friendly (benign race —
        # the locked re-check below arbitrates)
        if self._begun:  # tdnlint: disable=lock-discipline
            return self.drained
        with self._lock:
            if self._begun:
                return self.drained
            self._begun = True
            # Health flips NOT_SERVING the instant the event sets —
            # before any server stops accepting, so the LB drains
            # routing ahead of the port refusing.
            self.draining.set()
            SERVER_DRAINING.set(1.0)
            servers = list(self._servers)
        log.info(
            "graceful drain: refusing new work, %.1fs grace for "
            "in-flight RPCs", self.grace_seconds,
        )
        events = [srv.stop(grace=self.grace_seconds) for srv in servers]

        def waiter():
            for ev in events:
                ev.wait()
            SERVER_DRAINING.set(0.0)
            self.drained.set()
            log.info("graceful drain complete")

        if events:
            threading.Thread(
                target=waiter, name="tdn-drain-wait", daemon=True
            ).start()
        else:
            SERVER_DRAINING.set(0.0)
            self.drained.set()
        return self.drained

    def wait(self, timeout: float | None = None) -> bool:
        return self.drained.wait(timeout)
