from tpu_dist_nn.core.schema import (  # noqa: F401
    LayerSpec,
    ModelSpec,
    StageSpec,
    load_examples,
    load_model,
    partition_model,
    save_model,
    validate_distribution,
)
from tpu_dist_nn.core.activations import apply_activation, ACTIVATION_IDS  # noqa: F401
