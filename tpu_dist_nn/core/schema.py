"""The public JSON model / examples schema, and pipeline partitioning.

This module is the framework's contract with the outside world and is
shared verbatim with the reference system:

* Model files: ``{"layers": [{"type", "nodes", "neurons":
  [{"weights", "bias", "activation"}]}]}``
  (reference ``config/config_sample.json:1-33``).  A neuron's
  ``weights`` list is a row; a layer's weight matrix is the stack of
  neuron rows **transposed** to ``(in_dim, out_dim)`` — the
  materialization rule of the reference node runtime
  (``grpc_node.py:51``).  The layer activation is taken from the first
  neuron (``grpc_node.py:53``).
* Example inputs: ``{"examples": [{"input": [...], "label": k}]}``
  (reference ``config/example_inputs/example_inputs_sample.json``).
* Per-stage configs: ``{"layer_0": [neurons...], "layer_1": [...]}`` —
  the format the reference orchestrator ships to each node via the
  ``NEURONS_CONFIG`` env var (``run_grpc_fcnn.py:208-218`` /
  ``grpc_node.py:46``), kept here as the stage-serialization format.
* Placement: a ``layer_distribution`` vector assigning contiguous layer
  runs to pipeline stages, validated as summing to the total layer
  count (``run_grpc_fcnn.py:182-183``).

The JSON model file doubles as the checkpoint/interchange format (the
reference has no other persistence).
"""

from __future__ import annotations

import dataclasses
import json
from pathlib import Path
from typing import Any, Sequence

import numpy as np

# Parity constants with the reference orchestrator (run_grpc_fcnn.py:18-22):
# stage naming and the port formula survive as stable stage identifiers,
# even though there is no TCP listener behind them on TPU.
STAGE_NAME_PREFIX = "fcnn_node_"
BASE_PORT = 5100
PORT_STRIDE = 100


def stage_port(index: int) -> int:
    """Stable per-stage id, reference port formula (run_grpc_fcnn.py:221)."""
    return BASE_PORT + PORT_STRIDE * index + 1


@dataclasses.dataclass
class LayerSpec:
    """One dense layer: ``act(x @ weights + biases)``.

    ``weights`` is ``(in_dim, out_dim)`` (already transposed from the
    per-neuron row layout, grpc_node.py:51). ``type_tag`` preserves the
    reference's "hidden"/"output" tag for lossless round-trip.
    """

    weights: np.ndarray
    biases: np.ndarray
    activation: str = "linear"
    type_tag: str = "hidden"
    kind: str = "dense"

    @property
    def in_dim(self) -> int:
        return int(self.weights.shape[0])

    @property
    def out_dim(self) -> int:
        return int(self.weights.shape[1])

    def validate(self) -> None:
        if self.weights.ndim != 2:
            raise ValueError(f"dense layer weights must be 2-D, got {self.weights.shape}")
        if self.biases.shape != (self.out_dim,):
            raise ValueError(
                f"bias shape {self.biases.shape} does not match out_dim {self.out_dim}"
            )

    @classmethod
    def from_neurons(cls, layer_json: dict) -> "LayerSpec":
        neurons = layer_json["neurons"]
        if not neurons:
            raise ValueError("layer has no neurons")
        widths = {len(n["weights"]) for n in neurons}
        if len(widths) != 1:
            raise ValueError(
                f"neurons in a layer must have equal weight counts, got {sorted(widths)}"
            )
        rows = np.asarray([n["weights"] for n in neurons], dtype=np.float64)
        weights = rows.T  # (in_dim, out_dim) — grpc_node.py:51
        biases = np.asarray([n["bias"] for n in neurons], dtype=np.float64)
        # All neurons in a layer share the first neuron's activation
        # (grpc_node.py:53).
        activation = neurons[0].get("activation", "linear")
        spec = cls(
            weights=weights,
            biases=biases,
            activation=activation,
            type_tag=layer_json.get("type", "hidden"),
        )
        spec.validate()
        return spec

    def to_neurons(self) -> dict:
        """Export back to the per-neuron JSON layout (notebook cell 10 format)."""
        neurons = [
            {
                "weights": self.weights[:, j].tolist(),
                "bias": float(self.biases[j]),
                "activation": self.activation,
            }
            for j in range(self.out_dim)
        ]
        return {"type": self.type_tag, "nodes": self.out_dim, "neurons": neurons}


@dataclasses.dataclass
class ModelSpec:
    """A whole model: an ordered list of layers plus passthrough metadata.

    ``metadata`` carries any non-"layers" keys of the model file —
    notably ``inference_metrics``, which the reference toolchain embeds
    into exported models (notebook cell 10) — so load→save round-trips.
    """

    layers: list[LayerSpec]
    metadata: dict = dataclasses.field(default_factory=dict)

    @property
    def input_dim(self) -> int:
        return self.layers[0].in_dim

    @property
    def output_dim(self) -> int:
        return self.layers[-1].out_dim

    @property
    def layer_sizes(self) -> list[int]:
        return [self.input_dim] + [l.out_dim for l in self.layers]

    @property
    def is_dense(self) -> bool:
        """True when every layer is a plain dense layer (the reference's
        only layer family); gates the uniform-width SPMD pipeline."""
        return all(l.kind == "dense" for l in self.layers)

    def validate_chain(self) -> None:
        """Check inter-layer dim consistency (the reference checks this
        per-forward at grpc_node.py:83-84; we fail fast at load)."""
        for i, layer in enumerate(self.layers):
            layer.validate()
            if i > 0 and layer.in_dim != self.layers[i - 1].out_dim:
                raise ValueError(
                    f"layer {i}: input dim {layer.in_dim} does not match "
                    f"previous layer output dim {self.layers[i - 1].out_dim}"
                )

    @classmethod
    def from_json_dict(cls, obj: dict) -> "ModelSpec":
        if not obj.get("layers"):
            raise ValueError("model has no layers")
        layers = [_layer_from_json(lj) for lj in obj["layers"]]
        metadata = {k: v for k, v in obj.items() if k != "layers"}
        spec = cls(layers=layers, metadata=metadata)
        spec.validate_chain()
        return spec

    def to_json_dict(self) -> dict:
        out: dict[str, Any] = {"layers": [_layer_to_json(l) for l in self.layers]}
        out.update(self.metadata)
        return out


def load_model(path: str | Path) -> ModelSpec:
    """Load a model JSON, preferring the native C++ codec.

    The native path (:mod:`tpu_dist_nn.native`) parses the per-neuron
    weight arrays straight into packed float64 buffers — the role the
    protobuf C++ fast path played in the reference (dist_nn_pb2.py:32) —
    and reports the byte span of the ``"layers"`` value so the (small)
    metadata remainder is parsed host-side without re-walking the
    weights. Falls back to pure Python when the library is unavailable
    or the model has non-dense layers.
    """
    with open(path, "rb") as f:
        data = f.read()
    try:
        from tpu_dist_nn.native import parse_model_layers

        native = parse_model_layers(data)
    except ImportError:
        native = None
    if native is None:
        return ModelSpec.from_json_dict(json.loads(data))
    raw_layers, (start, end) = native
    layers = []
    for rl in raw_layers:
        spec = LayerSpec(
            weights=rl["weights"],
            biases=rl["biases"],
            activation=rl["activation"],
            type_tag=rl["type"],
        )
        spec.validate()
        layers.append(spec)
    # Splice in *byte* space — the native spans are byte offsets, and a
    # non-ASCII char before "layers" would shift code-point indices.
    meta_obj = json.loads(data[:start] + b"null" + data[end:])
    meta_obj.pop("layers", None)
    model = ModelSpec(layers=layers, metadata=meta_obj)
    model.validate_chain()
    return model


def save_model(model: ModelSpec, path: str | Path) -> None:
    with open(path, "w") as f:
        json.dump(model.to_json_dict(), f)


@dataclasses.dataclass
class Conv2DSpec:
    """A 2-D convolution layer — the CIFAR extension (BASELINE configs[3]).

    The reference has no conv type (its node computes only dense chains,
    grpc_node.py:75-97); this extends the JSON schema with
    ``{"type": "conv2d", "in_shape": [H,W,C], "kernel_size": [kh,kw],
    "stride": [sh,sw], "padding": "same"|"valid", "weights": nested
    (kh,kw,cin,cout), "bias": [cout], "activation": ...}``. Activations
    stay flat vectors at layer boundaries (the reference's Matrix wire
    shape); the layer reshapes to NHWC internally.
    """

    in_shape: tuple[int, int, int]  # (H, W, C)
    weights: np.ndarray  # (kh, kw, cin, cout)
    biases: np.ndarray  # (cout,)
    stride: tuple[int, int] = (1, 1)
    padding: str = "same"
    activation: str = "relu"
    type_tag: str = "conv2d"
    kind: str = "conv2d"

    @property
    def out_shape(self) -> tuple[int, int, int]:
        h, w, _ = self.in_shape
        kh, kw, _, cout = self.weights.shape
        sh, sw = self.stride
        if self.padding.lower() == "same":
            oh, ow = -(-h // sh), -(-w // sw)
        else:
            oh, ow = (h - kh) // sh + 1, (w - kw) // sw + 1
        return (oh, ow, cout)

    @property
    def in_dim(self) -> int:
        h, w, c = self.in_shape
        return h * w * c

    @property
    def out_dim(self) -> int:
        oh, ow, oc = self.out_shape
        return oh * ow * oc

    def validate(self) -> None:
        if self.weights.ndim != 4:
            raise ValueError(f"conv2d weights must be 4-D, got {self.weights.shape}")
        if self.weights.shape[2] != self.in_shape[2]:
            raise ValueError(
                f"conv2d kernel expects {self.weights.shape[2]} input channels "
                f"but in_shape has {self.in_shape[2]}"
            )
        if self.biases.shape != (self.weights.shape[3],):
            raise ValueError(
                f"conv2d bias shape {self.biases.shape} does not match "
                f"{self.weights.shape[3]} filters"
            )
        if self.padding.lower() not in ("same", "valid"):
            raise ValueError(f"conv2d padding must be same|valid, got {self.padding!r}")
        oh, ow, _ = self.out_shape
        if oh <= 0 or ow <= 0:
            raise ValueError(
                f"conv2d kernel {self.weights.shape[:2]} with stride "
                f"{self.stride} does not fit input {self.in_shape} "
                f"(output would be {oh}x{ow})"
            )

    @classmethod
    def from_json(cls, obj: dict) -> "Conv2DSpec":
        spec = cls(
            in_shape=tuple(obj["in_shape"]),
            weights=np.asarray(obj["weights"], dtype=np.float64),
            biases=np.asarray(obj["bias"], dtype=np.float64),
            stride=tuple(obj.get("stride", (1, 1))),
            padding=obj.get("padding", "same"),
            activation=obj.get("activation", "relu"),
        )
        spec.validate()
        return spec

    def to_json(self) -> dict:
        return {
            "type": "conv2d",
            "in_shape": list(self.in_shape),
            "kernel_size": [int(self.weights.shape[0]), int(self.weights.shape[1])],
            "filters": int(self.weights.shape[3]),
            "stride": list(self.stride),
            "padding": self.padding,
            "activation": self.activation,
            "weights": self.weights.tolist(),
            "bias": self.biases.tolist(),
        }


@dataclasses.dataclass
class MaxPool2DSpec:
    """Max pooling over NHWC windows (flat-vector boundaries like conv)."""

    in_shape: tuple[int, int, int]
    window: tuple[int, int] = (2, 2)
    stride: tuple[int, int] | None = None  # defaults to window
    type_tag: str = "maxpool2d"
    kind: str = "maxpool2d"
    activation: str = "linear"

    @property
    def eff_stride(self) -> tuple[int, int]:
        return tuple(self.stride) if self.stride else tuple(self.window)

    @property
    def out_shape(self) -> tuple[int, int, int]:
        h, w, c = self.in_shape
        sh, sw = self.eff_stride
        kh, kw = self.window
        return ((h - kh) // sh + 1, (w - kw) // sw + 1, c)

    @property
    def in_dim(self) -> int:
        h, w, c = self.in_shape
        return h * w * c

    @property
    def out_dim(self) -> int:
        oh, ow, oc = self.out_shape
        return oh * ow * oc

    def validate(self) -> None:
        if any(k <= 0 for k in self.window):
            raise ValueError(f"maxpool2d window must be positive, got {self.window}")
        if any(s <= 0 for s in self.eff_stride):
            raise ValueError(
                f"maxpool2d stride must be positive, got {self.eff_stride}"
            )
        if any(d <= 0 for d in self.in_shape):
            raise ValueError(
                f"maxpool2d in_shape must be positive, got {self.in_shape}"
            )
        oh, ow, _ = self.out_shape
        if oh <= 0 or ow <= 0:
            raise ValueError(
                f"maxpool2d window {self.window} does not fit input "
                f"{self.in_shape} (output shape {self.out_shape})"
            )

    @classmethod
    def from_json(cls, obj: dict) -> "MaxPool2DSpec":
        spec = cls(
            in_shape=tuple(obj["in_shape"]),
            window=tuple(obj.get("window", (2, 2))),
            stride=tuple(obj["stride"]) if "stride" in obj else None,
        )
        spec.validate()
        return spec

    def to_json(self) -> dict:
        out = {
            "type": "maxpool2d",
            "in_shape": list(self.in_shape),
            "window": list(self.window),
        }
        if self.stride:
            out["stride"] = list(self.stride)
        return out


def _layer_from_json(obj: dict):
    """Dispatch a layer JSON object to its spec class by ``type``."""
    kind = obj.get("type", "hidden")
    if kind == "conv2d":
        return Conv2DSpec.from_json(obj)
    if kind == "maxpool2d":
        return MaxPool2DSpec.from_json(obj)
    # "hidden" / "output" / anything neuron-shaped: the reference's dense
    # format (grpc_node.py:44-55).
    return LayerSpec.from_neurons(obj)


def _layer_to_json(layer) -> dict:
    if isinstance(layer, LayerSpec):
        return layer.to_neurons()
    return layer.to_json()


# ---------------------------------------------------------------------------
# Example-inputs format (run_grpc_inference.py:35-52).


def load_examples(path: str | Path) -> tuple[np.ndarray, np.ndarray]:
    """Load ``{"examples": [{"input", "label"}]}`` → (inputs, labels).

    Inputs are flattened to 1-D per example (the shipped MNIST files are
    flat 784-vectors; the sample file nests rows, which the reference
    would have mis-sized — we flatten instead).
    """
    with open(path, "rb") as f:
        data = f.read()
    try:
        from tpu_dist_nn.native import parse_examples

        native = parse_examples(data)
    except ImportError:
        native = None
    if native is not None:
        return native
    obj = json.loads(data)
    examples = obj["examples"]
    inputs = np.asarray(
        [np.asarray(e["input"], dtype=np.float64).reshape(-1) for e in examples]
    )
    labels = np.asarray([e.get("label", -1) for e in examples], dtype=np.int32)
    return inputs, labels


def save_examples(inputs: np.ndarray, labels: np.ndarray, path: str | Path) -> None:
    try:
        from tpu_dist_nn.native import write_examples

        data = write_examples(inputs, labels)
    except ImportError:
        data = None
    if data is not None:
        with open(path, "wb") as f:
            f.write(data)
        return
    examples = [
        {"input": np.asarray(x).reshape(-1).tolist(), "label": int(y)}
        for x, y in zip(inputs, labels)
    ]
    with open(path, "w") as f:
        json.dump({"examples": examples}, f)


# ---------------------------------------------------------------------------
# Pipeline partitioning (the reference's calculate_layer_mappings,
# run_grpc_fcnn.py:176-252, re-expressed for mesh placement).


@dataclasses.dataclass
class StageSpec:
    """One pipeline stage: a contiguous run of layers placed on one device.

    Mirrors a reference node's identity (name + port, run_grpc_fcnn.py:
    199-221) and env contract (expected_input_dim, grpc_node.py:20).
    """

    index: int
    layers: list[LayerSpec]
    expected_input_dim: int

    @property
    def name(self) -> str:
        return f"{STAGE_NAME_PREFIX}{self.index}"

    @property
    def port(self) -> int:
        return stage_port(self.index)

    @property
    def output_dim(self) -> int:
        return self.layers[-1].out_dim if self.layers else self.expected_input_dim

    def to_stage_json(self) -> dict:
        """Serialize in the reference's per-node config format
        (``{"layer_N": [neurons...]}``, run_grpc_fcnn.py:208-218), plus an
        ``expected_input_dim`` key (our extension; the reference carries
        this via the EXPECTED_INPUT_DIM env var instead, grpc_node.py:20)
        so identity stages round-trip losslessly."""
        out = {
            f"layer_{i}": self.layers[i].to_neurons()["neurons"]
            for i in range(len(self.layers))
        }
        out["expected_input_dim"] = self.expected_input_dim
        return out

    @classmethod
    def from_stage_json(cls, obj: dict, index: int = 0, expected_input_dim: int | None = None) -> "StageSpec":
        """Parse the ``layer_N``-keyed format, sorting keys numerically
        (grpc_node.py:46)."""
        keys = sorted((k for k in obj if k.startswith("layer_")), key=lambda k: int(k.split("_")[1]))
        layers = [
            LayerSpec.from_neurons({"neurons": obj[k]}) for k in keys if obj[k]
        ]
        if expected_input_dim is None:
            expected_input_dim = obj.get("expected_input_dim")
        if expected_input_dim is None:
            if not layers:
                # The bare layer_N format carries no dims; an empty
                # (identity) stage is unrecoverable without the
                # pass-through width.
                raise ValueError(
                    "stage config has no layers; pass expected_input_dim explicitly"
                )
            expected_input_dim = layers[0].in_dim
        return cls(index=index, layers=layers, expected_input_dim=expected_input_dim)


def validate_distribution(distribution: Sequence[int], num_layers: int) -> None:
    """``sum(layer_distribution) == len(layers)`` (run_grpc_fcnn.py:182-183)."""
    if any(int(d) < 0 for d in distribution):
        raise ValueError(f"layer_distribution entries must be >= 0, got {list(distribution)}")
    if sum(int(d) for d in distribution) != num_layers:
        raise ValueError(
            f"sum(layer_distribution)={sum(distribution)} does not equal "
            f"number of layers={num_layers}"
        )


def partition_model(model: ModelSpec, distribution: Sequence[int]) -> list[StageSpec]:
    """Pack contiguous layer runs into stages per the distribution vector.

    Stages with zero layers are kept as identity stages (pass-through);
    the reference instead skipped them when chaining next-pointers
    (run_grpc_fcnn.py:224-237) — on a mesh every stage coordinate exists,
    so identity is the natural equivalent.
    """
    model.validate_chain()
    validate_distribution(distribution, len(model.layers))
    stages: list[StageSpec] = []
    cursor = 0
    current_dim = model.input_dim
    for i, count in enumerate(int(d) for d in distribution):
        layers = model.layers[cursor : cursor + count]
        stages.append(StageSpec(index=i, layers=layers, expected_input_dim=current_dim))
        if layers:
            current_dim = layers[-1].out_dim
        cursor += count
    return stages
