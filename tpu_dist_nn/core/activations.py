"""Activation functions, usable both eagerly and inside traced pipeline stages.

Reproduces the activation semantics of the reference node runtime
(``/root/reference/src/grpc_node.py:62-73``): relu, sigmoid, numerically
stable softmax (max-subtracted along the last axis), and linear as the
fallback for unknown names.  ``tanh`` and ``gelu`` are additions for the
wider model families (conv / transformer configs in BASELINE.json).

Activations also exist as dense integer ids so that a pipeline stage —
which under SPMD must be a single traced program shared by all stages —
can select its activation with ``lax.switch`` instead of Python control
flow.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

# Order matters: index == activation id used by the stage executor's
# lax.switch. "linear" is id 0 so zero-initialized padding layers are
# identity-friendly.
_ACTIVATION_ORDER = ("linear", "relu", "sigmoid", "softmax", "tanh", "gelu")

ACTIVATION_IDS = {name: i for i, name in enumerate(_ACTIVATION_ORDER)}

#: Public id -> name view (index == activation id).
ACTIVATION_NAMES = _ACTIVATION_ORDER


def _linear(x):
    return x


def _relu(x):
    return jnp.maximum(0, x)


def _sigmoid(x):
    return jax.nn.sigmoid(x)


def _softmax(x):
    # Stable softmax over the last axis, mirroring grpc_node.py:68-71.
    return jax.nn.softmax(x, axis=-1)


def _tanh(x):
    return jnp.tanh(x)


def _gelu(x):
    return jax.nn.gelu(x)


_ACTIVATION_FNS = (_linear, _relu, _sigmoid, _softmax, _tanh, _gelu)

SOFTMAX_ID = ACTIVATION_IDS["softmax"]


def activation_branches() -> list:
    """The id-ordered activation function list, for building lax.switch
    tables elsewhere (e.g. the pipeline's masked variant) without
    duplicating the ordering — lax.switch clamps out-of-range ids, so a
    desynced copy would silently compute the wrong activation."""
    return list(_ACTIVATION_FNS)


def activation_id(name: str) -> int:
    """Map an activation name to its dense id; unknown names are linear.

    The reference treats any unrecognized activation as linear
    (grpc_node.py:72-73), so we do the same rather than raising.
    """
    return ACTIVATION_IDS.get(name.lower(), 0)


def apply_activation(x: jnp.ndarray, name: str) -> jnp.ndarray:
    """Apply a named activation eagerly (host-side dispatch on the name)."""
    return _ACTIVATION_FNS[activation_id(name)](x)


def apply_activation_by_id(x: jnp.ndarray, act_id) -> jnp.ndarray:
    """Apply an activation selected by a traced integer id.

    Used inside the pipeline stage executor where the activation is data
    (part of the stacked per-stage parameters), not Python structure.
    """
    return lax.switch(act_id, _ACTIVATION_FNS, x)
