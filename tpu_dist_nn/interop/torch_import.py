"""PyTorch state-dict ↔ JSON-schema model conversion.

The reference trains its MNIST FCNN in torch and (in commented-out
code, ``scripts/generate_mnist_pytorch.py:68-103``) exports per-neuron
``{"weights", "bias", "activation"}`` JSON with relu tagging on hidden
layers and softmax on the output — the same tagging the shipped model
uses (notebook cell 10). This module is that exporter made real and
bidirectional, so torch-trained weights drop straight into the TPU
pipeline and TPU-trained models load back into torch for comparison.

Torch ``nn.Linear`` stores ``weight`` as ``(out_features, in_features)``;
the schema stores ``(in_dim, out_dim)`` (``grpc_node.py:51`` transpose
rule), so each weight matrix is transposed on the way through.
"""

from __future__ import annotations

from typing import Mapping, Sequence

import numpy as np

from tpu_dist_nn.core.schema import LayerSpec, ModelSpec


def _dense_pairs(state_dict: Mapping) -> list[tuple[str, np.ndarray, np.ndarray]]:
    """Extract ordered (name, weight(out,in), bias(out,)) Linear triples."""
    pairs = []
    for key in state_dict:
        if key != "weight" and not key.endswith(".weight"):
            continue
        base = key[: -len("weight")].rstrip(".")
        w = np.asarray(state_dict[key].detach().cpu().numpy()
                       if hasattr(state_dict[key], "detach")
                       else state_dict[key], dtype=np.float64)
        if w.ndim > 2:
            raise ValueError(
                f"{key}: {w.ndim}-D (conv-style) weights are not importable "
                "from a bare state dict — conv layers need "
                "in_shape/stride/padding; export them via the JSON schema's "
                "conv2d layer type instead"
            )
        if w.ndim != 2:
            continue  # 1-D norm scales etc.
        bias_key = f"{base}.bias" if base else "bias"
        if bias_key not in state_dict:
            raise ValueError(f"{base}: Linear layer without a bias "
                             "(the schema requires per-neuron biases)")
        b = np.asarray(state_dict[bias_key].detach().cpu().numpy()
                       if hasattr(state_dict[bias_key], "detach")
                       else state_dict[bias_key], dtype=np.float64)
        pairs.append((base, w, b))
    if not pairs:
        raise ValueError("state dict contains no Linear (2-D weight) layers")
    return pairs


def model_from_torch_state_dict(
    state_dict: Mapping,
    activations: Sequence[str] | None = None,
) -> ModelSpec:
    """Convert a torch state dict (or any name→array mapping) to a
    :class:`ModelSpec`.

    ``activations`` optionally names one activation per dense layer;
    the default is the reference exporter's tagging — relu on hidden
    layers, softmax on the output (``generate_mnist_pytorch.py:30-32``
    + notebook cell 10).
    """
    from tpu_dist_nn.core.activations import ACTIVATION_IDS

    pairs = _dense_pairs(state_dict)
    n = len(pairs)
    if activations is None:
        activations = ["relu"] * (n - 1) + ["softmax"]
    else:
        # Inference treats unknown names as linear (reference parity,
        # grpc_node.py:72-73); a user-*supplied* name is validated here
        # instead, so a typo fails at import rather than silently
        # serving raw logits.
        activations = [a.strip().lower() for a in activations]
        unknown = [a for a in activations if a not in ACTIVATION_IDS]
        if unknown:
            raise ValueError(
                f"unknown activations {unknown}; "
                f"known: {sorted(ACTIVATION_IDS)}"
            )
    if len(activations) != n:
        raise ValueError(
            f"got {len(activations)} activations for {n} dense layers"
        )
    layers = []
    for i, ((name, w, b), act) in enumerate(zip(pairs, activations)):
        if i and w.shape[1] != layers[-1].out_dim:
            raise ValueError(
                f"{name}: in_features {w.shape[1]} does not chain from "
                f"previous layer's out_dim {layers[-1].out_dim}"
            )
        layers.append(
            LayerSpec(
                weights=w.T.copy(),  # (in, out) — grpc_node.py:51
                biases=b.copy(),
                activation=act,
                type_tag="output" if i == n - 1 else "hidden",
            )
        )
    model = ModelSpec(layers=layers)
    model.validate_chain()
    return model


def model_to_torch_state_dict(model: ModelSpec):
    """Inverse conversion: dense :class:`ModelSpec` → an OrderedDict of
    torch tensors with keys ``layers.{i}.weight/bias`` (weights back to
    torch's (out, in) layout) — loadable into a module whose Linears
    live in ``self.layers = nn.ModuleList([...])``, or re-keyed by the
    caller for other module shapes. Round-trips exactly through
    :func:`model_from_torch_state_dict` (which matches by order, not
    name)."""
    import collections

    import torch

    if not model.is_dense:
        raise ValueError("only all-dense models convert to Linear stacks")
    out = collections.OrderedDict()
    for i, layer in enumerate(model.layers):
        out[f"layers.{i}.weight"] = torch.from_numpy(
            np.ascontiguousarray(layer.weights.T)
        )
        out[f"layers.{i}.bias"] = torch.from_numpy(
            np.ascontiguousarray(layer.biases)
        )
    return out
