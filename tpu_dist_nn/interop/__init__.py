"""Interop with external training toolchains.

The reference's model-production layer (SURVEY.md §1 L5) trains
centrally in PyTorch/TensorFlow and exports per-neuron JSON
(``scripts/generate_mnist_pytorch.py:68-103``,
``scripts/generate_mnist_tensorflow.py:41-78``, notebook cell 10).
This package subsumes that export path natively: torch state dicts and
saved Keras models convert to the public
:class:`~tpu_dist_nn.core.schema.ModelSpec` and back, so models trained
anywhere drop into the TPU pipeline.
"""

from tpu_dist_nn.interop.keras_import import (
    model_from_keras,
    model_from_keras_file,
    model_to_keras,
)
from tpu_dist_nn.interop.torch_import import (
    model_from_torch_state_dict,
    model_to_torch_state_dict,
)

__all__ = [
    "model_from_keras",
    "model_from_keras_file",
    "model_from_torch_state_dict",
    "model_to_keras",
    "model_to_torch_state_dict",
]
