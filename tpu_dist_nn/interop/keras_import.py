"""Keras / TensorFlow ↔ JSON-schema model conversion (C9's twin).

The reference trains the same MNIST FCNN in Keras
(``scripts/generate_mnist_tensorflow.py:14-27``) with the exporter
commented out (``:41-78``); the live exporter in the notebook (cell 10)
iterates ``layer.get_weights()`` and tags hidden layers relu / the
output softmax. This module is that exporter made real and
bidirectional, mirroring :mod:`tpu_dist_nn.interop.torch_import`.

Layout notes vs the torch twin: Keras ``Dense`` stores its kernel as
``(in_dim, out_dim)`` — already the schema's layout (``grpc_node.py:51``
transpose rule applies to torch's ``(out, in)``, not here) — and each
layer carries its own activation, so the default tagging comes from the
model itself rather than a positional convention.

TensorFlow/Keras are imported lazily: they are heavyweight and only
needed for loading ``.keras``/``.h5`` files or building live models,
never for the conversion math.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from tpu_dist_nn.core.schema import LayerSpec, ModelSpec

# Keras activation identifiers that map onto the schema's set
# (core/activations.py; reference set grpc_node.py:62-73).
_KERAS_ACTIVATIONS = {
    "relu": "relu",
    "sigmoid": "sigmoid",
    "softmax": "softmax",
    "linear": "linear",
    None: "linear",
}


def _dense_triples(model) -> list[tuple[str, np.ndarray, np.ndarray, str]]:
    """Extract ordered (name, kernel(in,out), bias(out,), activation)
    from a live Keras model's Dense layers."""
    triples = []
    for layer in model.layers:
        weights = layer.get_weights()
        if (
            len(weights) == 1
            and np.ndim(weights[0]) == 2
            and getattr(layer, "use_bias", None) is False
        ):
            # Dense(use_bias=False): a single 2-D kernel. The schema
            # always carries a bias, so import with zeros — numerically
            # identical. The use_bias gate keeps other single-2D-weight
            # layers (e.g. Embedding) on the error path below.
            weights = [weights[0], np.zeros(weights[0].shape[1])]
        if len(weights) != 2 or np.ndim(weights[0]) != 2:
            cls = type(layer).__name__
            if cls in ("InputLayer", "Flatten", "Dropout"):
                continue  # shape/regularization plumbing, no parameters
            raise ValueError(
                f"layer {layer.name} ({cls}) is not a Dense layer; only "
                "dense stacks import from Keras — export conv models via "
                "the JSON schema's conv2d layer type instead"
            )
        kernel, bias = weights
        act_fn = getattr(layer, "activation", None)
        act_name = getattr(act_fn, "__name__", None) if act_fn else None
        if act_name not in _KERAS_ACTIVATIONS:
            raise ValueError(
                f"layer {layer.name}: activation {act_name!r} has no "
                f"schema equivalent; known: "
                f"{sorted(k for k in _KERAS_ACTIVATIONS if k)}"
            )
        triples.append(
            (
                layer.name,
                np.asarray(kernel, dtype=np.float64),
                np.asarray(bias, dtype=np.float64),
                _KERAS_ACTIVATIONS[act_name],
            )
        )
    if not triples:
        raise ValueError("Keras model contains no Dense layers")
    return triples


def model_from_keras(
    model,
    activations: Sequence[str] | None = None,
) -> ModelSpec:
    """Convert a live Keras model (Sequential/Functional dense stack)
    to a :class:`ModelSpec`.

    ``activations`` optionally overrides the per-layer names; the
    default reads each layer's own activation (the notebook cell 10
    exporter read the architecture the same way).
    """
    from tpu_dist_nn.core.activations import ACTIVATION_IDS

    triples = _dense_triples(model)
    n = len(triples)
    if activations is not None:
        activations = [a.strip().lower() for a in activations]
        unknown = [a for a in activations if a not in ACTIVATION_IDS]
        if unknown:
            raise ValueError(
                f"unknown activations {unknown}; known: "
                f"{sorted(ACTIVATION_IDS)}"
            )
        if len(activations) != n:
            raise ValueError(
                f"got {len(activations)} activations for {n} dense layers"
            )
    layers = []
    for i, (name, kernel, bias, act) in enumerate(triples):
        if i and kernel.shape[0] != layers[-1].out_dim:
            raise ValueError(
                f"{name}: input dim {kernel.shape[0]} does not chain from "
                f"previous layer's out_dim {layers[-1].out_dim}"
            )
        layers.append(
            LayerSpec(
                weights=kernel.copy(),  # already (in, out)
                biases=bias.copy(),
                activation=activations[i] if activations else act,
                type_tag="output" if i == n - 1 else "hidden",
            )
        )
    model_spec = ModelSpec(layers=layers)
    model_spec.validate_chain()
    return model_spec


def model_from_keras_file(
    path: str,
    activations: Sequence[str] | None = None,
) -> ModelSpec:
    """Load a saved Keras model (``.keras`` zip or legacy ``.h5``) and
    convert it. ``compile=False`` skips optimizer/loss deserialization —
    only the architecture and weights matter here."""
    loaders = []
    try:
        import keras  # Keras 3

        loaders.append(keras.models.load_model)
    except Exception:  # pragma: no cover - environment-specific
        pass
    try:
        import tf_keras  # legacy Keras 2 (reads old h5/SavedModel)

        loaders.append(tf_keras.models.load_model)
    except Exception:  # pragma: no cover - environment-specific
        pass
    if not loaders:
        raise RuntimeError(
            "neither keras nor tf_keras is importable; install one to "
            "load saved Keras models"
        )
    errors = []
    for load in loaders:
        try:
            km = load(path, compile=False)
        except Exception as e:  # loader/format mismatch: try the next
            # (Keras 3 raises ValueError for legacy formats tf_keras CAN
            # read, so even ValueError must not abort the chain here.)
            errors.append(f"{load.__module__}: {type(e).__name__}: {e}")
            continue
        # Loaded fine: conversion errors are real — propagate them.
        return model_from_keras(km, activations=activations)
    raise RuntimeError(
        f"could not load {path} with any available Keras loader:\n"
        + "\n".join(errors)
    )


def model_to_keras(model: ModelSpec):
    """Inverse conversion: dense :class:`ModelSpec` → a built Keras
    ``Sequential`` with the weights installed. Round-trips exactly
    through :func:`model_from_keras`."""
    import keras

    if not model.is_dense:
        raise ValueError("only all-dense models convert to Keras stacks")
    valid = {v for k, v in _KERAS_ACTIVATIONS.items() if k}
    for layer in model.layers:
        if layer.activation not in valid:
            raise ValueError(
                f"activation {layer.activation!r} has no Keras equivalent"
            )
    km = keras.Sequential(
        [keras.layers.Input(shape=(model.input_dim,))]
        + [
            keras.layers.Dense(layer.out_dim, activation=layer.activation)
            for layer in model.layers
        ]
    )
    for dense, layer in zip(km.layers, model.layers):
        dense.set_weights([
            layer.weights.astype(np.float32),
            layer.biases.astype(np.float32),
        ])
    return km
