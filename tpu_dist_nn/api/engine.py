"""The Engine: orchestrator + client surface in one object.

Replaces both reference drivers with a single-controller JAX program:

* ``run_grpc_fcnn.py`` (orchestrator): validate the distribution, infer
  the input dim, place stages, readiness-check, teardown — here
  ``Engine.up()`` validates, builds the mesh, compiles the executor
  (compilation *is* the readiness gate; there is no daemon to babysit,
  so the reference's supervisor sleep loop and container sweeps
  disappear), and ``setup_seconds`` mirrors its bring-up timing
  (run_grpc_fcnn.py:321-322).
* ``run_grpc_inference.py`` (client): single / whole-set / chunked-batch
  inference with accuracy + latency reporting
  (run_grpc_inference.py:162-216).

Placement semantics: ``layer_distribution`` comes from the model file's
metadata (the reference reads it from the same config JSON,
run_grpc_fcnn.py:266) or the caller. When the distribution names more
stages than there are devices, the engine collapses to the single-chip
executor — the TPU analogue of the reference running N containers on
one box — and notes it in the placement summary. A single-stage plan
always uses the unpadded single-chip path (no reason to pay padded
uniform-width matmuls on one device).
"""

from __future__ import annotations

import dataclasses
import logging
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

from tpu_dist_nn.core.schema import (
    ModelSpec,
    load_examples,
    load_model,
    partition_model,
)
from tpu_dist_nn.data.datasets import Dataset
from tpu_dist_nn.data.feed import batch_iterator
from tpu_dist_nn.models.fcnn import params_from_spec
from tpu_dist_nn.models.network import (
    build_network,
    jitted_network_forward,
    network_model_from_params,
)
from tpu_dist_nn.train.trainer import jitted_forward, train_network
from tpu_dist_nn.parallel.mesh import MeshSpec, batch_sharding, build_mesh, replicated
from tpu_dist_nn.parallel.pipeline import (
    build_pipeline_params,
    extract_model,
    pipeline_forward,
    pipeline_spec_summary,
)
from tpu_dist_nn.obs import trace as _trace
from tpu_dist_nn.obs.goodput import GOODPUT, fcnn_flops_per_row
from tpu_dist_nn.obs.log import get_logger
from tpu_dist_nn.obs.registry import REGISTRY
from tpu_dist_nn.train.metrics import classification_metrics
from tpu_dist_nn.train.trainer import TrainConfig, train_fcnn
from tpu_dist_nn.train.pipeline_trainer import train_pipelined

log = logging.getLogger("tpu_dist_nn.engine")
slog = get_logger("tpu_dist_nn.engine")

# Engine metric families (docs/OBSERVABILITY.md). Host-side float adds
# only — a time.monotonic() pair around a device call, never a fetch.
_INFER_SECONDS = REGISTRY.histogram(
    "tdn_engine_infer_seconds", "Engine.infer wall time per call",
)
_INFER_ROWS = REGISTRY.counter(
    "tdn_engine_infer_rows_total",
    "rows computed by Engine.infer (includes coalescing padding; "
    "tdn_batch_rows is the useful-rows view)",
)
_INFER_ERRORS = REGISTRY.counter(
    "tdn_engine_infer_errors_total", "Engine.infer calls that raised",
)
# jit caches one program per input shape: a shape this engine has not
# served before implies a compile (the bucketed batcher keeps this set
# at ~log2(max_rows)); a repeat shape is a cache hit.
_COMPILE_HITS = REGISTRY.counter(
    "tdn_engine_compile_cache_hits_total",
    "infer calls whose batch shape was already compiled",
)
_COMPILE_MISSES = REGISTRY.counter(
    "tdn_engine_compile_cache_misses_total",
    "infer calls whose batch shape was new (implies an XLA compile)",
)
_TRAIN_SECONDS = REGISTRY.histogram(
    "tdn_engine_train_seconds", "Engine.train wall time per call",
    buckets=(1.0, 5.0, 15.0, 60.0, 300.0, 1800.0, 7200.0),
)
_TRAIN_CALLS = REGISTRY.counter(
    "tdn_engine_train_calls_total", "Engine.train invocations",
)
# Warm state of the pow2 row-bucket ladder (warm_buckets): how many
# bucket programs this process has already compiled and executed, so an
# operator can tell "no live request will eat a compile" from a scrape.
_WARM_BUCKETS = REGISTRY.gauge(
    "tdn_engine_warm_buckets",
    "precompiled pow2 row-bucket programs resident in the jit cache",
)
# Measured at warm_buckets time on quantized engines: f32 wall time /
# int8 wall time for one warmed-bucket launch. > 1 means the int8 path
# pays off on the active backend; < 1 means quantized serving is
# SLOWER here (the BENCH int8_vs_f32 0.24-0.48x regression, made
# visible at serve time instead of only in the round artifacts).
_INT8_RATIO = REGISTRY.gauge(
    "tdn_int8_speedup_ratio",
    "f32 launch wall time / int8 launch wall time on the largest warm "
    "bucket (quantized engines; < 1 = int8 is slower on this backend; "
    "NaN until a quantized engine has measured)",
)
# Unlabeled gauges materialize at 0 immediately — which would read as
# "int8 is catastrophically slow" on every UNquantized process under
# the `< 1` alert the HELP text invites. NaN is the scrape-safe
# "no measurement yet" (renders as the text format's NaN literal;
# comparisons against it are false in PromQL).
_INT8_RATIO.set(float("nan"))


@dataclasses.dataclass
class PendingInference:
    """Handle from :meth:`Engine.infer_async`: a dispatched-but-not-
    materialized result. ``value`` is whatever the placement's executor
    returned (a device array on the async paths); ``materialize`` is
    the path-correct host read (``np.asarray`` for addressable arrays,
    the replicating collective for process-spanning ones). Pass to
    :meth:`Engine.fetch` — the fetch is the host sync, so everything
    between dispatch and fetch overlaps with device execution.
    ``release`` (when set) returns the launch's pooled host staging
    buffer; fetch calls it once the device can no longer alias the
    buffer (same discipline as the serving batcher's staging pool)."""

    value: object
    materialize: object
    t0: float
    release: object = None


@dataclasses.dataclass
class InferenceResult:
    """Client-side report (run_grpc_inference.py:185-216)."""

    outputs: np.ndarray
    seconds: float
    batch_seconds: list[float]
    metrics: dict | None = None

    @property
    def predictions(self) -> np.ndarray:
        return self.outputs.argmax(-1)

    def latency_summary(self) -> dict:
        """Percentiles over per-batch wall times — the structured form of
        the per-batch seconds the reference printed and discarded."""
        from tpu_dist_nn.utils.profiling import LatencyStats

        return LatencyStats("batch_infer", list(self.batch_seconds)).summary()


class Engine:
    """A brought-up model: placed, compiled, ready to serve or train."""

    def __init__(self, model: ModelSpec, distribution, mesh_spec: MeshSpec,
                 num_microbatches: int, dtype, devices=None,
                 quantize: str | None = None, virtual_stages: int = 1):
        # Fail fast on quantize mode/placement BEFORE building any
        # placement state (matches up()'s fail-fast convention).
        if quantize is not None:
            from tpu_dist_nn.utils.errors import InvalidArgumentError

            if quantize != "int8":
                raise InvalidArgumentError(
                    f"unknown quantize mode {quantize!r}; supported: 'int8'"
                )
            if not model.is_dense:
                raise InvalidArgumentError(
                    "quantize='int8' serves dense models only (conv/pool "
                    "layers have no int8 path); it composes with pipeline, "
                    "data-parallel, AND interleaved placements"
                )
        self.virtual_stages = int(virtual_stages)
        # Engine.up overwrites this with the ORIGINAL request when the
        # device-shortage degrade resets virtual_stages (train() uses
        # it to warn-and-fallback instead of raising a contradictory
        # "pass --virtual-stages" error).
        self.requested_virtual_stages = int(virtual_stages)
        # Copy metadata so export()'s annotations never mutate a
        # ModelSpec the caller still holds.
        self.model = ModelSpec(model.layers, dict(model.metadata))
        self.distribution = list(distribution)
        self.mesh_spec = mesh_spec
        self.num_microbatches = num_microbatches
        self.dtype = dtype
        # Interleaved placements pipeline V = stage*v chunks over a
        # stage-axis mesh of size V/v, so stage==1 with v>1 still runs
        # the (virtual-stage) pipeline executor.
        self.pipelined = mesh_spec.stage > 1 or virtual_stages > 1
        self.mesh = build_mesh(mesh_spec, devices)
        # Pure data parallelism on a single-stage plan: batch sharded
        # over the data axis, params replicated.
        self.data_sharded = not self.pipelined and mesh_spec.data > 1
        self._plan = None  # mixed-layer (conv/pool) networks only
        self._hp = None  # heterogeneous (non-dense) pipeline executor
        if self.pipelined and not model.is_dense:
            from tpu_dist_nn.parallel.hetero_pipeline import HeteroPipeline

            self._hp = HeteroPipeline(
                model, self.distribution,
                devices=list(self.mesh.devices.flat), dtype=dtype,
            )
            self._pp = None
            self._params = None
        elif self.pipelined:
            stages = partition_model(model, self.distribution)
            self._pp = build_pipeline_params(stages, dtype)
            self._params = None
        else:
            self._pp = None
            if model.is_dense:
                self._params = params_from_spec(model, dtype)
            else:
                self._plan, self._params = build_network(model, dtype)
            if self.data_sharded:
                self._params = jax.device_put(self._params, replicated(self.mesh))
        self._q = None  # int8 serving path, single-program placement
        self._q_pp = None  # int8 serving path, pipelined placement
        # Batch shapes this engine has LAUNCHED — the compile-cache
        # hit/miss proxy (jit compiles one program per input shape).
        # Keys are the device-launch shape recorded by _infer_impl
        # (after any internal padding), not the caller's row count.
        self._seen_infer_shapes: set[tuple] = set()
        # The numpy view of the engine dtype: the hot path casts input
        # ONCE, straight to this (the float64 wire contract stops at
        # the serving boundary).
        self._np_dtype = np.dtype(dtype)
        # Reusable host staging buffers for the feed path, keyed by
        # launch shape: a host-fed caller whose input needs a cast (or
        # pad) lands it in a pooled buffer instead of a fresh alloc
        # per batch. Buffers return to the pool at FETCH time
        # (PendingInference.release) — a backend that zero-copy-aliases
        # host memory into device buffers must never see one mutate
        # mid-flight (the serving batcher's staging rule). Depth 2 per
        # shape = the double-buffered steady state.
        self._host_staging: dict[tuple, list[np.ndarray]] = {}
        self._host_staging_keep = 2
        # Pow2 row buckets already compiled+executed by warm_buckets.
        self._warm_buckets: set[int] = set()
        # One automatic int8-payoff measurement per engine (warm_buckets
        # is idempotent and re-entered; the f32-arm compile is not free).
        self._int8_measured = False
        # When the payoff measurement finds int8 SLOWER than f32 on
        # this backend (ratio < 1), serving launches auto-route to the
        # f32 path instead of shipping the regression (TDN_INT8_AUTO=0
        # opts out; the quantized state is kept, so train()'s
        # re-quantization and explicit re-measurement still work).
        self.int8_auto_disabled = False
        # First-class fault-injection hook points (monkeypatch-free):
        # when set, called at the top of infer_async / fetch with the
        # batch / pending handle. tpu_dist_nn.testing.faults attaches
        # deterministic plans here; None costs one attribute check.
        self.launch_hook = None
        self.fetch_hook = None
        # Static activation names: passed explicitly on the hot path so
        # infer() never reads act ids back from the device.
        self._act_names = tuple(l.activation for l in model.layers)
        # Goodput accounting (obs/goodput.py): the analytic per-row
        # FLOP cost of this engine's dense chain, recorded per launch
        # at the infer_async boundary. None for non-dense models (no
        # FLOP model -> no accounting). Peak resolution happens here,
        # at configure time — the host-anchor measurement must never
        # ride a sampler tick.
        self._flops_per_row = (
            fcnn_flops_per_row(self.model.layer_sizes)
            if model.is_dense else None
        )
        if self._flops_per_row:
            # The peak must match the ledger's footprint: launches are
            # recorded whole, so a sharded placement's denominator is
            # per-device peak x mesh size.
            GOODPUT.ensure_peak(device_count=mesh_spec.num_devices)
        if quantize is not None:
            if self.pipelined:
                from tpu_dist_nn.kernels.quantized import (
                    quantize_pipeline_weights,
                )

                self._q_pp = quantize_pipeline_weights(self._pp.weights)
            else:
                from tpu_dist_nn.kernels.quantized import quantize_fcnn

                self._q = quantize_fcnn(self._params)
        self.setup_seconds: float | None = None

    # ---------------------------------------------------------------- up

    @classmethod
    def up(
        cls,
        model,
        distribution=None,
        *,
        data_parallel: int = 1,
        num_microbatches: int = 4,
        dtype=jnp.float32,
        devices=None,
        warmup: bool = True,
        quantize: str | None = None,
        virtual_stages: int = 1,
        warm_rows: int = 0,
    ) -> "Engine":
        """Validate, place, compile; returns a ready engine.

        ``model`` is a path or a ModelSpec. Bring-up wall time lands in
        ``engine.setup_seconds`` (run_grpc_fcnn.py:321-322 parity).
        ``quantize="int8"`` serves the dense chain through the fused
        int8 Pallas path (f32 masters kept for train/export).

        ``warm_rows > 0`` precompiles the whole pow2 row-bucket ladder
        up to that many rows at bring-up (:meth:`warm_buckets`), so a
        served engine never pays an XLA compile on a live request mix.

        ``virtual_stages=v > 1`` selects the INTERLEAVED (virtual-stage)
        inference placement: the distribution's ``V`` entries become
        ``V`` pipeline chunks with chunk ``c`` on device ``c % (V/v)``
        — a V-chunk pipeline on V/v devices, served by the table-driven
        forward executor (parallel/interleaved.make_interleaved_forward).
        """
        t0 = time.monotonic()
        if not isinstance(model, ModelSpec):
            model = load_model(model)
        if distribution is None:
            distribution = model.metadata.get("layer_distribution")
        if distribution is None:
            distribution = [len(model.layers)]
        # Fail fast on an invalid plan (run_grpc_fcnn.py:182-183).
        partition_model(model, distribution)

        n_devices = len(devices or jax.devices())
        stages = len(distribution)
        from tpu_dist_nn.utils.errors import InvalidArgumentError

        if virtual_stages < 1:
            raise InvalidArgumentError(
                f"virtual_stages must be >= 1, got {virtual_stages}"
            )
        # Remember the REQUEST: the device-shortage degrade below may
        # reset virtual_stages to 1, and train(schedule="interleaved")
        # must then warn-and-fallback rather than tell the user to pass
        # the flag they already passed.
        requested_virtual = virtual_stages
        if virtual_stages > 1:
            if not model.is_dense:
                raise InvalidArgumentError(
                    "virtual_stages applies to dense pipelined models "
                    "(the heterogeneous executor pins one stage per device)"
                )
            if stages % virtual_stages:
                raise InvalidArgumentError(
                    f"distribution has {stages} entries (chunks), not "
                    f"divisible by virtual_stages={virtual_stages}"
                )
            stage_devices = stages // virtual_stages
            if stage_devices * data_parallel > n_devices:
                # Same graceful-degradation contract as the plain
                # placement below: serve single-chip rather than fail.
                log.info(
                    "placement: interleaved %d stage device(s) x %d data "
                    "shards exceed %d device(s); collapsing to the "
                    "single-chip executor",
                    stage_devices, data_parallel, n_devices,
                )
                virtual_stages = 1
                mesh_spec = MeshSpec(stage=1, data=1)
                distribution = [len(model.layers)]
            else:
                mesh_spec = MeshSpec(stage=stage_devices, data=data_parallel)
        else:
            if stages > 1 and not model.is_dense and data_parallel > 1:
                # The heterogeneous executor pins one stage per device
                # and has no data axis; pipeline placement wins.
                log.info(
                    "placement: non-dense pipeline ignores data_parallel=%d",
                    data_parallel,
                )
                data_parallel = 1
            if stages * data_parallel > n_devices:
                log.info(
                    "placement: %d stages x %d data shards exceed %d "
                    "device(s); collapsing to the single-chip executor",
                    stages, data_parallel, n_devices,
                )
                mesh_spec = MeshSpec(stage=1, data=1)
                distribution = [len(model.layers)]
            else:
                mesh_spec = MeshSpec(stage=stages, data=data_parallel)
            if mesh_spec.stage == 1:
                distribution = [len(model.layers)]

        engine = cls(model, distribution, mesh_spec, num_microbatches, dtype,
                     devices, quantize=quantize,
                     virtual_stages=virtual_stages)
        engine.requested_virtual_stages = requested_virtual
        if warmup or warm_rows > 0:
            # Compilation is the readiness check (the analogue of the
            # orchestrator's TCP poll, run_grpc_fcnn.py:157-172); with
            # warm_rows the whole bucket ladder compiles here instead
            # of on the first unlucky live request mix.
            engine.warm_buckets(max(warm_rows, 1 if warmup else 0))
        engine.setup_seconds = time.monotonic() - t0
        slog.info("engine.up", seconds=round(engine.setup_seconds, 3),
                  placement=engine.placement())
        return engine

    def placement(self) -> dict:
        """Placement summary — the spawn-log analogue (run_grpc_fcnn.py:133-143)."""
        base = {
            "devices": self.mesh_spec.num_devices,
            "distribution": self.distribution,
            "data_parallel": self.mesh_spec.data,
            "pipelined": self.pipelined,
        }
        if self.virtual_stages > 1:
            base["virtual_stages"] = self.virtual_stages
        if self._hp is not None:
            base.update(self._hp.placement_summary())
        elif self.pipelined:
            base.update(pipeline_spec_summary(self._pp))
        else:
            base.update(
                {
                    "num_stages": 1,
                    "input_dim": self.model.input_dim,
                    "output_dim": self.model.output_dim,
                }
            )
        return base

    # ------------------------------------------------------------- infer

    def infer(self, x) -> np.ndarray:
        """Forward a batch → (N, out_dim) probabilities.

        Raises :class:`~tpu_dist_nn.utils.errors.InvalidArgumentError` on
        a feature-dim mismatch (the reference's per-forward check,
        grpc_node.py:83-84 → INVALID_ARGUMENT) and
        :class:`~tpu_dist_nn.utils.errors.UnavailableError` after
        :meth:`down` (the reference's dead-channel UNAVAILABLE).

        A direct call is ONE request, so the numeric guard's per-row
        failover collapses to request granularity here: any corrupt
        row raises :class:`~tpu_dist_nn.utils.errors.IntegrityError`
        rather than shipping a partially-poisoned batch (the batcher
        path keeps row granularity via ``PendingInference.bad_rows``).
        """
        pending = self.infer_async(x)
        out = self.fetch(pending)
        bad = getattr(pending, "bad_rows", None)
        if bad is not None and bad.any():
            from tpu_dist_nn.utils.errors import IntegrityError

            raise IntegrityError(
                f"numeric guard: {int(bad.sum())}/{len(out)} rows of "
                f"the result are non-finite or out of magnitude bounds"
            )
        return out

    def infer_async(self, x, *, useful_rows=None) -> PendingInference:
        """Validate, stage, and LAUNCH a batch without waiting for it.

        Returns a :class:`PendingInference` whose device result is
        still materializing (JAX async dispatch); :meth:`fetch` is the
        host sync. The serving batcher's dispatch stage launches batch
        N+1 through this while batch N's fetch is in flight — the
        double-buffered fast path. Validation errors raise HERE (at
        dispatch), so a bad request fails before it occupies the
        pipeline.

        ``useful_rows`` is the goodput declaration (obs/goodput.py):
        how many of this batch's rows carry request data. The batcher
        passes its pre-padding row count so bucket pad is accounted as
        pad FLOPs under ``path="batcher"``; direct callers omit it and
        the launch counts as all-useful under ``path="engine"``
        (data-shard padding on direct calls rides as useful — a named
        model simplification, single-chip launches have none).
        """
        t0 = time.monotonic()
        try:
            # getattr: hand-constructed engines (tests build the
            # single-chip path via Engine.__new__) may predate the slot.
            hook = getattr(self, "launch_hook", None)
            if hook is not None:
                hook(x)  # fault injection: may raise or delay
            out, materialize, shape, release = self._infer_impl(x)
        except Exception:
            _INFER_ERRORS.inc()
            raise
        # Goodput accounting at the launch boundary: one integer record
        # per device launch (never per row). getattr: hand-constructed
        # engines (Engine.__new__ in tests) may predate the slot.
        fpr = getattr(self, "_flops_per_row", None)
        if fpr:
            total_rows = int(shape[0])
            if useful_rows is None:
                GOODPUT.record_rows(fpr, total_rows, total_rows,
                                    path="engine")
            else:
                GOODPUT.record_rows(fpr, total_rows, int(useful_rows),
                                    path="batcher")
        # Trace annotations attach to whatever request span is active
        # on this thread (the batcher's launch span, a handler span, or
        # nothing) — the active() guard keeps the f-strings off the
        # untraced path entirely.
        if _trace.active():
            _trace.annotate(
                f"engine.infer_async launch_shape={shape} "
                f"dispatch_s={time.monotonic() - t0:.6f}"
            )
        # Compile-cache proxy keyed on the DEVICE-LAUNCH shape returned
        # by _infer_impl (after internal padding — e.g. the data-sharded
        # path pads rows to the shard count): jit compiles one program
        # per launch shape, so keying on the caller's unpadded row count
        # would overcount misses. Returned, not read off instance state:
        # concurrent infer callers (batcher dispatch + a health probe)
        # must not read each other's shapes.
        seen = self._seen_infer_shapes
        if shape in seen:
            _COMPILE_HITS.inc()
        else:
            seen.add(shape)
            _COMPILE_MISSES.inc()
            if _trace.active():
                # The event a slow-request trace most wants named: this
                # launch shape was new, so the request likely paid an
                # XLA compile (hundreds of ms) nothing else explains.
                _trace.annotate(f"engine.compile_cache_miss shape={shape}")
        return PendingInference(out, materialize, t0, release)

    def fetch(self, pending: PendingInference) -> np.ndarray:
        """Materialize an :meth:`infer_async` handle as host numpy —
        the ONE host sync of an inference. Wall time from dispatch to
        materialized result lands in ``tdn_engine_infer_seconds``."""
        try:
            hook = getattr(self, "fetch_hook", None)
            if hook is not None:
                hook(pending)  # fault injection: may raise or delay
            out = pending.materialize(pending.value)
            # Numeric guard at the ONE host sync: the result is already
            # materialized host-side, so the isfinite reduction is one
            # vectorized pass over hot memory. Partial corruption is
            # stashed as a row mask for the batcher's per-row failover
            # (unaffected rows ship bit-identical); a fully-bad launch
            # has no salvageable rows and raises outright.
            from tpu_dist_nn.serving.integrity import GUARD

            bad = GUARD.bad_rows(out) if GUARD.enabled else None
            if bad is not None and bad.any():
                pending.bad_rows = bad
                if bad.all():
                    from tpu_dist_nn.utils.errors import IntegrityError

                    raise IntegrityError(
                        f"numeric guard: all {len(out)} rows of the "
                        f"launch are non-finite or out of magnitude — "
                        f"refusing to ship the batch"
                    )
        except Exception:
            _INFER_ERRORS.inc()
            raise
        finally:
            # Return the launch's pooled host staging buffer: after the
            # materialize attempt the device result is (or will never
            # be) realized, so the input buffer can no longer alias a
            # mutating transfer. Cleared first — a double fetch must
            # not double-free the buffer into the pool.
            rel = getattr(pending, "release", None)
            if rel is not None:
                pending.release = None
                rel()
        _INFER_SECONDS.observe(time.monotonic() - pending.t0)
        _INFER_ROWS.inc(len(out))
        if _trace.active():
            _trace.annotate(
                f"engine.fetch rows={len(out)} "
                f"since_dispatch_s={time.monotonic() - pending.t0:.6f}"
            )
        return out

    def warm_buckets(self, max_rows: int) -> list[int]:
        """Precompile the pow2 row-bucket ladder (1, 2, 4, … up to the
        pow2 CEILING of ``max_rows`` — a coalesced batch of
        ``max_rows`` rows pads into that bucket, so stopping at the
        last pow2 below it would leave exactly the top bucket cold)
        so no live request ever eats an XLA compile.

        Each bucket runs one real zeros-batch inference rather than an
        AOT ``lower().compile()``: executing through the jit call site
        is the only warm that seeds the dispatch cache the live path
        actually hits (an AOT Compiled object is a separate executable),
        and it additionally lands the program in the persistent compile
        cache when ``JAX_COMPILATION_CACHE_DIR`` is set — which is what
        makes a standalone ``tdn warmup`` run pay off across processes.

        Already-warm buckets are skipped (idempotent); the warm-state
        count is published as the ``tdn_engine_warm_buckets`` gauge.
        Returns the bucket sizes newly warmed by THIS call.
        """
        warmed: list[int] = []
        if max_rows < 1:
            return warmed
        dim = self.model.input_dim
        top = 1 << (max_rows - 1).bit_length() if max_rows > 1 else 1
        n = 1
        while n <= top:
            if n not in self._warm_buckets:
                self.infer(np.zeros((n, dim), self._np_dtype))
                self._warm_buckets.add(n)
                warmed.append(n)
                # Per-bucket, not once at the end: a scrape DURING a
                # long warm (tdn warmup --metrics-port) sees progress.
                # This method is the gauge's ONLY writer — one-engine-
                # per-process semantics; a second engine's warm
                # overwrites with its own count.
                _WARM_BUCKETS.set(len(self._warm_buckets))
            n *= 2
        if (
            warmed
            and (self._q is not None or self._q_pp is not None)
            and not self._int8_measured
            and os.environ.get("TDN_INT8_WARMUP_MEASURE", "1") != "0"
        ):
            # The int8 payoff check rides the FIRST warm (the port is
            # not open yet): the BENCH int8_vs_f32 regression becomes a
            # serve-time gauge + structured warning instead of a
            # round-artifact archaeology find. Costs one f32 compile of
            # the never-warmed float path plus a few launches —
            # TDN_INT8_WARMUP_MEASURE=0 skips it where that compile is
            # too expensive (explicit measure_int8_speedup() calls
            # still work).
            self.measure_int8_speedup()
        return warmed

    def measure_int8_speedup(self, rows: int | None = None) -> float | None:
        """Time one f32 vs one int8 launch on the largest warm bucket
        (or ``rows``) and publish ``tdn_int8_speedup_ratio``.

        Returns f32_seconds / int8_seconds (> 1: the quantized path is
        faster on this backend), or None on a non-quantized engine.
        Runs the engine's OWN dispatch both ways — the f32 arm
        temporarily clears the quantized state so ``_infer_impl``
        selects the float path for any placement (single-chip, sharded,
        pipelined, interleaved). Best-of-3 after one warm call per arm,
        so neither side pays its XLA compile inside the timed window.
        Bring-up only: not safe concurrent with live traffic.
        """
        if self._q is None and self._q_pp is None:
            return None
        if rows is None:
            rows = max(self._warm_buckets) if self._warm_buckets else 1
        x = np.zeros((int(rows), self.model.input_dim), self._np_dtype)

        def best_of(n: int = 3) -> float:
            self.infer(x)  # warm (compile lands outside the timing)
            times = []
            for _ in range(n):
                t0 = time.monotonic()
                self.infer(x)
                times.append(time.monotonic() - t0)
            return min(times)

        q, q_pp, q_apply = self._q, self._q_pp, getattr(self, "_q_apply", None)
        self._q = self._q_pp = self._q_apply = None
        try:
            f32_s = best_of()
        finally:
            self._q, self._q_pp, self._q_apply = q, q_pp, q_apply
        # A RE-measurement on an auto-disabled engine must time the real
        # int8 path, not the f32 reroute the gate would select.
        gate = self.int8_auto_disabled
        self.int8_auto_disabled = False
        try:
            int8_s = best_of()
        finally:
            self.int8_auto_disabled = gate
        ratio = f32_s / int8_s if int8_s > 0 else float("inf")
        self._int8_measured = True
        _INT8_RATIO.set(ratio)
        if ratio < 1.0:
            slog.warning(
                "int8.slower_than_f32", ratio=round(ratio, 3),
                rows=int(rows), f32_ms=round(f32_s * 1e3, 3),
                int8_ms=round(int8_s * 1e3, 3),
                backend=jax.default_backend(),
                hint="serve without --quantize on this backend (int8 "
                     "is a dequantize-dominated loss here)",
            )
            if os.environ.get("TDN_INT8_AUTO", "1") != "0":
                # Close the regression instead of just warning about
                # it: the measured-slower path never serves traffic.
                # The f32 programs are already compiled (the f32 arm
                # of the measurement just ran them), so the reroute is
                # warm.
                self.int8_auto_disabled = True
                slog.warning(
                    "int8.auto_disabled", ratio=round(ratio, 3),
                    backend=jax.default_backend(),
                    hint="serving launches rerouted to the f32 path "
                         "(TDN_INT8_AUTO=0 opts out of the fallback)",
                )
            else:
                # Explicit opt-out means measure + warn ONLY: a
                # re-measurement must also clear any reroute a prior
                # env-enabled run left armed, or the opt-out would
                # leave the engine stuck on f32.
                self.int8_auto_disabled = False
        else:
            self.int8_auto_disabled = False
            slog.info(
                "int8.speedup", ratio=round(ratio, 3), rows=int(rows),
                backend=jax.default_backend(),
            )
        return ratio

    @property
    def warm_bucket_count(self) -> int:
        """Attribute-only warm state (the obs runtime sampler reads
        this — no device work, mirroring ``is_ready``)."""
        return len(self._warm_buckets)

    def _host_buffer(self, shape) -> tuple[np.ndarray, object]:
        """Pooled engine-dtype host staging buffer for a feed-path
        launch shape, plus its return-to-pool callable.

        The host-feed analogue of the batcher's per-bucket staging
        pool: a caller whose input needs a cast (or shard pad) fills a
        REUSED buffer instead of paying a fresh alloc per batch. The
        release callable runs at fetch time (PendingInference.release)
        — never earlier, so a backend that zero-copy-aliases host
        memory into device buffers cannot see the buffer mutate under
        an in-flight batch. getattr-guarded: hand-constructed engines
        (tests build the single-chip path via ``Engine.__new__``) may
        predate the pool slot."""
        pool = getattr(self, "_host_staging", None)
        if pool is None:
            pool = self._host_staging = {}
        bufs = pool.get(shape)
        buf = None
        if bufs:
            try:
                buf = bufs.pop()
            except IndexError:  # concurrent infer callers raced the pop
                buf = None
        if buf is None:
            buf = np.empty(shape, self._np_dtype)
        keep = getattr(self, "_host_staging_keep", 2)

        def release():
            held = pool.setdefault(shape, [])
            if len(held) < keep:
                held.append(buf)

        return buf, release

    def _infer_impl(self, x):
        from tpu_dist_nn.utils.errors import UnavailableError, check_input_dim

        if self._pp is None and self._params is None and self._hp is None:
            raise UnavailableError(
                "engine is down; relaunch with Engine.up from the model JSON"
            )
        x = np.asarray(x)
        in_dim = self.model.input_dim
        if x.ndim >= 2:
            check_input_dim(in_dim, int(x.shape[-1]), stage=0)
        elif x.size != in_dim:
            check_input_dim(in_dim, int(x.size), stage=0)
        x = x.reshape(-1, in_dim)
        # ONE cast, straight to the engine dtype (no float64 staging
        # array): the float64 wire contract lives at the serving
        # boundary only, and the dtype-aware decoder usually lands
        # rows here already converted — this is then a no-op. When a
        # cast IS needed (host-fed callers with f64/u8 inputs), it
        # lands in a pooled staging buffer released at fetch, so the
        # double-buffered feed loop recycles two buffers per shape
        # instead of allocating per batch.
        release = None
        if x.dtype != self._np_dtype:
            buf, release = self._host_buffer((len(x), in_dim))
            np.copyto(buf, x, casting="unsafe")
            x = buf
        # The shape the device actually launches (the compile-cache
        # proxy key); branches that pad internally override it.
        launch = (len(x), in_dim)
        if self._hp is not None:
            mb = max(1, len(x) // self.num_microbatches)
            return (self._hp.forward(x, microbatch_size=mb), np.asarray,
                    launch, release)
        # The int8 serving paths are skipped entirely when the warmup
        # payoff measurement auto-disabled them (measured slower than
        # f32 on this backend; measure_int8_speedup).
        use_int8 = not self.int8_auto_disabled
        if self.pipelined:
            from tpu_dist_nn.parallel.multihost import to_host_numpy

            if use_int8 and self._q_pp is not None \
                    and self.virtual_stages > 1:
                from tpu_dist_nn.parallel.pipeline import (
                    pipeline_forward_interleaved_quantized,
                )

                out = pipeline_forward_interleaved_quantized(
                    self.mesh, self._q_pp, self._pp.meta, x,
                    num_virtual=self.virtual_stages,
                    num_microbatches=self.num_microbatches,
                )
                return out, to_host_numpy, launch, release
            if use_int8 and self._q_pp is not None:
                from tpu_dist_nn.parallel.pipeline import (
                    pipeline_forward_quantized,
                )

                out = pipeline_forward_quantized(
                    self.mesh, self._q_pp, self._pp.meta, x,
                    num_microbatches=self.num_microbatches,
                )
                return out, to_host_numpy, launch, release
            if self.virtual_stages > 1:
                from tpu_dist_nn.parallel.pipeline import (
                    pipeline_forward_interleaved,
                )

                out = pipeline_forward_interleaved(
                    self.mesh, self._pp, x,
                    num_virtual=self.virtual_stages,
                    num_microbatches=self.num_microbatches,
                )
                return out, to_host_numpy, launch, release
            out = pipeline_forward(
                self.mesh, self._pp, x, num_microbatches=self.num_microbatches
            )
            return out, to_host_numpy, launch, release
        if use_int8 and self._q is not None and not self.data_sharded:
            from tpu_dist_nn.kernels.quantized import fcnn_quantized_forward

            return (
                fcnn_quantized_forward(
                    self._q, jnp.asarray(x, jnp.float32),
                    activations=self._act_names,
                ),
                np.asarray,
                launch,
                release,
            )
        if use_int8 and self._q is not None:
            # Data-sharded int8: the jnp quantized chain under jit on the
            # batch-sharded global array (weights replicated); XLA keeps
            # the int8 matmuls sharded over the data axis.
            apply = self._quantized_apply()
        else:
            apply = (
                jitted_forward
                if self._plan is None
                else jitted_network_forward(self._plan)
            )
        if self.data_sharded:
            from tpu_dist_nn.parallel.multihost import to_host_numpy

            n = len(x)
            shards = self.mesh_spec.data
            pad = -n % shards
            if pad:
                # Shard padding lands in a pooled staging buffer too
                # (rows copied in, pad tail zeroed in place) — np.pad
                # allocated a fresh padded matrix every batch. Chain
                # the cast buffer's release when one is outstanding so
                # both return to the pool at fetch.
                xb, pad_release = self._host_buffer((n + pad, in_dim))
                np.copyto(xb[:n], x, casting="unsafe")
                xb[n:] = 0
                if release is None:
                    release = pad_release
                else:
                    cast_release = release

                    def release(a=cast_release, b=pad_release):
                        a()
                        b()
            else:
                xb = x
            # jit sees the PADDED batch: that is the compiled shape.
            launch = (len(xb), in_dim)
            if jax.process_count() > 1:
                # Every host computed the same padded batch; each device
                # receives exactly the chunk the sharding assigns it.
                # (Deriving rows from process_index arithmetic instead
                # would silently permute outputs on meshes whose data
                # axis is not process-contiguous.)
                from jax.sharding import PartitionSpec as P

                from tpu_dist_nn.data.feed import global_from_replicated
                from tpu_dist_nn.parallel.mesh import AXIS_DATA

                xb = global_from_replicated(self.mesh, P(AXIS_DATA), xb)
            else:
                xb = jax.device_put(xb, batch_sharding(self.mesh))
            # The [:n] slice is a lazy device op: the unpadded view
            # materializes at fetch, the launch stays padded.
            return apply(self._params, xb)[:n], to_host_numpy, launch, release
        return (apply(self._params, jnp.asarray(x, self.dtype)), np.asarray,
                launch, release)

    def _quantized_apply(self):
        """Cached jitted (params, xb) -> logits closure over the int8
        blocks, signature-compatible with the data-sharded dispatch."""
        if getattr(self, "_q_apply", None) is None:
            from tpu_dist_nn.kernels.quantized import forward_quantized

            q, acts = self._q, self._act_names
            self._q_apply = jax.jit(
                lambda _params, xb: forward_quantized(q, xb, acts)
            )
        return self._q_apply

    def infer_single(self, x) -> tuple[np.ndarray, float]:
        """One example, with its wall time (run_grpc_inference.py:54-99)."""
        t0 = time.monotonic()
        out = self.infer(np.asarray(x).reshape(1, -1))[0]
        return out, time.monotonic() - t0

    def step_latency(self, batch_size: int = 256, iters: int = 20) -> dict:
        """The BASELINE "p50 per-stage pipeline step latency" probe.

        Times ``iters`` synchronous forward steps on a synthetic batch
        and reports the :class:`~tpu_dist_nn.utils.profiling.LatencyStats`
        percentiles plus ``p50_per_stage_s`` (step p50 divided by the
        stage count — the per-stage share of one pipeline step).
        """
        from tpu_dist_nn.utils.errors import InvalidArgumentError
        from tpu_dist_nn.utils.profiling import LatencyStats

        if iters < 1 or batch_size < 1:
            raise InvalidArgumentError(
                f"step_latency needs iters >= 1 and batch_size >= 1, "
                f"got iters={iters}, batch_size={batch_size}"
            )
        rng = np.random.default_rng(0)
        x = rng.uniform(0.0, 1.0, (batch_size, self.model.input_dim))
        self.infer(x)  # warmup / compile
        stats = LatencyStats("pipeline_step")
        for _ in range(iters):
            t0 = time.monotonic()
            self.infer(x)
            stats.record(time.monotonic() - t0)
        num_stages = self.placement().get("num_stages", 1)
        summary = stats.summary()
        summary["num_stages"] = num_stages
        summary["p50_per_stage_s"] = summary["p50_s"] / num_stages
        return summary

    def run_inference(
        self,
        inputs,
        labels=None,
        *,
        batch_size: int | None = None,
        num_classes: int | None = None,
    ) -> InferenceResult:
        """Whole-set or chunked-batch inference with accuracy + latency —
        the reference client's main loop (run_grpc_inference.py:185-216).

        The chunked path is a double-buffered host-feed loop: batch
        ``i+1`` is staged (pooled cast buffer) and LAUNCHED before
        batch ``i``'s fetch pays the host sync, so the host->device
        transfer of the next batch overlaps the previous batch's
        compute — the same overlap the serving batcher's dispatch/drain
        split buys, without a thread. Results and their order are
        identical to the serial loop; ``batch_seconds[i]`` spans batch
        i's dispatch to its materialized result.
        """
        inputs = np.asarray(inputs)
        t0 = time.monotonic()
        outputs = []
        batch_seconds = []
        if batch_size is None:
            bt0 = time.monotonic()
            outputs.append(self.infer(inputs))
            batch_seconds.append(time.monotonic() - bt0)
        else:
            pending = None
            pt0 = 0.0
            for bx in batch_iterator(inputs, batch_size=batch_size):
                bt0 = time.monotonic()
                nxt = self.infer_async(bx)
                if pending is not None:
                    outputs.append(self.fetch(pending))
                    batch_seconds.append(time.monotonic() - pt0)
                pending, pt0 = nxt, bt0
            if pending is not None:
                outputs.append(self.fetch(pending))
                batch_seconds.append(time.monotonic() - pt0)
        outputs = np.concatenate(outputs)
        seconds = time.monotonic() - t0
        metrics = None
        if labels is not None:
            metrics = classification_metrics(outputs, labels, num_classes)
        return InferenceResult(outputs, seconds, batch_seconds, metrics)

    # ------------------------------------------------------------- train

    def train(
        self,
        train_data: Dataset,
        config: TrainConfig = TrainConfig(),
        eval_data: Dataset | None = None,
        checkpoints=None,
        schedule: str = "gpipe",
    ) -> list[dict]:
        """Train in place (pipelined if placed that way); returns history."""
        _TRAIN_CALLS.inc()
        t0 = time.monotonic()
        try:
            return self._train_impl(
                train_data, config, eval_data, checkpoints, schedule
            )
        finally:
            _TRAIN_SECONDS.observe(time.monotonic() - t0)

    def _train_impl(
        self,
        train_data: Dataset,
        config: TrainConfig,
        eval_data: Dataset | None = None,
        checkpoints=None,
        schedule: str = "gpipe",
    ) -> list[dict]:
        """Train in place (pipelined if placed that way); returns history.

        ``checkpoints`` (a :class:`tpu_dist_nn.checkpoint.CheckpointManager`)
        turns on epoch-level save + resume for whichever trainer flavor
        this engine's placement selects. ``schedule``
        ("gpipe" | "1f1b" | "interleaved") picks the pipeline training
        schedule; it only applies to the pipelined placement. An
        interleaved (``virtual_stages > 1``) placement auto-selects
        "interleaved" (the default "gpipe" is upgraded; "1f1b" is
        rejected there — it assumes chunk-per-device); "interleaved" on
        a non-virtual placement is rejected with a pointer at
        ``virtual_stages``.
        """
        # Validate regardless of placement: a typo'd schedule on a
        # non-pipelined engine must not silently train with the default.
        from tpu_dist_nn.parallel.one_f_one_b import validate_schedule

        validate_schedule(schedule)
        if schedule in ("zb", "zb-v"):
            raise ValueError(
                "zero-bubble schedules are implemented for the "
                "transformer LM pipeline only (tdn lm --schedule zb); "
                "the classifier engine supports gpipe/1f1b/interleaved"
            )
        if self.virtual_stages > 1:
            # The placement determines the schedule: V chunks on V/v
            # devices can only run the table-driven interleaved
            # executors (gpipe/1f1b assume chunk-per-device).
            if schedule == "1f1b":
                raise ValueError(
                    "schedule='1f1b' does not apply to an interleaved "
                    "(virtual_stages > 1) placement; the schedule is "
                    "'interleaved' there (the default 'gpipe' auto-"
                    "selects it)"
                )
            if schedule == "gpipe":
                log.info(
                    "train: interleaved placement (virtual_stages=%d) "
                    "selects schedule='interleaved'", self.virtual_stages,
                )
            schedule = "interleaved"
        elif schedule == "interleaved":
            if self.requested_virtual_stages > 1:
                # The user DID request a virtual placement; the
                # device-shortage degrade collapsed it to single-chip.
                # Honor the degradation contract: train single-chip
                # with the default schedule instead of raising an error
                # that tells them to pass the flag they already passed.
                log.warning(
                    "train: interleaved placement was collapsed to the "
                    "single-chip executor at up() (too few devices); "
                    "training with the default schedule"
                )
                schedule = "gpipe"
            else:
                raise ValueError(
                    "schedule='interleaved' needs an interleaved "
                    "placement: bring the engine up with virtual_stages=v "
                    "(tdn train --virtual-stages v) so the distribution's "
                    "V chunks land on V/v devices"
                )
        # The heterogeneous executor trains through its own hand-rolled
        # GPipe schedule (train_hetero), which has no 1f1b variant.
        if schedule != "gpipe" and (not self.pipelined or self._hp is not None):
            raise ValueError(
                f"schedule={schedule!r} applies to the dense pipelined "
                "placement only (this engine was placed "
                + ("heterogeneous" if self._hp is not None else "single-program")
                + "); place a dense model with a multi-stage distribution "
                "to use it"
            )
        if self._hp is not None:
            # Train THROUGH the pipeline placement: per-stage jitted
            # VJPs with device_put hand-offs mirroring the forward
            # (parallel/hetero_pipeline.py training section; global-norm
            # clipping is applied across the stages by the step).
            from tpu_dist_nn.parallel.hetero_pipeline import train_hetero

            # num_microbatches is an inference knob set at up() time;
            # training only needs SOME equal split of the batch, so take
            # the largest batch_size divisor not exceeding it — any
            # batch_size trains, as it did pre-pipelined-training.
            mb = max(
                d for d in range(1, self.num_microbatches + 1)
                if config.batch_size % d == 0
            )
            if mb != self.num_microbatches:
                # mb == 1 means NO pipeline overlap at all (e.g. a prime
                # batch size): the user configured a pipelined placement
                # but training would fully serialize — warn, don't bury.
                log.log(
                    logging.WARNING if mb == 1 else logging.INFO,
                    "train: using %d microbatches (engine's %d does not "
                    "divide batch_size %d)%s",
                    mb, self.num_microbatches, config.batch_size,
                    " — pipelined training fully serializes; choose a "
                    "batch size with a divisor > 1" if mb == 1 else "",
                )
            params_list, history = train_hetero(
                self._hp, train_data, config,
                eval_data=eval_data, checkpoints=checkpoints,
                num_microbatches=mb,
            )
            flat = [p for stage_params in params_list for p in stage_params]
            self.model = network_model_from_params(self.model, flat)
            return history
        if self.pipelined:
            self._pp, history = train_pipelined(
                self._pp,
                self.mesh,
                train_data,
                config,
                num_microbatches=self.num_microbatches,
                eval_data=eval_data,
                checkpoints=checkpoints,
                schedule=schedule,
                num_virtual=self.virtual_stages,
            )
            self.model = extract_model(self._pp, self.model, self.distribution)
        elif self._plan is not None:
            self._params, history = train_network(
                self._plan, self._params, train_data, config,
                eval_data=eval_data, checkpoints=checkpoints,
            )
            self.model = network_model_from_params(self.model, self._params)
        else:
            self._params, history = train_fcnn(
                self._params, train_data, config,
                eval_data=eval_data, checkpoints=checkpoints,
                # Data-sharded placement: train over the data axis too
                # (batch sharded, params replicated, grads all-reduced).
                mesh=self.mesh if self.data_sharded else None,
            )
            trained = [
                {"weights": np.asarray(p["w"], np.float64),
                 "biases": np.asarray(p["b"], np.float64)}
                for p in self._params
            ]
            new_layers = [
                dataclasses.replace(l, weights=t["weights"], biases=t["biases"])
                for l, t in zip(self.model.layers, trained)
            ]
            self.model = ModelSpec(new_layers, dict(self.model.metadata))
        if self._q is not None:
            # Re-quantize so the int8 serving path tracks the trained
            # weights (it would otherwise serve the pre-training copy).
            from tpu_dist_nn.kernels.quantized import quantize_fcnn

            self._q = quantize_fcnn(self._params)
            self._q_apply = None
        if self._q_pp is not None:
            from tpu_dist_nn.kernels.quantized import quantize_pipeline_weights

            self._q_pp = quantize_pipeline_weights(self._pp.weights)
        return history

    # ------------------------------------------------------------ export

    def export(self, path, metrics: dict | None = None) -> ModelSpec:
        """Write the current weights to the public JSON schema, embedding
        metrics under inference_metrics (notebook cell 10 parity)."""
        from tpu_dist_nn.core.schema import save_model

        if metrics is not None:
            self.model.metadata["inference_metrics"] = metrics
        if "layer_distribution" not in self.model.metadata and self.pipelined:
            self.model.metadata["layer_distribution"] = self.distribution
        save_model(self.model, path)
        return self.model

    # -------------------------------------------------------------- down

    def down(self) -> None:
        """Release references. Idempotent; relaunch = ``Engine.up`` again
        from the JSON model (the reference's clean-teardown/stateless-
        relaunch contract, run_grpc_fcnn.py:329-344)."""
        self._pp = None
        self._params = None
        self._q = None
        self._q_pp = None
        self._q_apply = None
        self._hp = None

    # ------------------------------------------------------------ health

    @property
    def is_ready(self) -> bool:
        """Attribute-only readiness (no device work) — the ONE
        predicate health(), /healthz, and the obs runtime sampler
        share, so a new placement slot cannot silently drift one of
        them out of sync."""
        return (
            self._pp is not None
            or self._params is not None
            or self._hp is not None
        )

    def fingerprint(self) -> str:
        """Whole-model weights fingerprint (integrity.fingerprint_tree
        over every layer's host-side float64 weights/biases) — the
        value ``/healthz`` exposes so the pool can refuse to admit a
        replica whose loaded weights disagree with the fleet's.

        Computed from ``self.model`` (the canonical host copy every
        placement shares), so replicas of the same model file agree
        regardless of device layout or quantization. Cached per model
        object — training swaps ``self.model`` wholesale, which
        naturally invalidates."""
        cached = getattr(self, "_fingerprint_cache", None)
        if cached is not None and cached[0] is self.model:
            return cached[1]
        from tpu_dist_nn.serving.integrity import fingerprint_tree

        tree = {}
        for i, layer in enumerate(self.model.layers):
            tree[f"layer{i}/weights"] = layer.weights
            tree[f"layer{i}/biases"] = layer.biases
        fp = fingerprint_tree(tree)["model"]
        self._fingerprint_cache = (self.model, fp)
        return fp

    def health(self, probe: bool = True) -> dict:
        """Structured readiness report — the reference's TCP readiness
        poll (run_grpc_fcnn.py:157-172) as an inspectable status.

        ``probe=False`` skips the device inference probe: the
        per-request form served by ``/healthz`` (a liveness poller must
        not dispatch device work concurrent with training/serving, nor
        pay an XLA compile on its first hit).
        """
        ready = self.is_ready
        status = {
            "ready": ready,
            "devices": self.mesh_spec.num_devices,
            "pipelined": self.pipelined,
            "setup_seconds": self.setup_seconds,
        }
        try:
            # getattr-shaped: hand-constructed engines (Engine.__new__
            # in tests) may lack a model.
            status["fingerprint"] = self.fingerprint()
        except Exception:  # noqa: BLE001 — health must never crash
            pass
        if ready and probe:
            try:
                probe_x = np.zeros((1, self.model.input_dim))
                out = self.infer(probe_x)
                status["probe_ok"] = bool(np.isfinite(out).all())
            except Exception as e:  # a failing probe is the finding, not a crash
                status["probe_ok"] = False
                status["probe_error"] = repr(e)
        return status


def load_inputs(path) -> tuple[np.ndarray, np.ndarray]:
    """Examples-file loader re-export for driver code."""
    return load_examples(path)
