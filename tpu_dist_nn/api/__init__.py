from tpu_dist_nn.api.engine import Engine, InferenceResult  # noqa: F401
