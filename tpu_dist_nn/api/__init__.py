from tpu_dist_nn.api.engine import (  # noqa: F401
    Engine,
    InferenceResult,
    PendingInference,
)
