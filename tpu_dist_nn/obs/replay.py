"""Scenario engine: trace-driven workload capture & replay (ISSUE 18).

The flight recorder (obs/incident.py) freezes exactly what traffic
looked like when something broke; the SLO plane (obs/slo.py) can score
any window. This module closes the loop:

* :class:`WorkloadTrace` — one portable, JSON-serializable description
  of a request stream: per-request arrival offset, method, SLO class,
  session key, prompt tokens/length, max_new_tokens, budget, stream
  flag. Two sources produce it:

  - :func:`trace_from_bundle` extracts one from an incident bundle.
    The serving handlers annotate their root spans with every request
    attribute replay needs (``_annotate_capture_attrs`` in
    serving/server.py), the spans are epoch-anchored in ``trace.json``
    — so a bundle ALONE is a replayable workload.
  - the seeded synthetic :data:`GENERATORS` (diurnal, flash-crowd,
    heavy-tail prompt lengths, adversarial shared-prefix flood,
    mixed-SLO-class) emit the same schema, bit-reproducible under a
    seed (``random.Random`` only — never the wall clock).

* :func:`replay` — fires a WorkloadTrace against any gRPC target (a
  live fleet, or the :class:`LoopbackFleet` below) at ``--speed``
  multiples, preserving sessions, classes, budgets, and streaming, and
  reports how faithfully the achieved send process matched the trace
  (per-decile inter-arrival error — Orca makes arrival-process shape
  the dominant serving variable, so fidelity is itself a primitive).

* :class:`LoopbackFleet` — an in-process fleet: N fake-engine replicas
  (numpy-only, paced; all three RPC methods) behind the REAL router /
  pool / breaker / failover stack on 127.0.0.1 ephemeral ports. In-
  process on purpose: one shared TRACER sees both router and handler
  root spans (so capture round-trips work in one process), and chaos
  can kill a replica mid-run by stopping its server.

* :func:`run_scenario` — the matrix cell: a declarative spec (see
  ``scenarios/*.json``) names workload x faults x fleet events x SLO
  objectives; the run is scored by the real
  :class:`~tpu_dist_nn.obs.slo.SLOTracker` over a
  :class:`~tpu_dist_nn.obs.timeseries.TimeSeriesRing`, and the verdict
  is machine-readable (bench.py embeds it; tools/bench_gate.py gates
  ``scenario_pass_ratio``).

Stdlib + numpy + grpc only — importable (and runnable) without jax;
the tier-1 quick smoke drives a scenario end-to-end in seconds.
"""

from __future__ import annotations

import dataclasses
import io
import json
import math
import os
import random
import threading
import time
import zipfile
from concurrent import futures

import numpy as np

SCHEMA_VERSION = 1

#: Handler root-span names -> WorkloadTrace method names. Router root
#: spans share these names; the capture attrs (``slo_class`` is the
#: marker — the handlers always set it) tell the two apart.
_ROOT_SPANS = {
    "rpc.Process": "Process",
    "rpc.Generate": "Generate",
    "rpc.GenerateStream": "GenerateStream",
}

_CLASSES = ("critical", "standard", "best_effort")


# --------------------------------------------------------------- schema


@dataclasses.dataclass
class Request:
    """One request in a workload: WHEN it arrives (seconds from the
    trace start), WHAT it is, and the attrs that must survive replay
    (class, session affinity, budget, streaming)."""

    arrival_s: float
    method: str = "Process"
    rows: int = 1
    dim: int | None = None
    prompt_len: int | None = None
    prompt_tokens: list[int] | None = None
    max_new_tokens: int | None = None
    slo_class: str = "standard"
    session: str | None = None
    budget_ms: int | None = None
    stream: bool = False

    def to_dict(self) -> dict:
        d = {"arrival_s": round(float(self.arrival_s), 6),
             "method": self.method, "slo_class": self.slo_class}
        for k in ("rows", "dim", "prompt_len", "prompt_tokens",
                  "max_new_tokens", "session", "budget_ms"):
            v = getattr(self, k)
            if v is not None and v != (1 if k == "rows" else None):
                d[k] = v
        if self.stream:
            d["stream"] = True
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "Request":
        return cls(
            arrival_s=float(d["arrival_s"]),
            method=str(d.get("method", "Process")),
            rows=int(d.get("rows", 1)),
            dim=d.get("dim"),
            prompt_len=d.get("prompt_len"),
            prompt_tokens=d.get("prompt_tokens"),
            max_new_tokens=d.get("max_new_tokens"),
            slo_class=str(d.get("slo_class", "standard")),
            session=d.get("session"),
            budget_ms=d.get("budget_ms"),
            stream=bool(d.get("stream", False)),
        )


@dataclasses.dataclass
class WorkloadTrace:
    """An ordered request stream plus the provenance needed to rebuild
    it (``seed`` for synthetic content, ``source`` for where it came
    from). The list is kept sorted by arrival offset."""

    name: str
    seed: int = 0
    source: str = "synthetic"
    requests: list[Request] = dataclasses.field(default_factory=list)

    def __post_init__(self):
        self.requests.sort(key=lambda r: r.arrival_s)

    @property
    def duration_s(self) -> float:
        return self.requests[-1].arrival_s if self.requests else 0.0

    def to_dict(self) -> dict:
        return {"schema_version": SCHEMA_VERSION, "name": self.name,
                "seed": self.seed, "source": self.source,
                "requests": [r.to_dict() for r in self.requests]}

    def to_json(self) -> str:
        return json.dumps(self.to_dict())

    @classmethod
    def from_dict(cls, d: dict) -> "WorkloadTrace":
        ver = int(d.get("schema_version", SCHEMA_VERSION))
        if ver > SCHEMA_VERSION:
            raise ValueError(
                f"WorkloadTrace schema_version {ver} is newer than this "
                f"reader ({SCHEMA_VERSION})"
            )
        return cls(name=str(d.get("name", "trace")),
                   seed=int(d.get("seed", 0)),
                   source=str(d.get("source", "unknown")),
                   requests=[Request.from_dict(r)
                             for r in d.get("requests", ())])

    @classmethod
    def from_json(cls, text: str) -> "WorkloadTrace":
        return cls.from_dict(json.loads(text))

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.to_json())

    @classmethod
    def load(cls, path: str) -> "WorkloadTrace":
        with open(path) as f:
            return cls.from_json(f.read())

    # ------------------------------------------------- canonical shape

    def mix(self) -> dict:
        """The request mix as a canonical comparable dict: two traces
        with equal ``mix()`` carry the same requests (methods, classes,
        sessions, shapes, stream flags) — arrival TIMING is deliberately
        excluded (that is :meth:`inter_arrival_deciles`' job)."""
        by_method: dict[str, int] = {}
        by_class: dict[str, int] = {}
        sessions: dict[str, int] = {}
        shapes: dict[str, int] = {}
        streams = 0
        for r in self.requests:
            by_method[r.method] = by_method.get(r.method, 0) + 1
            by_class[r.slo_class] = by_class.get(r.slo_class, 0) + 1
            if r.session:
                sessions[r.session] = sessions.get(r.session, 0) + 1
            shape = f"{r.method}:{r.rows}x{r.prompt_len or r.dim or '?'}"
            shapes[shape] = shapes.get(shape, 0) + 1
            if r.stream:
                streams += 1
        return {
            "requests": len(self.requests),
            "by_method": dict(sorted(by_method.items())),
            "by_class": dict(sorted(by_class.items())),
            "sessions": dict(sorted(sessions.items())),
            "shapes": dict(sorted(shapes.items())),
            "streams": streams,
        }

    def inter_arrival_deciles(self) -> list[float]:
        """Deciles (d10..d90) of the inter-arrival gaps, seconds — the
        arrival-process fingerprint replay fidelity is judged against."""
        arr = [r.arrival_s for r in self.requests]
        gaps = [b - a for a, b in zip(arr, arr[1:])]
        return deciles(gaps)


def deciles(values) -> list[float]:
    """d10..d90 by linear interpolation ([] for < 2 values)."""
    vs = sorted(values)
    if len(vs) < 2:
        return []
    out = []
    for q in range(1, 10):
        pos = (len(vs) - 1) * q / 10.0
        lo = int(math.floor(pos))
        hi = min(lo + 1, len(vs) - 1)
        out.append(vs[lo] + (vs[hi] - vs[lo]) * (pos - lo))
    return out


def decile_errors(reference: list[float], achieved: list[float],
                  floor_s: float = 0.005) -> list[float]:
    """Per-decile relative error of ``achieved`` against ``reference``
    inter-arrival deciles. ``floor_s`` keeps a near-zero reference
    decile (back-to-back arrivals) from turning scheduler-tick jitter
    into an unbounded relative error."""
    return [abs(a - r) / max(r, floor_s)
            for r, a in zip(reference, achieved)]


# --------------------------------------------------- bundle extraction


def trace_from_chrome(doc: dict, *, name: str = "capture",
                      source: str = "chrome") -> WorkloadTrace:
    """Extract a WorkloadTrace from a Chrome trace-event document
    (``trace.json`` / ``trace_fleet.json``).

    Extraction rules (docs/OBSERVABILITY.md "Capture & replay"):

    * only complete (``ph == "X"``) events named ``rpc.Process`` /
      ``rpc.Generate`` / ``rpc.GenerateStream`` are considered;
    * only events whose ``args`` carry the capture attrs count — the
      handlers always set ``slo_class``, router roots never do, so
      router spans (same names) are skipped rather than double-counted;
    * events sharing a ``trace_id`` are ONE logical request (router
      failover lands the same request on a second replica) — the
      earliest handler span wins;
    * arrival offsets are the span ``ts`` deltas from the earliest kept
      span (epoch-anchored microseconds in the export).
    """
    best: dict[str, dict] = {}
    anon = 0
    for e in doc.get("traceEvents", ()):
        if e.get("ph") != "X":
            continue
        method = _ROOT_SPANS.get(e.get("name"))
        if method is None:
            continue
        args = e.get("args") or {}
        if "slo_class" not in args:
            continue  # router root or pre-ISSUE-18 capture
        key = args.get("trace_id")
        if not key:
            anon += 1
            key = f"_anon{anon}"
        cur = best.get(key)
        if cur is None or e["ts"] < cur["ts"]:
            best[key] = e
    picked = sorted(best.values(), key=lambda e: e["ts"])
    reqs: list[Request] = []
    t0 = picked[0]["ts"] if picked else 0.0
    for e in picked:
        args = e.get("args") or {}
        reqs.append(Request(
            arrival_s=(e["ts"] - t0) / 1e6,
            method=_ROOT_SPANS[e["name"]],
            rows=int(args.get("rows", 1)),
            dim=args.get("dim"),
            prompt_len=args.get("prompt_len"),
            max_new_tokens=args.get("max_new_tokens"),
            slo_class=str(args.get("slo_class", "standard")),
            session=args.get("session"),
            budget_ms=args.get("budget_ms"),
            stream=bool(args.get("stream", False)),
        ))
    return WorkloadTrace(name=name, source=source, requests=reqs)


def trace_from_bundle(bundle, *, name: str | None = None) -> WorkloadTrace:
    """Extract a WorkloadTrace from an incident bundle (zip bytes, a
    path, or a file-like). Prefers the stitched ``trace_fleet.json``
    (fleet captures: every replica's handler spans in one document)
    over the local ``trace.json``."""
    if isinstance(bundle, (bytes, bytearray)):
        fh = io.BytesIO(bundle)
        label = name or "bundle"
    elif isinstance(bundle, (str, os.PathLike)):
        fh = open(bundle, "rb")
        label = name or os.path.basename(os.fspath(bundle))
    else:
        fh = bundle
        label = name or "bundle"
    try:
        with zipfile.ZipFile(fh) as zf:
            names = set(zf.namelist())
            pick = ("trace_fleet.json" if "trace_fleet.json" in names
                    else "trace.json")
            if pick not in names:
                raise ValueError(
                    f"bundle has no trace.json (sections: {sorted(names)})"
                )
            doc = json.loads(zf.read(pick))
            iid = None
            if "manifest.json" in names:
                iid = json.loads(zf.read("manifest.json")).get("incident_id")
    finally:
        if isinstance(bundle, (str, os.PathLike)):
            fh.close()
    return trace_from_chrome(doc, name=label,
                             source=f"bundle:{iid or 'unknown'}")


# ------------------------------------------------- synthetic generators

GENERATORS: dict[str, "callable"] = {}


def _generator(name):
    def reg(fn):
        GENERATORS[name] = fn
        return fn
    return reg


def make_workload(generator: str, seed: int = 0, **kwargs) -> WorkloadTrace:
    """Build a named synthetic workload. Same (generator, seed, kwargs)
    -> bit-identical WorkloadTrace, always."""
    try:
        fn = GENERATORS[generator]
    except KeyError:
        raise ValueError(
            f"unknown workload generator {generator!r}; have "
            f"{sorted(GENERATORS)}"
        ) from None
    return fn(seed=seed, **kwargs)


def _shaped_arrivals(rng: random.Random, n: int, duration: float,
                     weight) -> list[float]:
    """``n`` arrival offsets over ``[0, duration]`` following the
    relative rate ``weight(t in [0,1])``, by inverse-CDF over a fine
    grid — deterministic under the rng, no rejection loops."""
    grid = 512
    w = [max(weight((i + 0.5) / grid), 1e-9) for i in range(grid)]
    cum = []
    tot = 0.0
    for x in w:
        tot += x
        cum.append(tot)
    cum = [c / tot for c in cum]
    us = sorted(rng.random() for _ in range(n))
    out = []
    j = 0
    for u in us:
        while j < grid - 1 and cum[j] < u:
            j += 1
        lo = cum[j - 1] if j else 0.0
        hi = cum[j]
        frac = (u - lo) / (hi - lo) if hi > lo else 0.0
        out.append((j + frac) / grid * duration)
    return out


def _pick_class(rng: random.Random, classes: dict | None) -> str:
    if not classes:
        return "standard"
    names = sorted(classes)
    weights = [float(classes[c]) for c in names]
    return rng.choices(names, weights=weights, k=1)[0]


def _pick_session(rng: random.Random, sessions: int,
                  p_none: float = 0.25) -> str | None:
    if sessions <= 0 or rng.random() < p_none:
        return None
    return f"sess-{rng.randrange(sessions)}"


@_generator("diurnal")
def gen_diurnal(seed: int = 0, *, requests: int = 100,
                duration: float = 8.0, peak_ratio: float = 4.0,
                cycles: float = 1.0, dim: int = 8, sessions: int = 6,
                classes: dict | None = None,
                budget_ms: int | None = None) -> WorkloadTrace:
    """Sinusoidal day/night rate: trough 1x, peak ``peak_ratio``x,
    ``cycles`` full cycles over the (compressed) duration."""
    rng = random.Random(seed)

    def weight(t):
        return 1.0 + (peak_ratio - 1.0) * 0.5 * (
            1.0 - math.cos(2 * math.pi * cycles * t)
        )

    reqs = [Request(arrival_s=t, method="Process", rows=1, dim=dim,
                    slo_class=_pick_class(rng, classes),
                    session=_pick_session(rng, sessions),
                    budget_ms=budget_ms)
            for t in _shaped_arrivals(rng, requests, duration, weight)]
    return WorkloadTrace(name=f"diurnal-{seed}", seed=seed,
                         source="generator:diurnal", requests=reqs)


@_generator("flash_crowd")
def gen_flash_crowd(seed: int = 0, *, requests: int = 120,
                    duration: float = 8.0, spike_at: float = 0.5,
                    spike_width: float = 0.15, spike_ratio: float = 8.0,
                    dim: int = 8, sessions: int = 6,
                    classes: dict | None = None,
                    budget_ms: int | None = None) -> WorkloadTrace:
    """Steady background rate with one ``spike_ratio``x flash crowd
    centred at ``spike_at`` (fraction of the duration)."""
    rng = random.Random(seed)
    lo, hi = spike_at - spike_width / 2, spike_at + spike_width / 2

    def weight(t):
        return spike_ratio if lo <= t <= hi else 1.0

    reqs = [Request(arrival_s=t, method="Process", rows=1, dim=dim,
                    slo_class=_pick_class(rng, classes),
                    session=_pick_session(rng, sessions),
                    budget_ms=budget_ms)
            for t in _shaped_arrivals(rng, requests, duration, weight)]
    return WorkloadTrace(name=f"flash_crowd-{seed}", seed=seed,
                         source="generator:flash_crowd", requests=reqs)


@_generator("heavy_tail")
def gen_heavy_tail(seed: int = 0, *, requests: int = 60,
                   duration: float = 8.0, alpha: float = 1.3,
                   prompt_len: int = 8, max_new_tokens: int = 8,
                   vocab_size: int = 64, sessions: int = 4,
                   stream_fraction: float = 0.0,
                   classes: dict | None = None) -> WorkloadTrace:
    """Poisson arrivals, Pareto(``alpha``) prompt lengths clamped to
    ``[1, prompt_len]`` — the Orca regime where a few giant prompts
    convoy everyone else. Replay pads each prompt to the endpoint's
    static width, so the tail survives in token CONTENT (sampled-length
    prefix) and in the trace itself."""
    rng = random.Random(seed)
    reqs = []
    for t in _shaped_arrivals(rng, requests, duration, lambda t: 1.0):
        raw = rng.paretovariate(alpha)
        plen = max(1, min(prompt_len, int(raw)))
        tokens = [rng.randrange(vocab_size) for _ in range(plen)]
        streaming = rng.random() < stream_fraction
        reqs.append(Request(
            arrival_s=t,
            method="GenerateStream" if streaming else "Generate",
            rows=1, prompt_len=plen, prompt_tokens=tokens,
            max_new_tokens=max_new_tokens,
            slo_class=_pick_class(rng, classes),
            session=_pick_session(rng, sessions),
            stream=streaming,
        ))
    return WorkloadTrace(name=f"heavy_tail-{seed}", seed=seed,
                         source="generator:heavy_tail", requests=reqs)


@_generator("shared_prefix_flood")
def gen_shared_prefix_flood(seed: int = 0, *, requests: int = 60,
                            duration: float = 4.0,
                            prompt_len: int = 8,
                            prefix_fraction: float = 0.75,
                            max_new_tokens: int = 8,
                            vocab_size: int = 64,
                            sessions: int = 2,
                            classes: dict | None = None) -> WorkloadTrace:
    """Adversarial prefix-cache flood: every prompt shares one long
    common prefix (``prefix_fraction`` of the width) with unique
    tails, arriving in a front-loaded burst from few sessions."""
    rng = random.Random(seed)
    npre = max(1, int(prompt_len * prefix_fraction))
    prefix = [rng.randrange(vocab_size) for _ in range(npre)]

    def weight(t):  # front-loaded: 4x rate in the first quarter
        return 4.0 if t < 0.25 else 1.0

    reqs = []
    for t in _shaped_arrivals(rng, requests, duration, weight):
        tail = [rng.randrange(vocab_size)
                for _ in range(prompt_len - npre)]
        reqs.append(Request(
            arrival_s=t, method="Generate", rows=1,
            prompt_len=prompt_len, prompt_tokens=prefix + tail,
            max_new_tokens=max_new_tokens,
            slo_class=_pick_class(rng, classes),
            session=_pick_session(rng, sessions, p_none=0.0),
        ))
    return WorkloadTrace(name=f"shared_prefix_flood-{seed}", seed=seed,
                         source="generator:shared_prefix_flood",
                         requests=reqs)


@_generator("mixed_class")
def gen_mixed_class(seed: int = 0, *, requests: int = 90,
                    duration: float = 6.0, dim: int = 8,
                    sessions: int = 6,
                    classes: dict | None = None,
                    budget_ms: int | None = None) -> WorkloadTrace:
    """Poisson arrivals with an explicit SLO-class mix (default
    20/50/30 critical/standard/best_effort) — the degradation-ladder
    workload."""
    rng = random.Random(seed)
    classes = classes or {"critical": 0.2, "standard": 0.5,
                          "best_effort": 0.3}
    reqs = [Request(arrival_s=t, method="Process", rows=1, dim=dim,
                    slo_class=_pick_class(rng, classes),
                    session=_pick_session(rng, sessions),
                    budget_ms=budget_ms)
            for t in _shaped_arrivals(rng, requests, duration,
                                      lambda t: 1.0)]
    return WorkloadTrace(name=f"mixed_class-{seed}", seed=seed,
                         source="generator:mixed_class", requests=reqs)


# --------------------------------------------------------- replay driver


def _payload_rng(trace: WorkloadTrace, i: int) -> random.Random:
    # Content seed: trace seed x request index — replaying the same
    # trace sends bit-identical payloads, independent of thread timing.
    return random.Random((int(trace.seed) << 20) ^ (i * 2654435761 % (1 << 31)))


def _prompt_ids(req: Request, rng: random.Random, prompt_len: int,
                vocab_size: int) -> np.ndarray:
    """The prompt matrix for a Generate/GenerateStream request: the
    captured tokens when present (clamped into vocab), else seeded
    synthetics of the recorded length, padded to the endpoint's static
    ``prompt_len``."""
    want = int(req.prompt_len or prompt_len)
    toks = list(req.prompt_tokens or ())
    if not toks:
        toks = [rng.randrange(vocab_size) for _ in range(want)]
    toks = [int(t) % vocab_size for t in toks][:prompt_len]
    if len(toks) < prompt_len:
        toks = toks + [0] * (prompt_len - len(toks))
    rows = max(1, int(req.rows)) if req.method == "Generate" else 1
    return np.asarray([toks] * rows, dtype=np.int64)


def replay(trace: WorkloadTrace, target: str, *, speed: float = 1.0,
           dim: int = 8, prompt_len: int = 8, vocab_size: int = 64,
           timeout: float = 30.0, gap_timeout: float | None = 10.0,
           max_workers: int = 32, client=None,
           on_start=None) -> dict:
    """Fire ``trace`` at ``target`` and return a replay report.

    ``speed`` compresses (>1) or dilates (<1) the arrival process; the
    request MIX is never altered. Dispatch is absolute-time paced (each
    request fires at ``t0 + arrival_s/speed``, no drift accumulation)
    from one scheduler thread into a worker pool; sessions, classes,
    budgets, and streaming all ride the real client headers.

    The report carries outcome counts, latency/TTFT percentiles, and
    ``arrival`` — the achieved per-decile inter-arrival error against
    the (speed-scaled) trace, the fidelity figure the round-trip
    acceptance asserts on.

    ``client`` overrides the auto-built one (auto: ``retry=None,
    breaker=None`` — the target's OWN resilience stack is the thing
    under test; client-side retries would mask it). ``on_start`` is
    called with the monotonic start time just before the first
    dispatch (the chaos timeline anchors on it).
    """
    from tpu_dist_nn.serving.server import GrpcClient

    if speed <= 0:
        raise ValueError(f"speed must be > 0, got {speed}")
    own_client = client is None
    if own_client:
        # wait_for_ready: the ~100ms first-connect handshake must land
        # BEFORE t0, not inside request 0's arrival offset — it would
        # shift the fidelity anchor by a whole decile.
        client = GrpcClient(target, timeout=timeout, retry=None,
                            breaker=None, wait_for_ready=True,
                            ready_timeout=10.0)
    results: list[dict] = []
    lock = threading.Lock()

    def fire(i: int, req: Request, planned: float, t0: float):
        rng = _payload_rng(trace, i)
        rec = {"i": i, "method": req.method, "slo_class": req.slo_class,
               "session": req.session, "ok": False, "code": None,
               "sent_s": time.monotonic() - t0, "planned_s": planned}
        t_req = time.monotonic()
        try:
            if req.method == "Process":
                d = int(req.dim or dim)
                x = np.asarray(
                    [[rng.random() for _ in range(d)]
                     for _ in range(max(1, int(req.rows)))]
                )
                client.process(x, session_key=req.session,
                               slo_class=req.slo_class)
            elif req.method == "Generate":
                ids = _prompt_ids(req, rng, prompt_len, vocab_size)
                client.generate(ids, session_key=req.session,
                                slo_class=req.slo_class)
            elif req.method == "GenerateStream":
                ids = _prompt_ids(req, rng, prompt_len, vocab_size)
                reply = client.generate_stream(
                    ids, session_key=req.session, slo_class=req.slo_class,
                    timeout=timeout, gap_timeout=gap_timeout,
                )
                ntok = 0
                for tok in reply:
                    if ntok == 0:
                        rec["ttft_s"] = time.monotonic() - t_req
                    ntok += 1
                rec["tokens"] = ntok
            else:
                raise ValueError(f"unknown method {req.method!r}")
            rec["ok"] = True
            rec["code"] = "OK"
        except Exception as e:  # noqa: BLE001 — outcome, not crash
            try:
                rec["code"] = e.code().name  # grpc.RpcError
            except Exception:  # noqa: BLE001
                rec["code"] = type(e).__name__
        rec["latency_s"] = time.monotonic() - t_req
        with lock:
            results.append(rec)

    pool = futures.ThreadPoolExecutor(max_workers=max_workers)
    t0 = time.monotonic()
    if on_start is not None:
        on_start(t0)
    pending = []
    try:
        for i, req in enumerate(trace.requests):
            planned = req.arrival_s / speed
            delay = t0 + planned - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            pending.append(pool.submit(fire, i, req, planned, t0))
        for f in pending:
            f.result()
    finally:
        pool.shutdown(wait=True)
        if own_client:
            client.close()
    wall = time.monotonic() - t0
    results.sort(key=lambda r: r["i"])
    return _replay_report(trace, target, speed, wall, results)


def _pcts(vals: list[float]) -> dict:
    if not vals:
        return {}
    vs = sorted(vals)

    def p(q):
        pos = (len(vs) - 1) * q
        lo = int(pos)
        hi = min(lo + 1, len(vs) - 1)
        return vs[lo] + (vs[hi] - vs[lo]) * (pos - lo)

    return {"p50_ms": round(p(0.50) * 1e3, 3),
            "p95_ms": round(p(0.95) * 1e3, 3),
            "p99_ms": round(p(0.99) * 1e3, 3)}


def _replay_report(trace, target, speed, wall, results) -> dict:
    errors: dict[str, int] = {}
    for r in results:
        if not r["ok"]:
            errors[r["code"] or "?"] = errors.get(r["code"] or "?", 0) + 1
    ref = [d / speed for d in trace.inter_arrival_deciles()]
    sent = deciles([b["sent_s"] - a["sent_s"]
                    for a, b in zip(results, results[1:])])
    errs = decile_errors(ref, sent) if ref and sent else []
    report = {
        "trace": trace.name,
        "target": target,
        "speed": speed,
        "wall_s": round(wall, 3),
        "requests": len(results),
        "ok": sum(1 for r in results if r["ok"]),
        "errors": dict(sorted(errors.items())),
        "latency": _pcts([r["latency_s"] for r in results if r["ok"]]),
        "ttft": _pcts([r["ttft_s"] for r in results if "ttft_s" in r]),
        "tokens_streamed": sum(r.get("tokens", 0) for r in results),
        "arrival": {
            "trace_deciles_ms": [round(d * 1e3, 3) for d in ref],
            "sent_deciles_ms": [round(d * 1e3, 3) for d in sent],
            "per_decile_error": [round(e, 4) for e in errs],
            "max_decile_error": round(max(errs), 4) if errs else None,
        },
    }
    return report


# ------------------------------------------------------- loopback fleet


def _fault_from_spec(d: dict):
    """{"kind": "unavailable"|...,"p"/"every"/"at","seed","seconds",
    "hold"} -> (FaultPlan, hook) where hook is "interceptor"|"launch"
    |"nan_launch"|"tamper". An optional "replica" key scopes the fault
    to ONE replica index (corruption cells model a single bad machine,
    not a fleet-wide defect) — honoured by ``LoopbackFleet``."""
    from tpu_dist_nn.testing import faults as F

    kind = d.get("kind", "unavailable")
    hook = d.get("hook", "interceptor")
    if kind == "delay":
        fault = F.delay(float(d.get("seconds", 0.05)))
    elif kind == "drop":
        fault = F.drop(float(d.get("hold", 0.2)))
    elif kind in ("nan_launch", "reply_tamper"):
        # Silent-corruption kinds: the fault is a schedulable marker —
        # nothing raises; the hook poisons data instead
        # (docs/ROBUSTNESS.md "Silent corruption & quarantine").
        fault = F.tamper(kind)
        hook = "nan_launch" if kind == "nan_launch" else "tamper"
    else:
        factory = {"unavailable": F.unavailable,
                   "deadline_exceeded": F.deadline_exceeded,
                   "internal": F.internal,
                   "resource_exhausted": F.resource_exhausted}.get(kind)
        if factory is None:
            raise ValueError(f"unknown fault kind {kind!r}")
        fault = factory()
    at = {int(k): fault for k in d.get("at", ())} or None
    plan = F.FaultPlan(at=at, every=d.get("every"),
                      fault=fault, p=d.get("p"),
                      seed=int(d.get("seed", 0)))
    return plan, hook


class _FakeModel:
    def __init__(self, dim):
        self.input_dim = dim


class _FakeEngine:
    """Numpy-only paced engine: ``per_row_ms`` per Process row. The
    first-class fault hooks exist exactly like the real Engine's."""

    def __init__(self, dim: int, per_row_ms: float):
        self.model = _FakeModel(dim)
        self.per_row_s = per_row_ms / 1e3
        self.launch_hook = None
        self.fetch_hook = None

    def infer(self, x):
        # Materialize to an OWNED buffer first: the handler passes a
        # lazy WireMatrix, and the corruption hooks mutate their input
        # in place — poisoning a temporary would be a silent no-op.
        x = np.array(x, dtype=np.float64)
        if self.launch_hook is not None:
            self.launch_hook(x)
        if self.per_row_s:
            time.sleep(self.per_row_s * len(x))
        out = x * 2.0
        # Same numeric-guard contract as the real Engine's fetch
        # boundary: a poisoned launch (faults.nan_launch) must fail
        # DATA_LOSS at the wire, never ship NaN — the scenario cells
        # exercise the router's guard -> strike -> quarantine ladder
        # through exactly the production detection path.
        from tpu_dist_nn.serving import integrity

        bad = integrity.GUARD.bad_rows(out)
        if bad is not None and bad.any():
            from tpu_dist_nn.utils.errors import IntegrityError

            raise IntegrityError(
                f"numeric guard: {int(bad.sum())}/{len(bad)} rows of "
                f"the launch are non-finite or out of magnitude bounds"
            )
        return out


class LoopbackFleet:
    """N in-process fake replicas (Process + Generate + GenerateStream)
    behind the real router/pool stack — the scenario engine's
    self-hosted target.

    In-process replicas share the parent's TRACER, so handler root
    spans (with the ISSUE-18 capture attrs) land in the same buffer the
    incident plane exports — a capture -> extract -> replay round trip
    needs exactly one process. Chaos kills a replica by stopping its
    gRPC server (in-flight RPCs surface as UNAVAILABLE and the router
    fails over, same as a process crash at the wire)."""

    def __init__(self, replicas: int = 2, *, dim: int = 8,
                 prompt_len: int = 8, max_new_tokens: int = 8,
                 vocab_size: int = 64, per_row_ms: float = 1.0,
                 per_token_ms: float = 1.0, prefill_ms: float = 2.0,
                 faults=(), hedge: bool = False, seed: int = 0,
                 forward_timeout: float | None = 30.0,
                 canary: dict | None = None,
                 spotcheck: dict | None = None):
        self.n = int(replicas)
        self.dim = int(dim)
        self.prompt_len = int(prompt_len)
        self.max_new_tokens = int(max_new_tokens)
        self.vocab_size = int(vocab_size)
        self.per_row_ms = float(per_row_ms)
        self.per_token_ms = float(per_token_ms)
        self.prefill_ms = float(prefill_ms)
        self.fault_specs = list(faults or ())
        self.hedge = bool(hedge)
        self.seed = int(seed)
        self.forward_timeout = forward_timeout
        self.canary_spec = dict(canary) if canary else None
        self.spotcheck_spec = dict(spotcheck) if spotcheck else None
        self.canary = None
        self.spotcheck = None
        self.servers: list = []
        self.engines: list[_FakeEngine] = []
        self.targets: list[str] = []
        self.fault_plans: list = []
        self.pool = None
        self.router_server = None
        self.target: str | None = None

    # ------------------------------------------------- replica innards

    def _gen_tokens(self, ids_row) -> list[int]:
        base = int(np.asarray(ids_row).sum()) % self.vocab_size
        return [(base + 7 * k) % self.vocab_size
                for k in range(1, self.max_new_tokens + 1)]

    def _make_replica(self, index: int):
        from tpu_dist_nn.serving.server import (
            _bind_or_close,
            _make_generate_handler,
            _make_generate_stream_handler,
            _make_handler,
            _new_grpc_server,
        )
        from tpu_dist_nn.serving.stream import TokenStream

        eng = _FakeEngine(self.dim, self.per_row_ms)
        prefill_s = self.prefill_ms / 1e3
        per_tok_s = self.per_token_ms / 1e3

        def run_submit(ids, budget, ctx=None, slo_class="standard"):
            if eng.launch_hook is not None:
                eng.launch_hook(ids)
            time.sleep(prefill_s + per_tok_s * self.max_new_tokens)
            out = np.asarray([self._gen_tokens(row) for row in ids],
                             dtype=np.int64)
            return np.concatenate(
                [np.asarray(ids, np.int64), out], axis=1
            )

        def run_submit_stream(ids, budget, ctx=None,
                              slo_class="standard", resume=None):
            ts = TokenStream()
            full = list(resume or ()) + self._gen_tokens(ids[0])[
                len(resume or ()):]

            def produce():
                time.sleep(prefill_s)
                nres = len(resume or ())
                if nres:
                    ts.seed(nres)
                known = list(full[:nres])
                for t in full[nres:]:
                    time.sleep(per_tok_s)
                    known.append(t)
                    if not ts.publish(list(known)):
                        return
                ts.finish("max_tokens")

            threading.Thread(target=produce, daemon=True).start()
            return ts

        interceptors = []
        for spec in self.fault_specs:
            if "replica" in spec and int(spec["replica"]) != index:
                continue
            plan, hook = _fault_from_spec(spec)
            self.fault_plans.append(plan)
            if hook == "launch":
                eng.launch_hook = plan.fire
            elif hook == "nan_launch":
                from tpu_dist_nn.testing.faults import nan_launch
                eng.launch_hook = nan_launch(
                    rows=tuple(spec.get("rows", (0,))), plan=plan
                )
            elif hook == "tamper":
                from tpu_dist_nn.testing.faults import (
                    make_tamper_interceptor,
                )
                interceptors.append(make_tamper_interceptor(plan))
            else:
                from tpu_dist_nn.testing.faults import make_interceptor
                interceptors.append(make_interceptor(plan))
        srv = _new_grpc_server(16, tuple(interceptors))
        srv.add_generic_rpc_handlers((
            _make_handler(eng, None),
            _make_generate_handler(run_submit, self.prompt_len,
                                   self.vocab_size,
                                   max_new_tokens=self.max_new_tokens),
            _make_generate_stream_handler(
                run_submit_stream, self.prompt_len, self.vocab_size,
                max_new_tokens=self.max_new_tokens),
        ))
        port = _bind_or_close(srv, "127.0.0.1", 0, None)
        srv.start()
        return srv, eng, f"127.0.0.1:{port}"

    # ------------------------------------------------------- lifecycle

    def start(self) -> "LoopbackFleet":
        from tpu_dist_nn.serving.pool import ReplicaPool
        from tpu_dist_nn.serving.router import HedgePolicy, serve_router

        for i in range(self.n):
            srv, eng, tgt = self._make_replica(i)
            self.servers.append(srv)
            self.engines.append(eng)
            self.targets.append(tgt)
        self.pool = ReplicaPool(self.targets, seed=self.seed)
        hedge = HedgePolicy() if self.hedge else None
        if self.canary_spec is not None or self.spotcheck_spec is not None:
            from tpu_dist_nn.serving.integrity import CanaryProber

            c = self.canary_spec or {}
            self.canary = CanaryProber(
                dim=self.dim, prompt_len=self.prompt_len,
                vocab_size=self.vocab_size,
                interval=float(c.get("interval", 1.0)),
                timeout=float(c.get("timeout", 5.0)),
                seed=int(c.get("seed", 0x7DD)),
            )
        if self.spotcheck_spec is not None:
            from tpu_dist_nn.serving.integrity import SpotChecker

            s = self.spotcheck_spec
            self.spotcheck = SpotChecker(
                self.pool, rate=float(s.get("rate", 0.25)),
                seed=int(s.get("seed", self.seed)),
                timeout=float(s.get("timeout", 5.0)),
                canary=self.canary,
                on_verdict=lambda tgt, reason, ev: self.pool.quarantine(
                    tgt, reason=reason, evidence=ev
                ),
            )
        self.router_server, port = serve_router(
            self.pool, 0, host="127.0.0.1",
            forward_timeout=self.forward_timeout, hedge=hedge,
            canary=self.canary, spotcheck=self.spotcheck,
        )
        self.target = f"127.0.0.1:{port}"
        return self

    def kill_replica(self, index: int) -> None:
        """Chaos: hard-stop replica ``index`` (in-flight RPCs die
        UNAVAILABLE at the wire, exactly like a crashed process)."""
        self.servers[index].stop(None)

    def drain_replica(self, index: int) -> None:
        self.pool.drain(self.targets[index], signal_process=False)

    def undrain_replica(self, index: int) -> None:
        self.pool.undrain(self.targets[index])

    def stop(self) -> None:
        if self.router_server is not None:
            self.router_server.stop(None)
        if self.pool is not None:
            self.pool.close(grace=0.5)
        for srv in self.servers:
            try:
                srv.stop(None)
            except Exception:  # noqa: BLE001 — already killed by chaos
                pass

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


# ------------------------------------------------------ scenario runner


def _objective_from_spec(d: dict):
    from tpu_dist_nn.obs.slo import (
        availability_objective,
        latency_objective,
    )

    kind = d.get("kind", "latency")
    if kind == "latency":
        return latency_objective(
            d["name"], d.get("family", "tdn_router_request_seconds"),
            float(d["threshold_ms"]) / 1e3, q=float(d.get("q", 0.99)),
            match=d.get("match"),
        )
    if kind == "availability":
        return availability_objective(
            d["name"], float(d["target"]),
            d.get("total_family", "tdn_router_requests_total"),
            bad_family=d.get("bad_family"),
            match=d.get("match"),
            bad_match=d.get("bad_match"),
            bad_exclude=d.get("bad_exclude",
                              None if d.get("bad_family")
                              or d.get("bad_match")
                              else {"outcome": "ok"}),
        )
    raise ValueError(f"unknown objective kind {kind!r}")


def load_scenario(path: str) -> dict:
    """Read + validate one scenario spec (see docs/ROBUSTNESS.md
    "Chaos-load matrix" for the format)."""
    with open(path) as f:
        spec = json.load(f)
    for key in ("name", "workload", "slo"):
        if key not in spec:
            raise ValueError(f"scenario {path}: missing {key!r}")
    wl = spec["workload"]
    if "generator" not in wl and "capture" not in wl and "trace" not in wl:
        raise ValueError(
            f"scenario {path}: workload needs generator|capture|trace"
        )
    if not spec["slo"].get("objectives"):
        raise ValueError(f"scenario {path}: slo.objectives is empty")
    spec.setdefault("_path", os.path.abspath(path))
    return spec


def _scale_workload_args(args: dict, scale: float) -> dict:
    """Quick-mode shrink: fewer requests over a shorter window, same
    shape (rates preserved — both axes scale together)."""
    out = dict(args)
    if "requests" in out:
        out["requests"] = max(8, int(out["requests"] * scale))
    if "duration" in out:
        out["duration"] = max(1.0, float(out["duration"]) * scale)
    return out


def _build_workload(spec: dict, seed: int, quick_scale: float | None):
    wl = spec["workload"]
    if "generator" in wl:
        args = dict(wl.get("args", {}))
        if quick_scale:
            args = _scale_workload_args(args, quick_scale)
        return make_workload(wl["generator"], seed=seed, **args)
    if "trace" in wl:
        path = wl["trace"]
        if not os.path.isabs(path) and "_path" in spec:
            path = os.path.join(os.path.dirname(spec["_path"]), path)
        return WorkloadTrace.load(path)
    # "capture": run a seed workload first, capture a bundle, extract.
    # Handled by run_scenario (needs the live fleet).
    return None


def run_scenario(spec: dict, *, seed: int | None = None,
                 speed: float | None = None,
                 quick_scale: float | None = None) -> dict:
    """Run one scenario cell end-to-end and return its verdict.

    Builds the workload (generator / checked-in trace / capture-then-
    replay), stands up the loopback fleet with the spec's fault plans,
    arms the chaos timeline, replays, and scores the run with the REAL
    SLOTracker over a TimeSeriesRing collected around the replay
    window. The verdict is machine-readable:

    ``{"scenario", "seed", "passed", "objectives": [{name, objective,
    burn_rate, measured, passed}], "replay": {...}, "fidelity": {...},
    "slo": <full tracker doc>}``

    An objective passes when its fast-window burn rate stays <= 1.0
    (the bad fraction fit the declared budget over the run window). A
    capture-derived scenario additionally requires the round-trip
    fidelity bar: exact mix match + per-decile inter-arrival error
    within ``fidelity_tolerance`` (default 0.10) at speed 1.
    """
    from tpu_dist_nn.obs.slo import SLOTracker
    from tpu_dist_nn.obs.timeseries import TimeSeriesRing

    seed = int(spec.get("seed", 0) if seed is None else seed)
    speed = float(spec.get("speed", 1.0) if speed is None else speed)
    fleet_spec = dict(spec.get("fleet", {}))
    chaos = list(spec.get("chaos", ()))
    for ev in chaos:
        if ev.get("action") == "overload":
            # Overload multiplier: the whole arrival process compressed
            # — an admission-control stressor, applied at setup.
            speed *= float(ev.get("factor", 2.0))
    tol = float(spec.get("fidelity_tolerance", 0.10))

    wl = _build_workload(spec, seed, quick_scale)
    capture_mode = wl is None

    fleet = LoopbackFleet(
        replicas=int(fleet_spec.get("replicas", 2)),
        dim=int(fleet_spec.get("dim", 8)),
        prompt_len=int(fleet_spec.get("prompt_len", 8)),
        max_new_tokens=int(fleet_spec.get("max_new_tokens", 8)),
        vocab_size=int(fleet_spec.get("vocab_size", 64)),
        per_row_ms=float(fleet_spec.get("per_row_ms", 1.0)),
        per_token_ms=float(fleet_spec.get("per_token_ms", 1.0)),
        prefill_ms=float(fleet_spec.get("prefill_ms", 2.0)),
        faults=fleet_spec.get("faults", ()),
        hedge=bool(fleet_spec.get("hedge", False)),
        seed=seed,
        canary=fleet_spec.get("canary"),
        spotcheck=fleet_spec.get("spotcheck"),
    )
    ring = TimeSeriesRing(resolution=0.5, retention=600.0)
    objectives = [_objective_from_spec(o)
                  for o in spec["slo"]["objectives"]]
    verdict: dict = {"scenario": spec["name"], "seed": seed,
                     "speed": round(speed, 3)}
    t_begin = time.monotonic()
    fidelity = None
    timers: list[threading.Timer] = []
    try:
        fleet.start()
        if capture_mode:
            wl, fidelity = _capture_leg(spec, fleet, seed, quick_scale,
                                        tol)
        # Window baseline AFTER any capture leg: the scored deltas
        # cover exactly the replay under chaos, nothing before it.
        ring.collect(now=time.time())
        # Both windows = the whole scored run (<= ring retention): the
        # verdict is "did the budget hold over THIS scenario", not a
        # production multi-window page.
        tracker = SLOTracker(ring, objectives,
                             fast_window=600.0, slow_window=600.0)

        def arm_chaos(_t0):
            for ev in chaos:
                action = ev.get("action")
                if action == "overload":
                    continue
                at = float(ev.get("at", 0.0)) / max(speed, 1e-9)
                idx = int(ev.get("replica", 0))
                fn = {"kill": fleet.kill_replica,
                      "drain": fleet.drain_replica,
                      "undrain": fleet.undrain_replica}.get(action)
                if fn is None:
                    raise ValueError(f"unknown chaos action {action!r}")
                t = threading.Timer(at, fn, args=(idx,))
                t.daemon = True
                t.start()
                timers.append(t)

        stop_tick = threading.Event()

        def tick():
            while not stop_tick.wait(0.5):
                ring.collect(now=time.time())

        ticker = threading.Thread(target=tick, daemon=True)
        ticker.start()
        report = replay(
            wl, fleet.target, speed=speed,
            dim=fleet.dim, prompt_len=fleet.prompt_len,
            vocab_size=fleet.vocab_size,
            timeout=float(spec.get("timeout_s", 15.0)),
            on_start=arm_chaos,
        )
        stop_tick.set()
        ticker.join(timeout=2.0)
        ring.collect(now=time.time())
        slo_doc = tracker.evaluate(now=time.time())
        quarantined = [
            {"target": s["target"], "reason": s.get("quarantine_reason"),
             "strikes": s.get("integrity_strikes", 0)}
            for s in fleet.pool.snapshot() if s["state"] == "quarantined"
        ]
    finally:
        for t in timers:
            t.cancel()
        fleet.stop()
    objs = []
    for o in slo_doc["objectives"]:
        burn = o["windows"]["fast"]["burn_rate"]
        measured = (o["windows"]["fast"].get("measured_quantile_ms")
                    if o["kind"] == "latency"
                    else o["windows"]["fast"].get("measured_availability"))
        objs.append({"name": o["name"], "objective": o["objective"],
                     "burn_rate": burn, "measured": measured,
                     "total": o["windows"]["fast"]["total"],
                     "passed": burn <= 1.0})
    passed = all(o["passed"] for o in objs)
    if fidelity is not None:
        passed = passed and fidelity["passed"]
        verdict["fidelity"] = fidelity
    integ_spec = spec.get("integrity")
    if integ_spec:
        # The corruption cell's teeth: the quarantine choreography must
        # have indicted the right number of replicas — catching the
        # corruption is the objective, not merely surviving it.
        lo = int(integ_spec.get("min_quarantines", 0))
        hi = integ_spec.get("max_quarantines")
        integ_ok = len(quarantined) >= lo and (
            hi is None or len(quarantined) <= int(hi)
        )
        verdict["integrity"] = {
            "quarantined": quarantined,
            "min_quarantines": lo,
            "max_quarantines": hi,
            "passed": integ_ok,
        }
        passed = passed and integ_ok
    elif quarantined:
        verdict["integrity"] = {"quarantined": quarantined}
    verdict.update({
        "passed": passed,
        "duration_s": round(time.monotonic() - t_begin, 3),
        "workload": wl.mix(),
        "replay": report,
        "objectives": objs,
        "slo": slo_doc,
        "faults_fired": sum(p.fired for p in fleet.fault_plans),
    })
    return verdict


def _capture_leg(spec, fleet, seed, quick_scale, tol):
    """The bundle-derived workload: drive the spec's seed generator
    against the live fleet, capture a REAL incident bundle from the
    shared tracer, extract the WorkloadTrace back out of it, and score
    round-trip fidelity (exact mix + per-decile arrival error)."""
    from tpu_dist_nn.obs.incident import capture_bundle
    from tpu_dist_nn.obs.trace import TRACER

    cap = spec["workload"]["capture"]
    args = dict(cap.get("args", {}))
    if quick_scale:
        args = _scale_workload_args(args, quick_scale)
    original = make_workload(cap["generator"], seed=seed, **args)
    cursor = TRACER.chrome_trace(limit=1)["cursor"]
    replay(original, fleet.target, speed=1.0, dim=fleet.dim,
           prompt_len=fleet.prompt_len, vocab_size=fleet.vocab_size)
    # Only spans finished after the cursor: an earlier scenario's
    # traffic in the same process must not leak into this bundle.
    doc = TRACER.chrome_trace(since=cursor)
    _, bundle = capture_bundle(
        "scenario_capture", reason=f"scenario {spec['name']} capture leg",
        tracer=_FrozenTracer(doc),
    )
    extracted = trace_from_bundle(bundle, name=f"{original.name}-replayed")
    mix_ok = extracted.mix() == original.mix()
    errs = decile_errors(original.inter_arrival_deciles(),
                         extracted.inter_arrival_deciles())
    fidelity = {
        "bundle_bytes": len(bundle),
        "mix_match": mix_ok,
        "per_decile_error": [round(e, 4) for e in errs],
        "max_decile_error": round(max(errs), 4) if errs else None,
        "tolerance": tol,
        "passed": bool(mix_ok and errs and max(errs) <= tol),
    }
    return extracted, fidelity


class _FrozenTracer:
    """Duck-typed tracer handing capture_bundle a pre-sliced chrome
    document (the since-cursor slice), so a long-lived process's older
    traffic stays out of the scenario's bundle."""

    def __init__(self, doc):
        self._doc = doc

    def chrome_trace(self, *a, **k):
        return self._doc

    def snapshot(self, *a, **k):
        return []


def run_scenario_file(path: str, *, seed: int | None = None,
                      speed: float | None = None,
                      quick_scale: float | None = None) -> dict:
    return run_scenario(load_scenario(path), seed=seed, speed=speed,
                        quick_scale=quick_scale)


def run_scenario_remote(spec: dict, target: str, *,
                        seed: int | None = None,
                        speed: float | None = None,
                        quick_scale: float | None = None) -> dict:
    """Fire a scenario's WORKLOAD at a live remote fleet — a load-test
    mode, not a scored verdict.

    Everything that makes a scenario a controlled experiment is
    loopback-only and is deliberately NOT applied here: no fault
    injection, no chaos timeline (killing someone's production replica
    from a load driver is not a feature), and no SLO scoring — the
    remote fleet's metrics live in ITS process, so burn rates must be
    read from the target's own ``/metrics``, not synthesized
    client-side. ``passed`` is ``None`` and the report says so in
    ``caveat``; what remains is the replay report — client-observed
    outcomes, latency/TTFT percentiles, and arrival fidelity.

    Capture-mode workloads (``workload.capture``) need the loopback
    fleet's shared tracer and are rejected."""
    seed = int(spec.get("seed", 0) if seed is None else seed)
    speed = float(spec.get("speed", 1.0) if speed is None else speed)
    wl = _build_workload(spec, seed, quick_scale)
    if wl is None:
        raise ValueError(
            f"scenario {spec['name']}: capture-mode workloads need the "
            f"loopback fleet; remote --target replay supports "
            f"generator|trace workloads"
        )
    fleet_spec = dict(spec.get("fleet", {}))
    disabled = sorted(
        k for k in ("chaos", "fleet", "slo", "integrity") if spec.get(k)
    )
    report = replay(
        wl, target, speed=speed,
        dim=int(fleet_spec.get("dim", 8)),
        prompt_len=int(fleet_spec.get("prompt_len", 8)),
        vocab_size=int(fleet_spec.get("vocab_size", 64)),
        timeout=float(spec.get("timeout_s", 15.0)),
    )
    return {
        "scenario": spec["name"], "seed": seed, "speed": round(speed, 3),
        "mode": "remote",
        "target": target,
        "caveat": (
            "remote load-test: fault injection, chaos events, and SLO "
            "scoring are loopback-only and were NOT applied; this "
            "report is the client-observed outcome only — score SLOs "
            "from the target fleet's own /metrics"
        ),
        "disabled": disabled,
        "passed": None,
        "duration_s": report["wall_s"],
        "workload": wl.mix(),
        "replay": report,
    }


def scenario_paths(directory: str) -> list[str]:
    """All scenario specs under ``directory``, sorted for stable run
    order."""
    return sorted(
        os.path.join(directory, f) for f in os.listdir(directory)
        if f.endswith(".json")
    )
