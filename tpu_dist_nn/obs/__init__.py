"""Observability: process-wide metrics registry + Prometheus exposition.

The reference stack's only telemetry is printed wall-clock spans
(``run_grpc_inference.py:195-216``) and the repo's own
:mod:`tpu_dist_nn.utils.profiling` counters — neither is visible while
the system RUNS. This package is the dependency-free (stdlib-only)
metrics layer the serving/training hot paths publish into:

  - :mod:`tpu_dist_nn.obs.registry` — ``Counter`` / ``Gauge`` /
    ``Histogram`` families with label support behind one process-wide
    :data:`~tpu_dist_nn.obs.registry.REGISTRY`, plus the bridge that
    teaches existing :class:`~tpu_dist_nn.utils.profiling.LatencyStats`
    objects to feed a histogram.
  - :mod:`tpu_dist_nn.obs.exposition` — Prometheus text-format
    rendering and the stdlib ``/metrics`` + ``/healthz`` HTTP endpoint
    (``tdn ... --metrics-port``).
  - :mod:`tpu_dist_nn.obs.runtime` — a background sampler publishing
    queue depth, in-flight rows, coalesce ratio, and host/device
    memory gauges.
  - :mod:`tpu_dist_nn.obs.trace` — request-scoped distributed tracing
    (Dapper-style): a span recorder behind one process-wide
    :data:`~tpu_dist_nn.obs.trace.TRACER`, ``x-tdn-trace`` wire
    propagation across the gRPC hop, and Chrome trace-event export
    served from ``GET /trace`` (``tdn trace`` pulls and saves it).
  - :mod:`tpu_dist_nn.obs.profile` — performance attribution: completed
    spans folded into a per-stage SELF-time breakdown (p50/p99/share
    per stage, per method), served from ``GET /profile`` (``tdn
    profile`` pretty-prints it; ``tools/bench_gate.py`` folds it into
    regression reports).
  - :mod:`tpu_dist_nn.obs.log` — structured JSON logging: event-shaped,
    trace-correlated, rate-limited records for the serving/engine
    operational paths (``tdn --log-json`` renders the whole process's
    logs as JSON lines).
  - :mod:`tpu_dist_nn.obs.timeseries` — a bounded in-memory ring the
    runtime sampler snapshots selected families into (default 5s x 1h),
    served as ``GET /timeseries`` — history without an external
    Prometheus.
  - :mod:`tpu_dist_nn.obs.slo` — declared objectives (latency,
    availability) evaluated from the ring's windowed deltas into
    fast/slow error-budget burn rates, the ``tdn_slo_*`` gauges,
    ``GET /slo``, and the rate-limited ``slo.burn`` event.
  - :mod:`tpu_dist_nn.obs.collect` — fleet collection: cross-replica
    trace stitching (one Chrome trace, a lane per process) and
    ``/profile`` merging behind ``tdn trace --aggregate`` /
    ``tdn metrics --aggregate --profile`` / the router's
    ``/trace/fleet``.
  - :mod:`tpu_dist_nn.obs.top` — the ``tdn top`` live ANSI dashboard
    over a router fleet or single server (rps, percentiles, slots,
    breaker state, SLO budget, sparklines).
  - :mod:`tpu_dist_nn.obs.goodput` — the goodput & MFU accounting
    plane: analytic per-launch FLOP models (FCNN rows, LM
    prefill/decode at their static kernel shapes) fed at the
    launch/fetch boundaries, every launch split exactly into
    ``useful + pad`` FLOPs with a pad taxonomy (bucket rows,
    idle/frozen slots, masked attention tails), one shared peak
    calibration with bench.py, ``tdn_mfu_ratio`` /
    ``tdn_pad_ratio{path}`` / ``tdn_goodput_flops_total{kind}`` /
    ``tdn_prefix_flops_saved_total``, and ``GET /goodput``.
  - :mod:`tpu_dist_nn.obs.incident` — the flight recorder: detectors
    on the sampler tick (SLO fast burn, error/shed spikes, breaker
    opens, drain/failover) plus crash hooks, each trigger freezing a
    diagnostic bundle (trace ring + profile + timeseries window + log
    ring + /slo + /metrics + manifest) into a bounded on-disk incident
    store; on a router the capture fans out to every replica and
    stitches the fleet trace. ``GET /debug/bundle``, ``GET
    /incidents``, ``tdn incident``, ``tdn debug bundle``.

Every metric this framework publishes is prefixed ``tdn_``; the
catalog lives in ``docs/OBSERVABILITY.md``. All updates are plain
host-side dict/float operations — nothing here ever touches a device
buffer or forces a fetch, so instrumentation stays off the XLA hot
path by construction.
"""

from tpu_dist_nn.obs.registry import (  # noqa: F401
    REGISTRY,
    Registry,
    bridge_latency_stats,
    histogram_quantile,
)
from tpu_dist_nn.obs.exposition import (  # noqa: F401
    MetricsServer,
    parse_prometheus_text,
    parsed_histogram_quantile,
    render,
    split_series,
    start_http_server,
)
from tpu_dist_nn.obs.timeseries import TimeSeriesRing  # noqa: F401
from tpu_dist_nn.obs.slo import (  # noqa: F401
    SLOTracker,
    availability_objective,
    latency_objective,
)
from tpu_dist_nn.obs.runtime import RuntimeSampler  # noqa: F401
from tpu_dist_nn.obs.trace import (  # noqa: F401
    SpanContext,
    TRACE_HEADER,
    TRACER,
    Tracer,
)
from tpu_dist_nn.obs.profile import (  # noqa: F401
    format_profile_table,
    profile_snapshot,
)
from tpu_dist_nn.obs.log import (  # noqa: F401
    LOG_RING,
    JsonFormatter,
    LogRing,
    get_logger,
    setup_json_logging,
)
from tpu_dist_nn.obs.goodput import (  # noqa: F401
    GOODPUT,
    GoodputTracker,
    LMFlopModel,
    fcnn_flops_per_row,
)
from tpu_dist_nn.obs.incident import (  # noqa: F401
    FlightRecorder,
    IncidentStore,
    capture_bundle,
    default_detectors,
    incident_routes,
    install_crash_hook,
)

__all__ = [
    "REGISTRY",
    "Registry",
    "bridge_latency_stats",
    "histogram_quantile",
    "MetricsServer",
    "parse_prometheus_text",
    "parsed_histogram_quantile",
    "render",
    "split_series",
    "start_http_server",
    "RuntimeSampler",
    "TimeSeriesRing",
    "SLOTracker",
    "latency_objective",
    "availability_objective",
    "SpanContext",
    "TRACE_HEADER",
    "TRACER",
    "Tracer",
    "profile_snapshot",
    "format_profile_table",
    "get_logger",
    "setup_json_logging",
    "JsonFormatter",
    "LogRing",
    "LOG_RING",
    "GOODPUT",
    "GoodputTracker",
    "LMFlopModel",
    "fcnn_flops_per_row",
    "FlightRecorder",
    "IncidentStore",
    "capture_bundle",
    "default_detectors",
    "incident_routes",
    "install_crash_hook",
]
