"""Observability: process-wide metrics registry + Prometheus exposition.

The reference stack's only telemetry is printed wall-clock spans
(``run_grpc_inference.py:195-216``) and the repo's own
:mod:`tpu_dist_nn.utils.profiling` counters — neither is visible while
the system RUNS. This package is the dependency-free (stdlib-only)
metrics layer the serving/training hot paths publish into:

  - :mod:`tpu_dist_nn.obs.registry` — ``Counter`` / ``Gauge`` /
    ``Histogram`` families with label support behind one process-wide
    :data:`~tpu_dist_nn.obs.registry.REGISTRY`, plus the bridge that
    teaches existing :class:`~tpu_dist_nn.utils.profiling.LatencyStats`
    objects to feed a histogram.
  - :mod:`tpu_dist_nn.obs.exposition` — Prometheus text-format
    rendering and the stdlib ``/metrics`` + ``/healthz`` HTTP endpoint
    (``tdn ... --metrics-port``).
  - :mod:`tpu_dist_nn.obs.runtime` — a background sampler publishing
    queue depth, in-flight rows, coalesce ratio, and host/device
    memory gauges.
  - :mod:`tpu_dist_nn.obs.trace` — request-scoped distributed tracing
    (Dapper-style): a span recorder behind one process-wide
    :data:`~tpu_dist_nn.obs.trace.TRACER`, ``x-tdn-trace`` wire
    propagation across the gRPC hop, and Chrome trace-event export
    served from ``GET /trace`` (``tdn trace`` pulls and saves it).

Every metric this framework publishes is prefixed ``tdn_``; the
catalog lives in ``docs/OBSERVABILITY.md``. All updates are plain
host-side dict/float operations — nothing here ever touches a device
buffer or forces a fetch, so instrumentation stays off the XLA hot
path by construction.
"""

from tpu_dist_nn.obs.registry import (  # noqa: F401
    REGISTRY,
    Registry,
    bridge_latency_stats,
)
from tpu_dist_nn.obs.exposition import (  # noqa: F401
    MetricsServer,
    parse_prometheus_text,
    render,
    start_http_server,
)
from tpu_dist_nn.obs.runtime import RuntimeSampler  # noqa: F401
from tpu_dist_nn.obs.trace import (  # noqa: F401
    SpanContext,
    TRACE_HEADER,
    TRACER,
    Tracer,
)

__all__ = [
    "REGISTRY",
    "Registry",
    "bridge_latency_stats",
    "MetricsServer",
    "parse_prometheus_text",
    "render",
    "start_http_server",
    "RuntimeSampler",
    "SpanContext",
    "TRACE_HEADER",
    "TRACER",
    "Tracer",
]
