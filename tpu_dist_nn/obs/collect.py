"""Fleet observability collection: cross-replica trace stitching and
profile merging.

PR 8 scaled the data plane to a router + replica fleet, but every
observability surface stayed per-process: one request's trace lives
half in the router's ring buffer and half in one replica's, and
``/profile`` attributes only the spans its own process recorded.
Following Dapper's collection model (Sigelman et al., 2010 — spans are
logged locally, joined by trace id centrally), this module is the
"central" half for a tdn fleet:

* **Discovery** reuses the router's ``/router/replicas`` admin route
  (the same fan-out ``tdn metrics --aggregate`` does): each replica
  snapshot carries its ``metrics_target``, which serves ``/trace`` and
  ``/profile``.
* **Stitching** (:func:`stitch_chrome_traces`) merges per-process
  Chrome trace documents into ONE document with a lane per process:
  the ``x-tdn-trace`` header already carries trace ids across the
  wire, so spans from the router and the serving replica share a
  trace id — this module just re-keys ``pid`` per source, names the
  lanes (``router``, ``replica <target>``; a replica that RESTARTED
  mid-window gets a second lane per boot, keyed by its original pid),
  and de-duplicates spans that multiple endpoints exported (an
  in-process loopback fleet shares one ring).
* **Profile merging** (:func:`merge_profiles`) folds per-process
  ``/profile`` breakdowns into one fleet view — counts and self-time
  totals sum exactly; p50 is count-weighted, p99/max take the fleet
  worst (percentiles do not merge exactly from summaries, and the
  fields say which rule produced them via ``merged_estimates``).

Served two ways: ``tdn trace --aggregate`` / ``tdn metrics --aggregate
--profile`` run the fan-out client-side; the router's metrics endpoint
mounts the same stitcher as ``GET /trace/fleet``
(:func:`fleet_trace_route`). Stdlib-only.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request


def _base_url(target: str) -> str:
    if "://" not in target:
        target = f"http://{target}"
    return target.rstrip("/")


def http_get_json(target: str, path: str, timeout: float = 5.0):
    """GET one endpoint route as parsed JSON; raises ValueError with a
    nameable reason on any transport/parse failure (the CLI's
    user-error convention)."""
    url = _base_url(target) + path
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return json.loads(resp.read())
    except (urllib.error.URLError, OSError, ValueError) as e:
        raise ValueError(f"could not fetch {url}: {e}") from e


def discover_fleet(router_target: str, timeout: float = 5.0) -> list[dict]:
    """The router's replica snapshots (``/router/replicas``); raises
    ValueError when the target is not a router metrics endpoint."""
    doc = http_get_json(router_target, "/router/replicas", timeout)
    if not isinstance(doc, list):
        raise ValueError(
            f"{router_target}/router/replicas did not return a replica "
            f"list — is this a ROUTER metrics endpoint?"
        )
    return doc


def _pull_replicas(snapshots, path: str, timeout: float):
    """Fan one GET out over replica snapshots (each names its
    ``metrics_target``) -> ``(docs_by_source, unreachable)`` — the ONE
    per-replica pull loop behind the client-side fan-outs AND the
    router-side /trace/fleet route."""
    docs: dict[str, dict] = {}
    unreachable: list[dict] = []
    for rep in snapshots:
        mt = rep.get("metrics_target")
        name = f"replica {rep.get('target', mt)}"
        if not mt:
            unreachable.append({
                "source": name,
                "error": "no metrics_target registered (start the "
                         "replica with --metrics-port / pass "
                         "--replica-metrics)",
            })
            continue
        try:
            docs[name] = http_get_json(mt, path, timeout)
        except ValueError as e:
            unreachable.append({"source": name, "error": str(e)})
    return docs, unreachable


def _collect_sources(router_target: str, path: str, timeout: float):
    """Router (by HTTP) + discovered replicas -> ``(docs_by_source,
    unreachable)`` — the client-side fan-out (`tdn trace/metrics
    --aggregate`)."""
    docs: dict[str, dict] = {}
    unreachable: list[dict] = []
    try:
        docs["router"] = http_get_json(router_target, path, timeout)
    except ValueError as e:
        unreachable.append({"source": "router", "error": str(e)})
    rep_docs, rep_unreachable = _pull_replicas(
        discover_fleet(router_target, timeout), path, timeout
    )
    docs.update(rep_docs)
    unreachable.extend(rep_unreachable)
    return docs, unreachable


def _trace_path(limit: int | None, trace_id: str | None) -> str:
    params = []
    if limit is not None:
        params.append(f"limit={limit}")
    if trace_id is not None:
        params.append(f"trace_id={trace_id}")
    return "/trace" + ("?" + "&".join(params) if params else "")


# ------------------------------------------------------------ stitching


def _span_key(event: dict):
    args = event.get("args") or {}
    sid = args.get("span_id")
    if sid:
        return ("span", sid)
    return ("anon", event.get("name"), event.get("ts"), event.get("dur"))


def stitch_chrome_traces(docs_by_source: dict[str, dict],
                         trace_id: str | None = None) -> dict:
    """Merge per-process Chrome trace documents into one stitched
    document with a lane per process.

    Lanes are keyed by ``(source, original pid)``: one source address
    that contributed two pids is a replica that RESTARTED inside the
    collection window (its boot_id changed between scrapes), and its
    boots must stay separate lanes — folding them would interleave two
    processes' threads on one track. Lane names are the source label,
    with ``#N`` suffixes for later boots. Spans exported by more than
    one endpoint (loopback fleets sharing a ring) de-duplicate by span
    id, first source wins — sources iterate router-first, so shared
    spans land on the router lane.

    ``trace_id`` keeps only that trace's events. The result carries a
    ``metadata`` block (sources, span/trace counts) that Perfetto
    ignores and ``tdn trace --aggregate`` reports.
    """
    lane_pid: dict[tuple, int] = {}
    lane_name: dict[tuple, str] = {}
    per_source_pids: dict[str, list] = {}
    seen_spans = set()
    seen_instants = set()
    events: list[dict] = []
    threads: dict[tuple, str] = {}  # (new_pid, tid) -> name
    trace_ids = set()
    deduped = 0

    def lane_of(source: str, orig_pid) -> int:
        key = (source, orig_pid)
        if key not in lane_pid:
            lane_pid[key] = len(lane_pid) + 1
            boots = per_source_pids.setdefault(source, [])
            boots.append(orig_pid)
            lane_name[key] = source if len(boots) == 1 \
                else f"{source} #{len(boots)}"
        return lane_pid[key]

    for source, doc in docs_by_source.items():
        if not isinstance(doc, dict):
            continue
        src_threads: dict[tuple, str] = {}
        for e in doc.get("traceEvents", ()):
            ph = e.get("ph")
            if ph == "M":
                if e.get("name") == "thread_name":
                    src_threads[(e.get("pid"), e.get("tid"))] = (
                        (e.get("args") or {}).get("name", "")
                    )
                continue
            args = e.get("args") or {}
            tid_of_trace = args.get("trace_id")
            if trace_id is not None and tid_of_trace != trace_id:
                continue
            if ph == "X":
                key = _span_key(e)
                if key in seen_spans:
                    deduped += 1
                    continue
                seen_spans.add(key)
            elif ph == "i":
                key = (args.get("span_id"), e.get("ts"), e.get("name"))
                if key in seen_instants:
                    deduped += 1
                    continue
                seen_instants.add(key)
            if tid_of_trace:
                trace_ids.add(tid_of_trace)
            new_pid = lane_of(source, e.get("pid"))
            out = dict(e)
            out["pid"] = new_pid
            events.append(out)
            tname = src_threads.get((e.get("pid"), e.get("tid")))
            if tname is not None:
                threads.setdefault((new_pid, e.get("tid")), tname)
    events.sort(key=lambda e: e.get("ts", 0))
    meta: list[dict] = []
    for key, pid in sorted(lane_pid.items(), key=lambda kv: kv[1]):
        meta.append({
            "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
            "args": {"name": lane_name[key]},
        })
    for (pid, tid), name in sorted(threads.items()):
        meta.append({
            "ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
            "args": {"name": name},
        })
    spans = sum(1 for e in events if e.get("ph") == "X")
    return {
        "traceEvents": meta + events,
        "displayTimeUnit": "ms",
        "metadata": {
            "stitched_sources": sorted(docs_by_source),
            "lanes": [
                {"pid": pid, "source": key[0], "source_pid": key[1],
                 "name": lane_name[key]}
                for key, pid in sorted(lane_pid.items(),
                                       key=lambda kv: kv[1])
            ],
            "spans": spans,
            "traces": len(trace_ids),
            "deduped_events": deduped,
            "trace_id_filter": trace_id,
        },
    }


def collect_fleet_trace(router_target: str, *, timeout: float = 5.0,
                        limit: int | None = None,
                        trace_id: str | None = None) -> dict:
    """Fan ``GET /trace`` out over router + replicas and stitch
    (the ``tdn trace --aggregate`` core)."""
    docs, unreachable = _collect_sources(
        router_target, _trace_path(limit, trace_id), timeout
    )
    stitched = stitch_chrome_traces(docs, trace_id=trace_id)
    stitched["metadata"]["unreachable"] = unreachable
    return stitched


def fleet_trace_route(pool, tracer=None):
    """The router-side ``GET /trace/fleet`` route closure (mounted by
    :func:`tpu_dist_nn.serving.router.admin_routes`): stitches the
    router's OWN tracer with every replica's ``/trace`` pull — the
    fleet trace without a client-side fan-out."""
    import urllib.parse

    def route(query: str):
        if tracer is None:
            from tpu_dist_nn.obs.trace import TRACER as t
        else:
            t = tracer
        q = urllib.parse.parse_qs(query)
        trace_id = (q.get("trace_id") or [None])[0]
        limit = None
        raw_limit = (q.get("limit") or [None])[0]
        if raw_limit:
            try:
                limit = int(raw_limit)
            except ValueError:
                return 400, "application/json", \
                    b'{"error": "limit must be an integer"}\n'
        timeout = 5.0
        raw_t = (q.get("timeout") or [None])[0]
        if raw_t:
            try:
                timeout = float(raw_t)
            except ValueError:
                return 400, "application/json", \
                    b'{"error": "timeout must be a number"}\n'
        # The router's own export comes straight off the local tracer
        # (no HTTP round trip to itself); replicas ride the shared
        # pull loop the client-side fan-out uses.
        docs: dict[str, dict] = {
            "router": t.chrome_trace(limit, trace_id=trace_id),
        }
        rep_docs, unreachable = _pull_replicas(
            pool.snapshot(), _trace_path(limit, trace_id), timeout
        )
        docs.update(rep_docs)
        stitched = stitch_chrome_traces(docs, trace_id=trace_id)
        stitched["metadata"]["unreachable"] = unreachable
        return 200, "application/json", \
            json.dumps(stitched).encode() + b"\n"

    return route


# ------------------------------------------------------ profile merging


def merge_profiles(docs_by_source: dict[str, dict], top: int = 5) -> dict:
    """Fold per-process ``/profile`` documents into one fleet
    breakdown. Self-time totals and counts SUM exactly (self time
    partitions wall time per process, and processes never share a
    wall-clock instant's attribution); p50 merges count-weighted,
    p99/max take the fleet-worst source. Slowest exemplars carry their
    ``source``."""
    methods: dict[str, dict] = {}
    per_source_traces: dict[str, int] = {}
    for source, doc in docs_by_source.items():
        if not isinstance(doc, dict):
            continue
        per_source_traces[source] = int(doc.get("traces", 0))
        for method, m in (doc.get("methods") or {}).items():
            agg = methods.setdefault(method, {
                "traces": 0, "wall": 0.0, "stages": {}, "slowest": [],
            })
            agg["traces"] += int(m.get("traces", 0))
            agg["wall"] += float(m.get("wall_seconds_total", 0.0))
            for s in m.get("stages", ()):
                st = agg["stages"].setdefault(s["stage"], {
                    "count": 0, "total_s": 0.0, "p50_weighted": 0.0,
                    "p99_s": 0.0, "max_s": 0.0,
                })
                st["count"] += int(s.get("count", 0))
                st["total_s"] += float(s.get("total_s", 0.0))
                st["p50_weighted"] += (
                    float(s.get("p50_s", 0.0)) * int(s.get("count", 0))
                )
                st["p99_s"] = max(st["p99_s"], float(s.get("p99_s", 0.0)))
                st["max_s"] = max(st["max_s"], float(s.get("max_s", 0.0)))
            for ex in m.get("slowest", ()):
                agg["slowest"].append({**ex, "source": source})
    out_methods: dict[str, dict] = {}
    for method, agg in methods.items():
        wall = agg["wall"]
        stages = []
        for name, st in agg["stages"].items():
            stages.append({
                "stage": name,
                "count": st["count"],
                "total_s": round(st["total_s"], 6),
                "share": round(st["total_s"] / wall, 4) if wall else 0.0,
                "p50_s": round(
                    st["p50_weighted"] / st["count"], 6
                ) if st["count"] else 0.0,
                "p99_s": round(st["p99_s"], 6),
                "max_s": round(st["max_s"], 6),
            })
        stages.sort(key=lambda s: s["total_s"], reverse=True)
        slowest = sorted(agg["slowest"],
                         key=lambda e: e.get("wall_s", 0.0), reverse=True)
        out_methods[method] = {
            "traces": agg["traces"],
            "wall_seconds_total": round(wall, 6),
            "share_sum": round(sum(s["share"] for s in stages), 4),
            "stages": stages,
            "slowest": slowest[:max(int(top), 0)],
        }
    return {
        "window_seconds": None,
        "traces": sum(per_source_traces.values()),
        "methods": out_methods,
        "sources": per_source_traces,
        "merged_estimates": {
            "p50_s": "count-weighted mean of per-source p50",
            "p99_s": "fleet-worst source", "max_s": "fleet-worst source",
        },
    }


def collect_fleet_profile(router_target: str, *, timeout: float = 5.0,
                          window: float | None = None,
                          top: int = 5) -> dict:
    """Fan ``GET /profile`` out over router + replicas and merge
    (the ``tdn metrics --aggregate --profile`` core)."""
    path = "/profile" + (f"?window={window}" if window is not None else "")
    docs, unreachable = _collect_sources(router_target, path, timeout)
    merged = merge_profiles(docs, top=top)
    merged["unreachable"] = unreachable
    return merged


# ---------------------------------------------------------- SLO merging


def merge_slo(docs_by_source: dict[str, dict]) -> dict:
    """Fold per-process ``/slo`` documents into one fleet verdict.

    Objectives group by name. Per window, ``bad`` and ``total`` are
    EVENT COUNTS over the same wall-clock window on every process, so
    they sum exactly — the fleet burn rate recomputes from the summed
    fraction rather than averaging per-process rates (a busy replica
    burning hard must outweigh an idle one coasting). Measured
    availability recomputes the same way; a latency objective's
    measured quantile takes the FLEET-WORST source (quantiles do not
    merge from summaries — the rule is named in ``merged_estimates``,
    the profile-merge convention)."""
    objectives: dict[str, dict] = {}
    order: list[str] = []
    fast_s = slow_s = None
    for source, doc in docs_by_source.items():
        if not isinstance(doc, dict):
            continue
        fast_s = fast_s or doc.get("fast_window_seconds")
        slow_s = slow_s or doc.get("slow_window_seconds")
        for obj in doc.get("objectives", ()):
            name = obj.get("name")
            if name is None:
                continue
            agg = objectives.get(name)
            if agg is None:
                agg = objectives[name] = {
                    "describe": {
                        k: v for k, v in obj.items() if k not in (
                            "windows", "error_budget_remaining", "burning",
                        )
                    },
                    "budget_fraction": float(
                        obj.get("budget_fraction") or 0.0
                    ),
                    "windows": {},
                    "sources": [],
                }
                order.append(name)
            agg["sources"].append(source)
            for label, win in (obj.get("windows") or {}).items():
                w = agg["windows"].setdefault(label, {
                    "seconds": win.get("seconds"),
                    "bad": 0.0, "total": 0.0, "worst_quantile_ms": None,
                })
                w["bad"] += float(win.get("bad") or 0.0)
                w["total"] += float(win.get("total") or 0.0)
                q = win.get("measured_quantile_ms")
                if q is not None:
                    w["worst_quantile_ms"] = (
                        q if w["worst_quantile_ms"] is None
                        else max(w["worst_quantile_ms"], q)
                    )
    out = []
    for name in order:
        agg = objectives[name]
        budget = agg["budget_fraction"]
        windows = {}
        for label, w in agg["windows"].items():
            bad_frac = (w["bad"] / w["total"]) if w["total"] > 0 else 0.0
            burn = bad_frac / budget if budget > 0 else 0.0
            win_doc = {
                "seconds": w["seconds"],
                "bad": round(w["bad"], 3),
                "total": round(w["total"], 3),
                "bad_fraction": round(bad_frac, 6),
                "burn_rate": round(burn, 4),
            }
            if agg["describe"].get("kind") == "latency":
                win_doc["measured_quantile_ms"] = w["worst_quantile_ms"]
            else:
                win_doc["measured_availability"] = (
                    round(1.0 - bad_frac, 6) if w["total"] > 0 else None
                )
            windows[label] = win_doc
        slow_burn = (windows.get("slow") or {}).get("burn_rate", 0.0)
        fast = windows.get("fast") or {}
        out.append({
            **agg["describe"],
            "windows": windows,
            "error_budget_remaining": round(
                max(0.0, 1.0 - slow_burn), 4
            ),
            "burning": (fast.get("burn_rate", 0.0) > 1.0
                        and fast.get("total", 0.0) > 0),
            "sources": sorted(agg["sources"]),
        })
    return {
        "fleet": True,
        "fast_window_seconds": fast_s,
        "slow_window_seconds": slow_s,
        "objectives": out,
        "merged_estimates": {
            "burn_rate": "recomputed from summed bad/total",
            "measured_quantile_ms": "fleet-worst source",
        },
    }


def collect_fleet_slo(router_target: str, *,
                      timeout: float = 5.0) -> dict:
    """Fan ``GET /slo`` out over router + replicas and merge (the
    ``tdn metrics --aggregate`` / ``tdn top`` fleet-SLO core). A
    source without a tracker attached (404) lands in ``unreachable``
    with its reason — declaring the SLO on only the router is the
    common shape and must not fail the whole view."""
    docs, unreachable = _collect_sources(router_target, "/slo", timeout)
    merged = merge_slo(docs)
    merged["unreachable"] = unreachable
    return merged


# ------------------------------------------------------ goodput merging


def merge_goodput(docs_by_source: dict[str, dict]) -> dict:
    """Fold per-process ``/goodput`` documents into one fleet verdict.

    Useful/pad/saved FLOP totals are cumulative event counts, so they
    SUM exactly and the fleet pad ratio recomputes from the summed
    split (the merge_slo rule — a busy replica must outweigh an idle
    one). Fleet MFU recomputes as the sum of per-source useful-FLOP
    rates over the sum of per-source peaks: each source's ``mfu`` is
    ``useful_rate / peak``, so ``sum(mfu_i * peak_i) / sum(peak_i)``
    is the fleet's achieved fraction of its aggregate hardware —
    sources without a resolved peak are excluded from the MFU
    denominator (named in ``merged_estimates``). Per-source MFU stays
    visible (the which-replica-is-cold question a fleet view exists
    to answer)."""
    useful = pad = saved = 0
    launches = 0
    mfu_num = mfu_den = 0.0
    per_source: dict[str, dict] = {}
    stages: dict[str, dict] = {}
    reasons: dict[str, int] = {}
    for source, doc in docs_by_source.items():
        if not isinstance(doc, dict) or "flops" not in doc:
            continue
        flops = doc.get("flops") or {}
        useful += int(flops.get("useful") or 0)
        pad += int(flops.get("pad") or 0)
        saved += int(flops.get("prefix_saved") or 0)
        launches += int(doc.get("launches") or 0)
        peak = doc.get("peak_flops")
        mfu = doc.get("mfu")
        if peak and mfu is not None:
            mfu_num += float(mfu) * float(peak)
            mfu_den += float(peak)
        per_source[source] = {
            "mfu": mfu,
            "pad_ratio": doc.get("pad_ratio"),
            "peak_flops": peak,
            "peak_source": doc.get("peak_source"),
            "useful": int(flops.get("useful") or 0),
            "pad": int(flops.get("pad") or 0),
        }
        for name, st in (doc.get("stages") or {}).items():
            agg = stages.setdefault(name, {"useful": 0, "pad": 0,
                                           "launches": 0})
            agg["useful"] += int(st.get("useful") or 0)
            agg["pad"] += int(st.get("pad") or 0)
            agg["launches"] += int(st.get("launches") or 0)
        for reason, v in (doc.get("pad_reasons") or {}).items():
            reasons[reason] = reasons.get(reason, 0) + int(v)
    total = useful + pad
    for st in stages.values():
        st["total"] = st["useful"] + st["pad"]
        st["share"] = st["total"] / total if total else 0.0
    return {
        "fleet": True,
        "mfu": mfu_num / mfu_den if mfu_den else None,
        "pad_ratio": pad / total if total else 0.0,
        "flops": {"useful": useful, "pad": pad, "total": total,
                  "prefix_saved": saved},
        "launches": launches,
        "stages": stages,
        "pad_reasons": reasons,
        "sources": per_source,
        "merged_estimates": {
            "mfu": "sum(useful rates) / sum(peaks) over sources with "
                   "a resolved peak",
            "pad_ratio": "recomputed from summed useful/pad FLOPs",
        },
    }


def collect_fleet_goodput(router_target: str, *,
                          timeout: float = 5.0) -> dict:
    """Fan ``GET /goodput`` out over router + replicas and merge (the
    ``tdn metrics --aggregate`` goodput core). A source without a
    tracker attached (404) lands in ``unreachable`` — the router
    itself usually has no engine, so only replicas contribute FLOPs."""
    docs, unreachable = _collect_sources(router_target, "/goodput", timeout)
    merged = merge_goodput(docs)
    merged["unreachable"] = unreachable
    return merged


# --------------------------------------------------- timeseries merging


def merge_timeseries(docs_by_source: dict[str, dict]) -> dict:
    """Fold per-process ``/timeseries`` documents into one fleet view.

    Series stay NAMESPACED per source (``{series: {source: points}}``)
    — cumulative counters from different processes can be summed by a
    consumer that wants fleet totals, but collapsing them here would
    hide which replica moved, the exact question a fleet view answers
    (the ``--aggregate`` gauges-stay-per-source rule)."""
    families: set[str] = set()
    series: dict[str, dict[str, list]] = {}
    resolution = None
    for source, doc in docs_by_source.items():
        if not isinstance(doc, dict):
            continue
        families.update(doc.get("families") or ())
        if resolution is None:
            resolution = doc.get("resolution_seconds")
        for key, pts in (doc.get("series") or {}).items():
            series.setdefault(key, {})[source] = pts
    return {
        "fleet": True,
        "resolution_seconds": resolution,
        "families": sorted(families),
        "series": series,
        "sources": sorted(
            s for s, d in docs_by_source.items() if isinstance(d, dict)
        ),
    }


def collect_fleet_timeseries(router_target: str, *,
                             family: str | None = None,
                             window: float | None = None,
                             timeout: float = 5.0) -> dict:
    """Fan ``GET /timeseries`` out over router + replicas and merge."""
    params = []
    if family is not None:
        params.append(f"family={family}")
    if window is not None:
        params.append(f"window={window}")
    path = "/timeseries" + ("?" + "&".join(params) if params else "")
    docs, unreachable = _collect_sources(router_target, path, timeout)
    merged = merge_timeseries(docs)
    merged["unreachable"] = unreachable
    return merged
