"""``tdn top``: a live ANSI dashboard over a serving fleet.

The fleet's health story is spread over four HTTP surfaces — /metrics
(counters/gauges), /router/replicas (membership + breaker state), /slo
(budget), /timeseries (history). ``tdn top`` polls them on an interval
and renders the operator's one-screen view: per-replica rps, p50/p99,
decode-slot occupancy, pending rows, breaker/health state, prefix-
cache hit ratio, MFU / pad-FLOP share (the goodput plane,
docs/OBSERVABILITY.md "Goodput & MFU"), SLO budget remaining, and
sparklines of recent request rate and MFU per lane.

Pointed at a ROUTER metrics endpoint it discovers the fleet via
``/router/replicas`` and shows router + every replica; pointed at a
single server's endpoint it shows that process alone. Rates and
percentiles are BETWEEN-POLL deltas (the live view), not all-time
aggregates: differencing two scrapes of cumulative ``le`` buckets
yields the interval's distribution, fed through the same shared
quantile estimator the server itself uses.

Plain ANSI (clear + home + inverse header), not curses: renders
anywhere a terminal escapes, degrades to a frame dump under
``--no-color``/non-TTY, and stays unit-testable as a pure
``render_frame``. Stdlib-only.
"""

from __future__ import annotations

import json
import sys
import time
import urllib.error
import urllib.request

from tpu_dist_nn.obs.exposition import (
    parse_prometheus_text,
    parsed_histogram_quantile,
    split_series,
)

SPARK_CHARS = " ▁▂▃▄▅▆▇█"

CLEAR = "\x1b[2J\x1b[H"
BOLD = "\x1b[1m"
DIM = "\x1b[2m"
RED = "\x1b[31m"
YELLOW = "\x1b[33m"
GREEN = "\x1b[32m"
RESET = "\x1b[0m"


def sparkline(values, width: int = 24) -> str:
    """Values -> a fixed-width unicode sparkline (newest right;
    all-equal series render mid-height, empty series render blank)."""
    vals = list(values)[-width:]
    if not vals:
        return " " * width
    lo, hi = min(vals), max(vals)
    span = hi - lo
    out = []
    for v in vals:
        if span <= 0:
            out.append(SPARK_CHARS[4] if hi > 0 else SPARK_CHARS[1])
        else:
            idx = 1 + int((v - lo) / span * (len(SPARK_CHARS) - 2))
            out.append(SPARK_CHARS[min(idx, len(SPARK_CHARS) - 1)])
    return "".join(out).rjust(width)


def _get(base: str, path: str, timeout: float):
    if "://" not in base:
        base = f"http://{base}"
    url = base.rstrip("/") + path
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read()


def _sum_family(parsed: dict, family: str, **match) -> float:
    total = 0.0
    for series, value in parsed.items():
        s = str(series)
        if s.startswith("__type__:"):
            continue
        name, labels = split_series(s)
        if name != family:
            continue
        if any(labels.get(k) != str(v) for k, v in match.items()):
            continue
        total += float(value)
    return total


def _family_present(parsed: dict, family: str) -> bool:
    """Whether ANY series of ``family`` exists in the scrape — a sum of
    0.0 over an absent family must render as '-', not as a real 0."""
    for series in parsed:
        s = str(series)
        if s.startswith("__type__:"):
            continue
        if split_series(s)[0] == family:
            return True
    return False


def _delta_parsed(prev: dict | None, cur: dict) -> dict:
    """Pointwise series delta of two scrapes (cumulative families only
    stay meaningful; the caller picks which families it reads).
    Negative deltas (restart) clamp to the new value."""
    if prev is None:
        return dict(cur)
    out = {}
    for k, v in cur.items():
        if str(k).startswith("__type__:"):
            out[k] = v
            continue
        p = prev.get(k)
        try:
            d = float(v) - float(p) if p is not None else float(v)
        except (TypeError, ValueError):
            continue
        out[k] = d if d >= 0 else float(v)
    return out


class FleetPoller:
    """Polls the fleet's HTTP surfaces and computes per-source rows;
    keeps the previous scrape per source for between-poll deltas."""

    def __init__(self, target: str, timeout: float = 3.0):
        self.target = target
        self.timeout = timeout
        self._prev: dict[str, tuple[float, dict]] = {}

    def _sources(self) -> tuple[list[tuple[str, str, dict]], bool]:
        """[(label, metrics_base, replica_snapshot)], fleet_mode."""
        try:
            snap = json.loads(
                _get(self.target, "/router/replicas", self.timeout)
            )
            if isinstance(snap, list):
                out = [("router", self.target, {})]
                for rep in snap:
                    out.append((
                        f"replica {rep.get('target')}",
                        rep.get("metrics_target") or "",
                        rep,
                    ))
                return out, True
        except (urllib.error.URLError, OSError, ValueError):
            pass
        return [(self.target, self.target, {})], False

    def _row(self, label: str, base: str, snap: dict, now: float) -> dict:
        row: dict = {"source": label, "state": snap.get("state", "")}
        if snap:
            row["breaker"] = snap.get("breaker", "")
            row["outstanding"] = snap.get("outstanding")
        if not base:
            row["error"] = "no metrics endpoint"
            return row
        try:
            parsed = parse_prometheus_text(
                _get(base, "/metrics", self.timeout).decode()
            )
        except (urllib.error.URLError, OSError) as e:
            row["error"] = f"unreachable ({e})"
            return row
        prev = self._prev.get(label)
        self._prev[label] = (now, parsed)
        dt = now - prev[0] if prev else None
        delta = _delta_parsed(prev[1] if prev else None, parsed)
        is_router = label == "router"
        req_family = ("tdn_router_requests_total" if is_router
                      else "tdn_rpc_requests_total")
        lat_family = ("tdn_router_request_seconds" if is_router
                      else "tdn_batch_wait_seconds")
        if dt and dt > 0:
            row["rps"] = _sum_family(delta, req_family) / dt
        for q, key in ((0.50, "p50_ms"), (0.99, "p99_ms")):
            est = parsed_histogram_quantile(delta if dt else parsed,
                                            lat_family, q)
            row[key] = est * 1e3 if est is not None else None
        row["pending"] = _sum_family(parsed, "tdn_batcher_pending_rows")
        row["slots"] = _sum_family(parsed, "tdn_gen_slots_active")
        row["occupancy"] = _sum_family(
            parsed, "tdn_gen_slot_occupancy_ratio"
        )
        hits = _sum_family(parsed, "tdn_prefix_cache_hits_total")
        misses = _sum_family(parsed, "tdn_prefix_cache_misses_total")
        row["prefix_hit"] = hits / (hits + misses) if hits + misses else None
        # Goodput view (ISSUE 14): the server's own windowed
        # tdn_mfu_ratio gauge verbatim; pad ratio from the between-poll
        # FLOP-counter deltas (the live view — falls back to cumulative
        # on the first frame).
        row["mfu"] = (
            _sum_family(parsed, "tdn_mfu_ratio")
            if _family_present(parsed, "tdn_mfu_ratio") else None
        )
        gp_src = delta if dt else parsed
        gp_useful = _sum_family(gp_src, "tdn_goodput_flops_total",
                                kind="useful")
        gp_pad = _sum_family(gp_src, "tdn_goodput_flops_total", kind="pad")
        row["pad_ratio"] = (
            gp_pad / (gp_useful + gp_pad) if gp_useful + gp_pad > 0 else None
        )
        ts = self._fetch_timeseries(base, "tdn_mfu_ratio")
        if ts is not None:
            pts: list = []
            for key, series_pts in (ts.get("series") or {}).items():
                pts = [v for _t, v in series_pts]  # one unlabeled gauge
            row["mfu_spark"] = pts or None
        else:
            row["mfu_spark"] = None
        ts = self._fetch_timeseries(base, req_family)
        if ts is not None:
            by_t: dict[float, float] = {}
            for key, pts in (ts.get("series") or {}).items():
                if "_bucket" in key or "_sum" in key:
                    continue
                for t, v in pts:
                    by_t[t] = by_t.get(t, 0.0) + v
            seq = [by_t[t] for t in sorted(by_t)]
            res = float(ts.get("resolution_seconds") or 1.0)
            row["spark"] = [
                max(b - a, 0.0) / res for a, b in zip(seq, seq[1:])
            ]
        else:
            row["spark"] = None
        return row

    def _fetch_timeseries(self, base: str, family: str) -> dict | None:
        """One /timeseries family pull (the rps- and mfu-sparkline
        fetches share it), degrading to None on any transport/parse
        failure — a sparkline is garnish, never an error row."""
        try:
            return json.loads(_get(
                base, f"/timeseries?family={family}&window=600",
                self.timeout,
            ))
        except (urllib.error.URLError, OSError, ValueError):
            return None

    def poll(self) -> dict:
        now = time.monotonic()
        sources, fleet = self._sources()
        # Per-source fan-out in parallel: a couple of wedged replicas
        # (each 2 serial GETs x timeout) must not stall the whole frame
        # past --interval — the same rule ReplicaPool.scrape_once
        # follows. Rows keep source order.
        import concurrent.futures

        with concurrent.futures.ThreadPoolExecutor(
            max_workers=min(16, max(len(sources), 1)),
            thread_name_prefix="tdn-top",
        ) as ex:
            rows = list(ex.map(
                lambda s: self._row(s[0], s[1], s[2], now), sources
            ))
        slo = self._poll_slo(sources, fleet)
        return {"target": self.target, "fleet": fleet, "rows": rows,
                "slo": slo, "at": time.time()}

    def _poll_slo(self, sources, fleet: bool):
        """The SLO table's source: single endpoint -> that process's
        /slo verbatim; fleet -> every source's /slo folded through
        :func:`~tpu_dist_nn.obs.collect.merge_slo` (the same merge
        ``tdn metrics --aggregate`` reports), so a burn on a REPLICA
        that declared its own objective pages on the router's
        dashboard too. Sources without a tracker (404) just drop out.
        The fleet fetch fans out in parallel — the same wedged-replica
        rule as the row fan-out: a couple of dead endpoints must not
        stall every frame by a timeout apiece."""
        import concurrent.futures

        def fetch(src):
            label, base, _snap = src
            if not base:
                return None
            try:
                doc = json.loads(_get(base, "/slo", self.timeout))
            except (urllib.error.URLError, OSError, ValueError):
                return None
            if isinstance(doc, dict) and doc.get("objectives"):
                return label, doc
            return None

        docs: dict[str, dict] = {}
        if fleet:
            with concurrent.futures.ThreadPoolExecutor(
                max_workers=min(16, max(len(sources), 1)),
                thread_name_prefix="tdn-top-slo",
            ) as ex:
                for hit in ex.map(fetch, sources):
                    if hit is not None:
                        docs[hit[0]] = hit[1]
        else:
            hit = fetch(sources[0]) if sources else None
            if hit is not None:
                docs[hit[0]] = hit[1]
        if not docs:
            return None
        if not fleet:
            return next(iter(docs.values()))
        from tpu_dist_nn.obs.collect import merge_slo

        return merge_slo(docs)


def _fmt(v, pattern="{:.1f}", dash="-") -> str:
    if v is None:
        return dash
    return pattern.format(v)


def render_frame(state: dict, color: bool = True) -> str:
    """One dashboard frame as text (pure — the unit under test)."""
    def c(code, s):
        return f"{code}{s}{RESET}" if color else s

    lines = []
    mode = "fleet" if state.get("fleet") else "single"
    lines.append(c(BOLD, (
        f"tdn top — {state['target']} [{mode}]  "
        f"{time.strftime('%H:%M:%S', time.localtime(state['at']))}"
    )))
    header = (
        f"{'source':<28} {'state':<9} {'rps':>8} {'p50ms':>8} "
        f"{'p99ms':>8} {'pend':>6} {'slots':>6} {'occ':>5} "
        f"{'pfx%':>5} {'mfu%':>6} {'pad%':>5}  {'rps trend':<24} "
        f"{'mfu trend':<12}"
    )
    lines.append(c(DIM, header))
    for row in state.get("rows", ()):
        if "error" in row:
            lines.append(
                f"{row['source']:<28} " + c(RED, row["error"])
            )
            continue
        st = row.get("state") or "up"
        breaker = row.get("breaker")
        if breaker and breaker != "closed":
            st = f"{st}/{breaker}"
        st_col = GREEN if st in ("up", "active") else YELLOW
        spark = sparkline(row["spark"]) if row.get("spark") else " " * 24
        mfu_spark = (
            sparkline(row["mfu_spark"], width=12)
            if row.get("mfu_spark") else " " * 12
        )
        mfu = row.get("mfu")
        mfu_pct = None if mfu is None else mfu * 100
        pad = row.get("pad_ratio")
        pad_pct = None if pad is None else pad * 100
        lines.append(
            f"{row['source']:<28} " + c(st_col, f"{st:<9}")
            + f" {_fmt(row.get('rps')):>8}"
            + f" {_fmt(row.get('p50_ms'), '{:.2f}'):>8}"
            + f" {_fmt(row.get('p99_ms'), '{:.2f}'):>8}"
            + f" {_fmt(row.get('pending'), '{:.0f}'):>6}"
            + f" {_fmt(row.get('slots'), '{:.0f}'):>6}"
            + f" {_fmt(row.get('occupancy'), '{:.2f}'):>5}"
            + f" {_fmt(row.get('prefix_hit') and row['prefix_hit'] * 100, '{:.0f}'):>5}"
            + f" {_fmt(mfu_pct, '{:.2f}'):>6}"
            + f" {_fmt(pad_pct, '{:.0f}'):>5}"
            + f"  {spark} {mfu_spark}"
        )
    slo = state.get("slo")
    if slo and slo.get("objectives"):
        lines.append("")
        lines.append(c(DIM, (
            f"{'SLO':<34} {'objective':<24} {'fast burn':>10} "
            f"{'slow burn':>10} {'budget left':>12}"
        )))
        for obj in slo["objectives"]:
            fast = obj["windows"]["fast"]["burn_rate"]
            slow = obj["windows"]["slow"]["burn_rate"]
            left = obj["error_budget_remaining"]
            col = RED if obj.get("burning") else (
                YELLOW if left < 0.25 else GREEN
            )
            lines.append(
                f"{obj['name']:<34} {obj['objective']:<24} "
                + c(col, f"{fast:>10.2f} {slow:>10.2f} {left:>11.0%}")
            )
    else:
        lines.append("")
        lines.append(c(DIM, "no SLOs declared (--slo-latency-p99-ms / "
                           "--slo-availability on the serving command)"))
    return "\n".join(lines)


def run_top(target: str, *, interval: float = 2.0,
            iterations: int | None = None, timeout: float = 3.0,
            color: bool | None = None, out=None) -> int:
    """The ``tdn top`` loop: poll, render, repeat until interrupted
    (or for ``iterations`` frames — the testable/CI bound). Returns an
    exit code; a completely unreachable target is a user error (2)."""
    stream = out if out is not None else sys.stdout
    use_color = color if color is not None else bool(
        getattr(stream, "isatty", lambda: False)()
    )
    poller = FleetPoller(target, timeout=timeout)
    frame = 0
    try:
        while True:
            state = poller.poll()
            if frame == 0 and all(
                "error" in r for r in state["rows"]
            ) and not state["fleet"]:
                print(f"error: {state['rows'][0].get('error', 'unreachable')}"
                      f" — is {target} a --metrics-port endpoint?",
                      file=sys.stderr)
                return 2
            body = render_frame(state, color=use_color)
            if use_color:
                stream.write(CLEAR + body + "\n")
            else:
                stream.write(body + "\n" + "-" * 40 + "\n")
            stream.flush()
            frame += 1
            if iterations is not None and frame >= iterations:
                return 0
            time.sleep(interval)
    except KeyboardInterrupt:
        return 0
