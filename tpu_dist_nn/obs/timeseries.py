"""Embedded time-series ring: bounded history for the live registry.

Every metric the registry serves is a point-in-time scrape — "what is
pending_rows NOW" — with no history unless an external Prometheus is
running, which on the boxes this framework actually runs on (CI
containers, tunneled TPU hosts) it never is. This module is the
embedded alternative: a bounded in-memory ring the
:class:`~tpu_dist_nn.obs.runtime.RuntimeSampler` tick snapshots
selected metric families into, at a configurable resolution and
retention (default 5s x 1h = 720 points per series), served as
``GET /timeseries?family=F&window=S`` JSON.

It is the data plane under two consumers:

* the SLO tracker (:mod:`tpu_dist_nn.obs.slo`) computes windowed
  deltas of cumulative counters and histogram buckets from it — burn
  rates need "errors over the last 5 minutes", which a gauge of the
  all-time total cannot answer;
* ``tdn top`` pulls sparkline history from it, so the dashboard shows
  trend, not just the instant.

Design constraints (the registry's own discipline):

* **Stdlib-only, host-side only** — dict + deque under one lock; a
  sample tick is O(selected series), never touches a device.
* **Bounded** — each series is a ``deque(maxlen=retention/resolution)``;
  the family allowlist bounds series count (histogram families record
  one series per bucket edge, so an unbounded allowlist would
  multiply).
* **Cumulative stays cumulative** — counters and histogram buckets are
  recorded as their raw cumulative values; consumers difference them
  (and treat a value drop as a process restart). Storing rates here
  would bake one window into the data.

Series keys are exposition-format (``name{label="v"}``), with
histogram children fanned out as ``name_count`` / ``name_sum`` /
``name_bucket{...,le="edge"}`` — the same naming a scrape would yield,
so :func:`~tpu_dist_nn.obs.exposition.split_series` parses both.
"""

from __future__ import annotations

import collections
import threading
import time

from tpu_dist_nn.obs.registry import REGISTRY, Registry

# The families the serving data plane's health story needs, kept small
# on purpose (each histogram fans out per bucket edge). Callers with
# different workloads pass their own allowlist.
DEFAULT_FAMILIES = (
    "tdn_rpc_requests_total",
    "tdn_rpc_errors_total",
    "tdn_batch_wait_seconds",
    "tdn_batcher_pending_rows",
    "tdn_batcher_shed_total",
    # Degradation ladder (ISSUE 15): per-class sheds/backlog, expiry,
    # and the governor's tightening level — the /timeseries evidence
    # of an overload handled selectively.
    "tdn_sched_class_shed_total",
    "tdn_sched_class_pending_rows",
    "tdn_batcher_expired_total",
    "tdn_sched_pressure",
    "tdn_gen_preemptions_total",
    "tdn_gen_ttft_seconds",
    "tdn_gen_tokens_total",
    "tdn_gen_slots_active",
    "tdn_gen_slot_occupancy_ratio",
    "tdn_prefix_cache_hits_total",
    "tdn_prefix_cache_misses_total",
    "tdn_goodput_flops_total",
    "tdn_mfu_ratio",
    "tdn_pad_ratio",
    "tdn_prefix_flops_saved_total",
    "tdn_router_requests_total",
    "tdn_router_request_seconds",
    "tdn_router_failovers_total",
    "tdn_router_replica_healthy",
    "tdn_router_replica_pending_rows",
    "tdn_host_rss_bytes",
)


def _labelstr(names, values) -> str:
    if not names:
        return ""
    inner = ",".join(f'{n}="{v}"' for n, v in zip(names, values))
    return "{" + inner + "}"


class TimeSeriesRing:
    """Bounded per-series history of selected registry families.

    ``collect()`` snapshots every allowlisted family's children at the
    current grid bucket (``floor(now / resolution)``); two collects in
    one bucket overwrite rather than append, so the cadence of the
    caller (the runtime sampler's tick) and the ring's resolution can
    differ without double points. Timestamps are wall-clock
    (``time.time()``) — the JSON consumers line them up with logs and
    other processes, which monotonic values cannot.
    """

    def __init__(self, resolution: float = 5.0, retention: float = 3600.0,
                 *, families=DEFAULT_FAMILIES,
                 registry: Registry | None = None):
        if resolution <= 0:
            raise ValueError(f"resolution must be > 0, got {resolution}")
        if retention < resolution:
            raise ValueError(
                f"retention {retention} must be >= resolution {resolution}"
            )
        self.resolution = float(resolution)
        self.retention = float(retention)
        self._families = set(families)
        self._reg = registry if registry is not None else REGISTRY
        self._capacity = max(int(retention / resolution), 1)
        self._lock = threading.Lock()
        # series key -> deque[(bucket_ts, value)], plus the base family
        # each key belongs to (a histogram's _bucket series resolve
        # back to their family for filtered reads).
        self._data: dict[str, collections.deque] = {}  # guarded-by: _lock
        self._family_of: dict[str, str] = {}  # guarded-by: _lock
        # Bucket of the previous collect() pass: a cumulative series
        # first seen on a LATER pass was born since then, and gets a
        # zero baseline at this bucket — without it, an error counter
        # whose first increment IS the incident would have one point,
        # no computable delta, and an invisible burn (the labeled-
        # children-are-lazy corollary of the registry's unlabeled-
        # counter rule).
        self._last_collect_bucket: float | None = None  # guarded-by: _lock

    # ------------------------------------------------------------ write

    def record(self, series: str, value: float, *, family: str | None = None,
               now: float | None = None, born_zero: bool = False) -> None:
        """Record one point (grid-aligned; same-bucket writes
        overwrite). ``family`` defaults to the series' bare name;
        ``born_zero`` seeds a first-seen series with a 0.0 baseline at
        the previous collect tick (cumulative families only — see
        :meth:`collect`)."""
        t = time.time() if now is None else float(now)
        bucket = (t // self.resolution) * self.resolution
        fam = family if family is not None else series.split("{", 1)[0]
        with self._lock:
            dq = self._data.get(series)
            if dq is None:
                dq = self._data[series] = collections.deque(
                    maxlen=self._capacity
                )
                self._family_of[series] = fam
                last = self._last_collect_bucket
                if born_zero and last is not None and last < bucket:
                    dq.append((last, 0.0))
            if dq and dq[-1][0] == bucket:
                dq[-1] = (bucket, float(value))
            else:
                dq.append((bucket, float(value)))

    def collect(self, now: float | None = None) -> None:
        """One snapshot of every allowlisted family into the ring (the
        runtime sampler calls this per tick; tests call it with a
        controlled ``now``)."""
        for m in self._reg.collect():
            if m.name not in self._families:
                continue
            cumulative = m.kind in ("counter", "histogram")
            for values, child in m.samples():
                base = _labelstr(m.labelnames, values)
                if m.kind == "histogram":
                    self.record(f"{m.name}_count{base}", child.value,
                                family=m.name, now=now, born_zero=True)
                    self.record(f"{m.name}_sum{base}", child.sum,
                                family=m.name, now=now, born_zero=True)
                    for edge, n in zip(m.buckets, child.counts):
                        key = _labelstr(
                            m.labelnames + ("le",),
                            values + (repr(float(edge)),),
                        )
                        # Per-bucket (NOT le-cumulative) counts: the
                        # windowed-delta consumer wants each bucket's
                        # own increments, and histogram_quantile takes
                        # exactly this layout.
                        self.record(f"{m.name}_bucket{key}", n,
                                    family=m.name, now=now,
                                    born_zero=True)
                else:
                    self.record(f"{m.name}{base}", child.value,
                                family=m.name, now=now,
                                born_zero=cumulative)
        t = time.time() if now is None else float(now)
        with self._lock:
            self._last_collect_bucket = (
                t // self.resolution
            ) * self.resolution

    # ------------------------------------------------------------- read

    def families(self) -> list[str]:
        with self._lock:
            return sorted(set(self._family_of.values()))

    def keys(self, family: str | None = None) -> list[str]:
        """Series KEYS only (``family`` filters like :meth:`series`) —
        for consumers that enumerate then :meth:`delta` per key (the
        incident spike detectors): materializing every point list just
        to read the dict keys would allocate the whole retained window
        per tick."""
        with self._lock:
            return [
                k for k in self._data
                if family is None or self._family_of[k] == family
            ]

    def series(self, family: str | None = None,
               window: float | None = None,
               now: float | None = None) -> dict[str, list]:
        """``{series_key: [[t, value], ...]}``, oldest first.
        ``family`` filters to one base family (histogram-derived keys
        included); ``window`` keeps points from the last S seconds."""
        t_now = time.time() if now is None else float(now)
        cutoff = None if window is None else t_now - float(window)
        out: dict[str, list] = {}
        with self._lock:
            for key, dq in self._data.items():
                if family is not None and self._family_of[key] != family:
                    continue
                pts = [
                    [t, v] for t, v in dq
                    if cutoff is None or t >= cutoff
                ]
                if pts:
                    out[key] = pts
        return out

    def delta(self, series: str, window: float,
              now: float | None = None) -> tuple[float, float]:
        """Windowed increase of one CUMULATIVE series ->
        ``(delta, covered_seconds)``. The baseline is the newest point
        at or before the window start (so a window that opened between
        two samples still counts the straddling increment), else the
        oldest retained point. A value drop is a process restart: the
        delta restarts from zero at the new value (the Prometheus
        ``increase()`` convention, minus interpolation)."""
        t_now = time.time() if now is None else float(now)
        start = t_now - float(window)
        with self._lock:
            dq = self._data.get(series)
            pts = list(dq) if dq else []
        if len(pts) < 2:
            return 0.0, 0.0
        base_t, base_v = pts[0]
        for t, v in pts:
            if t <= start:
                base_t, base_v = t, v
            else:
                break
        last_t, last_v = pts[-1]
        if last_t <= base_t:
            return 0.0, 0.0
        delta = last_v - base_v
        if delta < 0:  # counter reset across a restart
            delta = last_v
        return delta, last_t - base_t
