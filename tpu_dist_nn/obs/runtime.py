"""Background runtime sampler: the gauges nobody increments.

Counters and histograms are pushed by the code paths that own the
events; STATE (queue depth, rows in flight on the device, coalescing
efficiency, memory) has no event to hook, so a daemon thread samples
it on an interval. Everything read here is a plain python attribute
or a host syscall — sampling never blocks the batcher or dispatches
device work (``device.memory_stats()`` is a local runtime query, not
a computation).
"""

from __future__ import annotations

import logging
import threading

from tpu_dist_nn.obs.registry import REGISTRY, Registry

log = logging.getLogger(__name__)


def _read_rss_bytes() -> int | None:
    """Resident set size from /proc (linux); None where unavailable."""
    try:
        with open("/proc/self/statm") as f:
            fields = f.read().split()
        import resource

        return int(fields[1]) * resource.getpagesize()
    except (OSError, IndexError, ValueError):
        return None


class RuntimeSampler:
    """Samples registered sources into gauges every ``interval`` s.

    Sources attach after construction (``add_batcher`` from the
    serving wiring, ``add_engine`` where one exists); host RSS and —
    when the backend exposes them — per-device memory stats are
    sampled unconditionally. ``start()`` publishes one immediate
    sample so a scrape right after bring-up is never empty.
    """

    def __init__(self, interval: float = 5.0, *,
                 registry: Registry | None = None):
        reg = registry if registry is not None else REGISTRY
        self._interval = float(interval)
        self._batchers: list[tuple[str, object]] = []
        self._engines: list[object] = []
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._g_queue = reg.gauge(
            "tdn_batcher_queue_depth",
            "requests waiting in the coalescing queue", labels=("method",),
        )
        self._g_pending_rows = reg.gauge(
            "tdn_batcher_pending_rows",
            "rows waiting in the coalescing queue (the admission-control "
            "watermark ledger; sheds start when this would pass "
            "--max-pending-rows)", labels=("method",),
        )
        self._g_inflight = reg.gauge(
            "tdn_batcher_inflight_rows",
            "rows in the batch currently on the device", labels=("method",),
        )
        self._g_ratio = reg.gauge(
            "tdn_batcher_coalesce_ratio",
            "requests served per device launch (cumulative)",
            labels=("method",),
        )
        self._g_overlap = reg.gauge(
            "tdn_batcher_overlap_ratio",
            "fraction of launches issued while a prior batch was still "
            "materializing (cumulative; > 0 means the double-buffered "
            "pipeline is actually overlapping)",
            labels=("method",),
        )
        self._g_class_pending = reg.gauge(
            "tdn_sched_class_pending_rows",
            "rows waiting in the scheduler queue per SLO class (the "
            "degradation ladder's per-class backlog view; sheds start "
            "at each class's watermark fraction)",
            labels=("method", "slo_class"),
        )
        self._g_rss = reg.gauge(
            "tdn_host_rss_bytes", "resident set size of this process",
        )
        self._g_dev = reg.gauge(
            "tdn_device_memory_bytes",
            "per-device memory from the backend allocator",
            labels=("device", "kind"),
        )
        self._g_ready = reg.gauge(
            "tdn_engine_ready",
            "1 when every registered engine would report ready",
        )
        # Continuous-batching decode (serving/continuous.py): slot
        # residency now, plus the cumulative occupancy ratio — the
        # decode-efficiency figure (1.0 = every step advanced a full
        # slot ladder; low values say --gen-slots is oversized for the
        # offered load).
        self._g_gen_slots = reg.gauge(
            "tdn_gen_slots_active",
            "decode slots currently occupied by a generating request",
        )
        self._g_gen_occ = reg.gauge(
            "tdn_gen_slot_occupancy_ratio",
            "cumulative active-slot-steps / (steps * slots) of the "
            "continuous decode scheduler",
        )
        self._g_prefix_used = reg.gauge(
            "tdn_prefix_cache_blocks_used",
            "prefix-pool blocks currently holding a cached shared "
            "prefix (continuous scheduler; hit/miss/evict counters are "
            "the tdn_prefix_cache_* families)",
        )
        self._gen_scheds: list[object] = []
        # Router replica pools (serving/pool.py): the fleet-state
        # gauges nobody increments — per-replica outstanding requests
        # and the blended load view the placement policy compares.
        self._g_pool_outstanding = reg.gauge(
            "tdn_router_replica_outstanding",
            "requests this router currently has in flight on each "
            "replica (the p2c fallback signal when gauges are stale)",
            labels=("replica",),
        )
        self._g_pool_pending = reg.gauge(
            "tdn_router_replica_pending_rows",
            "last scraped tdn_batcher_pending_rows backlog per replica "
            "(the p2c load signal while fresh)",
            labels=("replica",),
        )
        self._pools: list[object] = []
        # Replica labels written on the previous tick: membership churn
        # (pool.remove) must retire the dead series, not leave phantom
        # last values on /metrics forever.
        self._pool_replicas_seen: set[str] = set()
        # The tracer observing itself: buffer occupancy plus an
        # eviction counter, so "why is my slow request's trace gone"
        # has a scrapeable answer (dropped > 0: raise the buffer or
        # lower the sample rate).
        self._g_trace_buf = reg.gauge(
            "tdn_trace_buffer_spans",
            "completed spans resident in the trace ring buffer",
        )
        self._c_trace_dropped = reg.counter(
            "tdn_trace_spans_dropped_total",
            "spans evicted from the trace ring buffer before export",
        )
        self._tracers: list = []
        # Last dropped_total seen per tracer (by position): counters
        # tick by DELTA at sample time, so the drop path itself stays a
        # plain int increment with no registry work.
        self._trace_dropped_seen: list[float] = []
        # Goodput trackers (ISSUE 14) tick BEFORE the time-series rings
        # collect, so a ring tick records this tick's tdn_mfu_ratio /
        # tdn_pad_ratio values, not last tick's.
        self._goodput: list = []
        # Fleet observability plane (ISSUE 9): time-series rings sample
        # AFTER the gauges above are refreshed (so a ring tick sees
        # this tick's state, not last tick's), and SLO trackers
        # evaluate after the rings (their windows read ring deltas).
        self._timeseries: list = []
        self._slo_trackers: list = []
        # Flight recorders (ISSUE 11) check their detectors LAST in a
        # tick: the rings have collected and the SLO trackers have
        # evaluated, so a detector sees this tick's state.
        self._incident_recorders: list = []
        # Autoscalers (ISSUE 12) tick after the SLO trackers (their
        # burn-rate signal is the tracker's fresh verdict) and BEFORE
        # the incident recorders (an autoscale.flap must be visible to
        # the detector pass of the same tick).
        self._autoscalers: list = []
        # Admission governors (ISSUE 15) tick right after the SLO
        # trackers too: the burn verdict they map to admission
        # pressure is this tick's, and a tightening this tick must be
        # visible to the detector pass.
        self._admission_governors: list = []

    # ------------------------------------------------------------ wiring

    def add_batcher(self, batcher, method: str = "Process") -> None:
        self._batchers.append((method, batcher))

    def add_engine(self, engine) -> None:
        self._engines.append(engine)

    def add_generation_scheduler(self, sched) -> None:
        """Register a continuous decode scheduler for the tdn_gen_*
        slot gauges (its queue/counter families ride :meth:`add_batcher`
        — the scheduler satisfies the batcher attribute contract)."""
        self._gen_scheds.append(sched)

    def add_pool(self, pool) -> None:
        """Register a router :class:`~tpu_dist_nn.serving.pool
        .ReplicaPool` for the per-replica fleet gauges (the pool's own
        scraper refreshes load; this publishes the router-side view —
        tdn_router_replica_healthy is written by the pool itself on
        state transitions, so it is live even without a sampler)."""
        self._pools.append(pool)

    def add_tracer(self, tracer) -> None:
        self._tracers.append(tracer)
        self._trace_dropped_seen.append(float(tracer.dropped_total))

    def add_goodput(self, tracker) -> None:
        """Register a :class:`~tpu_dist_nn.obs.goodput.GoodputTracker`
        whose :meth:`~tpu_dist_nn.obs.goodput.GoodputTracker.tick`
        refreshes the MFU/pad gauges once per tick — before the
        time-series rings collect, so the ring records this tick's
        utilization. The tick is pure ledger math (tick-purity gated by
        tdnlint); peak calibration happened at configure time."""
        self._goodput.append(tracker)

    def add_timeseries(self, ring) -> None:
        """Register a :class:`~tpu_dist_nn.obs.timeseries.TimeSeriesRing`
        to snapshot once per tick (after the gauges refresh)."""
        self._timeseries.append(ring)

    def add_slo_tracker(self, tracker) -> None:
        """Register an :class:`~tpu_dist_nn.obs.slo.SLOTracker` to
        evaluate once per tick (after its ring collected)."""
        self._slo_trackers.append(tracker)

    def add_autoscaler(self, autoscaler) -> None:
        """Register a :class:`~tpu_dist_nn.serving.autoscale.Autoscaler`
        whose control loop evaluates once per tick — after the SLO
        trackers (burn rate is its scale-up signal), before the
        incident recorders (a flap suppression this tick must be seen
        by this tick's detector pass)."""
        self._autoscalers.append(autoscaler)

    def add_admission_governor(self, governor) -> None:
        """Register an :class:`~tpu_dist_nn.serving.sched_core
        .AdmissionGovernor` to tick once per sample, after the SLO
        trackers evaluate (its input is the tracker's fresh fast-burn
        verdict) and before the autoscalers/incident recorders see
        the tick. The tick is pure — it reads the tracker's cached
        status and flips an int on each scheduling core."""
        self._admission_governors.append(governor)

    def add_incident_recorder(self, recorder) -> None:
        """Register a :class:`~tpu_dist_nn.obs.incident.FlightRecorder`
        whose detectors run once per tick, after the rings collected
        and the SLO trackers evaluated — arming the recorder adds ONE
        host-side detector pass per tick to this daemon thread and
        nothing to any request path."""
        self._incident_recorders.append(recorder)

    # ------------------------------------------------------------ loop

    def start(self) -> "RuntimeSampler":
        if self._thread is not None:
            return self
        self._safe_sample()
        self._thread = threading.Thread(
            target=self._run, name="tdn-obs-sampler", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self._interval):
            self._safe_sample()

    def _safe_sample(self) -> None:
        try:
            self.sample_once()
        except Exception:  # noqa: BLE001 — sampling must never kill serving
            log.exception("runtime sample failed")

    def sample_once(self) -> None:
        """One synchronous sample of every source (also used by tests)."""
        for method, b in self._batchers:
            # queue_depth() is the schedulers' lock-free O(1) read;
            # len(_pending) (a full queue copy under the admission
            # lock on the rebased schedulers) stays as the fallback
            # for fakes predating the shared core.
            depth_fn = getattr(b, "queue_depth", None)
            self._g_queue.labels(method=method).set(
                depth_fn() if callable(depth_fn) else len(b._pending)
            )
            self._g_pending_rows.labels(method=method).set(
                getattr(b, "pending_rows", 0)
            )
            self._g_inflight.labels(method=method).set(
                getattr(b, "inflight_rows", 0)
            )
            launches = max(b.batches_total, 1)
            self._g_ratio.labels(method=method).set(
                b.requests_total / launches
            )
            self._g_overlap.labels(method=method).set(
                getattr(b, "overlapped_total", 0) / launches
            )
            by_class = getattr(b, "pending_by_class", None)
            if by_class is not None:
                for cls, rows in by_class().items():
                    self._g_class_pending.labels(
                        method=method, slo_class=cls
                    ).set(rows)
        if self._gen_scheds:
            self._g_gen_slots.set(
                sum(int(s.slots_active) for s in self._gen_scheds)
            )
            steps = sum(
                int(s.steps_total) * int(s.slots) for s in self._gen_scheds
            )
            slot_steps = sum(
                int(s.slot_steps_total) for s in self._gen_scheds
            )
            self._g_gen_occ.set(slot_steps / steps if steps else 0.0)
            self._g_prefix_used.set(
                sum(
                    int(getattr(s, "prefix_blocks_used", 0))
                    for s in self._gen_scheds
                )
            )
        if self._pools:
            seen: set[str] = set()
            for pool in self._pools:
                for snap in pool.snapshot():
                    seen.add(snap["target"])
                    self._g_pool_outstanding.labels(
                        replica=snap["target"]
                    ).set(float(snap["outstanding"]))
                    self._g_pool_pending.labels(replica=snap["target"]).set(
                        float(snap["pending_rows"] or 0.0)
                    )
            for gone in self._pool_replicas_seen - seen:
                self._g_pool_outstanding.remove(replica=gone)
                self._g_pool_pending.remove(replica=gone)
            self._pool_replicas_seen = seen
        if self._engines:
            # (tdn_engine_warm_buckets is NOT sampled here: the engine's
            # warm_buckets method is its single writer — a second writer
            # with aggregate semantics would flap the series between
            # per-engine and summed values.)
            # Engine.is_ready is attribute-only (health()'s probe would
            # launch a device program per sample). All engines must be
            # up: a per-engine overwrite would let the last-registered
            # one mask a dead sibling.
            ready = all(
                bool(getattr(e, "is_ready", False)) for e in self._engines
            )
            self._g_ready.set(1.0 if ready else 0.0)
        if self._tracers:
            self._g_trace_buf.set(
                sum(t.buffer_len() for t in self._tracers)
            )
            for i, t in enumerate(self._tracers):
                now = float(t.dropped_total)
                delta = now - self._trace_dropped_seen[i]
                if delta > 0:
                    self._c_trace_dropped.inc(delta)
                    self._trace_dropped_seen[i] = now
        rss = _read_rss_bytes()
        if rss is not None:
            self._g_rss.set(rss)
        self._sample_devices()
        for tracker in self._goodput:
            tracker.tick()
        for ring in self._timeseries:
            ring.collect()
        for tracker in self._slo_trackers:
            tracker.evaluate()
        for governor in self._admission_governors:
            # Guarded per governor: one broken policy tick must not
            # starve the autoscalers/detectors below of the same tick.
            try:
                governor.tick()
            except Exception:  # noqa: BLE001 — admission must never kill sampling
                log.exception("admission governor tick failed")
        for autoscaler in self._autoscalers:
            # Guarded per autoscaler: one broken policy tick must not
            # starve the incident recorders below of the same tick.
            try:
                autoscaler.tick()
            except Exception:  # noqa: BLE001 — scaling must never kill sampling
                log.exception("autoscaler tick failed")
        for recorder in self._incident_recorders:
            # check() contains its own per-detector/per-capture guards;
            # anything escaping still only costs this tick (the
            # _safe_sample wrapper), never the serving path.
            recorder.check()

    def _sample_devices(self) -> None:
        try:
            import jax

            for d in jax.local_devices():
                stats = getattr(d, "memory_stats", lambda: None)()
                if not stats:
                    continue
                name = f"{d.platform}:{d.id}"
                for kind in ("bytes_in_use", "peak_bytes_in_use",
                             "bytes_limit"):
                    if kind in stats:
                        self._g_dev.labels(device=name, kind=kind).set(
                            stats[kind]
                        )
        except Exception:  # noqa: BLE001 — no backend / no stats: skip quietly
            log.debug("device memory stats unavailable", exc_info=True)
