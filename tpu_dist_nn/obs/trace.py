"""Request-scoped distributed tracing: span recorder + Chrome export.

PR 1's metrics answer "how is the fleet doing" in aggregate; they
cannot answer "where did THIS slow request spend its time" — the exact
question the pipelined batcher raises (queue wait vs. staging vs.
launch vs. device vs. fetch). Following Dapper (Sigelman et al., 2010)
this module records per-request span trees, propagates the trace
context across the gRPC hop in an ``x-tdn-trace`` metadata header, and
exports completed spans in Chrome trace-event JSON — the format
Perfetto / ``chrome://tracing`` load directly, so request spans land
in the same timeline as ``jax.profiler`` device captures.

Design constraints (same discipline as the registry):

* **Stdlib-only** — no numpy, no jax, no protobuf. A span is a tiny
  ``__slots__`` object; recording one is an id draw + a deque append.
* **Head sampling** — the root of a trace decides once
  (``sample_rate``); the decision rides the wire so every process in a
  chain keeps or drops the SAME requests. Rate 0 reduces every hot-path
  call to an id draw and a boolean check (the bench ``--overlap``
  no-regression bar).
* **Bounded memory** — completed spans live in a ring buffer
  (``capacity``); eviction ticks ``dropped_total``. A fixed set of
  *exemplar slots* always keeps the slowest locally-rooted traces seen
  so the worst-case evidence survives any amount of fast traffic.
* **Cross-thread spans** — the serving pipeline starts a span on one
  thread (submit) and finishes it on another (dispatch/drain), so the
  recorder accepts retroactive ``record_span(name, parent, t0, dur)``
  in addition to the ``with``-style live span.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
import uuid

# Wire header carrying the trace context across the gRPC hop
# (lowercase: gRPC metadata keys must be). Value format:
# "<32-hex trace_id>-<16-hex span_id>-<2-digit flags>", flags 01 =
# sampled (a W3C-traceparent-shaped triple without the version field).
TRACE_HEADER = "x-tdn-trace"
# Server -> client trailing metadata naming the server-side trace, so
# a client-side failure can name the exact trace to pull via /trace.
TRACE_ID_HEADER = "x-tdn-trace-id"
# Client -> server remaining-budget hint in milliseconds (the
# grpc-timeout analogue a proxy cannot strip silently): the batcher
# bounds its wait by min(grpc deadline, this hint).
TIMEOUT_HEADER = "x-tdn-timeout-ms"

# Anchor mapping time.monotonic() spans onto the epoch microsecond
# timeline Chrome trace events use: one offset captured at import, so
# every ts in an export shares a consistent (and monotonic) base.
_EPOCH_OFFSET = time.time() - time.monotonic()


def _new_trace_id() -> str:
    return uuid.uuid4().hex  # 32 hex chars


def _new_span_id() -> str:
    return os.urandom(8).hex()  # 16 hex chars


_HEX_DIGITS = frozenset("0123456789abcdefABCDEF")


def _is_hex(s: str) -> bool:
    # Strict bare-hex: int(s, 16) would tolerate '0x' prefixes,
    # underscores, and signs — ids must be canonical hex or rejected.
    return bool(s) and all(c in _HEX_DIGITS for c in s)


class SpanContext:
    """The propagatable identity of a span: what crosses the wire."""

    __slots__ = ("trace_id", "span_id", "sampled", "remote")

    def __init__(self, trace_id: str, span_id: str, sampled: bool,
                 remote: bool = False):
        self.trace_id = trace_id
        self.span_id = span_id
        self.sampled = sampled
        self.remote = remote

    def header(self) -> str:
        return f"{self.trace_id}-{self.span_id}-" \
               f"{'01' if self.sampled else '00'}"

    @classmethod
    def from_header(cls, value: str | None) -> "SpanContext | None":
        """Parse an ``x-tdn-trace`` value; None on anything malformed
        (a bad header must degrade to local sampling, never abort the
        RPC that carried it)."""
        if not value:
            return None
        parts = value.strip().split("-")
        if len(parts) != 3:
            return None
        tid, sid, flags = parts
        if len(tid) != 32 or len(sid) != 16 or len(flags) != 2:
            return None
        if not (_is_hex(tid) and _is_hex(sid) and _is_hex(flags)):
            return None
        return cls(tid, sid, sampled=bool(int(flags, 16) & 1), remote=True)


class Span:
    """One recorded operation. Live spans are created by
    :meth:`Tracer.start` / :meth:`Tracer.span` and closed by ``end()``
    (or the ``with`` block); ``annotate()`` adds timestamped notes that
    export as instant events inside the span."""

    __slots__ = ("_tracer", "name", "trace_id", "span_id", "parent_id",
                 "parent_remote", "t0", "dur", "tid", "tname", "attrs",
                 "annotations", "_ended", "seq")

    def __init__(self, tracer, name, trace_id, span_id, parent_id,
                 parent_remote, t0, attrs=None):
        self._tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.parent_remote = parent_remote
        self.t0 = t0
        self.dur = None
        th = threading.current_thread()
        self.tid = th.ident or 0
        self.tname = th.name
        self.attrs = dict(attrs) if attrs else {}
        self.annotations: list[tuple[float, str]] = []
        self._ended = False
        # Completion sequence number, assigned by the tracer at finish
        # time: the /trace?since= cursor (0 = not yet finished).
        self.seq = 0

    @property
    def sampled(self) -> bool:
        return True

    @property
    def ctx(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id, sampled=True)

    def annotate(self, text: str) -> None:
        self.annotations.append((time.monotonic(), text))

    def set(self, key: str, value) -> None:
        self.attrs[key] = value

    def end(self) -> None:
        if self._ended:  # idempotent: finally blocks + with blocks mix
            return
        self._ended = True
        self.dur = time.monotonic() - self.t0
        self._tracer._finish(self)

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, *exc) -> None:
        self.end()


class _NoopSpan:
    """The unsampled span: carries real ids (so the not-sampled
    decision propagates coherently downstream and trailing metadata can
    still name the trace) but records nothing."""

    __slots__ = ("ctx",)

    def __init__(self, ctx: SpanContext):
        self.ctx = ctx

    @property
    def sampled(self) -> bool:
        return False

    def annotate(self, text: str) -> None:
        pass

    def set(self, key: str, value) -> None:
        pass

    def end(self) -> None:
        pass

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> None:
        pass


# Ambient span/sink for annotation attachment (utils like the engine
# annotate "whatever request is active on this thread" without
# threading a context through every signature). threading.local, not
# contextvars: the serving pipeline is plain threads.
_ACTIVE = threading.local()


def active() -> bool:
    """True when an annotation would land somewhere — guard any
    f-string formatting behind this so rate-0 paths pay nothing."""
    return getattr(_ACTIVE, "span", None) is not None or \
        getattr(_ACTIVE, "sink", None) is not None


def annotate(text: str) -> None:
    """Attach a timestamped note to the thread's active span (or
    collection sink); silently a no-op when tracing is off."""
    span = getattr(_ACTIVE, "span", None)
    if span is not None:
        span.annotate(text)
        return
    sink = getattr(_ACTIVE, "sink", None)
    if sink is not None:
        sink.append((time.monotonic(), text))


class _Activation:
    """``with tracer.activate(span):`` — the thread's ambient span for
    the duration (annotations from called code attach to it)."""

    __slots__ = ("_span", "_prev")

    def __init__(self, span):
        self._span = span

    def __enter__(self):
        self._prev = getattr(_ACTIVE, "span", None)
        _ACTIVE.span = self._span if getattr(
            self._span, "sampled", False
        ) else None
        return self._span

    def __exit__(self, *exc):
        _ACTIVE.span = self._prev


class _AnnotationSink:
    """``with annotation_sink() as notes:`` — collect annotations from
    called code into a plain list, for retroactive spans that do not
    exist yet while the work runs (the batcher's per-batch launch,
    recorded per-request afterwards)."""

    __slots__ = ("_notes", "_prev")

    def __enter__(self) -> list:
        self._notes: list[tuple[float, str]] = []
        self._prev = getattr(_ACTIVE, "sink", None)
        _ACTIVE.sink = self._notes
        return self._notes

    def __exit__(self, *exc):
        _ACTIVE.sink = self._prev


def annotation_sink() -> _AnnotationSink:
    return _AnnotationSink()


def _env_sample_rate() -> float:
    """TDN_TRACE_SAMPLE_RATE, parsed defensively: the process-wide
    TRACER is constructed at import time, so a garbled or out-of-range
    value must degrade to the default with a visible warning — it must
    NOT take down every ``tdn`` command with a float() traceback."""
    raw = os.environ.get("TDN_TRACE_SAMPLE_RATE")
    if raw is None:
        return 1.0
    try:
        rate = float(raw)
    except ValueError:
        rate = -1.0
    if not 0.0 <= rate <= 1.0:
        import logging

        logging.getLogger(__name__).warning(
            "TDN_TRACE_SAMPLE_RATE=%r is not a number in [0, 1]; "
            "tracing at the default rate 1.0", raw,
        )
        return 1.0
    return rate


class Tracer:
    """Span recorder: head sampling, bounded ring buffer, slowest-trace
    exemplar slots, Chrome trace-event export."""

    def __init__(self, capacity: int = 4096, sample_rate: float | None = None,
                 exemplar_slots: int = 4):
        if sample_rate is None:
            sample_rate = _env_sample_rate()
        self.configure(sample_rate=sample_rate)
        self._capacity = int(capacity)
        self._lock = threading.Lock()
        # ring: index _head is the oldest entry
        self._buf: list[Span] = []  # guarded-by: _lock
        self._head = 0  # guarded-by: _lock
        self._exemplar_slots = int(exemplar_slots)
        # [(dur, trace_id, [spans of the whole trace])] — the slowest
        # locally-rooted traces ever seen, immune to ring eviction; at
        # most one slot per trace id (a loopback client root and its
        # wire-joined handler must not burn two slots on one trace).
        self._exemplars: list[tuple[float, str, list[Span]]] = []  # guarded-by: _lock
        self.dropped_total = 0  # guarded-by: _lock
        # Monotonic completion counter: every finished span gets the
        # next value, and /trace?since=N returns only spans with
        # seq > N — an incremental poller re-downloads nothing. Never
        # reset (a cursor must stay monotonic for the process life).
        self.seq = 0

    # ------------------------------------------------------------ config

    def configure(self, sample_rate: float) -> None:
        rate = float(sample_rate)
        if not 0.0 <= rate <= 1.0:
            raise ValueError(f"sample_rate must be in [0, 1], got {rate}")
        self.sample_rate = rate

    def reset(self) -> None:
        """Drop recorded state (tests); configuration survives."""
        with self._lock:
            self._buf = []
            self._head = 0
            self._exemplars = []
            self.dropped_total = 0

    # ------------------------------------------------------------ record

    def start(self, name: str, parent: SpanContext | None = None,
              attrs=None) -> "Span | _NoopSpan":
        """Begin a span. No ``parent``: a new trace whose sampling this
        tracer decides (head sampling). With a ``parent`` (local or
        parsed off the wire): the parent's trace id AND sampling
        decision are inherited — one decision per trace, everywhere.

        Exception: rate 0 is this PROCESS's kill switch. A remote
        caller's sampled flag is a request, not a mandate — honoring it
        at rate 0 would let any stock client (whose own tracer defaults
        to 1.0) force recording onto a server that explicitly disabled
        it, handing clients control of server memory and lock traffic.
        Ids still propagate so the chain stays coherent downstream.
        """
        if parent is None:
            sampled = self.sample_rate > 0.0 and \
                random.random() < self.sample_rate
            trace_id = _new_trace_id()
            parent_id = None
            parent_remote = False
        else:
            sampled = parent.sampled and self.sample_rate > 0.0
            trace_id = parent.trace_id
            parent_id = parent.span_id
            parent_remote = parent.remote
        span_id = _new_span_id()
        if not sampled:
            return _NoopSpan(SpanContext(trace_id, span_id, sampled=False))
        return Span(self, name, trace_id, span_id, parent_id, parent_remote,
                    time.monotonic(), attrs)

    def span(self, name: str, parent: SpanContext, attrs=None):
        """Child-span shorthand for ``with`` blocks."""
        return self.start(name, parent=parent, attrs=attrs)

    def activate(self, span) -> _Activation:
        return _Activation(span)

    def record_span(self, name: str, parent: SpanContext | None,
                    t0: float, dur: float, attrs=None,
                    annotations=None) -> Span | None:
        """Record an already-measured span retroactively — the
        cross-thread form (start time observed on one thread, completion
        on another). ``t0``/``dur`` are ``time.monotonic()`` values."""
        if parent is None or not parent.sampled:
            return None
        sp = Span(self, name, parent.trace_id, _new_span_id(),
                  parent.span_id, parent.remote, t0, attrs)
        if annotations:
            sp.annotations.extend(annotations)
        sp._ended = True
        sp.dur = float(dur)
        self._finish(sp)
        return sp

    def _finish(self, span: Span) -> None:
        buf_copy = None
        with self._lock:
            self.seq += 1
            span.seq = self.seq
            if len(self._buf) < self._capacity:
                self._buf.append(span)
            else:
                # Ring overwrite: the oldest span falls out.
                self._buf[self._head] = span
                self._head = (self._head + 1) % self._capacity
                self.dropped_total += 1
            # A locally-rooted span completing is the moment the whole
            # trace is known (children end before their root): consider
            # it for an exemplar slot. Only the cheap qualification
            # check and a C-level list copy run under the lock — the
            # O(buffer) trace_id scan happens outside it, so other
            # threads' span completion never serializes behind it.
            if (
                (span.parent_id is None or span.parent_remote)
                and self._exemplar_slots > 0
                and self._qualifies_locked(span.dur or 0.0)
            ):
                buf_copy = list(self._buf)
        if buf_copy is not None:
            self._keep_exemplar(span, buf_copy)

    def _qualifies_locked(self, dur: float) -> bool:  # caller-holds: _lock
        return (
            len(self._exemplars) < self._exemplar_slots
            or dur > min(d for d, _, _ in self._exemplars)
        )

    def _keep_exemplar(self, root: Span, buf_copy: list[Span]) -> None:
        """Keep the slowest locally-rooted traces whole, outside the
        ring (lock NOT held during the scan). Re-checks qualification
        under the lock before inserting: a concurrent slower root may
        have taken the slot while we scanned. One slot per trace id —
        a same-process client root and its wire-joined handler span
        replace (never duplicate) each other's entry, keeping the
        slot's span list the outermost/fullest capture."""
        dur = root.dur or 0.0
        trace = [s for s in buf_copy if s.trace_id == root.trace_id]
        with self._lock:
            for i, (d, tid, _) in enumerate(self._exemplars):
                if tid == root.trace_id:
                    if dur > d:
                        self._exemplars[i] = (dur, tid, trace)
                        self._exemplars.sort(
                            key=lambda e: e[0], reverse=True
                        )
                    return
            if not self._qualifies_locked(dur):
                return
            self._exemplars.append((dur, root.trace_id, trace))
            self._exemplars.sort(key=lambda e: e[0], reverse=True)
            del self._exemplars[self._exemplar_slots:]

    # ------------------------------------------------------------ export

    def snapshot(self, limit: int | None = None,
                 trace_id: str | None = None,
                 since: int | None = None) -> list[Span]:
        """Completed spans, oldest first: the ring's last ``limit``
        spans (all when None) plus every exemplar-trace span not
        already present. ``trace_id`` keeps only that trace — the
        "pull one slow exemplar without dumping the whole ring" path
        (the filter applies AFTER the limit window, so an explicit id
        is never crowded out of an unlimited pull by later traffic).
        ``since`` keeps only spans that FINISHED after that cursor
        value (:attr:`seq`) — the incremental-poll form; exemplar
        extras obey it too, so a poller is never re-sent the same
        slow trace every tick."""
        with self._lock:
            spans = self._buf[self._head:] + self._buf[:self._head]
            if limit is not None and limit >= 0:
                spans = spans[-limit:] if limit else []
            seen = {id(s) for s in spans}
            extra = [
                s for _, _, tr in self._exemplars for s in tr
                if id(s) not in seen
            ]
        out = extra + spans
        if trace_id is not None:
            out = [s for s in out if s.trace_id == trace_id]
        if since is not None:
            out = [s for s in out if s.seq > since]
        return out

    def buffer_len(self) -> int:
        with self._lock:
            return len(self._buf)

    def chrome_trace(self, limit: int | None = None,
                     trace_id: str | None = None,
                     since: int | None = None) -> dict:
        """The buffer as a Chrome trace-event JSON object —
        ``json.dump`` it and open in Perfetto / ``chrome://tracing``.
        Spans become complete (``ph: "X"``) events with epoch-anchored
        microsecond ``ts``, annotations become thread-scoped instant
        (``ph: "i"``) events, and thread names come along as metadata
        so the serving pipeline's stages are labelled tracks.
        ``trace_id`` exports just that trace (``/trace?trace_id=``);
        ``since`` exports only spans finished after that cursor. The
        document carries a top-level ``cursor`` (the newest completion
        sequence number) to pass back as the next ``since`` — an extra
        key Perfetto ignores."""
        # Cursor read BEFORE the snapshot: a span finishing in between
        # is then re-sent on the next poll (pollers dedupe by span_id)
        # rather than silently skipped forever.
        with self._lock:
            cursor = self.seq
        spans = self.snapshot(limit, trace_id=trace_id, since=since)
        events: list[dict] = []
        pid = os.getpid()
        threads: dict[int, str] = {}
        for s in spans:
            ts = (s.t0 + _EPOCH_OFFSET) * 1e6
            args = {"trace_id": s.trace_id, "span_id": s.span_id}
            if s.parent_id is not None:
                args["parent_id"] = s.parent_id
            for k, v in s.attrs.items():
                args[str(k)] = v
            events.append({
                "ph": "X", "cat": "tdn", "name": s.name,
                "ts": ts, "dur": (s.dur or 0.0) * 1e6,
                "pid": pid, "tid": s.tid, "args": args,
            })
            threads.setdefault(s.tid, s.tname)
            for (at, text) in s.annotations:
                events.append({
                    "ph": "i", "cat": "tdn", "name": text, "s": "t",
                    "ts": (at + _EPOCH_OFFSET) * 1e6,
                    "pid": pid, "tid": s.tid,
                    "args": {"trace_id": s.trace_id, "span_id": s.span_id},
                })
        # Monotonic ts within (and across) tracks: sorted globally.
        events.sort(key=lambda e: e["ts"])
        meta = [{
            "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
            "args": {"name": f"tdn[{pid}]"},
        }]
        for tid, tname in sorted(threads.items()):
            meta.append({
                "ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
                "args": {"name": tname},
            })
        return {"traceEvents": meta + events, "displayTimeUnit": "ms",
                "cursor": cursor}

    def render_json(self, limit: int | None = None,
                    trace_id: str | None = None,
                    since: int | None = None) -> str:
        return json.dumps(self.chrome_trace(limit, trace_id=trace_id,
                                            since=since))


# The process-wide tracer every built-in instrumentation site records
# into and the ``/trace`` route exports from (mirrors REGISTRY).
TRACER = Tracer()
