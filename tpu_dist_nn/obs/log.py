"""Structured JSON logging: trace-correlated, rate-limited, stdlib-only.

The serving path's operational events (server start, shed storms,
client retry exhaustion, decode-step faults, int8 regressions) were
free-form ``%``-formatted strings — grep-able by a human, opaque to a
log pipeline, and unbounded under a fault storm. This module is the
structured channel the serving/engine modules log through:

* **One event, many fields.** ``slog.info("server.start", port=5101,
  method="Process")`` — the event name is a stable key (dashboards and
  alerts match on it), the fields are data, not prose.
* **Trace correlation.** When the calling thread has an active sampled
  span (:func:`tpu_dist_nn.obs.trace.annotate`'s ambient span), its
  ``trace_id``/``span_id`` are stamped onto the record automatically —
  a log line and the ``/trace`` span tree name each other.
* **Rate limiting.** A token bucket per ``(logger, event)``: a fault
  storm logs its first ``burst`` occurrences then ``rate`` per second,
  and the next emitted record carries ``suppressed=N`` so the gap is
  visible instead of silent. Events that fire once (startup) are never
  affected.
* **Bounded in-memory ring.** Every record that passes the limiter
  also lands in the process-wide :data:`LOG_RING` (default 4096
  records), so the recent log tail is queryable AFTER the fact —
  ``GET /logs?window=S&level=L`` on the metrics endpoint, and the
  flight recorder (:mod:`tpu_dist_nn.obs.incident`) freezes it into
  every diagnostic bundle. stderr never leaves the box; the ring does.
* **Readable either way.** Through the default CLI handler a record
  renders ``event key=value ...``; with :func:`setup_json_logging`
  (``tdn --log-json`` / ``TDN_LOG_JSON=1``) the same record renders as
  one JSON object per line.

Stdlib-only (``json`` + ``logging`` + ``threading``), no handler is
installed implicitly: importing this module never changes process-wide
logging config.
"""

from __future__ import annotations

import json
import logging
import threading
import time

# Record attributes the structured path sets; JsonFormatter reads them.
_EVENT_ATTR = "tdn_event"
_FIELDS_ATTR = "tdn_fields"

# Keys the formatter owns; a field with one of these names is nested
# under "fields" instead of silently clobbering the envelope.
_RESERVED = frozenset(("ts", "level", "logger", "event", "exc"))


def current_trace_ids() -> tuple[str, str] | None:
    """(trace_id, span_id) of the calling thread's active sampled span,
    or None — the correlation hook (reads the tracer's ambient slot,
    never records anything)."""
    from tpu_dist_nn.obs import trace as _trace

    span = getattr(_trace._ACTIVE, "span", None)
    if span is not None and getattr(span, "sampled", False):
        return span.trace_id, span.span_id
    return None


class JsonFormatter(logging.Formatter):
    """One JSON object per line. Structured records (emitted through
    :class:`StructuredLogger`) keep their event/fields; plain records
    from any other logger in the process degrade to ``{"event":
    <message>}`` so a mixed stream stays machine-parseable."""

    def format(self, record: logging.LogRecord) -> str:
        doc: dict = {
            "ts": round(record.created, 6),
            "level": record.levelname.lower(),
            "logger": record.name,
            "event": getattr(record, _EVENT_ATTR, None)
            or record.getMessage(),
        }
        fields = getattr(record, _FIELDS_ATTR, None)
        if fields:
            for k, v in fields.items():
                if k in _RESERVED:
                    doc.setdefault("fields", {})[k] = v
                else:
                    doc[k] = v
        if record.exc_info:
            doc["exc"] = self.formatException(record.exc_info)
        return json.dumps(doc, default=repr)


class _TokenBucket:
    """Per-key token bucket; also counts what it suppressed so the
    next allowed record can report the gap."""

    __slots__ = ("_rate", "_burst", "_lock", "_state")

    def __init__(self, rate: float, burst: int):
        self._rate = float(rate)
        self._burst = float(burst)
        self._lock = threading.Lock()
        # key -> [tokens, last_refill, suppressed_since_last_emit]
        # guarded-by: _lock
        self._state: dict = {}

    def allow(self, key, now: float | None = None) -> tuple[bool, int]:
        """-> (allowed, suppressed_count_to_report)."""
        t = time.monotonic() if now is None else now
        with self._lock:
            st = self._state.get(key)
            if st is None:
                st = self._state[key] = [self._burst, t, 0]
            tokens, last, suppressed = st
            tokens = min(self._burst, tokens + (t - last) * self._rate)
            if tokens >= 1.0:
                st[0] = tokens - 1.0
                st[1] = t
                st[2] = 0
                return True, suppressed
            st[0] = tokens
            st[1] = t
            st[2] = suppressed + 1
            return False, 0


class LogRing:
    """Bounded ring of structured log records (plain dicts), the
    queryable tail behind ``GET /logs`` and the incident bundles.

    Appends sit BEHIND the rate limiter (a storm costs the ring its
    ``rate``-per-second trickle, not one append per suppressed call)
    and are one dict build + deque append under a lock — cheap enough
    for every emitted record. ``dropped_total`` counts ring evictions
    so "the window you wanted is gone" is visible, the tracer-ring
    convention."""

    __slots__ = ("_buf", "_lock", "capacity", "dropped_total")

    def __init__(self, capacity: int = 4096):
        import collections

        self.capacity = int(capacity)
        # guarded-by: _lock
        self._buf: "collections.deque" = collections.deque(
            maxlen=self.capacity
        )
        self._lock = threading.Lock()
        self.dropped_total = 0  # guarded-by: _lock

    def append(self, record: dict) -> None:
        with self._lock:
            if len(self._buf) == self.capacity:
                self.dropped_total += 1
            self._buf.append(record)

    def __len__(self) -> int:
        with self._lock:
            return len(self._buf)

    def snapshot(self, window: float | None = None,
                 level: str | None = None,
                 limit: int | None = None) -> list[dict]:
        """Records oldest-first; ``window`` keeps the last S seconds,
        ``level`` is a MINIMUM severity name (``warning`` returns
        warnings and errors), ``limit`` keeps the newest N after the
        filters."""
        cutoff = None if window is None else time.time() - float(window)
        floor = None
        if level is not None:
            floor = logging.getLevelName(str(level).upper())
            if not isinstance(floor, int):
                raise ValueError(f"unknown log level {level!r}")
        with self._lock:
            records = list(self._buf)
        out = [
            r for r in records
            if (cutoff is None or r.get("ts", 0.0) >= cutoff)
            and (floor is None
                 or logging.getLevelName(
                     str(r.get("level", "info")).upper()
                 ) >= floor)
        ]
        if limit is not None and limit >= 0:
            out = out[-limit:] if limit else []
        return out

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()
            self.dropped_total = 0


# The process-wide ring every StructuredLogger feeds (mirrors REGISTRY
# and TRACER: one per process, served by the metrics endpoint).
LOG_RING = LogRing()


class StructuredLogger:
    """Event-shaped logging facade over one stdlib logger.

    ``info/warning/error/debug(event, **fields)`` and
    ``exception(event, **fields)`` (which attaches the active
    exception). The plain-handler rendering is ``event key=value ...``;
    under :class:`JsonFormatter` the record is a JSON object.
    """

    def __init__(self, logger: logging.Logger, limiter: _TokenBucket):
        self._logger = logger
        self._limiter = limiter

    def _log(self, level: int, event: str, exc_info=False, /,
             **fields) -> None:
        # Positional-only parameters: a caller's field legitimately
        # named `level` or `event` must land in **fields, not collide.
        if not self._logger.isEnabledFor(level):
            return
        allowed, suppressed = self._limiter.allow((self._logger.name, event))
        if not allowed:
            return
        ids = current_trace_ids()
        if ids is not None:
            fields.setdefault("trace_id", ids[0])
            fields.setdefault("span_id", ids[1])
        if suppressed:
            fields["suppressed"] = suppressed
        LOG_RING.append({
            "ts": time.time(),
            "level": logging.getLevelName(level).lower(),
            "logger": self._logger.name,
            "event": event,
            "fields": dict(fields),
        })
        msg = event + "".join(
            f" {k}={self._render(v)}" for k, v in fields.items()
        )
        self._logger.log(
            level, msg, exc_info=exc_info,
            extra={_EVENT_ATTR: event, _FIELDS_ATTR: fields},
        )

    @staticmethod
    def _render(v) -> str:
        if isinstance(v, float):
            return f"{v:.6g}"
        s = str(v)
        return repr(s) if " " in s else s

    def debug(self, event: str, **fields) -> None:
        self._log(logging.DEBUG, event, **fields)

    def info(self, event: str, **fields) -> None:
        self._log(logging.INFO, event, **fields)

    def warning(self, event: str, **fields) -> None:
        self._log(logging.WARNING, event, **fields)

    def error(self, event: str, **fields) -> None:
        self._log(logging.ERROR, event, **fields)

    def exception(self, event: str, **fields) -> None:
        self._log(logging.ERROR, event, True, **fields)


def get_logger(name: str, *, rate: float = 1.0,
               burst: int = 10) -> StructuredLogger:
    """The structured logger for ``name`` (wraps
    ``logging.getLogger(name)``; level/handler config stays the stdlib
    logger's). ``rate``/``burst`` shape the per-event token bucket —
    the defaults allow 10 back-to-back occurrences of one event, then
    1/s, with the suppressed count surfacing on the next emission."""
    return StructuredLogger(logging.getLogger(name), _TokenBucket(rate, burst))


def setup_json_logging(level: int | None = None, stream=None) -> None:
    """Install :class:`JsonFormatter` on the root logger (replacing its
    handlers — the ``tdn --log-json`` switch). Every logger in the
    process then emits one JSON object per line, structured or not."""
    handler = logging.StreamHandler(stream)
    handler.setFormatter(JsonFormatter())
    root = logging.getLogger()
    root.handlers[:] = [handler]
    if level is not None:
        root.setLevel(level)
