"""Process-wide metric registry: Counter, Gauge, Histogram.

Design constraints (the serving/training hot paths publish here):

* **Dependency-free** — stdlib only; the container has no
  prometheus_client and must not grow one.
* **Lock-cheap** — one ``threading.Lock`` per family, held only for a
  dict lookup + float add. No allocation on the repeat-update path:
  ``labels(...)`` returns a cached child whose update methods touch
  pre-bound slots.
* **Host-side only** — values are python floats; updating a metric
  never touches a jax array (a device fetch on the batcher thread
  would serialize the launch pipeline — the exact failure the r4
  forensics rules exist to catch).

Get-or-create semantics: asking the registry for an existing family
name returns the SAME family (so module-level instrumentation in
server/engine/trainer modules converges on one set of series), and
asking with a conflicting kind or label schema raises — a typo must
not silently fork a second family.
"""

from __future__ import annotations

import bisect
import threading

# Latency-shaped default: sub-ms serving spans up to multi-second
# compile/step outliers. "+Inf" is implicit (rendered by exposition).
DEFAULT_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
)

# Row-count-shaped buckets: the batcher pads coalesced batches to
# powers of two, so bucket edges ON the powers make the histogram an
# exact per-bucket launch count.
POW2_BUCKETS = tuple(float(1 << i) for i in range(17))  # 1 .. 65536


def histogram_quantile(buckets, counts, q: float) -> float | None:
    """Prometheus-style quantile estimate from bucketed counts.

    ``buckets`` are the finite upper edges, ``counts`` the PER-BUCKET
    (not cumulative) observation counts with one extra entry for the
    implicit +Inf bucket — exactly a ``_Child``'s ``counts`` layout, and
    what scrape-side cumulative ``le`` series differentiate back to.

    Linear interpolation inside the containing bucket (lower edge 0 for
    the first bucket — these are latency/row-count shaped families, all
    non-negative); an estimate landing in the +Inf bucket clamps to the
    highest finite edge, same as ``histogram_quantile()`` in PromQL.
    Returns None when the histogram is empty. Shared by the SLO
    evaluator, ``tdn top``, and the scrape-side helper in
    :mod:`tpu_dist_nn.obs.exposition` so the estimate cannot drift
    between the in-process and fleet views.
    """
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"quantile must be in [0, 1], got {q}")
    total = sum(counts)
    if total <= 0:
        return None
    rank = q * total
    cum = 0.0
    for i, n in enumerate(counts):
        if n <= 0:
            continue
        if cum + n >= rank:
            if i >= len(buckets):  # +Inf bucket: clamp to top edge
                return float(buckets[-1]) if buckets else 0.0
            lo = float(buckets[i - 1]) if i > 0 else 0.0
            hi = float(buckets[i])
            frac = (rank - cum) / n
            return lo + (hi - lo) * max(0.0, min(1.0, frac))
        cum += n
    return float(buckets[-1]) if buckets else 0.0


class _Child:
    """One labeled series. Value semantics depend on the family kind."""

    __slots__ = ("kind", "value", "sum", "counts", "_buckets", "_lock")

    def __init__(self, kind, buckets, lock):
        self.kind = kind
        self.value = 0.0  # guarded-by: _lock
        self.sum = 0.0  # guarded-by: _lock
        self._buckets = buckets
        self._lock = lock
        # guarded-by: _lock
        self.counts = [0] * (len(buckets) + 1) if buckets is not None else None

    def _expect(self, *kinds) -> None:
        if self.kind not in kinds:
            raise ValueError(f"operation not valid for a {self.kind}")

    # -- counter / gauge ------------------------------------------------
    def inc(self, amount: float = 1.0) -> None:
        self._expect("counter", "gauge")
        if self.kind == "counter" and amount < 0:
            raise ValueError(f"counter increment must be >= 0, got {amount}")
        with self._lock:
            self.value += amount

    def dec(self, amount: float = 1.0) -> None:
        self._expect("gauge")
        with self._lock:
            self.value -= amount

    def set(self, value: float) -> None:
        self._expect("gauge")
        with self._lock:
            self.value = float(value)

    # -- histogram ------------------------------------------------------
    def observe(self, value: float) -> None:
        self._expect("histogram")
        v = float(value)
        i = bisect.bisect_left(self._buckets, v)
        with self._lock:
            self.counts[i] += 1
            self.sum += v
            self.value += 1  # total count

    def quantile(self, q: float) -> float | None:
        """Bucket-interpolated quantile estimate of everything this
        series has observed (None while empty); the error bound is the
        containing bucket's width — see :func:`histogram_quantile`."""
        self._expect("histogram")
        with self._lock:
            counts = list(self.counts)
        return histogram_quantile(self._buckets, counts, q)


class Metric:
    """One metric family: a name, a kind, a label schema, N children."""

    def __init__(self, name: str, help: str, kind: str,
                 labelnames: tuple = (), buckets=None):
        _validate_name(name)
        for ln in labelnames:
            _validate_name(ln)
        self.name = name
        self.help = help
        self.kind = kind
        self.labelnames = tuple(labelnames)
        self.buckets = tuple(buckets) if buckets is not None else None
        if kind == "histogram" and self.buckets is None:
            self.buckets = DEFAULT_BUCKETS
        if self.buckets is not None and list(self.buckets) != sorted(
            set(self.buckets)
        ):
            raise ValueError(
                f"{name}: buckets must be strictly increasing, got "
                f"{self.buckets}"
            )
        self._lock = threading.Lock()
        self._children: dict[tuple, _Child] = {}  # guarded-by: _lock
        if not self.labelnames:
            # Unlabeled families materialize at 0 immediately: an
            # error-class counter born at its first increment is
            # invisible to rate()/increase() alerts for exactly the
            # event that mattered (labeled children stay lazy — the
            # label space is open-ended).
            self._children[()] = _Child(self.kind, self.buckets, self._lock)

    def labels(self, **labels) -> _Child:
        """The child series for this label-value assignment (cached)."""
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, got "
                f"{tuple(labels)}"
            )
        key = tuple(str(labels[ln]) for ln in self.labelnames)
        # Lock-free fast path for the repeat-update case (benign race:
        # a miss falls through to the locked setdefault, which
        # arbitrates; dict reads are atomic under the GIL).
        child = self._children.get(key)  # tdnlint: disable=lock-discipline
        if child is None:
            with self._lock:
                child = self._children.setdefault(
                    key, _Child(self.kind, self.buckets, self._lock)
                )
        return child

    def remove(self, **labels) -> None:
        """Drop one child series — for label values that have left the
        system (a removed pool replica): without this the label set
        only ever grows, and the dead series keeps exposing its stale
        last value. No-op when the child never existed."""
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, got "
                f"{tuple(labels)}"
            )
        key = tuple(str(labels[ln]) for ln in self.labelnames)
        with self._lock:
            self._children.pop(key, None)

    def remove_matching(self, **labels) -> None:
        """Drop every child series whose values match the given SUBSET
        of labels — e.g. all ``outcome`` series of one departed
        ``replica`` — where :meth:`remove` needs the full label set."""
        unknown = set(labels) - set(self.labelnames)
        if unknown:
            raise ValueError(
                f"{self.name}: unknown labels {tuple(sorted(unknown))}; "
                f"has {self.labelnames}"
            )
        idx = {ln: i for i, ln in enumerate(self.labelnames)}
        want = {idx[ln]: str(v) for ln, v in labels.items()}
        with self._lock:
            for key in [k for k in self._children
                        if all(k[i] == v for i, v in want.items())]:
                self._children.pop(key, None)

    # Unlabeled convenience: metric.inc() == metric.labels().inc().
    def _default(self) -> _Child:
        if self.labelnames:
            raise ValueError(
                f"{self.name} has labels {self.labelnames}; use "
                ".labels(...)"
            )
        return self.labels()

    def inc(self, amount: float = 1.0) -> None:
        self._default().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._default().dec(amount)

    def set(self, value: float) -> None:
        self._default().set(value)

    def observe(self, value: float) -> None:
        self._default().observe(value)

    def quantile(self, q: float, **labels) -> float | None:
        """Quantile estimate for one labeled series (the unlabeled one
        when no labels are given) — does NOT create the child, so
        probing a series that never observed returns None instead of
        materializing an empty one."""
        if self.kind != "histogram":
            raise ValueError(f"quantile() not valid for a {self.kind}")
        key = tuple(str(labels.get(ln)) for ln in self.labelnames)
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, got "
                f"{tuple(labels)}"
            )
        with self._lock:
            child = self._children.get(key)
        return child.quantile(q) if child is not None else None

    def samples(self):
        """-> [(label_values_tuple, child)] snapshot for exposition."""
        with self._lock:
            return list(self._children.items())


def _validate_name(name: str) -> None:
    import re

    if not re.fullmatch(r"[a-zA-Z_:][a-zA-Z0-9_:]*", name):
        raise ValueError(f"invalid metric/label name: {name!r}")


class Registry:
    """Name -> Metric map with get-or-create family factories."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, Metric] = {}  # guarded-by: _lock

    def _get_or_create(self, name, help, kind, labelnames, buckets=None):
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if existing.kind != kind or existing.labelnames != tuple(
                    labelnames
                ):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{existing.kind}{existing.labelnames}; requested "
                        f"{kind}{tuple(labelnames)}"
                    )
                if buckets is not None and tuple(buckets) != existing.buckets:
                    # Silently keeping the first schema would bucket the
                    # caller's observations on edges it never asked for.
                    raise ValueError(
                        f"metric {name!r} already registered with buckets "
                        f"{existing.buckets}; requested {tuple(buckets)}"
                    )
                return existing
            m = Metric(name, help, kind, labelnames, buckets)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "", labels: tuple = ()) -> Metric:
        return self._get_or_create(name, help, "counter", labels)

    def gauge(self, name: str, help: str = "", labels: tuple = ()) -> Metric:
        return self._get_or_create(name, help, "gauge", labels)

    def histogram(self, name: str, help: str = "", labels: tuple = (),
                  buckets=None) -> Metric:
        return self._get_or_create(name, help, "histogram", labels, buckets)

    def collect(self) -> list[Metric]:
        with self._lock:
            return list(self._metrics.values())

    def get(self, name: str) -> Metric | None:
        with self._lock:
            return self._metrics.get(name)

    def reset(self) -> None:
        """Drop every family — test isolation only; production callers
        hold Metric references that would silently detach."""
        with self._lock:
            self._metrics.clear()


# The process-wide registry every built-in instrumentation site
# publishes into and ``/metrics`` renders from.
REGISTRY = Registry()


def bridge_latency_stats(stats, name: str | None = None,
                         registry: Registry | None = None,
                         buckets=None, **labels):
    """Teach an existing :class:`~tpu_dist_nn.utils.profiling.LatencyStats`
    to ALSO feed a registry histogram — current callers (``summary()``,
    ``percentile()``, ``step_latency``) keep working unchanged, and
    every span they record from now on lands in
    ``{name}`` (default ``tdn_<stats.name>_seconds``).

    Returns ``stats`` (for chaining at construction sites).
    """
    reg = registry if registry is not None else REGISTRY
    metric = reg.histogram(
        name or f"tdn_{stats.name}_seconds",
        f"bridged from LatencyStats({stats.name!r})",
        labels=tuple(labels),
        buckets=buckets,
    )
    child = metric.labels(**labels) if labels else metric.labels()
    inner = stats.record

    def record(seconds: float) -> None:
        inner(seconds)
        child.observe(seconds)

    stats.record = record
    return stats
