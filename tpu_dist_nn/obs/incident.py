"""Flight recorder: anomaly-triggered incident bundles.

PRs 1/3/6/9 built the SENSOR half of the observability plane — metrics,
``x-tdn-trace`` distributed tracing, ``/profile`` attribution, the
``/timeseries`` ring, SLO burn rates. Detection and diagnosis stayed
disconnected: every one of those surfaces is a bounded ring, so by the
time a human reacts to a ``slo.burn`` page the slow exemplar traces,
the log lines, and the timeseries window around the anomaly have been
evicted, and a crash leaves nothing at all. This module is the
black-box flight recorder closing that gap:

* **Detectors** (:class:`SLOBurnDetector`, :class:`SpikeDetector`,
  :class:`BreakerOpenDetector`, :class:`DrainFailoverDetector`) are
  evaluated on the EXISTING runtime-sampler tick
  (:meth:`~tpu_dist_nn.obs.runtime.RuntimeSampler
  .add_incident_recorder`) — never on a request path. Arming the
  recorder costs the serving hot path nothing: detectors read the
  time-series ring, the SLO tracker's last verdict, and registry
  gauges, all host-side dict reads, once per tick on the sampler's
  daemon thread.
* **Bundles** (:func:`capture_bundle`): on trigger, one zip snapshots
  everything a post-incident debug needs — the Chrome trace ring
  (slowest exemplars included), ``/profile`` attribution, the
  ``/timeseries`` window bracketing the trigger, the structured-log
  ring (:class:`~tpu_dist_nn.obs.log.LogRing`), ``/slo`` state, the
  full ``/metrics`` exposition, and a ``manifest.json`` naming the
  trigger, reason, process identity, and versions.
* **Bounded on-disk store** (:class:`IncidentStore`): bundles land in
  ``--incident-dir`` as ``<id>.zip``; the oldest are pruned past
  ``--incident-max`` (default 20) so a flapping detector can never
  fill a disk. Per-detector cooldowns (default 300s) bound capture
  frequency the same way.
* **Crash hook** (:func:`install_crash_hook`): ``sys.excepthook`` /
  ``threading.excepthook`` capture a bundle naming an unhandled
  exception before the process dies; fatal signals (SIGABRT by
  default) capture-then-rethrow through the default handler; and
  ``faulthandler`` is enabled into ``<incident-dir>/faulthandler.log``
  so even a C-level death that outruns Python leaves its stack next
  to the bundles.
* **Fleet capture**: the router's recorder carries the
  :class:`~tpu_dist_nn.serving.pool.ReplicaPool`; on trigger it fans
  ``GET /debug/bundle`` out to every replica's metrics endpoint within
  the same detector tick, embeds each reply under ``replicas/``, and
  stitches every process's ``trace.json`` into one
  ``trace_fleet.json`` (reusing :func:`~tpu_dist_nn.obs.collect
  .stitch_chrome_traces`) — the cross-replica trace of the exact slow
  request survives each replica's ring eviction because it was pulled
  the moment the anomaly fired, not when a human arrived.

Surfaces: ``GET /debug/bundle`` (on-demand capture, built into every
metrics endpoint), ``GET /incidents`` + ``GET /incidents/get?id=``
(:func:`incident_routes`), ``tdn incident ls|show|pull``, ``tdn debug
bundle``, and ``--incident-dir``/``--incident-max`` on
``up``/``lm``/``router``. Stdlib-only; docs/OBSERVABILITY.md
"Incidents & flight recorder" is the operator guide.
"""

from __future__ import annotations

import io
import json
import logging
import os
import re
import signal
import sys
import threading
import time
import traceback
import urllib.request
import zipfile

from tpu_dist_nn.obs.log import LOG_RING, get_logger

log = logging.getLogger(__name__)
slog = get_logger(__name__)

DEFAULT_MAX_INCIDENTS = 20
DEFAULT_COOLDOWN_SECONDS = 300.0
# The timeseries/log window a bundle brackets around its trigger.
DEFAULT_WINDOW_SECONDS = 600.0

_ID_SAFE = re.compile(r"[^A-Za-z0-9._-]+")
# The minted incident-id shape (new_incident_id): the store only
# lists/prunes files matching it, so a foreign zip dropped in the
# directory (an operator's pulled copy, a stray artifact) neither
# masquerades as an incident nor costs a max_incidents slot — pruning
# must never delete real evidence to make room for a copy.
_BUNDLE_NAME = re.compile(
    r"^\d{8}T\d{6}_[A-Za-z0-9._-]+_[0-9a-f]{6}\.zip$"
)


def _safe(text: str, limit: int = 48) -> str:
    return (_ID_SAFE.sub("-", str(text)).strip("-") or "x")[:limit]


def new_incident_id(trigger: str, now: float | None = None) -> str:
    t = time.time() if now is None else now
    stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime(t))
    return f"{stamp}_{_safe(trigger)}_{os.urandom(3).hex()}"


# --------------------------------------------------------------- store


class IncidentStore:
    """Bounded on-disk incident directory: ``<dir>/<incident_id>.zip``.

    ``save`` prunes the OLDEST bundles past ``max_incidents`` (by the
    sortable timestamp prefix of the id, mtime as the tiebreak), so a
    misbehaving detector bounds its own disk damage. Listing reads each
    zip's ``manifest.json`` — at N <= max_incidents that is a handful
    of small reads, not a scan worth indexing.
    """

    def __init__(self, directory: str,
                 max_incidents: int = DEFAULT_MAX_INCIDENTS):
        if max_incidents < 1:
            raise ValueError(
                f"max_incidents must be >= 1, got {max_incidents}"
            )
        self.directory = directory
        self.max_incidents = int(max_incidents)
        self._lock = threading.Lock()
        os.makedirs(directory, exist_ok=True)

    def _path(self, incident_id: str) -> str:
        # The id came off the wire for reads: never let it traverse.
        return os.path.join(self.directory, _safe(incident_id, 120) + ".zip")

    def save(self, incident_id: str, data: bytes) -> str:
        path = self._path(incident_id)
        with self._lock:
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(data)
            os.replace(tmp, path)  # a reader never sees a half bundle
            self._prune_locked()
        return path

    def _entries(self) -> list[str]:
        """Bundle filenames (minted-id shape only — see _BUNDLE_NAME),
        oldest first: mtime then name, so ids minted within the same
        second still prune in arrival order."""
        try:
            names = os.listdir(self.directory)
        except OSError:
            return []

        def key(n):
            try:
                mt = os.path.getmtime(os.path.join(self.directory, n))
            except OSError:
                mt = 0.0
            return (mt, n)

        return sorted((n for n in names if _BUNDLE_NAME.match(n)),
                      key=key)

    def _prune_locked(self) -> None:
        entries = self._entries()
        for name in entries[: max(len(entries) - self.max_incidents, 0)]:
            try:
                os.remove(os.path.join(self.directory, name))
            except OSError:  # already gone / perms: pruning is advisory
                pass

    def ids(self) -> list[str]:
        return [n[:-4] for n in self._entries()]

    def manifest(self, incident_id: str) -> dict | None:
        path = self._path(incident_id)
        try:
            with zipfile.ZipFile(path) as z:
                doc = json.loads(z.read("manifest.json"))
        except (OSError, KeyError, ValueError, zipfile.BadZipFile):
            return None
        if isinstance(doc, dict):
            doc.setdefault("bytes", os.path.getsize(path))
        return doc if isinstance(doc, dict) else None

    def read(self, incident_id: str) -> bytes | None:
        try:
            with open(self._path(incident_id), "rb") as f:
                return f.read()
        except OSError:
            return None

    def list(self) -> list[dict]:
        """Newest first: each incident's manifest (or a stub naming an
        unreadable bundle — a truncated crash-time write is itself
        evidence, not a listing failure)."""
        out = []
        for incident_id in reversed(self.ids()):
            doc = self.manifest(incident_id)
            if doc is None:
                doc = {"incident_id": incident_id,
                       "error": "unreadable bundle"}
            out.append(doc)
        return out


# ------------------------------------------------------- bundle capture


def _versions() -> dict:
    v = {"python": sys.version.split()[0]}
    jax = sys.modules.get("jax")  # never IMPORT jax for a bundle —
    if jax is not None:           # the router process deliberately
        v["jax"] = getattr(jax, "__version__", "?")  # does not load it
    return v


def _boot_id() -> str | None:
    # The same boot_id /healthz reports (resilience.BOOT_ID) when the
    # serving stack is loaded; None in processes that never imported it
    # (importing grpc from here would break obs/'s stdlib-only rule).
    res = sys.modules.get("tpu_dist_nn.serving.resilience")
    return getattr(res, "BOOT_ID", None) if res is not None else None


def capture_bundle(trigger: str, reason: str = "", details=None, *,
                   tracer=None, registry=None, ring=None, slo=None,
                   log_ring=None, window: float = DEFAULT_WINDOW_SECONDS,
                   extra_files: dict | None = None,
                   extra_manifest: dict | None = None,
                   incident_id: str | None = None) -> tuple[str, bytes]:
    """One diagnostic bundle as ``(incident_id, zip_bytes)``.

    Sections degrade independently: a source that is absent (no ring
    attached) is skipped, a source that RAISES is recorded in the
    manifest's ``section_errors`` — a crash-time capture must salvage
    whatever it can reach, never abort on the first broken surface.
    """
    if tracer is None:
        from tpu_dist_nn.obs.trace import TRACER as tracer  # noqa: N813
    if registry is None:
        from tpu_dist_nn.obs.registry import REGISTRY as registry
    if log_ring is None:
        log_ring = LOG_RING
    iid = incident_id or new_incident_id(trigger)
    files: dict[str, bytes] = {}
    errors: dict[str, str] = {}

    def section(name, fn):
        try:
            body = fn()
        except Exception as e:  # noqa: BLE001 — salvage the rest
            errors[name] = repr(e)
            return
        if body is not None:
            files[name] = body

    section("trace.json", lambda: json.dumps(
        tracer.chrome_trace()
    ).encode())
    section("profile.json", lambda: _profile_json(tracer))
    section("metrics.txt", lambda: _metrics_text(registry))
    if ring is not None:
        section("timeseries.json", lambda: json.dumps({
            "resolution_seconds": ring.resolution,
            "retention_seconds": ring.retention,
            "window_seconds": window,
            "series": ring.series(window=window),
        }).encode())
    if slo is not None:
        section("slo.json", lambda: json.dumps(slo.status()).encode())
    if log_ring is not None:
        # default=repr, like the /logs route: StructuredLogger fields
        # are arbitrary objects, and one numpy scalar in the ring must
        # not cost the bundle its ENTIRE log section.
        section("logs.json", lambda: json.dumps({
            "window_seconds": window,
            "dropped_total": log_ring.dropped_total,
            "records": log_ring.snapshot(window=window),
        }, default=repr).encode())
    if extra_files:
        files.update(extra_files)
    manifest = {
        "incident_id": iid,
        "trigger": trigger,
        "reason": reason,
        "captured_at": time.time(),
        "captured_at_iso": time.strftime(
            "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
        ),
        "pid": os.getpid(),
        "boot_id": _boot_id(),
        "argv": list(sys.argv),
        "versions": _versions(),
        "window_seconds": window,
        "sections": sorted(files),
    }
    if details:
        manifest["details"] = details
    if errors:
        manifest["section_errors"] = errors
    if extra_manifest:
        manifest.update(extra_manifest)
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
        z.writestr("manifest.json", json.dumps(manifest, default=repr))
        for name, body in sorted(files.items()):
            z.writestr(name, body)
    return iid, buf.getvalue()


def _profile_json(tracer) -> bytes:
    from tpu_dist_nn.obs.profile import profile_snapshot

    return json.dumps(profile_snapshot(tracer)).encode()


def _metrics_text(registry) -> bytes:
    from tpu_dist_nn.obs.exposition import render

    return render(registry).encode()


# ------------------------------------------------------------ detectors


class SLOBurnDetector:
    """Fires while any objective's FAST window burns above
    ``threshold`` (the page condition — Site Reliability Workbook ch.5
    fast-burn). Reads the SLO tracker's LAST verdict: the sampler
    evaluates trackers earlier in the same tick, so the view is this
    tick's, and the detector never recomputes windows itself."""

    name = "slo.burn"

    def __init__(self, threshold: float = 1.0):
        self.threshold = float(threshold)

    def check(self, rec, now=None) -> str | None:
        if rec.slo is None:
            return None
        doc = rec.slo.status()
        burning = []
        for obj in doc.get("objectives", ()):
            fast = (obj.get("windows") or {}).get("fast") or {}
            if (fast.get("burn_rate", 0.0) > self.threshold
                    and fast.get("total", 0.0) > 0):
                burning.append(
                    f"{obj.get('name')} fast burn "
                    f"{fast.get('burn_rate'):g} "
                    f"({obj.get('objective', '')})"
                )
        return "; ".join(burning) if burning else None


class SpikeDetector:
    """Fires when a cumulative family's windowed ring delta crosses
    ``min_count`` — the shed-storm / error-spike shape. ``exclude``
    drops label matches from the sum (router outcomes: everything but
    ``ok`` is an error)."""

    def __init__(self, name: str, family: str, *, window: float = 60.0,
                 min_count: float = 5.0, match: dict | None = None,
                 exclude: dict | None = None):
        self.name = name
        self.family = family
        self.window = float(window)
        self.min_count = float(min_count)
        self.match = dict(match or {})
        self.exclude = dict(exclude or {})

    def check(self, rec, now=None) -> str | None:
        if rec.ring is None:
            return None
        from tpu_dist_nn.obs.exposition import split_series

        total = 0.0
        for key in rec.ring.keys(family=self.family):
            sname, labels = split_series(key)
            if sname != self.family:
                continue
            if any(labels.get(k) != str(v) for k, v in self.match.items()):
                continue
            if self.exclude and all(
                labels.get(k) == str(v) for k, v in self.exclude.items()
            ):
                continue
            total += rec.ring.delta(key, self.window, now)[0]
        if total >= self.min_count:
            return (f"{self.family} +{total:g} in the last "
                    f"{self.window:g}s (threshold {self.min_count:g})")
        return None


class BreakerOpenDetector:
    """Fires on a breaker TRANSITION to open (``tdn_breaker_state`` ==
    2): edge-triggered on the per-target state seen last tick, so a
    breaker that stays open across many ticks is one incident, and the
    next open after recovery is a new one."""

    name = "breaker.open"
    _OPEN = 2.0

    def __init__(self):
        self._last: dict[tuple, float] = {}

    def check(self, rec, now=None) -> str | None:
        fam = rec.registry.get("tdn_breaker_state")
        if fam is None:
            return None
        opened = []
        seen: dict[tuple, float] = {}
        for values, child in fam.samples():
            seen[values] = child.value
            if (child.value == self._OPEN
                    and self._last.get(values) != self._OPEN):
                opened.append(",".join(values) or "default")
        self._last = seen
        if opened:
            return f"circuit breaker opened for {'; '.join(opened)}"
        return None


class DrainFailoverDetector:
    """Router-side: fires when the pool's membership/drain choreography
    moved (a replica began draining, was removed, crashed and is being
    respawned) or the router re-placed requests onto another replica
    (``tdn_router_failovers_total`` rose since last tick) — the fleet
    absorbing a replica loss is exactly the moment its state is worth
    freezing."""

    name = "drain.failover"

    def __init__(self):
        self._transitions: float | None = None
        self._failovers: float | None = None

    def check(self, rec, now=None) -> str | None:
        reasons = []
        pool = rec.pool
        if pool is not None:
            cur = float(getattr(pool, "transitions_total", 0))
            if self._transitions is not None and cur > self._transitions:
                states = {
                    s["target"]: s["state"] for s in pool.snapshot()
                    if s["state"] != "active"
                }
                reasons.append(
                    f"{cur - self._transitions:g} replica state "
                    f"transition(s); non-active: {states or 'none now'}"
                )
            self._transitions = cur
        fam = rec.registry.get("tdn_router_failovers_total")
        if fam is not None:
            cur = sum(child.value for _, child in fam.samples())
            if self._failovers is not None and cur > self._failovers:
                reasons.append(
                    f"{cur - self._failovers:g} failover(s) since last "
                    f"tick"
                )
            self._failovers = cur
        return "; ".join(reasons) if reasons else None


class AutoscaleFlapDetector:
    """Router-side: fires when the autoscaler muted itself
    (``tdn_autoscale_flaps_total`` rose since last tick) — scale
    decisions reversing direction inside the flap window mean the
    policy's inputs are oscillating (crash-respawn storm, thrashing
    load, mis-tuned hysteresis), exactly the moment the fleet's state
    is worth freezing alongside the decision history in the log ring."""

    name = "autoscale.flap"

    def __init__(self):
        self._flaps: float | None = None

    def check(self, rec, now=None) -> str | None:
        fam = rec.registry.get("tdn_autoscale_flaps_total")
        if fam is None:
            return None
        cur = sum(child.value for _, child in fam.samples())
        reason = None
        if self._flaps is not None and cur > self._flaps:
            reason = (
                f"{cur - self._flaps:g} autoscaler flap "
                f"suppression(s) since last tick (scale decisions "
                f"reversing; automatic scaling muted)"
            )
        self._flaps = cur
        return reason


def default_detectors(*, router: bool = False) -> list:
    """The standard detector set ``--incident-dir`` arms: SLO fast
    burn, error/shed spikes, breaker opens — plus the drain/failover
    and autoscaler-flap detectors on a router."""
    dets: list = [
        SLOBurnDetector(),
        BreakerOpenDetector(),
    ]
    if router:
        dets += [
            SpikeDetector("router.error_spike",
                          "tdn_router_requests_total",
                          exclude={"outcome": "ok"}),
            DrainFailoverDetector(),
            AutoscaleFlapDetector(),
        ]
    else:
        dets += [
            SpikeDetector("rpc.error_spike", "tdn_rpc_errors_total"),
            SpikeDetector("batcher.shed_spike", "tdn_batcher_shed_total",
                          min_count=1.0),
        ]
    return dets


# ------------------------------------------------------------- recorder


class FlightRecorder:
    """The armed recorder: sources + detectors + store.

    ``check()`` runs on the runtime sampler's tick (after the SLO
    trackers evaluated): each detector returning a reason outside its
    cooldown triggers :meth:`capture`. Nothing here ever runs on a
    request thread, and a capture (or a broken detector) can never
    break sampling — every failure is logged and swallowed.
    """

    def __init__(self, store: IncidentStore | None = None, *,
                 detectors=(), tracer=None, registry=None, ring=None,
                 slo=None, log_ring=None, pool=None,
                 cooldown: float = DEFAULT_COOLDOWN_SECONDS,
                 window: float = DEFAULT_WINDOW_SECONDS,
                 fleet_timeout: float = 5.0):
        if tracer is None:
            from tpu_dist_nn.obs.trace import TRACER as tracer  # noqa: N813
        if registry is None:
            from tpu_dist_nn.obs.registry import REGISTRY as registry
        self.store = store
        self.detectors = list(detectors)
        self.tracer = tracer
        self.registry = registry
        self.ring = ring
        self.slo = slo
        self.log_ring = log_ring if log_ring is not None else LOG_RING
        self.pool = pool
        self.cooldown = float(cooldown)
        self.window = float(window)
        self.fleet_timeout = float(fleet_timeout)
        self.captured_total = 0
        self._last_fired: dict[str, float] = {}
        # One capture at a time: a detector storm plus a manual
        # /debug/bundle must serialize, not interleave store writes.
        self._capture_lock = threading.Lock()

    # ---------------------------------------------------------- capture

    def bundle(self, trigger: str, reason: str = "", details=None, *,
               fleet: bool | None = None) -> tuple[str, bytes]:
        """Build one bundle in memory (no store write): the
        ``/debug/bundle`` on-demand body. ``fleet`` defaults to "this
        recorder fronts a pool"."""
        if fleet is None:
            fleet = self.pool is not None
        extra_files: dict[str, bytes] = {}
        extra_manifest: dict = {}
        if fleet and self.pool is not None:
            extra_files, extra_manifest = self._fleet_sections()
        return capture_bundle(
            trigger, reason, details,
            tracer=self.tracer, registry=self.registry, ring=self.ring,
            slo=self.slo, log_ring=self.log_ring, window=self.window,
            extra_files=extra_files, extra_manifest=extra_manifest,
        )

    def capture(self, trigger: str, reason: str = "", details=None, *,
                fleet: bool | None = None) -> tuple[str, str | None]:
        """Capture AND persist: ``(incident_id, path)`` (path None
        without a store — the bundle still existed long enough to be
        returned, but detector-triggered captures without a store are
        refused upstream)."""
        with self._capture_lock:
            iid, data = self.bundle(trigger, reason, details, fleet=fleet)
            path = self.store.save(iid, data) if self.store else None
        self.captured_total += 1
        slog.warning(
            "incident.captured", incident_id=iid, trigger=trigger,
            reason=reason, bytes=len(data),
            path=path or "(not persisted)",
        )
        return iid, path

    def _fleet_sections(self) -> tuple[dict, dict]:
        """Fan ``GET /debug/bundle`` out over every replica (parallel,
        bounded by ``fleet_timeout`` — the capture must finish within
        one detector tick, a wedged replica just goes missing from the
        bundle) and stitch every process's trace into one lane-per-
        process document."""
        from tpu_dist_nn.obs.collect import stitch_chrome_traces

        snapshots = self.pool.snapshot()
        # Every target's entry is PRE-SEEDED: a pull thread that
        # outlives its bounded join (urlopen timeouts are per socket
        # op — a trickling replica can) then only REPLACES a value; it
        # can never resize the dict under the iteration below, and a
        # timed-out replica reads "no reply in time" instead of
        # silently vanishing from the manifest.
        results: dict[str, dict] = {
            rep.get("target"): {"target": rep.get("target"),
                                "error": "no reply in time"}
            for rep in snapshots
        }

        def pull(rep):
            target = rep.get("target")
            base = rep.get("metrics_target")
            entry: dict = {"target": target}
            if not base:
                entry["error"] = "no metrics_target registered"
                results[target] = entry
                return
            if "://" not in base:
                base = f"http://{base}"
            url = base.rstrip("/") + "/debug/bundle?fleet=0"
            try:
                with urllib.request.urlopen(
                    url, timeout=self.fleet_timeout
                ) as resp:
                    entry["bundle"] = resp.read()
                entry["bytes"] = len(entry["bundle"])
            except Exception as e:  # noqa: BLE001 — missing, not fatal
                entry["error"] = repr(e)
            results[target] = entry

        threads = [
            threading.Thread(target=pull, args=(rep,), daemon=True)
            for rep in snapshots
        ]
        for t in threads:
            t.start()
        # ONE shared deadline across the joins: per-thread budgets
        # would stack (N wedged replicas x timeout) and freeze the
        # sampler thread — and with it every other detector — well
        # past the one-tick contract.
        deadline = time.monotonic() + self.fleet_timeout + 1.0
        for t in threads:
            t.join(max(0.0, deadline - time.monotonic()))
        files: dict[str, bytes] = {}
        replicas_meta = []
        trace_docs: dict[str, dict] = {
            "router": self.tracer.chrome_trace(),
        }
        for target, entry in sorted(results.items()):
            meta = {"target": target}
            data = entry.get("bundle")
            if data is None:
                meta["error"] = entry.get("error", "no reply in time")
            else:
                meta["bytes"] = entry["bytes"]
                files[f"replicas/{_safe(target, 80)}.zip"] = data
                try:
                    with zipfile.ZipFile(io.BytesIO(data)) as z:
                        trace_docs[f"replica {target}"] = json.loads(
                            z.read("trace.json")
                        )
                except (KeyError, ValueError, zipfile.BadZipFile) as e:
                    meta["trace_error"] = repr(e)
            replicas_meta.append(meta)
        try:
            files["trace_fleet.json"] = json.dumps(
                stitch_chrome_traces(trace_docs)
            ).encode()
        except Exception as e:  # noqa: BLE001 — per-replica zips remain
            replicas_meta.append({"stitch_error": repr(e)})
        return files, {"fleet": True, "replicas": replicas_meta}

    # --------------------------------------------------------- checking

    def check(self, now: float | None = None) -> list[str]:
        """One detector pass (the sampler tick): returns the incident
        ids captured. Without a store there is nowhere durable to put
        a triggered bundle, so detector checks are skipped entirely —
        "armed" means store + detectors."""
        if self.store is None or not self.detectors:
            return []
        t = time.monotonic() if now is None else float(now)
        captured = []
        for det in self.detectors:
            try:
                reason = det.check(self, now)
            except Exception:  # noqa: BLE001 — one bad detector only
                log.exception("incident detector %s failed",
                              getattr(det, "name", det))
                continue
            if not reason:
                continue
            name = getattr(det, "name", type(det).__name__)
            cooldown = float(getattr(det, "cooldown", self.cooldown))
            last = self._last_fired.get(name)
            if last is not None and t - last < cooldown:
                continue
            try:
                iid, _ = self.capture(name, reason)
                captured.append(iid)
                # Stamped on SUCCESS: a failed capture (transient
                # ENOSPC, a wedged fleet pull) must not silence the
                # detector for the whole cooldown with nothing on disk
                # — the evidence windows would evict before the next
                # attempt. Failures back off ~30s instead, so a
                # persistent failure doesn't rebuild (and fleet-fan-
                # out) the bundle every single tick either.
                self._last_fired[name] = t
            except Exception:  # noqa: BLE001 — capture must not kill ticks
                log.exception("incident capture for %s failed", name)
                self._last_fired[name] = t - max(cooldown - 30.0, 0.0)
        return captured


# ----------------------------------------------------------- crash hook


def install_crash_hook(recorder: FlightRecorder, *,
                       signals=(signal.SIGABRT,),
                       enable_faulthandler: bool = True) -> None:
    """Arm the hard-death paths: an unhandled exception (main thread or
    any serving thread) captures a ``crash.exception`` /
    ``crash.thread_exception`` bundle before the previous hook runs; a
    listed signal captures ``crash.signal`` then re-raises through the
    default handler so the process still dies with the right status;
    and ``faulthandler`` writes C-level stacks into the incident
    directory for deaths Python never sees. Crash captures never fan
    out to the fleet (the process is dying — spend nothing)."""
    prev_hook = sys.excepthook

    def excepthook(tp, value, tb):
        _crash_capture(
            recorder, "crash.exception",
            f"{getattr(tp, '__name__', tp)}: {value}", tp, value, tb,
        )
        prev_hook(tp, value, tb)

    sys.excepthook = excepthook

    prev_thread_hook = threading.excepthook

    def thread_hook(args):
        if args.exc_type is not SystemExit:
            _crash_capture(
                recorder, "crash.thread_exception",
                f"{args.exc_type.__name__}: {args.exc_value} "
                f"(thread {getattr(args.thread, 'name', '?')})",
                args.exc_type, args.exc_value, args.exc_traceback,
            )
        prev_thread_hook(args)

    threading.excepthook = thread_hook

    if enable_faulthandler and recorder.store is not None:
        import faulthandler

        try:
            # Deliberately leaked: faulthandler holds the fd for the
            # process lifetime — closing it would crash the crash path.
            f = open(  # noqa: SIM115
                os.path.join(recorder.store.directory,
                             "faulthandler.log"), "a",
            )
            faulthandler.enable(f)
        except OSError:
            log.warning("faulthandler file unavailable", exc_info=True)

    # AFTER faulthandler.enable: it installs its own C-level handler
    # for SIGABRT (among others), and for the listed signals the
    # bundle-capturing Python handler must be the one that wins —
    # faulthandler keeps SIGSEGV/SIGBUS/SIGILL, where Python cannot
    # safely run anyway.
    for sig in signals:
        def handler(signum, frame, _sig=sig):
            try:
                name = signal.Signals(signum).name
            except ValueError:
                name = str(signum)
            _crash_capture(recorder, "crash.signal", name, None, None,
                           None)
            signal.signal(signum, signal.SIG_DFL)
            signal.raise_signal(signum)

        try:
            signal.signal(sig, handler)
        except (ValueError, OSError):  # non-main thread / exotic signal
            log.warning("could not install crash handler for %s", sig)


def _crash_capture(recorder, trigger, reason, tp, value, tb) -> None:
    try:
        details = None
        if tp is not None:
            details = {"traceback": "".join(
                traceback.format_exception(tp, value, tb)
            )[-16000:]}
        recorder.capture(trigger, reason, details, fleet=False)
    except Exception:  # noqa: BLE001 — the death in progress wins
        log.exception("crash-path incident capture failed")


# --------------------------------------------------------------- routes


def incident_routes(recorder: FlightRecorder) -> dict:
    """Extra GET routes for the metrics endpoint
    (:meth:`~tpu_dist_nn.obs.exposition.MetricsServer.add_routes`):

    * ``/incidents`` — manifest list, newest first (404 with a hint
      when no ``--incident-dir`` store exists);
    * ``/incidents/get?id=`` — one bundle zip;
    * ``/debug/bundle`` — on-demand capture through THIS recorder
      (``?fleet=0|1`` overrides the pool default, ``?persist=1`` also
      saves it to the store; the stock MetricsServer route captures
      process-local state only — mounting this one upgrades a router's
      endpoint to fleet capture).
    """

    def incidents(query: str):
        if recorder.store is None:
            return 404, "application/json", (
                b'{"error": "no incident store (start the serving '
                b'command with --incident-dir)"}\n'
            )
        return 200, "application/json", json.dumps({
            "directory": recorder.store.directory,
            "max_incidents": recorder.store.max_incidents,
            "captured_total": recorder.captured_total,
            "incidents": recorder.store.list(),
        }).encode() + b"\n"

    def incident_get(query: str):
        import urllib.parse

        if recorder.store is None:
            return 404, "application/json", (
                b'{"error": "no incident store (start the serving '
                b'command with --incident-dir)"}\n'
            )
        q = urllib.parse.parse_qs(query)
        iid = (q.get("id") or [None])[0]
        if not iid:
            return 400, "application/json", \
                b'{"error": "id= query parameter required"}\n'
        data = recorder.store.read(iid)
        if data is None:
            return 404, "application/json", json.dumps(
                {"error": f"no incident {iid!r}"}
            ).encode() + b"\n"
        return 200, "application/zip", data

    def debug_bundle(query: str):
        import urllib.parse

        q = urllib.parse.parse_qs(query)
        fleet = None
        raw = (q.get("fleet") or [None])[0]
        if raw is not None:
            fleet = raw not in ("0", "false", "no")
        reason = (q.get("reason") or ["on-demand capture"])[0]
        persist = (q.get("persist") or ["0"])[0] not in ("0", "", "false")
        if persist:
            if recorder.store is None:
                # Silently returning an unpersisted bundle would break
                # the documented ?persist=1 contract; the operator
                # finds out only when `tdn incident ls` is empty.
                return 409, "application/json", (
                    b'{"error": "persist=1 needs an incident store '
                    b'(start the serving command with '
                    b'--incident-dir)"}\n'
                )
            iid, _path = recorder.capture("manual", reason, fleet=fleet)
            data = recorder.store.read(iid)
            if data is None:
                return 500, "application/json", json.dumps({
                    "error": f"bundle {iid} persisted but unreadable",
                }).encode() + b"\n"
        else:
            _iid, data = recorder.bundle("manual", reason, fleet=fleet)
        return 200, "application/zip", data

    return {
        "/incidents": incidents,
        "/incidents/get": incident_get,
        "/debug/bundle": debug_bundle,
    }
