"""Performance attribution: spans -> per-stage SELF-time breakdown.

The tracer (obs/trace.py) answers "where did THIS request go"; nothing
answered "where does the time go in AGGREGATE" — the question the bench
trajectory raises (host-fed throughput decaying while device-resident
holds: which stage is eating it?). This module folds the tracer's
completed spans into a rolling per-stage profile:

* **Self time, not inclusive time.** A span's self time is the part of
  its duration no deeper span covers — so a slow ``fetch`` no longer
  inflates its ``rpc.Process`` parent's row, and the shares of one
  request's stages sum to its root wall time instead of
  double-counting every level of the tree.
* **Innermost-cover sweep, not parent links.** The serving pipeline
  records spans that are *siblings by parent id* but *nested in time*
  (every ``decode.step`` hangs off the handler span but runs inside
  the request's ``decode`` phase span), and siblings that PARTIALLY
  overlap (two rows of one Generate request decoding in different
  slots). A parent-link tree would double-count both shapes. Instead,
  each instant of a trace is attributed to the innermost span covering
  it (latest start wins, shortest on ties) — a timeline sweep that
  partitions wall time exactly no matter how the spans interleave.
* **Per method.** Traces are grouped by their handler root
  (``rpc.Process`` / ``rpc.Generate``): the two wire paths have
  different stage taxonomies and different SLOs, so their breakdowns
  never mix. The handler's own uncovered time reports as the
  ``handler`` pseudo-stage, which is what makes the shares sum to ~1.

Stdlib-only, read-only over a snapshot: profiling a live server never
takes the tracer's lock for longer than ``snapshot()`` does, and never
touches a device. Serves ``GET /profile`` (obs/exposition.py) and
``tdn profile`` (cli.py); ``tools/bench_gate.py`` folds the breakdown
into its regression reports.
"""

from __future__ import annotations

import time

# Span-name prefix identifying a method root: "rpc.Process" ->
# method "Process". Client-side spans (client.*) are never attribution
# roots — in a loopback process both sides record into one tracer, and
# attributing the same wall time to both would double every share.
_ROOT_PREFIX = "rpc."

# The uncovered remainder of a root span (handler overhead: metadata,
# validation, result fan-in) reports under this pseudo-stage so every
# breakdown sums to the measured root wall time.
HANDLER_STAGE = "handler"


class SpanRecord:
    """The minimal span view attribution needs — constructable from
    tracer ``Span`` objects (:func:`records_from_spans`) or from Chrome
    trace events (``tdn trace``'s self-time summary)."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "t0", "dur")

    def __init__(self, name, trace_id, span_id, parent_id, t0, dur):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.t0 = float(t0)
        self.dur = max(float(dur), 0.0)

    @property
    def end(self) -> float:
        return self.t0 + self.dur


def records_from_spans(spans) -> list[SpanRecord]:
    """Tracer ``Span`` objects -> records (unfinished spans skipped)."""
    return [
        SpanRecord(s.name, s.trace_id, s.span_id, s.parent_id, s.t0, s.dur)
        for s in spans if s.dur is not None
    ]


def compute_self_times(records) -> dict[str, float]:
    """``span_id -> self seconds``: the measure of the time where the
    span is the INNERMOST cover of its trace's timeline.

    Per trace, the span boundaries cut the timeline into elementary
    segments; each segment is attributed to the covering span that
    started latest (shortest on ties) — the innermost one. This
    partitions covered wall time exactly, for every interleaving the
    recorders produce: strict nesting (``fetch`` inside
    ``rpc.Process``), time-nested siblings (``decode.step`` inside the
    request's ``decode`` phase but parented to the handler), and
    PARTIALLY overlapping siblings (two rows of one Generate request
    decoding concurrently in different slots) — the case a parent-link
    tree would double-count.

    Quadratic in spans-per-trace; request trees are tens of spans, and
    the tracer's ring bounds the total.
    """
    selfs: dict[str, float] = {r.span_id: 0.0 for r in records}
    by_trace: dict[str, list[SpanRecord]] = {}
    for r in records:
        by_trace.setdefault(r.trace_id, []).append(r)
    for trace in by_trace.values():
        points = sorted({p for r in trace for p in (r.t0, r.end)})
        for a, b in zip(points, points[1:]):
            mid = (a + b) / 2.0
            cover = [r for r in trace if r.t0 <= mid < r.end]
            if not cover:
                continue
            innermost = max(cover, key=lambda r: (r.t0, -r.end))
            selfs[innermost.span_id] += b - a
    return selfs


def _percentile(sorted_vals: list[float], q: float) -> float:
    """Nearest-rank percentile over an ascending list (stdlib-only —
    this module must not import numpy on the serving endpoint path)."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, max(0, round(q * (len(sorted_vals) - 1))))
    return sorted_vals[int(idx)]


def profile_snapshot(tracer=None, *, window: float | None = None,
                     top: int = 5, now: float | None = None) -> dict:
    """The rolling "where does the time go" breakdown as a JSON-ready
    dict (the ``GET /profile`` schema — documented in
    docs/OBSERVABILITY.md "Profiling").

    ``window`` keeps only traces whose root ENDED within the last
    ``window`` seconds (None = everything still in the tracer's buffer
    — itself a ring, so the profile is always rolling). ``top`` bounds
    the slowest-trace exemplar list per method.
    """
    if tracer is None:
        from tpu_dist_nn.obs.trace import TRACER as tracer  # noqa: N811
    records = records_from_spans(tracer.snapshot())
    selfs = compute_self_times(records)
    by_trace: dict[str, list[SpanRecord]] = {}
    for r in records:
        by_trace.setdefault(r.trace_id, []).append(r)
    t_now = time.monotonic() if now is None else now
    roots = [r for r in records if r.name.startswith(_ROOT_PREFIX)]
    if window is not None:
        roots = [r for r in roots if r.end >= t_now - float(window)]

    # A root's breakdown covers the same-trace spans whose window lies
    # inside the root's (parent links would miss the time-nested /
    # partially-overlapping sibling shapes — see compute_self_times).
    # Client-side spans CONTAIN the handler and so never qualify, which
    # is what keeps a loopback process from attributing the same wall
    # time twice.
    eps = 1e-7
    methods: dict[str, dict] = {}
    for root in roots:
        method = root.name[len(_ROOT_PREFIX):]
        m = methods.setdefault(method, {
            "traces": 0, "wall": 0.0, "stages": {}, "roots": [],
        })
        m["traces"] += 1
        m["wall"] += root.dur
        per_trace: dict[str, float] = {
            HANDLER_STAGE: selfs.get(root.span_id, 0.0)
        }
        hst = m["stages"].setdefault(
            HANDLER_STAGE, {"count": 0, "durs": []}
        )
        hst["count"] += 1
        hst["durs"].append(per_trace[HANDLER_STAGE])
        for d in by_trace[root.trace_id]:
            if d.span_id == root.span_id or not (
                d.t0 >= root.t0 - eps and d.end <= root.end + eps
            ):
                continue
            per_trace[d.name] = per_trace.get(d.name, 0.0) + \
                selfs.get(d.span_id, 0.0)
            st = m["stages"].setdefault(d.name, {"count": 0, "durs": []})
            st["count"] += 1
            st["durs"].append(selfs.get(d.span_id, 0.0))
        m["roots"].append((root, per_trace))

    out_methods: dict[str, dict] = {}
    for method, m in methods.items():
        wall = m["wall"]
        stages = []
        for name, st in m["stages"].items():
            durs = sorted(st["durs"])
            total = sum(durs)
            stages.append({
                "stage": name,
                "count": st["count"],
                "total_s": round(total, 6),
                "share": round(total / wall, 4) if wall else 0.0,
                "p50_s": round(_percentile(durs, 0.50), 6),
                "p99_s": round(_percentile(durs, 0.99), 6),
                "max_s": round(durs[-1], 6),
            })
        stages.sort(key=lambda s: s["total_s"], reverse=True)
        slowest = sorted(m["roots"], key=lambda e: e[0].dur, reverse=True)
        out_methods[method] = {
            "traces": m["traces"],
            "wall_seconds_total": round(wall, 6),
            "share_sum": round(sum(s["share"] for s in stages), 4),
            "stages": stages,
            "slowest": [
                {
                    "trace_id": root.trace_id,
                    "wall_s": round(root.dur, 6),
                    "stages": {
                        k: round(v, 6)
                        for k, v in sorted(
                            per.items(), key=lambda kv: kv[1], reverse=True
                        )
                    },
                }
                for root, per in slowest[:max(int(top), 0)]
            ],
        }
    return {
        "window_seconds": window,
        "traces": len(roots),
        "methods": out_methods,
    }


def format_profile_table(doc: dict) -> str:
    """Human table of a :func:`profile_snapshot` document (the ``tdn
    profile`` output): one block per method, stages sorted by total
    self time, plus the slowest exemplar traces."""
    lines: list[str] = []
    methods = doc.get("methods", {})
    if not methods:
        lines.append(
            "no completed request traces in the window (is tracing "
            "enabled? --trace-sample-rate > 0 and traffic flowing)"
        )
        return "\n".join(lines)
    for method in sorted(methods):
        m = methods[method]
        lines.append(
            f"== {method}: {m['traces']} traces, "
            f"{m['wall_seconds_total'] * 1e3:.1f} ms total wall, "
            f"stage shares sum {m['share_sum'] * 100:.1f}% =="
        )
        lines.append(
            f"  {'stage':<14} {'share':>7} {'total_ms':>10} "
            f"{'p50_ms':>9} {'p99_ms':>9} {'count':>7}"
        )
        for s in m["stages"]:
            lines.append(
                f"  {s['stage']:<14} {s['share'] * 100:>6.1f}% "
                f"{s['total_s'] * 1e3:>10.2f} {s['p50_s'] * 1e3:>9.3f} "
                f"{s['p99_s'] * 1e3:>9.3f} {s['count']:>7}"
            )
        for i, ex in enumerate(m.get("slowest", ()), 1):
            top3 = list(ex["stages"].items())[:3]
            where = "  ".join(
                f"{k}={v * 1e3:.2f}ms" for k, v in top3
            )
            lines.append(
                f"  slowest[{i}] {ex['trace_id'][:16]} "
                f"wall={ex['wall_s'] * 1e3:.2f}ms  {where}"
            )
    return "\n".join(lines)
