"""Prometheus text-format exposition + the stdlib /metrics endpoint.

Renders the process registry in text format 0.0.4 (the format every
scraper speaks) and serves it from a ``http.server`` daemon thread —
no web framework, no asyncio, startable NEXT TO the gRPC server on a
second port (``tdn up --grpc-port 5101 --metrics-port 9100``).

``/healthz`` mirrors :meth:`tpu_dist_nn.api.engine.Engine.health`
(structured readiness, the reference's TCP poll as JSON): HTTP 200
when ``ready``, 503 when not — so the same probe a human curls is the
one a load balancer gates on.

``/profile`` serves the per-stage self-time breakdown
(:func:`tpu_dist_nn.obs.profile.profile_snapshot`); ``/debug/profile``
runs an on-demand ``jax.profiler`` device capture and returns the
artifact as a zip (degrading to a JSON 503 on backends without
profiler support).
"""

from __future__ import annotations

import json
import logging
import math
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from tpu_dist_nn.obs.registry import REGISTRY, Registry

log = logging.getLogger(__name__)

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _escape_help(text: str) -> str:
    return text.replace("\\", r"\\").replace("\n", r"\n")


def _escape_label(value: str) -> str:
    return (
        value.replace("\\", r"\\").replace("\n", r"\n").replace('"', r"\"")
    )


def _fmt(v: float) -> str:
    # Integral values print bare (the common counter case); floats keep
    # repr fidelity so scrape->parse round-trips exactly. Non-finite
    # values use the text format's literals — a diverged-loss NaN gauge
    # must not make the whole endpoint unscrapable.
    f = float(v)
    if math.isnan(f):
        return "NaN"
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def _labelstr(names, values) -> str:
    if not names:
        return ""
    inner = ",".join(
        f'{n}="{_escape_label(v)}"' for n, v in zip(names, values)
    )
    return "{" + inner + "}"


def render(registry: Registry | None = None) -> str:
    """The whole registry in Prometheus text format 0.0.4."""
    reg = registry if registry is not None else REGISTRY
    out = []
    for m in reg.collect():
        samples = m.samples()
        if not samples:
            continue
        if m.help:
            out.append(f"# HELP {m.name} {_escape_help(m.help)}")
        out.append(f"# TYPE {m.name} {m.kind}")
        for values, child in samples:
            if m.kind == "histogram":
                # Cumulative le-buckets, then +Inf == _count.
                cum = 0
                for edge, n in zip(m.buckets, child.counts):
                    cum += n
                    out.append(
                        f"{m.name}_bucket"
                        + _labelstr(
                            m.labelnames + ("le",), values + (_fmt(edge),)
                        )
                        + f" {cum}"
                    )
                total = cum + child.counts[-1]
                out.append(
                    f"{m.name}_bucket"
                    + _labelstr(m.labelnames + ("le",), values + ("+Inf",))
                    + f" {total}"
                )
                ls = _labelstr(m.labelnames, values)
                out.append(f"{m.name}_sum{ls} {_fmt(child.sum)}")
                out.append(f"{m.name}_count{ls} {total}")
            else:
                out.append(
                    f"{m.name}{_labelstr(m.labelnames, values)} "
                    f"{_fmt(child.value)}"
                )
    return "\n".join(out) + ("\n" if out else "")


def parse_prometheus_text(text: str) -> dict:
    """Text format -> ``{series_name_with_labels: float}`` (plus
    ``__type__:<name>`` entries). The inverse of :func:`render` for the
    ``tdn metrics`` pretty-printer and tests — not a general parser,
    but it round-trips everything render emits."""
    out: dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split(None, 3)
            out[f"__type__:{name}"] = kind
            continue
        if line.startswith("#"):
            continue
        series, _, value = line.rpartition(" ")
        try:
            out[series] = float(value)
        except ValueError:
            continue
    return out


def split_series(series: str) -> tuple[str, dict[str, str]]:
    """``'name{a="x",b="y"}'`` -> ``("name", {"a": "x", "b": "y"})`` —
    the inverse of :func:`render`'s label formatting, for consumers of
    :func:`parse_prometheus_text` keys (the SLO evaluator's label
    matching, ``tdn top``'s per-replica views). Handles the escaping
    render emits; a malformed tail degrades to no labels rather than
    raising mid-scrape."""
    name, brace, rest = series.partition("{")
    if not brace or not rest.endswith("}"):
        return series, {}
    labels: dict[str, str] = {}
    body = rest[:-1]
    i = 0
    while i < len(body):
        eq = body.find('="', i)
        if eq < 0:
            break
        key = body[i:eq]
        j = eq + 2
        val: list[str] = []
        while j < len(body):
            c = body[j]
            if c == "\\" and j + 1 < len(body):
                nxt = body[j + 1]
                val.append({"n": "\n"}.get(nxt, nxt))
                j += 2
                continue
            if c == '"':
                break
            val.append(c)
            j += 1
        labels[key] = "".join(val)
        i = j + 1
        if i < len(body) and body[i] == ",":
            i += 1
    return name, labels


def parsed_histogram_quantile(parsed: dict, family: str, q: float,
                              **labels) -> float | None:
    """Quantile estimate for one histogram family out of a
    :func:`parse_prometheus_text` scrape — the SCRAPE-SIDE twin of
    ``Histogram.quantile`` (same interpolation, via the shared
    :func:`~tpu_dist_nn.obs.registry.histogram_quantile`), so ``tdn
    top`` and fleet SLO views estimate exactly what the serving process
    itself would. ``labels`` is a SUBSET constraint; series matching it
    are summed bucket-wise first (e.g. all ``method`` series when no
    method is pinned). Returns None when no matching buckets exist."""
    from tpu_dist_nn.obs.registry import histogram_quantile

    prefix = family + "_bucket"
    cum: dict[float, float] = {}
    inf = 0.0
    for series, value in parsed.items():
        s = str(series)
        if not s.startswith(prefix):
            continue
        name, lbl = split_series(s)
        if name != prefix or "le" not in lbl:
            continue
        if any(lbl.get(k) != str(v) for k, v in labels.items()):
            continue
        if lbl["le"] == "+Inf":
            inf += float(value)
        else:
            try:
                edge = float(lbl["le"])
            except ValueError:
                continue
            cum[edge] = cum.get(edge, 0.0) + float(value)
    if not cum and inf <= 0:
        return None
    edges = sorted(cum)
    # Cumulative le-series -> per-bucket counts (+Inf tail last).
    counts = []
    prev = 0.0
    for e in edges:
        counts.append(max(cum[e] - prev, 0.0))
        prev = cum[e]
    counts.append(max(inf - prev, 0.0))
    return histogram_quantile(edges, counts, q)


class MetricsServer:
    """The /metrics + /healthz + /trace + /profile + /timeseries +
    /slo + /goodput + /logs + /debug/bundle endpoint on a daemon
    thread.

    ``GET /goodput`` serves the attached
    :class:`~tpu_dist_nn.obs.goodput.GoodputTracker`'s per-stage
    useful/pad FLOP breakdown (404 with a hint until attached).

    ``GET /logs?window=S&level=L&limit=N`` serves the process log ring
    (:data:`tpu_dist_nn.obs.log.LOG_RING`); ``GET /debug/bundle``
    captures an on-demand diagnostic bundle zip (trace ring, profile,
    timeseries window, SLO state, log ring, /metrics text + manifest —
    :mod:`tpu_dist_nn.obs.incident`).

    ``health_fn`` is polled per /healthz request (``Engine.health`` in
    the serving wiring); omit it for processes with no engine — the
    endpoint then reports ``{"ready": true}`` for liveness.

    ``GET /trace?limit=N&trace_id=ID`` exports the process tracer's
    completed spans (plus its slowest-trace exemplars) as Chrome
    trace-event JSON — save the body and open it in Perfetto /
    ``chrome://tracing``, or let ``tdn trace`` do both; ``trace_id``
    pulls ONE trace (a slow exemplar named by a log line or trailing
    metadata) without dumping the whole ring. ``tracer`` overrides the
    process-wide :data:`tpu_dist_nn.obs.trace.TRACER` (tests).

    ``GET /timeseries?family=F&window=S`` serves the attached
    :class:`~tpu_dist_nn.obs.timeseries.TimeSeriesRing`'s recent
    samples; ``GET /slo`` the attached
    :class:`~tpu_dist_nn.obs.slo.SLOTracker`'s objective/burn-rate
    status. Both 404 with a JSON reason until :meth:`attach` wires the
    sources in (the endpoint binds BEFORE the sampler exists on the
    serving bring-up path).

    ``GET /profile?window=S&top=N`` serves the per-stage self-time
    breakdown over the same tracer (``tdn profile`` pretty-prints it).
    ``GET /debug/profile?seconds=N`` captures a ``jax.profiler`` device
    trace for N seconds and returns the TensorBoard-format artifact as
    one zip body; one capture at a time (409 while busy), 503 with a
    JSON error where the backend has no profiler.
    """

    # On-demand device captures are bounded: a typo'd ?seconds= must
    # not pin the profiler (and its buffer growth) for an hour.
    MAX_CAPTURE_SECONDS = 60.0

    def __init__(self, port: int = 0, host: str = "0.0.0.0", *,
                 registry: Registry | None = None, health_fn=None,
                 tracer=None, routes=None, timeseries=None, slo=None,
                 goodput=None, post_routes=None):
        reg = registry if registry is not None else REGISTRY
        outer = self
        # Extra GET routes, ``{path: fn(query) -> (status, content_type,
        # body_bytes)}`` — the admin seam (the router mounts its
        # /router/* drain + fleet-introspection paths here). A raising
        # route degrades to a JSON 500, never a handler traceback.
        # ``post_routes`` is the same shape for state-CHANGING admin
        # verbs (the router's /router/scale manual override): a scraper
        # sweeping every GET path must not be able to actuate the fleet.
        self._routes = dict(routes or {})
        self._post_routes = dict(post_routes or {})

        class Handler(BaseHTTPRequestHandler):
            def _run_route(self, fn, query):
                try:
                    return fn(query)
                except Exception as e:  # noqa: BLE001 — degrade
                    log.warning("route %s failed: %r", self.path, e)
                    return (
                        500, "application/json",
                        json.dumps({"error": repr(e)}).encode() + b"\n",
                    )

            def do_POST(self):  # noqa: N802 — http.server API
                path, _, query = self.path.partition("?")
                # Any request body is drained (keep-alive hygiene) but
                # unused: the admin verbs are query-parameter shaped.
                length = int(self.headers.get("Content-Length") or 0)
                if length:
                    self.rfile.read(length)
                if path in outer._post_routes:
                    status, ctype, body = self._run_route(
                        outer._post_routes[path], query
                    )
                    self._reply(status, ctype, body)
                elif path in outer._routes:
                    self._reply(405, "application/json",
                                b'{"error": "use GET for this path"}\n')
                else:
                    self._reply(404, "text/plain", b"not found\n")

            def do_GET(self):  # noqa: N802 — http.server API
                path, _, query = self.path.partition("?")
                if path in outer._post_routes and path not in outer._routes:
                    self._reply(405, "application/json",
                                b'{"error": "use POST for this path"}\n')
                    return
                if path in outer._routes:
                    status, ctype, body = self._run_route(
                        outer._routes[path], query
                    )
                    self._reply(status, ctype, body)
                elif path == "/metrics":
                    body = render(reg).encode()
                    self._reply(200, CONTENT_TYPE, body)
                elif path == "/healthz":
                    status, body = outer._health_body()
                    self._reply(status, "application/json", body)
                elif path == "/trace":
                    status, body = outer._trace_body(query)
                    self._reply(status, "application/json", body)
                elif path == "/logs":
                    status, body = outer._logs_body(query)
                    self._reply(status, "application/json", body)
                elif path == "/debug/bundle":
                    status, ctype, body = outer._debug_bundle_body(query)
                    self._reply(status, ctype, body)
                elif path == "/profile":
                    status, body = outer._profile_body(query)
                    self._reply(status, "application/json", body)
                elif path == "/timeseries":
                    status, body = outer._timeseries_body(query)
                    self._reply(status, "application/json", body)
                elif path == "/slo":
                    status, body = outer._slo_body(query)
                    self._reply(status, "application/json", body)
                elif path == "/goodput":
                    status, body = outer._goodput_body(query)
                    self._reply(status, "application/json", body)
                elif path == "/debug/profile":
                    status, ctype, body = outer._debug_profile_body(query)
                    self._reply(status, ctype, body)
                else:
                    self._reply(404, "text/plain", b"not found\n")

            def _reply(self, status, ctype, body):
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt, *args):  # scrapes are not news
                log.debug("metrics http: " + fmt, *args)

        self._registry = reg
        self._health_fn = health_fn
        self._tracer = tracer
        self._timeseries = timeseries
        self._slo = slo
        self._goodput = goodput
        # One device capture at a time: jax.profiler.trace is a
        # process-global session — a second concurrent start raises
        # deep inside the profiler instead of returning a clean 409.
        self._capture_lock = threading.Lock()
        self._closed = False
        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="tdn-metrics-http",
            daemon=True,
        )
        self._thread.start()
        log.info("metrics endpoint on :%d (/metrics, /healthz)", self.port)

    def _health_body(self):
        if self._health_fn is None:
            return 200, b'{"ready": true}\n'
        try:
            health = self._health_fn()
        except Exception as e:  # noqa: BLE001 — a failing probe IS the report
            return 503, json.dumps(
                {"ready": False, "error": repr(e)}
            ).encode() + b"\n"
        status = 200 if health.get("ready") else 503
        return status, json.dumps(health).encode() + b"\n"

    def _resolve_tracer(self):
        if self._tracer is not None:
            return self._tracer
        from tpu_dist_nn.obs.trace import TRACER

        return TRACER

    def attach(self, *, timeseries=None, slo=None, goodput=None) -> None:
        """Late-bind the /timeseries ring, /slo tracker, and /goodput
        tracker: the serving bring-up binds this endpoint BEFORE the
        sampler (and the ring it feeds) exists, so the routes 404 until
        attachment instead of holding the port hostage to construction
        order."""
        if timeseries is not None:
            self._timeseries = timeseries
        if slo is not None:
            self._slo = slo
        if goodput is not None:
            self._goodput = goodput

    def add_routes(self, routes: dict) -> None:
        """Late-mount extra GET routes (same shape as ``routes=``):
        the incident recorder's ``/incidents`` + fleet
        ``/debug/bundle`` bind here AFTER the serving bring-up built
        the recorder — the same construction-order seam as
        :meth:`attach`. Later mounts win (a router's fleet-capturing
        ``/debug/bundle`` overrides the built-in local one)."""
        self._routes.update(routes)

    def add_post_routes(self, routes: dict) -> None:
        """Late-mount extra POST routes (same shape as ``post_routes=``):
        the router's ``/router/scale`` manual-override verb binds here
        once the autoscaler exists."""
        self._post_routes.update(routes)

    def _trace_body(self, query: str):
        tracer = self._resolve_tracer()
        limit = None
        trace_id = None
        since = None
        for part in query.split("&"):
            k, _, v = part.partition("=")
            if k == "limit" and v:
                try:
                    limit = int(v)
                except ValueError:
                    return 400, b'{"error": "limit must be an integer"}\n'
            elif k == "trace_id" and v:
                trace_id = v
            elif k == "since" and v:
                # Monotonic cursor: only spans recorded AFTER sequence
                # number N (the previous reply's "cursor"), so a poller
                # stops re-downloading the whole ring every tick.
                try:
                    since = int(v)
                except ValueError:
                    return 400, b'{"error": "since must be an integer"}\n'
        return 200, tracer.render_json(
            limit, trace_id=trace_id, since=since
        ).encode() + b"\n"

    def _logs_body(self, query: str):
        from tpu_dist_nn.obs.log import LOG_RING

        window = None
        level = None
        limit = None
        for part in query.split("&"):
            k, _, v = part.partition("=")
            if not v:
                continue
            try:
                if k == "window":
                    window = float(v)
                elif k == "limit":
                    limit = int(v)
                elif k == "level":
                    level = v
            except ValueError:
                return 400, (b'{"error": "window must be a number of '
                             b'seconds, limit an integer"}\n')
        try:
            records = LOG_RING.snapshot(window=window, level=level,
                                        limit=limit)
        except ValueError as e:
            return 400, json.dumps({"error": str(e)}).encode() + b"\n"
        return 200, json.dumps({
            "capacity": LOG_RING.capacity,
            "dropped_total": LOG_RING.dropped_total,
            "records": records,
        }, default=repr).encode() + b"\n"

    def _debug_bundle_body(self, query: str):
        """Process-local on-demand diagnostic bundle: the stock route
        every ``--metrics-port`` endpoint serves (a router's recorder
        overrides it via :meth:`add_routes` with the fleet version).
        Captures whatever is attached to THIS endpoint — tracer,
        timeseries ring, SLO tracker, the log ring, /metrics text."""
        import urllib.parse

        from tpu_dist_nn.obs.incident import capture_bundle

        q = urllib.parse.parse_qs(query)
        reason = (q.get("reason") or ["on-demand capture"])[0]
        try:
            _iid, data = capture_bundle(
                "manual", reason,
                tracer=self._resolve_tracer(), registry=self._registry,
                ring=self._timeseries, slo=self._slo,
            )
        except Exception as e:  # noqa: BLE001 — degrade, never traceback
            log.warning("debug bundle capture failed: %r", e)
            return (500, "application/json", json.dumps(
                {"error": repr(e)}
            ).encode() + b"\n")
        return 200, "application/zip", data

    def _timeseries_body(self, query: str):
        ring = self._timeseries
        if ring is None:
            return 404, (b'{"error": "no time-series ring attached '
                         b'(start a serving command with '
                         b'--metrics-port)"}\n')
        family = None
        window = None
        for part in query.split("&"):
            k, _, v = part.partition("=")
            if not v:
                continue
            if k == "family":
                family = v
            elif k == "window":
                try:
                    window = float(v)
                except ValueError:
                    return 400, (b'{"error": "window must be a number '
                                 b'of seconds"}\n')
        doc = {
            "resolution_seconds": ring.resolution,
            "retention_seconds": ring.retention,
            "families": ring.families(),
            "series": ring.series(family=family, window=window),
        }
        return 200, json.dumps(doc).encode() + b"\n"

    def _slo_body(self, query: str):
        tracker = self._slo
        if tracker is None:
            return 404, (b'{"error": "no SLO tracker attached (pass '
                         b'--slo-latency-p99-ms / --slo-availability '
                         b'on the serving command)"}\n')
        return 200, json.dumps(tracker.status()).encode() + b"\n"

    def _goodput_body(self, query: str):
        tracker = self._goodput
        if tracker is None:
            return 404, (b'{"error": "no goodput tracker attached '
                         b'(start a serving command with '
                         b'--metrics-port)"}\n')
        return 200, json.dumps(tracker.snapshot()).encode() + b"\n"

    def _profile_body(self, query: str):
        from tpu_dist_nn.obs.profile import profile_snapshot

        window = None
        top = 5
        for part in query.split("&"):
            k, _, v = part.partition("=")
            if not v:
                continue
            try:
                if k == "window":
                    window = float(v)
                elif k == "top":
                    top = int(v)
            except ValueError:
                return 400, (
                    b'{"error": "window must be a number of seconds, '
                    b'top an integer"}\n'
                )
        doc = profile_snapshot(self._resolve_tracer(), window=window, top=top)
        return 200, json.dumps(doc).encode() + b"\n"

    def _debug_profile_body(self, query: str):
        """On-demand device capture: run ``jax.profiler.trace`` for
        ``?seconds=N`` (default 2, capped) and return the TensorBoard-
        format artifact directory as one zip body. Every failure mode
        is a JSON status, never a handler traceback: backends without
        profiler support 503, a concurrent capture 409."""
        seconds = 2.0
        for part in query.split("&"):
            k, _, v = part.partition("=")
            if k == "seconds" and v:
                try:
                    seconds = float(v)
                except ValueError:
                    return (400, "application/json",
                            b'{"error": "seconds must be a number"}\n')
        if not 0 < seconds <= self.MAX_CAPTURE_SECONDS:
            return (400, "application/json", json.dumps({
                "error": f"seconds must be in (0, "
                         f"{self.MAX_CAPTURE_SECONDS:g}]",
            }).encode() + b"\n")
        if not self._capture_lock.acquire(blocking=False):
            return (409, "application/json",
                    b'{"error": "a device capture is already running"}\n')
        try:
            import io
            import os
            import shutil
            import tempfile
            import zipfile

            tmp = tempfile.mkdtemp(prefix="tdn_device_profile_")
            try:
                import jax

                with jax.profiler.trace(tmp):
                    # The capture window: whatever the serving/training
                    # threads dispatch during it lands in the trace.
                    time.sleep(seconds)
                buf = io.BytesIO()
                with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
                    for root, _, files in os.walk(tmp):
                        for fname in files:
                            p = os.path.join(root, fname)
                            z.write(p, os.path.relpath(p, tmp))
                return 200, "application/zip", buf.getvalue()
            finally:
                shutil.rmtree(tmp, ignore_errors=True)
        except Exception as e:  # noqa: BLE001 — degrade, never traceback
            log.warning("device profile capture failed: %r", e)
            return (503, "application/json", json.dumps({
                "error": f"device profiler unavailable: {e!r}",
            }).encode() + b"\n")
        finally:
            self._capture_lock.release()

    def close(self) -> None:
        """Idempotent — a second close is a no-op, not a hang (stdlib
        shutdown() blocks forever if serve_forever already exited)."""
        if self._closed:
            return
        self._closed = True
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)


def start_http_server(port: int = 0, host: str = "0.0.0.0", *,
                      registry: Registry | None = None,
                      health_fn=None, routes=None, timeseries=None,
                      slo=None, goodput=None,
                      post_routes=None) -> MetricsServer:
    """Start the /metrics endpoint; returns the server (``.port`` holds
    the bound port when ``port=0`` picked an ephemeral one). ``routes``
    mounts extra GET paths and ``post_routes`` extra POST paths (see
    :class:`MetricsServer`); ``timeseries``/``slo``/``goodput``
    pre-attach the /timeseries, /slo, and /goodput sources (or
    late-bind them with :meth:`MetricsServer.attach`)."""
    return MetricsServer(port, host, registry=registry, health_fn=health_fn,
                         routes=routes, timeseries=timeseries, slo=slo,
                         goodput=goodput, post_routes=post_routes)
