"""Goodput & MFU accounting plane: how much of the hardware the live
workload actually uses, and where the rest went.

Every observability layer so far measures TIME (latency histograms,
traces, /profile self-time, SLO burn) — none measures UTILIZATION.
``bench.py``'s ``mfu`` comes from a synthetic offline matmul sweep, so
the serving path has no absolute-efficiency axis at all, and the
dominant serving waste Orca names (pad rows in static buckets, idle /
frozen decode slots at iteration granularity) is invisible. This module
is the accounting half:

* **Analytic per-launch FLOP models** — :func:`fcnn_flops_per_row` for
  the dense classifier chain and :class:`LMFlopModel` for the
  transformer prefill/decode kernels. Counts are matmul FLOPs (2mnk) at
  the STATIC kernel shapes the device actually launches: under
  static-shape jit a decode step attends over the full cache extent and
  a prefill chunk's scores span the whole key ladder, masked — masked
  lanes still burn MXU cycles, and that structural waste is exactly
  what this plane exists to expose. Elementwise/layernorm/softmax work
  is excluded (sub-percent on these shapes).
* **Exact useful/pad split** — every recorded launch's FLOPs divide
  into ``useful + pad == total`` BY CONSTRUCTION (pad is computed as
  the remainder of the same integer model, never re-derived), so
  conservation is testable to the FLOP. Pad carries a reason
  (``pad_rows``, ``idle_slot``, ``mid_prefill_slot``, ``attn_tail``,
  ``chunk_tail``, ``eos_frozen``) and a path (``batcher`` for the
  Process coalescer, ``gen`` for the generation schedulers, ``engine``
  for direct host-fed calls).
* **One peak calibration** — :data:`PEAK_FLOPS` (the per-device-kind
  dense bf16 table) and :func:`host_calibration_gflops` (the
  jax-independent host-BLAS anchor) moved here FROM bench.py, and
  bench.py now imports them back — offline ``mfu`` and the runtime
  ``tdn_mfu_ratio`` resolve their peak through the same code, so the
  two can never use divergent peaks. Off-accelerator the measured host
  anchor is the peak (an honest CPU-fallback MFU instead of null).

Exports (docs/OBSERVABILITY.md "Goodput & MFU"):

* ``tdn_goodput_flops_total{kind=useful|pad}`` — cumulative counters.
* ``tdn_mfu_ratio`` — windowed useful-FLOP rate / peak, refreshed on
  the runtime-sampler tick (:meth:`GoodputTracker.tick` — tick-pure:
  plain float math, no blocking call, calibration happens at
  configure time, never on the tick).
* ``tdn_pad_ratio{path}`` — cumulative pad share per path.
* ``tdn_prefix_flops_saved_total`` — prefill FLOPs the prefix cache
  made unnecessary (counted as SAVINGS, not as useful work done).
* ``GET /goodput`` — the per-stage breakdown (shares sum to 1).

Cost discipline: recording is a handful of integer adds per DEVICE
LAUNCH (not per request, not per row) on the thread that already owns
the launch; the armed-vs-disarmed A/B in bench.py keeps the bill
honest.
"""

from __future__ import annotations

import threading
import time

from tpu_dist_nn.obs.registry import REGISTRY, Registry

# Peak dense bf16 FLOP/s per JAX device, by device_kind substring.
# v2/v3 expose one device per core (half a chip); v4+ one per chip.
# (Moved from bench.py — the ONE table both offline and runtime MFU
# resolve through.)
PEAK_FLOPS = (
    ("v6", 918e12),  # Trillium / v6e chip
    ("v5p", 459e12),
    ("v5", 197e12),  # v5e / "TPU v5 lite"
    ("v4", 275e12),
    ("v3", 61.5e12),  # per core
    ("v2", 23e12),  # per core
)


def device_peak_flops(device_kind: str | None) -> float | None:
    """Table peak for a device kind (substring match), or None."""
    if not device_kind:
        return None
    kind = device_kind.lower()
    for key, peak in PEAK_FLOPS:
        if key in kind:
            return peak
    return None


def host_calibration_gflops(reps: int = 5) -> float:
    """Fixed host-BLAS anchor: f32 1024^2 matmul GFLOP/s, min-of-reps.

    jax-independent, so it measures the BOX, not the framework. Records
    in bench JSON so cross-round deltas can separate machine drift from
    code drift (docs/PERF.md "Cross-round drift"), and doubles as the
    measured peak for CPU-fallback MFU: off-accelerator the best this
    host can do at a dense matmul IS the denominator utilization should
    be judged against.
    """
    import numpy as np

    a = np.ones((1024, 1024), np.float32)
    b = np.ones((1024, 1024), np.float32)
    a @ b  # warm the BLAS path
    best = float("inf")
    for _ in range(reps):
        t0 = time.monotonic()
        a @ b
        best = min(best, time.monotonic() - t0)
    return 2 * 1024**3 / best / 1e9


_HOST_PEAK_CACHE: list[float] = []
_HOST_PEAK_LOCK = threading.Lock()


def measured_host_peak_flops() -> float:
    """One-shot cached host-BLAS peak in FLOP/s (the CPU-fallback MFU
    denominator). Measured at configure time, never on a sampler tick."""
    with _HOST_PEAK_LOCK:
        if not _HOST_PEAK_CACHE:
            _HOST_PEAK_CACHE.append(host_calibration_gflops() * 1e9)
        return _HOST_PEAK_CACHE[0]


def resolve_peak(device_kind: str | None = None) -> tuple[float, str]:
    """``(peak_flops, source)``: the table entry for ``device_kind``
    when it names a known accelerator, else the measured host anchor.
    ``source`` records which, so an artifact diff can tell a real MFU
    change from a peak-resolution change."""
    peak = device_peak_flops(device_kind)
    if peak is not None:
        return peak, f"table:{device_kind}"
    return measured_host_peak_flops(), "measured-host-blas"


# ---------------------------------------------------------------- models


def fcnn_flops_per_row(dims) -> int:
    """Matmul FLOPs for ONE row through a dense chain with layer widths
    ``dims = [d0, d1, ..., dk]``: sum of 2*a*b per layer (the standard
    dense count; bias adds and activations excluded)."""
    dims = [int(d) for d in dims]
    return sum(2 * a * b for a, b in zip(dims, dims[1:]))


class LMFlopModel:
    """Analytic FLOPs for the transformer generation kernels at their
    STATIC launch shapes (models/generate.py).

    Per token, per layer: QKV+output projections cost ``8*d^2``, the
    FFN ``4*d*f``; attention scores+apply cost ``4*d`` per KEY POSITION
    in the einsum — the static kernels compute the full ``cache_extent``
    key ladder and mask, so a launch's TOTAL counts every position
    while its USEFUL counts only the causally-live ones (the dead tail
    is ``attn_tail`` pad). The unembed costs ``2*d*V`` per position;
    only sampled positions (the decode token, a final chunk's last
    position) count as useful — the rest is ``chunk_tail``.

    All quantities are exact python ints so the useful+pad==total
    conservation contract is testable without float slop.
    """

    def __init__(self, n_layers: int, d_model: int, d_ff: int,
                 vocab_size: int, cache_extent: int):
        self.L = int(n_layers)
        self.d = int(d_model)
        self.f = int(d_ff)
        self.V = int(vocab_size)
        self.M = int(cache_extent)
        # Per-token constants (see class docstring).
        self._proj = self.L * (8 * self.d * self.d + 4 * self.d * self.f)
        self._attn_per_key = 4 * self.d * self.L
        self._logit = 2 * self.d * self.V

    @classmethod
    def from_config(cls, cfg, cache_extent: int) -> "LMFlopModel":
        return cls(cfg.n_layers, cfg.d_model, cfg.d_ff, cfg.vocab_size,
                   cache_extent)

    # -- decode step (decode_step_slots: one token per slot) ----------
    def step_flops(self) -> int:
        """Static per-slot cost of one decode-step launch."""
        return self._proj + self._attn_per_key * self.M + self._logit

    def step_useful_flops(self, pos: int) -> int:
        """Live per-slot cost at position ``pos`` (attends ``pos + 1``
        keys; its logits are sampled)."""
        return self._proj + self._attn_per_key * (int(pos) + 1) + self._logit

    def steps_useful_sum(self, start_pos: int, n_steps: int) -> int:
        """Sum of :meth:`step_useful_flops` over positions
        ``start_pos .. start_pos + n_steps - 1`` (closed form)."""
        n = int(n_steps)
        if n <= 0:
            return 0
        keys = n * int(start_pos) + n * (n + 1) // 2  # sum of (pos + 1)
        return n * (self._proj + self._logit) + self._attn_per_key * keys

    # -- prefill chunk (prefill_chunk_into_cache) ---------------------
    def chunk_flops(self, size: int) -> int:
        """Static cost of one chunk launch of ``size`` tokens: every
        query scores the full ``cache_extent`` key ladder and the
        unembed is expressed over all ``size`` positions."""
        c = int(size)
        return c * (self._proj + self._attn_per_key * self.M + self._logit)

    def chunk_useful_flops(self, start: int, size: int,
                           final: bool) -> int:
        """Live cost of that chunk: query ``i`` (absolute position
        ``start + i``) attends ``start + i + 1`` keys; only the FINAL
        chunk's last-position logits are sampled."""
        c, s = int(size), int(start)
        keys = c * s + c * (c + 1) // 2
        return (c * self._proj + self._attn_per_key * keys
                + (self._logit if final else 0))

    def prefill_chunks_flops(self, start: int, end: int,
                             chunk: int | None) -> int:
        """Static cost of the chunk launches covering token span
        ``[start, end)`` under a ``prefill_chunk`` budget (None = one
        monolithic chunk) — what a prefix hit of ``end - start`` tokens
        SAVES."""
        total = 0
        pos = int(start)
        end = int(end)
        while pos < end:
            c = end - pos if chunk is None else min(int(chunk), end - pos)
            total += self.chunk_flops(c)
            pos += c
        return total


# --------------------------------------------------------------- tracker


class GoodputTracker:
    """Process-wide FLOP ledger behind the goodput metric families.

    ``record_*`` calls run on the thread that owns the launch (batcher
    dispatch, scheduler loop, engine caller) and cost a few integer
    adds under one lock; :meth:`tick` runs on the runtime-sampler tick
    and only does float math over the ledger (tick-pure — peak
    calibration happens in :meth:`ensure_peak` at configure time).
    ``enabled = False`` turns every record into a no-op (the disarmed
    arm of bench.py's overhead A/B).
    """

    def __init__(self, registry: Registry | None = None):
        reg = registry if registry is not None else REGISTRY
        self._lock = threading.Lock()
        self.enabled = True
        # Integer FLOP ledgers (exact conservation is asserted on these;
        # the registry counters are their float mirrors).
        self._paths: dict[str, list[int]] = {}  # guarded-by: _lock
        self._stages: dict[str, list[int]] = {}  # guarded-by: _lock
        self._reasons: dict[str, int] = {}  # guarded-by: _lock
        self._saved = 0  # guarded-by: _lock
        self._launches = 0  # guarded-by: _lock
        self._peak: float | None = None  # guarded-by: _lock
        self._peak_source: str | None = None  # guarded-by: _lock
        self._tick_state: tuple[float, int] | None = None  # guarded-by: _lock
        self._last_mfu = 0.0  # guarded-by: _lock
        fam = reg.counter(
            "tdn_goodput_flops_total",
            "analytic model FLOPs by the live workload's device "
            "launches, split exactly into useful work vs structural "
            "pad (bucket pad rows, idle/frozen slots, masked attention "
            "tails)",
            labels=("kind",),
        )
        self._c_useful = fam.labels(kind="useful")
        self._c_pad = fam.labels(kind="pad")
        self._c_saved = reg.counter(
            "tdn_prefix_flops_saved_total",
            "prefill FLOPs skipped via prefix-cache hits (savings — "
            "work NOT done; never counted in tdn_goodput_flops_total)",
        )
        self._g_mfu = reg.gauge(
            "tdn_mfu_ratio",
            "useful model FLOPs per second over the last sampler "
            "window, divided by the resolved hardware peak (table for "
            "a known accelerator, measured host-BLAS anchor on the "
            "CPU fallback); 0 while idle",
        )
        self._g_pad = reg.gauge(
            "tdn_pad_ratio",
            "cumulative pad / (useful + pad) FLOP share per "
            "accounting path (batcher = Process coalescer buckets, "
            "gen = generation schedulers, engine = direct host-fed "
            "calls)",
            labels=("path",),
        )

    # ------------------------------------------------------------ peak

    def set_peak(self, peak_flops: float, source: str) -> None:
        with self._lock:
            self._peak = float(peak_flops)
            self._peak_source = source

    def ensure_peak(self, device_kind: str | None = None,
                    device_count: int | None = None) -> float:
        """Resolve the peak: the table entry for the active accelerator
        times the DEVICE COUNT the workload launches over (the ledger
        records whole multi-device launches, so a one-chip denominator
        would overstate MFU by the shard count), else the measured host
        anchor (the CPU fallback's virtual devices are slices of one
        box — no multiplier). Callers pass their placement's count
        (``Engine`` its mesh size); probing defaults to every visible
        accelerator device. The LARGEST peak configured so far wins —
        MFU is conservative, never overstated by a smaller later
        placement. Called at CONFIGURE time (engine/scheduler
        construction) — the host measurement is a real matmul and must
        never ride a tick."""
        kind = device_kind
        if kind is None:
            try:
                import jax

                devs = jax.devices()
                if devs and devs[0].platform != "cpu":
                    kind = devs[0].device_kind
                    if device_count is None:
                        device_count = len(devs)
            except Exception:  # noqa: BLE001 — no backend: host anchor
                kind = None
        per_device = device_peak_flops(kind)
        if per_device is not None:
            n = max(int(device_count or 1), 1)
            peak = per_device * n
            source = f"table:{kind}" + (f" x{n}" if n > 1 else "")
        else:
            peak = measured_host_peak_flops()
            source = "measured-host-blas"
        with self._lock:
            if self._peak is not None and peak <= self._peak:
                return self._peak
            self._peak = peak
            self._peak_source = source
            return peak

    # ---------------------------------------------------------- record

    def _add(self, stage: str, path: str, useful: int,
             pads: dict[str, int]) -> None:
        pad = sum(pads.values())
        with self._lock:
            self._launches += 1
            st = self._stages.setdefault(stage, [0, 0, 0])
            st[0] += useful
            st[1] += pad
            st[2] += 1
            pp = self._paths.setdefault(path, [0, 0])
            pp[0] += useful
            pp[1] += pad
            for reason, v in pads.items():
                self._reasons[reason] = self._reasons.get(reason, 0) + v
        if useful:
            self._c_useful.inc(useful)
        if pad:
            self._c_pad.inc(pad)

    def record_rows(self, flops_per_row: int, total_rows: int,
                    useful_rows: int, *, path: str = "engine",
                    stage: str = "infer",
                    reason: str = "pad_rows") -> None:
        """One row-shaped launch (the FCNN paths): ``total_rows`` went
        to the device, ``useful_rows`` of them carried request data —
        the remainder is bucket/shard pad."""
        if not self.enabled or flops_per_row <= 0 or total_rows <= 0:
            return
        useful_rows = max(0, min(int(useful_rows), int(total_rows)))
        useful = int(flops_per_row) * useful_rows
        pad = int(flops_per_row) * (int(total_rows) - useful_rows)
        self._add(stage, path, useful, {reason: pad} if pad else {})

    def record_decode_step(self, model: LMFlopModel, active_pos,
                           idle_slots: int, mid_prefill_slots: int, *,
                           replay_slots: int = 0,
                           path: str = "gen") -> None:
        """One ``decode_step_slots`` launch: ``active_pos`` is the
        launch-time position of every ACTIVE slot; inactive lanes split
        into empty (``idle_slot``) and occupied-but-still-prefilling
        (``mid_prefill_slot``); active lanes' dead key extent is
        ``attn_tail``. ``replay_slots`` are active lanes re-running
        tokens a preemption threw away (``preempt_replay`` — work
        re-done, never useful twice)."""
        if not self.enabled:
            return
        sf = model.step_flops()
        useful = sum(model.step_useful_flops(p) for p in active_pos)
        pads: dict[str, int] = {}
        if idle_slots > 0:
            pads["idle_slot"] = int(idle_slots) * sf
        if mid_prefill_slots > 0:
            pads["mid_prefill_slot"] = int(mid_prefill_slots) * sf
        if replay_slots > 0:
            pads["preempt_replay"] = int(replay_slots) * sf
        tail = len(list(active_pos)) * sf - useful
        if tail > 0:
            pads["attn_tail"] = tail
        self._add("decode", path, useful, pads)

    def record_prefill_chunk(self, model: LMFlopModel, start: int,
                             size: int, final: bool, *,
                             path: str = "gen") -> None:
        """One prefill-chunk launch: the masked key tail and the
        non-sampled unembed positions are ``chunk_tail`` pad."""
        if not self.enabled:
            return
        total = model.chunk_flops(size)
        useful = model.chunk_useful_flops(start, size, final)
        tail = total - useful
        self._add("prefill", path, useful,
                  {"chunk_tail": tail} if tail > 0 else {})

    def record_static_generate(self, model: LMFlopModel, outputs,
                               useful_rows: int, total_rows: int,
                               prompt_len: int,
                               eos_id: int | None, *,
                               dead_rows: int = 0,
                               path: str = "gen") -> None:
        """One run-to-completion Generate launch (the static scheduler
        behind ``_Batcher``): ``outputs (total_rows, T + N)`` are the
        materialized sequences. Bucket pad rows cost their full
        prefill+decode; real rows split per token — positions after a
        row's first EOS are ``eos_frozen`` pad (the done-mask keeps
        decoding them), masked attention tails are ``attn_tail``, the
        prefill's non-final logits/tail ``chunk_tail``. ``dead_rows``
        of the useful rows had waiters that abandoned after dispatch
        (the one window deadline expiry cannot close): their full ride
        is ``dead_waiter`` pad, never useful."""
        if not self.enabled or total_rows <= 0:
            return
        import numpy as np

        out = np.asarray(outputs)
        T = int(prompt_len)
        width = int(out.shape[1]) if out.ndim == 2 else 0
        steps = max(width - T - 1, 0)  # decode steps after the prefill
        n_gen = width - T  # tokens per row (first one from the prefill)
        useful_rows = max(0, min(int(useful_rows), int(total_rows)))
        pad_rows = int(total_rows) - useful_rows
        dead_rows = max(0, min(int(dead_rows), useful_rows))
        useful_rows -= dead_rows
        prefill_total = model.chunk_flops(T)
        prefill_useful = model.chunk_useful_flops(0, T, final=True)
        sf = model.step_flops()
        # Per-row useful token counts (first EOS inclusive; everything
        # after it is frozen).
        if useful_rows and n_gen > 0:
            gen = out[:useful_rows, T:]
            if eos_id is None:
                useful_tokens = np.full(useful_rows, n_gen, np.int64)
            else:
                hit = gen == int(eos_id)
                found = hit.any(axis=1)
                first = hit.argmax(axis=1)
                useful_tokens = np.where(found, first + 1, n_gen)
        else:
            useful_tokens = np.zeros(0, np.int64)
        pre_pads: dict[str, int] = {}
        dec_pads: dict[str, int] = {}
        if pad_rows:
            pre_pads["pad_rows"] = pad_rows * prefill_total
            if steps:
                dec_pads["pad_rows"] = pad_rows * steps * sf
        if dead_rows:
            # Full static ride at pad cost: the launch happened, nobody
            # was waiting for these rows' results.
            pre_pads["dead_waiter"] = dead_rows * prefill_total
            if steps:
                dec_pads["dead_waiter"] = dead_rows * steps * sf
        pre_tail = useful_rows * (prefill_total - prefill_useful)
        if pre_tail > 0:
            pre_pads["chunk_tail"] = pre_tail
        dec_useful = 0
        frozen = attn_tail = 0
        for k in useful_tokens:
            u_steps = max(int(k) - 1, 0)  # steps producing useful tokens
            row_useful = model.steps_useful_sum(T, u_steps)
            dec_useful += row_useful
            frozen += (steps - u_steps) * sf
            attn_tail += u_steps * sf - row_useful
        if frozen > 0:
            dec_pads["eos_frozen"] = frozen
        if attn_tail > 0:
            dec_pads["attn_tail"] = attn_tail
        self._add("prefill", path, useful_rows * prefill_useful, pre_pads)
        if steps or dec_pads:
            self._add("decode", path, dec_useful, dec_pads)

    def record_prefix_saved(self, flops: int) -> None:
        if not self.enabled or flops <= 0:
            return
        with self._lock:
            self._saved += int(flops)
        self._c_saved.inc(int(flops))

    # ------------------------------------------------------------ tick

    def tick(self, now: float | None = None) -> None:
        """The runtime-sampler callback: refresh ``tdn_mfu_ratio``
        (windowed useful-FLOP rate over resolved peak) and the per-path
        ``tdn_pad_ratio`` gauges. Pure ledger math — no calibration, no
        blocking call, no device work."""
        t = time.monotonic() if now is None else float(now)
        with self._lock:
            useful_total = sum(p[0] for p in self._paths.values())
            paths = {k: (v[0], v[1]) for k, v in self._paths.items()}
            peak = self._peak
            last = self._tick_state
            self._tick_state = (t, useful_total)
            mfu = 0.0
            if last is not None and peak:
                dt = t - last[0]
                if dt > 0:
                    mfu = max((useful_total - last[1]) / (peak * dt), 0.0)
            self._last_mfu = mfu
        self._g_mfu.set(mfu)
        for path, (u, p) in paths.items():
            total = u + p
            self._g_pad.labels(path=path).set(p / total if total else 0.0)

    # -------------------------------------------------------- snapshot

    def snapshot(self) -> dict:
        """The ``GET /goodput`` document: per-stage and per-path FLOP
        breakdown whose shares sum to 1, plus the peak provenance."""
        with self._lock:
            paths = {k: list(v) for k, v in self._paths.items()}
            stages = {k: list(v) for k, v in self._stages.items()}
            reasons = dict(self._reasons)
            saved = self._saved
            launches = self._launches
            peak = self._peak
            source = self._peak_source
            mfu = self._last_mfu
        useful = sum(v[0] for v in paths.values())
        pad = sum(v[1] for v in paths.values())
        total = useful + pad
        return {
            "enabled": self.enabled,
            "peak_flops": peak,
            "peak_source": source,
            "launches": launches,
            "mfu": mfu,
            "pad_ratio": pad / total if total else 0.0,
            "flops": {
                "useful": useful,
                "pad": pad,
                "total": total,
                "prefix_saved": saved,
            },
            "shares": {
                "useful": useful / total if total else 0.0,
                "pad": pad / total if total else 0.0,
            },
            "paths": {
                k: {
                    "useful": v[0],
                    "pad": v[1],
                    "pad_ratio": v[1] / (v[0] + v[1]) if v[0] + v[1] else 0.0,
                }
                for k, v in paths.items()
            },
            "stages": {
                k: {
                    "useful": v[0],
                    "pad": v[1],
                    "total": v[0] + v[1],
                    "share": (v[0] + v[1]) / total if total else 0.0,
                    "launches": v[2],
                }
                for k, v in stages.items()
            },
            "pad_reasons": reasons,
        }


# The process-wide tracker the serving/engine wiring records into and
# ``GET /goodput`` / the runtime sampler read from (the REGISTRY /
# TRACER convention). Tests build private ``GoodputTracker(registry=)``
# instances for isolation.
GOODPUT = GoodputTracker()
