"""SLO tracking: objectives, error budgets, multi-window burn rates.

The metrics stack says what the fleet is DOING; nothing says whether
that is GOOD ENOUGH. This module declares service-level objectives
(request latency, availability), evaluates them continuously from the
time-series ring's windowed deltas, and exports the two numbers an
operator actually pages on (Beyer et al., *Site Reliability Workbook*
ch. 5, multi-window multi-burn-rate alerting):

* ``burn_rate{window}`` — the rate the error budget is being consumed,
  normalized so 1.0 means "exactly on track to spend the whole budget
  over the compliance window". A FAST window (default 5 min) catches
  sudden breakage; a SLOW window (default 1 h, also the compliance
  window here) filters blips.
* ``error_budget_remaining`` — the fraction of the slow window's
  budget still unspent; 0 means the objective is blown for the window.

Objectives are fraction-of-bad-events shaped, the form burn rates
need:

* :func:`latency_objective` — "at most (1-q) of requests may take
  longer than T": bad = observations over T (bucket-interpolated from
  the family's histogram deltas), budget fraction = 1-q. The measured
  windowed p-quantile (via the shared
  :func:`~tpu_dist_nn.obs.registry.histogram_quantile`) is reported
  alongside, so "p99 = 212ms against a 100ms objective" reads
  directly.
* :func:`availability_objective` — "at least A of requests must
  succeed": bad = error-family delta (or a label-predicate over the
  total family, e.g. router outcomes != ok), budget fraction = 1-A.

Exports ``tdn_slo_burn_rate{slo,window}`` and
``tdn_slo_error_budget_remaining{slo}`` gauges, serves ``GET /slo``
(obs/exposition.py), and emits a rate-limited ``slo.burn`` structured
event (obs/log.py) while the fast window burns above 1.0. Stdlib-only,
evaluated on the runtime sampler's tick — never on a request path.
"""

from __future__ import annotations

import threading
import time

from tpu_dist_nn.obs.log import get_logger
from tpu_dist_nn.obs.registry import REGISTRY, Registry, histogram_quantile

# Burn alerts are news but not a stream: first couple fire, then one
# per ~30s per objective while the burn persists (suppressed counts
# surface on the next emit, the obs/log contract). The bucket is
# PER OBJECTIVE (each tracker builds one logger per objective via
# _burn_logger) — obs/log keys its bucket on (logger, event), and one
# continuously-burning objective must not starve another's alerts.


def _burn_logger():
    return get_logger(__name__, rate=1.0 / 30.0, burst=2)

DEFAULT_FAST_WINDOW = 300.0
DEFAULT_SLOW_WINDOW = 3600.0


class Objective:
    """One declared objective. ``kind`` is ``latency`` or
    ``availability``; ``budget_fraction`` is the tolerated bad-event
    fraction (1-q, 1-A). Construct through the two factories below."""

    def __init__(self, name: str, kind: str, budget_fraction: float,
                 family: str, match: dict | None = None, *,
                 threshold_s: float | None = None, q: float = 0.99,
                 bad_family: str | None = None,
                 bad_match: dict | None = None,
                 bad_exclude: dict | None = None,
                 description: str = ""):
        if not 0.0 < budget_fraction < 1.0:
            raise ValueError(
                f"{name}: budget fraction must be in (0, 1), got "
                f"{budget_fraction}"
            )
        self.name = name
        self.kind = kind
        self.budget_fraction = float(budget_fraction)
        self.family = family
        self.match = dict(match or {})
        self.threshold_s = threshold_s
        self.q = float(q)
        self.bad_family = bad_family
        self.bad_match = dict(bad_match or {})
        self.bad_exclude = dict(bad_exclude or {})
        self.description = description

    def describe(self) -> dict:
        doc = {
            "name": self.name,
            "kind": self.kind,
            "budget_fraction": self.budget_fraction,
            "family": self.family,
        }
        if self.match:
            doc["match"] = self.match
        if self.kind == "latency":
            doc["objective"] = (
                f"p{self.q * 100:g} <= {self.threshold_s * 1e3:g}ms"
            )
            doc["threshold_ms"] = round(self.threshold_s * 1e3, 3)
            doc["quantile"] = self.q
        else:
            doc["objective"] = f"availability >= {1 - self.budget_fraction}"
            doc["target"] = 1 - self.budget_fraction
        if self.description:
            doc["description"] = self.description
        return doc


def latency_objective(name: str, family: str, threshold_s: float,
                      q: float = 0.99, match: dict | None = None,
                      description: str = "") -> Objective:
    """p<q> of ``family`` (a histogram) must be <= ``threshold_s``;
    equivalently at most 1-q of requests may exceed it."""
    if threshold_s <= 0:
        raise ValueError(f"{name}: threshold must be > 0, got {threshold_s}")
    return Objective(name, "latency", 1.0 - q, family, match,
                     threshold_s=float(threshold_s), q=q,
                     description=description)


def availability_objective(name: str, target: float, total_family: str,
                           bad_family: str | None = None,
                           match: dict | None = None,
                           bad_match: dict | None = None,
                           bad_exclude: dict | None = None,
                           description: str = "") -> Objective:
    """At least ``target`` of ``total_family`` events must be good.
    Bad events come from ``bad_family`` (e.g. the errors counter), or —
    when the total family itself carries the verdict in a label — from
    ``total_family`` filtered by ``bad_match``/``bad_exclude`` (e.g.
    router outcomes with ``bad_exclude={"outcome": "ok"}``)."""
    if bad_family is None and not bad_match and not bad_exclude:
        raise ValueError(
            f"{name}: name the bad events — pass bad_family, or "
            "bad_match/bad_exclude over the total family"
        )
    return Objective(name, "availability", 1.0 - float(target),
                     total_family, match,
                     bad_family=bad_family,
                     bad_match=bad_match, bad_exclude=bad_exclude,
                     description=description)


class SLOTracker:
    """Evaluates objectives from a
    :class:`~tpu_dist_nn.obs.timeseries.TimeSeriesRing` on demand (the
    runtime sampler ticks :meth:`evaluate`), publishes the burn-rate /
    budget gauges, and keeps the last verdict for ``GET /slo``."""

    def __init__(self, ring, objectives, *,
                 fast_window: float = DEFAULT_FAST_WINDOW,
                 slow_window: float = DEFAULT_SLOW_WINDOW,
                 registry: Registry | None = None, logger=None):
        if fast_window <= 0 or slow_window <= 0:
            raise ValueError("SLO windows must be > 0")
        if fast_window > slow_window:
            raise ValueError(
                f"fast window {fast_window} must be <= slow window "
                f"{slow_window}"
            )
        reg = registry if registry is not None else REGISTRY
        self.ring = ring
        self.objectives = list(objectives)
        self.fast_window = float(fast_window)
        self.slow_window = float(slow_window)
        # One logger (= one token bucket) per objective; an injected
        # logger (tests) is shared deliberately.
        self._slogs = {
            obj.name: (logger if logger is not None else _burn_logger())
            for obj in self.objectives
        }
        self._g_burn = reg.gauge(
            "tdn_slo_burn_rate",
            "error-budget burn rate per objective and window (1.0 = "
            "on track to spend the whole budget over the window; "
            "fast > 1 pages, slow > 1 confirms)",
            labels=("slo", "window"),
        )
        self._g_budget = reg.gauge(
            "tdn_slo_error_budget_remaining",
            "fraction of the slow window's error budget still unspent "
            "(0 = objective blown for the window)",
            labels=("slo",),
        )
        self._lock = threading.Lock()
        # guarded-by: _lock
        self._last: dict = {"objectives": [], "evaluated_at": None}

    # ------------------------------------------------------- evaluation

    def _series_keys(self, family: str, suffix: str, match: dict,
                     exclude: dict | None = None) -> list[str]:
        from tpu_dist_nn.obs.exposition import split_series

        keys = []
        want = family + suffix
        for key in self.ring.series(family=family):
            name, labels = split_series(key)
            if name != want:
                continue
            if any(labels.get(k) != str(v) for k, v in match.items()):
                continue
            if exclude and all(
                labels.get(k) == str(v) for k, v in exclude.items()
            ):
                continue
            keys.append(key)
        return keys

    def _window_counts(self, obj: Objective, window: float,
                       now: float | None):
        """-> (bad, total, measured) over the window, from ring deltas."""
        from tpu_dist_nn.obs.exposition import split_series

        if obj.kind == "latency":
            # Per-bucket deltas -> windowed distribution.
            per_edge: dict[float, float] = {}
            for key in self._series_keys(obj.family, "_bucket", obj.match):
                _, labels = split_series(key)
                try:
                    edge = float(labels.get("le", ""))
                except ValueError:
                    continue
                d, _ = self.ring.delta(key, window, now)
                per_edge[edge] = per_edge.get(edge, 0.0) + d
            total_d = sum(
                self.ring.delta(key, window, now)[0]
                for key in self._series_keys(obj.family, "_count", obj.match)
            )
            edges = sorted(per_edge)
            counts = [per_edge[e] for e in edges]
            # +Inf tail: observations past the last finite edge.
            counts.append(max(total_d - sum(counts), 0.0))
            # Bad fraction: observations over the threshold, with
            # linear interpolation inside the containing bucket (the
            # quantile estimator's dual).
            bad = counts[-1]
            lo = 0.0
            for e, n in zip(edges, counts):
                if obj.threshold_s < lo:
                    bad += n
                elif obj.threshold_s < e:
                    frac_over = (e - obj.threshold_s) / (e - lo) if e > lo \
                        else 0.0
                    bad += n * frac_over
                lo = e
            measured = histogram_quantile(edges, counts, obj.q)
            return bad, total_d, measured
        bad = 0.0
        if obj.bad_family is not None:
            for key in self._series_keys(obj.bad_family, "", obj.bad_match):
                bad += self.ring.delta(key, window, now)[0]
        else:
            for key in self._series_keys(obj.family, "", obj.bad_match,
                                         obj.bad_exclude):
                bad += self.ring.delta(key, window, now)[0]
        total = sum(
            self.ring.delta(key, window, now)[0]
            for key in self._series_keys(obj.family, "", obj.match)
        )
        measured = 1.0 - (bad / total) if total > 0 else None
        return bad, total, measured

    def evaluate(self, now: float | None = None) -> dict:
        """One evaluation pass: compute per-objective burn rates over
        both windows, publish the gauges, emit ``slo.burn`` while the
        fast window burns > 1, and return (and cache) the /slo doc."""
        t = time.time() if now is None else float(now)
        out = []
        for obj in self.objectives:
            windows = {}
            for label, window in (("fast", self.fast_window),
                                  ("slow", self.slow_window)):
                bad, total, measured = self._window_counts(obj, window, now)
                bad_frac = (bad / total) if total > 0 else 0.0
                burn = bad_frac / obj.budget_fraction
                self._g_burn.labels(slo=obj.name, window=label).set(burn)
                windows[label] = {
                    "seconds": window,
                    "bad": round(bad, 3),
                    "total": round(total, 3),
                    "bad_fraction": round(bad_frac, 6),
                    "burn_rate": round(burn, 4),
                }
                if obj.kind == "latency":
                    windows[label]["measured_quantile_ms"] = (
                        round(measured * 1e3, 3) if measured is not None
                        else None
                    )
                else:
                    windows[label]["measured_availability"] = (
                        round(measured, 6) if measured is not None else None
                    )
            remaining = max(0.0, 1.0 - windows["slow"]["burn_rate"])
            self._g_budget.labels(slo=obj.name).set(remaining)
            breaching = (windows["fast"]["burn_rate"] > 1.0
                         and windows["fast"]["total"] > 0)
            if breaching:
                self._slogs[obj.name].warning(
                    "slo.burn", slo=obj.name,
                    objective=obj.describe()["objective"],
                    burn_fast=windows["fast"]["burn_rate"],
                    burn_slow=windows["slow"]["burn_rate"],
                    budget_remaining=round(remaining, 4),
                )
            out.append({
                **obj.describe(),
                "windows": windows,
                "error_budget_remaining": round(remaining, 4),
                "burning": breaching,
            })
        doc = {
            "evaluated_at": t,
            "fast_window_seconds": self.fast_window,
            "slow_window_seconds": self.slow_window,
            "objectives": out,
        }
        with self._lock:
            self._last = doc
        return doc

    def status(self) -> dict:
        """The last evaluation (the ``GET /slo`` body); evaluates once
        if nothing has ticked yet so the route is never empty."""
        with self._lock:
            last = self._last
        if last.get("evaluated_at") is None:
            return self.evaluate()
        return last
