"""Loopback throughput for the LM generation endpoint (VERDICT r4
item 7's artifact): the continuous-batching pipelined decoder behind
the real gRPC wire, measured end to end.

Two servings of the SAME model answer the same request mix:

* single-chip KV-cached decode (`serve_lm_generate(num_stages=1)`)
* pipelined OVERLAPPED round-robin decode (`num_stages=2`), where the
  batcher's coalesced rows pad into the decoder's (G, Bg) group grid

Measured: wall seconds for R concurrent clients x K requests of
(rows, T) prompts each, -> requests/s and generated tokens/s, plus the
coalescing counters (batches < requests proves rows actually fused).

Honest scope (same rule as examples/schedule_walltime.py): the 8
virtual devices share ONE physical core, so the pipelined endpoint's
wall time reflects total compute + collective overhead, not parallel
makespan — single-chip WINS here by construction. The pipelined row's
evidentiary value is end-to-end function + coalescing into group
slots; the decoder-level overlapped-vs-masked speedup on real parallel
placement is artifacts/pp_decode_r04 (2.55x). Parity of every served
token against models.generate is asserted inline.

Writes artifacts/serving_generate_r05/RECORD.json.
Run: python examples/serve_generate_throughput.py [--fast]
"""

from __future__ import annotations

import argparse
import json
import os
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=8"
).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from tpu_dist_nn.models.generate import generate  # noqa: E402
from tpu_dist_nn.models.transformer import (  # noqa: E402
    TransformerConfig,
    init_transformer,
)
from tpu_dist_nn.serving import GrpcClient, serve_lm_generate  # noqa: E402

ART = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "artifacts", "serving_generate_r05",
)

T, N = 16, 24


def drive(port: int, clients: int, rpcs: int, rows: int, ref) -> dict:
    pool = [GrpcClient(f"127.0.0.1:{port}", timeout=120.0) for _ in range(clients)]
    rng = np.random.default_rng(3)
    prompts = [
        rng.integers(0, 64, (rows, T)) for _ in range(clients * rpcs)
    ]

    def worker(i):
        c = pool[i % clients]
        outs = []
        for j in range(rpcs):
            outs.append(c.generate(prompts[i * rpcs + j]))
        return outs

    t0 = time.monotonic()
    with ThreadPoolExecutor(max_workers=clients) as ex:
        all_outs = list(ex.map(worker, range(clients)))
    wall = time.monotonic() - t0
    # Parity: every served row equals the single-chip decode of its
    # prompt (greedy endpoint).
    for i, outs in enumerate(all_outs):
        for j, out in enumerate(outs):
            want = ref(prompts[i * rpcs + j])
            np.testing.assert_array_equal(out[:, T:], want)
    n_req = clients * rpcs
    return {
        "clients": clients, "rpcs_per_client": rpcs, "rows_per_rpc": rows,
        "wall_s": round(wall, 3),
        "requests_per_s": round(n_req / wall, 2),
        "generated_tokens_per_s": round(n_req * rows * N / wall, 1),
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--fast", action="store_true")
    args = ap.parse_args()
    clients, rpcs, rows = (4, 2, 2) if args.fast else (8, 4, 2)
    os.makedirs(ART, exist_ok=True)

    cfg = TransformerConfig(
        vocab_size=64, d_model=64, n_heads=4, n_layers=4, d_ff=128,
        max_seq_len=T + N,
    )
    params = init_transformer(jax.random.key(11), cfg)

    def ref(prompts):
        return np.asarray(generate(params, cfg, prompts, N, temperature=0.0))

    record = {
        "task": "LM generation endpoint loopback throughput "
                "(VERDICT r4 item 7)",
        "model": "d64/h4/L4 byte-vocab toy", "prompt_len": T,
        "max_new_tokens": N,
        "scope_note": "1 physical core under 8 virtual devices: the "
                      "pipelined row evidences end-to-end function + "
                      "coalescing into group slots, not parallel "
                      "speedup (see artifacts/pp_decode_r04 for the "
                      "decoder-level overlapped 2.55x)",
        "endpoints": {},
    }

    for name, kw in (
        ("single_chip", dict(num_stages=1)),
        ("pipelined_overlapped", dict(num_stages=2, num_groups=4)),
    ):
        server, port = serve_lm_generate(
            params, cfg, 0, max_new_tokens=N, prompt_len=T,
            host="127.0.0.1", warm_rows=rows * clients, **kw,
        )
        try:
            m = drive(port, clients, rpcs, rows, ref)
            b = server.batcher
            m["requests_total"] = b.requests_total
            m["batches_total"] = b.batches_total
            m["coalesced"] = b.batches_total < b.requests_total
            record["endpoints"][name] = m
        finally:
            server.stop(0)
        with open(os.path.join(ART, "RECORD.json"), "w") as f:
            json.dump(record, f, indent=2)
            f.write("\n")
    print(json.dumps(record["endpoints"], indent=2))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
