#!/usr/bin/env bash
# Real multi-host training on one box: two JAX processes, one model.
#
# The moral equivalent of the reference spawning N containers on a
# bridge network (run_grpc_fcnn.py:83-155): each process owns half the
# (virtual) devices, batches assemble across processes per step, and
# both hosts stay bit-identical (same losses, same exported JSON —
# compare the two output files to see it).
#
# On real TPU pods, drop JAX_PLATFORMS/XLA_FLAGS and give every host
# the same --coordinator; everything else is unchanged.
set -euo pipefail
PORT=${PORT:-29900}
COMMON=(--coordinator "localhost:$PORT" --num-hosts 2
        --layers 20,16,6 --data synthetic --num-examples 1280 --epochs 2
        --batch-size 128 --distribution 1,1 --data-parallel 4 --lr 1e-2)
run() {
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=4 \
  python -m tpu_dist_nn.cli train "${COMMON[@]}" \
    --host-id "$1" --out "/tmp/tdn_mh_model_$1.json"
}
rm -f /tmp/tdn_mh_model_0.json /tmp/tdn_mh_model_1.json
run 0 & PID0=$!
run 1 & PID1=$!
wait "$PID0"; wait "$PID1"   # propagate either child's failure
cmp /tmp/tdn_mh_model_0.json /tmp/tdn_mh_model_1.json \
  && echo "hosts exported identical models"
