"""BASELINE configs[2]: an 8-layer MLP on an 8-stage pipeline, one
layer per device, end to end — train on REAL digits, export to the
reference JSON schema, serve, and measure what the deep placement
costs.

The reference never recorded numbers for its deep-pipeline shape
("Fashion-MNIST 8-layer MLP, 8-stage pipeline (one layer per core)");
this experiment closes that config with committed evidence
(artifacts/deep_pipeline_r04/). Workload: the vendored real
handwritten digits (64-dim — the zero-egress real-data anchor,
tests/test_real_data.py), an 8-dense-layer MLP sized
64-96-80-64-48-32-24-16-10, distribution [1]*8 so every layer is its
own pipeline stage.

Measurements, all through the public Engine surface:

* held-out accuracy of the 8-layer model trained THROUGH the 8-stage
  pipelined trainer (gradients cross 7 ppermute hops every step);
* p50 step latency + p50 per-stage share (``Engine.step_latency`` —
  the BASELINE metric) for the 8-stage placement vs a 3-stage
  placement of the same model vs single-chip;
* pipeline bubble overhead: measured step-latency ratios next to the
  tick model's prediction ((M + S - 1)/M forward ticks).

Run (8 virtual devices):

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    JAX_PLATFORMS=cpu python examples/deep_pipeline_8stage.py
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np

SIZES = [64, 96, 80, 64, 48, 32, 24, 16, 10]  # 8 dense layers
DEEP_DIST = [1] * 8
SHALLOW_DIST = [3, 3, 2]


def run(out_json: str | None = None, epochs: int = 30) -> dict:
    import jax

    from tpu_dist_nn.api.engine import Engine
    from tpu_dist_nn.data.datasets import real_digits
    from tpu_dist_nn.models.fcnn import init_fcnn, spec_from_params
    from tpu_dist_nn.train.trainer import TrainConfig

    n_dev = len(jax.devices())
    data, eval_data = real_digits("train"), real_digits("test")
    acts = ["relu"] * 7 + ["softmax"]
    model = spec_from_params(init_fcnn(jax.random.key(0), SIZES), acts)

    # --- train THROUGH the 8-stage pipeline (one layer per stage) ----
    engine = Engine.up(model, DEEP_DIST)
    placement = engine.placement()
    t0 = time.monotonic()
    engine.train(
        data,
        TrainConfig(epochs=epochs, batch_size=64, learning_rate=1e-3),
        eval_data=eval_data,
    )
    train_seconds = time.monotonic() - t0
    res = engine.run_inference(eval_data.x, eval_data.y, batch_size=256)
    metrics = res.metrics

    # --- export (reference JSON schema, metrics embedded) and re-serve
    import tempfile

    path = out_json or (tempfile.mkdtemp() + "/deep8_model.json")
    exported = engine.export(path, metrics=metrics)

    # --- the BASELINE latency metric across placements ---------------
    lat_deep = Engine.up(exported, DEEP_DIST).step_latency(256, 30)
    lat_shallow = Engine.up(exported, SHALLOW_DIST).step_latency(256, 30)
    lat_single = Engine.up(exported, [8]).step_latency(256, 30)

    M = 4  # engine default microbatches
    record = {
        "experiment": "BASELINE configs[2] — 8-layer MLP, 8-stage pipeline (one layer/stage)",
        "devices": n_dev,
        "model_sizes": SIZES,
        "placement": placement,
        "train_seconds": round(train_seconds, 2),
        "epochs": epochs,
        "held_out_accuracy": metrics["accuracy"],
        "metrics": metrics,
        "step_latency": {
            "deep_8stage": lat_deep,
            "shallow_3stage": lat_shallow,
            "single_chip": lat_single,
        },
        "bubble_model": {
            "note": "forward tick count is M + S - 1; overhead vs an "
                    "ideal bubble-free pipeline is (S - 1)/M extra ticks",
            "deep_ticks": M + 8 - 1,
            "shallow_ticks": M + 3 - 1,
            "predicted_deep_vs_shallow": round((M + 7) / (M + 2), 3),
            "measured_deep_vs_shallow_p50": round(
                lat_deep["p50_s"] / lat_shallow["p50_s"], 3
            ),
            "measured_deep_vs_single_p50": round(
                lat_deep["p50_s"] / lat_single["p50_s"], 3
            ),
        },
    }
    return record


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None, help="export trained model JSON here")
    ap.add_argument("--record", default=None, help="write the experiment record JSON here")
    ap.add_argument("--epochs", type=int, default=30)
    args = ap.parse_args(argv)
    record = run(args.out, epochs=args.epochs)
    text = json.dumps(record, indent=1, default=float)
    print(text)
    if args.record:
        with open(args.record, "w") as f:
            f.write(text + "\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
