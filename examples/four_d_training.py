"""4D-parallel LM training on one 8-device mesh: PP x TP x SP (x DP)
in a single hand-rolled schedule, on REAL text.

Round-4 session 3 closed the schedule x sharding matrix; this
experiment drives the headline composition end to end through the
public trainer surface: a byte-level Transformer trained with

* pipeline parallelism over ``stage`` (2 stages),
* Megatron tensor parallelism over ``model`` (2 shards — two psums
  per block inside the schedule's switch branches),
* sequence parallelism over ``seq`` (2 shards — ring attention with
  the branch-safe group-local K/V rotation),

on the vendored real-English corpus, for each of the four schedules
that support the 3-way composition (gpipe, 1f1b, interleaved, zb) —
recording per-schedule losses and verifying they agree at matched
step count and seed (they run the SAME math: one shared masked-CE
oracle, parity-tested in tests/test_pipeline_tp_sp.py — here we show
it holds over a real multi-step training trajectory, not just one
gradient).

Run (8 virtual devices):

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/four_d_training.py
"""

from __future__ import annotations

import argparse
import json
import time


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--out", default=None, help="write the record JSON here")
    args = ap.parse_args()

    import jax

    if jax.default_backend() not in ("cpu", "tpu"):  # pragma: no cover
        jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import optax

    from tpu_dist_nn.data.text import encode, lm_sequences, load_corpus
    from tpu_dist_nn.models.transformer import (
        TransformerConfig,
        init_transformer,
    )
    from tpu_dist_nn.parallel.mesh import MeshSpec, build_mesh
    from tpu_dist_nn.train.lm_trainer import (
        lm_block_layout,
        make_pipeline_sp_lm_train_step,
    )

    if len(jax.devices()) < 8:
        raise SystemExit(
            "needs 8 devices (set XLA_FLAGS=--xla_force_host_platform_"
            "device_count=8)"
        )

    cfg = TransformerConfig(
        vocab_size=256, d_model=64, n_heads=4, n_layers=4, d_ff=128,
        max_seq_len=64,
    )
    text, source = load_corpus(None)
    rows = lm_sequences(encode(text), 63)  # rows carry 64 = input+target
    rng = np.random.default_rng(0)
    batch_ids = rng.integers(0, len(rows), (args.steps, 8))
    mesh = build_mesh(MeshSpec(stage=2, model=2, seq=2))
    optimizer = optax.adam(1e-3)
    base = init_transformer(jax.random.key(0), cfg)

    record = {
        "mesh": "stage=2 x model=2 x seq=2 (8 devices)",
        "corpus": source,
        "config": "d64/h4/L4, seq 63 (+1 target), batch 8",
        "steps": args.steps,
        "schedules": {},
    }
    finals = {}
    for sched in ("gpipe", "1f1b", "interleaved", "zb"):
        # The CLI's shared (schedule, sharding) -> layout dispatch.
        shard, unshard = lm_block_layout(sched, 2, 1, cfg=cfg, tp=2)
        params = dict(base, blocks=shard(base["blocks"]))
        step = make_pipeline_sp_lm_train_step(
            mesh, cfg, 2, 2, optimizer, mode="ring", schedule=sched,
            num_virtual=1, tensor_parallel=2,
        )
        opt_state = optimizer.init(params)
        t0 = time.monotonic()
        losses = []
        for i in range(args.steps):
            tokens = np.stack([rows[j] for j in batch_ids[i]])
            params, opt_state, loss = step(params, opt_state, tokens)
            losses.append(float(loss))
        wall = time.monotonic() - t0
        finals[sched] = losses[-1]
        record["schedules"][sched] = {
            "first_loss": round(losses[0], 6),
            "final_loss": round(losses[-1], 6),
            "wall_seconds_incl_compile": round(wall, 2),
        }
        # sanity: the params came back trainable and unshard cleanly
        unshard(params["blocks"])

    # All four schedules run the same math on the same data/seed: the
    # trajectories must agree to float tolerance.
    vals = list(finals.values())
    spread = max(vals) - min(vals)
    record["final_loss_spread_across_schedules"] = spread
    assert spread < 1e-3, finals
    assert vals[0] < record["schedules"]["gpipe"]["first_loss"], "no learning"
    out = json.dumps(record, indent=2)
    print(out)
    if args.out:
        with open(args.out, "w") as f:
            f.write(out + "\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
