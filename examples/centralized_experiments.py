"""The reference notebook's experiment suite, natively on TPU.

``scripts/Centralized_MNIST_Experimentation.ipynb`` is the reference's
model-production toolchain (SURVEY.md C10): it (a) trains a
linear-softmax baseline, (b) times per-sample sequential inference,
(c) trains the 784-32-16-10 MLP that ships as the serving config and
scores accuracy/precision/recall/F1 + batched latency (cell 9:
0.9685 / 0.9691 / 0.9685 / 0.9686, 76 us/sample), (d) exports it to
the per-neuron JSON schema with the metrics embedded (cell 10), and
(e) sizes one input payload (cell 11: 6 272 B as float64).

Same experiments here, driven through the framework's own pieces
(trainer, metrics, schema, engine) — runs on one chip or the CPU test
mesh:

    python examples/centralized_experiments.py [--out model.json]

The default dataset is the vendored REAL handwritten digits
(``tpu_dist_nn.data.datasets.real_digits`` — 1,797 genuine 8x8 scans,
zero egress), so the printed accuracies are real generalization
numbers on a real held-out split, directly comparable in kind to the
reference's recorded MNIST metrics. ``--data synthetic`` keeps the
MNIST-shaped synthetic task (easier — expect ~1.0 accuracies; metric
*shapes* only); real MNIST IDX files drop in via
``load_mnist_idx`` / ``--data idx:DIR`` when egress exists.
"""

from __future__ import annotations

import argparse
import json
import time

import jax
import jax.numpy as jnp
import numpy as np

from tpu_dist_nn.data.datasets import synthetic_mnist
from tpu_dist_nn.models.fcnn import forward, init_fcnn, spec_from_params
from tpu_dist_nn.train.trainer import TrainConfig, evaluate_fcnn, train_fcnn


def experiment_linear_softmax(data, eval_data, epochs=15):
    """(a) Notebook cell 2: 784->10 linear-softmax, 15 epochs.

    ``epochs`` scales with the dataset: the reference's 15 epochs on
    54k MNIST rows is ~6.3k optimizer steps; callers with smaller real
    sets pass more epochs to grant the linear model a comparable
    optimization budget (steps, not passes, is what converges it).
    """
    params = init_fcnn(jax.random.key(0), [data.x.shape[1], data.num_classes],
                       ["softmax"])
    params, history = train_fcnn(
        params, data, TrainConfig(epochs=epochs, batch_size=128), eval_data
    )
    acc = history[-1]["eval"]["accuracy"]
    print(f"[a] linear-softmax: eval accuracy {acc:.4f} "
          f"(reference cell 2: 0.9265)")
    return acc


def experiment_per_sample_latency(params, eval_data, n=100):
    """(b) Notebook cell 4: sequential single-sample inference x100."""
    n = min(n, len(eval_data))
    apply = jax.jit(forward)
    x = jnp.asarray(eval_data.x[:n], jnp.float32)
    jax.block_until_ready(apply(params, x[:1]))  # compile once
    t0 = time.monotonic()
    correct = 0
    for i in range(n):
        out = np.asarray(apply(params, x[i : i + 1]))
        correct += int(out.argmax(-1)[0] == eval_data.y[i])
    dt = time.monotonic() - t0
    print(f"[b] per-sample x{n}: acc {correct / n:.3f}, {dt:.4f} s total "
          f"({dt / n * 1e3:.3f} ms/sample; reference cell 4: 9.9891 s, "
          f"~99.9 ms/sample)")
    return dt


def experiment_serving_mlp(data, eval_data):
    """(c) Notebook cells 8-9: the 784-32-16-10 serving model."""
    sizes = [data.x.shape[1], 32, 16, data.num_classes]
    params = init_fcnn(jax.random.key(1), sizes)
    t0 = time.monotonic()
    params, _ = train_fcnn(
        params, data, TrainConfig(epochs=30, batch_size=128), eval_data=None
    )
    train_s = time.monotonic() - t0
    evaluate_fcnn(params, eval_data, batch_size=8192)  # warm-up compile
    t0 = time.monotonic()
    metrics = evaluate_fcnn(params, eval_data, batch_size=8192)
    eval_s = time.monotonic() - t0
    per_sample_us = eval_s / len(eval_data) * 1e6
    print(f"[c] 784-32-16-10 MLP (30 epochs, {train_s:.1f}s): "
          f"acc {metrics['accuracy']:.4f} precision {metrics['precision']:.4f} "
          f"recall {metrics['recall']:.4f} f1 {metrics['f1_score']:.4f}; "
          f"batched eval {eval_s:.4f}s ({per_sample_us:.1f} us/sample; "
          f"reference cell 9: 0.9685/0.9691/0.9685/0.9686, 76 us/sample)")
    return params, metrics


def experiment_export(params, metrics, out):
    """(d) Notebook cell 10: per-neuron JSON export + embedded metrics."""
    model = spec_from_params(params, ["relu", "relu", "softmax"])
    model.metadata["inference_metrics"] = metrics
    from tpu_dist_nn.core.schema import save_model

    save_model(model, out)
    with open(out) as f:
        obj = json.load(f)
    n_neurons = sum(len(l["neurons"]) for l in obj["layers"])
    print(f"[d] exported {out}: {len(obj['layers'])} layers, "
          f"{n_neurons} neurons, inference_metrics embedded "
          f"(acc {obj['inference_metrics']['accuracy']:.4f})")
    return obj


def experiment_payload_size(data):
    """(e) Notebook cell 11: one input example's wire size."""
    as_f64 = data.x[0].astype(np.float64).nbytes
    as_u8 = data.x[0].astype(np.uint8).nbytes
    print(f"[e] one input payload: {as_f64} B float64 (reference cell 11: "
          f"6272 B), {as_u8} B as uint8 pixels (the framework's wire format)")
    return as_f64


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default="/tmp/centralized_model.json")
    ap.add_argument("--num-examples", type=int, default=12000,
                    help="synthetic mode only")
    ap.add_argument("--data", default="digits",
                    help="digits (vendored REAL handwritten digits, "
                         "default) | synthetic | idx:DIR (real MNIST)")
    args = ap.parse_args(argv)

    linear_epochs = 15
    if args.data == "digits":
        from tpu_dist_nn.data.datasets import real_digits

        data, eval_data = real_digits("train"), real_digits("test")
        print("dataset: vendored REAL handwritten digits "
              f"({len(data)} train / {len(eval_data)} held-out)")
        linear_epochs = 150  # ~1.7k steps on 1438 rows (see docstring)
    elif args.data.startswith("idx:"):
        from tpu_dist_nn.data.datasets import load_mnist_idx

        data = load_mnist_idx(args.data[4:], "train")
        eval_data = load_mnist_idx(args.data[4:], "test")
    else:
        full = synthetic_mnist(args.num_examples)
        data, eval_data = full.split(0.9)

    experiment_linear_softmax(data, eval_data, epochs=linear_epochs)
    params, metrics = experiment_serving_mlp(data, eval_data)
    experiment_per_sample_latency(params, eval_data)
    experiment_export(params, metrics, args.out)
    experiment_payload_size(data)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
