"""Pipelined decode: masked one-group vs overlapped round-robin.

Measures what the round-robin structure buys: both decoders produce
token-for-token identical streams (parity-tested in
tests/test_generate.py), but the one-group scheme computes every stage
every tick with only one stage's result live (S× redundant FLOPs),
while the overlapped scheme keeps every stage useful every tick. The
tick model says the same total batch decoded as G = S groups should
take ~S× less wall time; this experiment measures it on the 8-device
virtual mesh and records both the ratio and the per-token numbers.

Run (8 virtual devices):

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/pp_decode_throughput.py
"""

from __future__ import annotations

import argparse
import json
import time


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default=None)
    ap.add_argument("--repeat", type=int, default=3)
    args = ap.parse_args()

    import jax

    if jax.default_backend() not in ("cpu", "tpu"):  # pragma: no cover
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from tpu_dist_nn.models.transformer import (
        TransformerConfig,
        init_transformer,
    )
    from tpu_dist_nn.parallel.mesh import MeshSpec, build_mesh
    from tpu_dist_nn.parallel.pp_generate import (
        make_pipeline_generate,
        make_pipeline_generate_overlapped,
    )
    from tpu_dist_nn.parallel.transformer_pipeline import shard_blocks

    S, G, Bg, T, N = 4, 4, 8, 16, 33
    cfg = TransformerConfig(
        vocab_size=256, d_model=128, n_heads=4, n_layers=8, d_ff=256,
        max_seq_len=T + N,
    )
    params = init_transformer(jax.random.key(0), cfg)
    rng = np.random.default_rng(1)
    prompts = jnp.asarray(rng.integers(0, 256, (G, Bg, T)), jnp.int32)
    mesh = build_mesh(MeshSpec(stage=S, data=1))
    params_pp = dict(params, blocks=shard_blocks(params["blocks"], S))

    masked = make_pipeline_generate(mesh, cfg, S, N)
    overlapped = make_pipeline_generate_overlapped(mesh, cfg, S, N, G)
    flat = prompts.reshape(G * Bg, T)

    def bench(fn, arg):
        out = fn(params_pp, arg)  # compile + warm
        jax.block_until_ready(out)
        best = float("inf")
        for _ in range(args.repeat):
            t0 = time.perf_counter()
            out = fn(params_pp, arg)
            jax.block_until_ready(out)
            best = min(best, time.perf_counter() - t0)
        return best, out

    t_mask, out_mask = bench(masked, flat)
    t_ovl, out_ovl = bench(overlapped, prompts)
    np.testing.assert_array_equal(
        np.asarray(out_mask), np.asarray(out_ovl).reshape(G * Bg, T + N)
    )

    tokens = G * Bg * N
    record = {
        "config": f"d{cfg.d_model}/L{cfg.n_layers}, S={S} stages, "
                  f"G={G} groups x Bg={Bg} rows, T={T} prompt, N={N} new",
        "masked_one_group": {
            "wall_s": round(t_mask, 4),
            "tokens_per_s": round(tokens / t_mask, 1),
            "ticks": f"~{N * S} (S per token, one stage live per tick)",
        },
        "overlapped_round_robin": {
            "wall_s": round(t_ovl, 4),
            "tokens_per_s": round(tokens / t_ovl, 1),
            "ticks": f"~{(N - 1) * G + S - 1} (one token leaves per tick)",
        },
        "speedup": round(t_mask / t_ovl, 2),
        "tick_model_prediction": (
            f"~{S}x: masked computes the FULL {G * Bg}-row batch on "
            f"every stage every tick; overlapped computes one "
            f"{Bg}-row group per stage per tick with no waste"
        ),
        "identical_outputs": True,
    }
    out = json.dumps(record, indent=2)
    print(out)
    if args.out:
        with open(args.out, "w") as f:
            f.write(out + "\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
