"""Wall-clock the pipeline-schedule family against the tick model.

VERDICT r4 weak item 2: the zero-bubble family's superiority rested
only on tick accounting + symbolic replay — "no wall-clock measurement
on any backend confirms ticks translate to time (per-branch cost
asymmetry, switch overhead, recompute could eat the margin)". This
experiment supplies the measurement, honestly scoped to what a 1-core
virtual-device box can show:

* The table executors dispatch per-device branches with ``lax.switch``
  (parallel/interleaved.py:381), so on ONE physical core a step's wall
  time is the SUM of taken-branch costs plus per-tick overhead — idle
  ticks are nearly free. A serialized wall-clock therefore CANNOT show
  the bubble advantage directly (that is a property of parallel
  hardware); what it CAN do is validate a measured per-branch cost
  model, which then prices the tick tables into a hardware-honest
  makespan prediction.

* **Branch microbench**: the four executor branch bodies are mirrored
  as standalone jitted programs at the exact chunk widths the
  schedules use — FWD (chunk forward), BWD (forward recompute + full
  vjp, interleaved.py `bwd`), BWD_B (recompute + input grad only,
  `bwd_b` — weight grads DCE'd), BWD_W (recompute + weight grads only,
  `bwd_w`). Measured min-of-R with value-fetch barriers. This exposes
  the asymmetry the tick model ignores: the zero-bubble split pays the
  forward RECOMPUTE twice (once in B, once in W).

* **Tick-table pricing**: for each schedule's real ``ScheduleTables``
  the parallel makespan is ``sum_t max_s c(op[s,t])`` and the
  serialized cost is ``sum_t sum_s c(op[s,t])``.

* **Validation**: the REAL train step (make_pipeline_lm_train_step —
  the same programs `tdn lm --schedule ...` runs) is wall-clocked on
  the 8-virtual-device mesh and compared against the serialized
  prediction; the residual per tick is the measured switch/dispatch +
  collective overhead, reported and folded into the parallel
  prediction.

Matched-granularity pairs (S=4, L=8): {1f1b(v=1), zb(v=1)} at 2
blocks/chunk and {interleaved(v=2), zb-v} at 1 block/chunk.

Writes artifacts/schedule_walltime_r05/RECORD.json. Run:
    python examples/schedule_walltime.py [--fast]
"""

from __future__ import annotations

import argparse
import json
import os
import time

import numpy as np

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=8"
).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import optax  # noqa: E402

from tpu_dist_nn.models.transformer import (  # noqa: E402
    TransformerConfig,
    init_transformer,
)
from tpu_dist_nn.parallel import schedule_table as st  # noqa: E402
from tpu_dist_nn.parallel.mesh import MeshSpec, build_mesh  # noqa: E402
from tpu_dist_nn.train.lm_trainer import (  # noqa: E402
    make_pipeline_lm_train_step,
)

ART = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "artifacts", "schedule_walltime_r05")

S = 4           # pipeline stages
L = 8           # transformer blocks
D_MODEL, N_HEADS, D_FF = 128, 4, 512
SEQ = 128
MICRO_B = 4     # rows per microbatch


def _cfg() -> TransformerConfig:
    return TransformerConfig(
        vocab_size=64, d_model=D_MODEL, n_heads=N_HEADS, n_layers=L,
        d_ff=D_FF, max_seq_len=SEQ,
    )


def _time(fn, *args, reps: int = 5) -> float:
    """min-of-reps seconds; a value fetch is the barrier (repo rule)."""
    out = fn(*args)
    np.asarray(jax.tree.leaves(out)[0]).ravel()[:1]
    best = float("inf")
    for _ in range(reps):
        t0 = time.monotonic()
        out = fn(*args)
        np.asarray(jax.tree.leaves(out)[0]).ravel()[:1]
        best = min(best, time.monotonic() - t0)
    return best


def _chunk_apply(blocks, x, cfg):
    """Forward through a chunk's block stack (the executor's per-tick
    compute, minus wire/buffer bookkeeping)."""
    from tpu_dist_nn.models.transformer import block_apply

    def body(carry, blk):
        return block_apply(blk, carry, cfg), None

    y, _ = jax.lax.scan(body, x, blocks)
    return y


def branch_costs(cfg, n_blocks: int, reps: int) -> dict:
    """Measured seconds for the four executor branch bodies at this
    chunk width (see module docstring for the mirrored structure)."""
    key = jax.random.key(0)
    params = init_transformer(key, cfg)
    blocks = jax.tree.map(lambda a: a[:n_blocks], params["blocks"])
    x = jax.random.normal(
        jax.random.key(1), (MICRO_B, SEQ, D_MODEL), jnp.float32
    )
    dy = jax.random.normal(jax.random.key(2), x.shape, jnp.float32)

    fwd = jax.jit(lambda b, xx: _chunk_apply(b, xx, cfg))

    def bwd_full(b, xx, cot):       # recompute fwd + full vjp
        y, vjp = jax.vjp(lambda bb, xi: _chunk_apply(bb, xi, cfg), b, xx)
        db, dx = vjp(cot)
        return dx, db

    def bwd_b(b, xx, cot):          # recompute fwd + input grad only
        y, vjp = jax.vjp(lambda xi: _chunk_apply(b, xi, cfg), xx)
        (dx,) = vjp(cot)
        return dx

    def bwd_w(b, xx, cot):          # recompute fwd + weight grads only
        y, vjp = jax.vjp(lambda bb: _chunk_apply(bb, xx, cfg), b)
        (db,) = vjp(cot)
        return db

    # The round-5 cotangent-stash split (parallel/split_backward.py):
    # B = one forward + backbone + dx GEMMs, stashing (act, cot) pairs;
    # W = pure dW GEMMs, no recompute — the executor-side fix the
    # recompute finding motivates, measured here at the same widths.
    from tpu_dist_nn.parallel.split_backward import (
        chunk_backward_split,
        chunk_weight_grads,
    )

    stash_b = jax.jit(
        lambda b, xx, cot: chunk_backward_split(b, xx, cot, cfg)
    )
    _, _, wstash = stash_b(blocks, x, dy)

    return {
        "F": _time(jax.jit(fwd), blocks, x, reps=reps),
        "B": _time(jax.jit(bwd_full), blocks, x, dy, reps=reps),
        "B_split_dx": _time(jax.jit(bwd_b), blocks, x, dy, reps=reps),
        "B_split_dw": _time(jax.jit(bwd_w), blocks, x, dy, reps=reps),
        "B_stash": _time(stash_b, blocks, x, dy, reps=reps),
        "W_gemm": _time(jax.jit(chunk_weight_grads), wstash, reps=reps),
    }


def price_tables(tb: st.ScheduleTables, c: dict) -> dict:
    """Tick-table pricing under measured branch costs."""
    cost = np.zeros_like(tb.op, dtype=np.float64)
    cost[tb.op == st.FWD] = c["F"]
    cost[tb.op == st.BWD] = c["B"]
    cost[tb.op == st.BWD_B] = c["B_split_dx"]
    cost[tb.op == st.BWD_W] = c["B_split_dw"]
    per_tick_max = cost.max(axis=0)
    return {
        "ticks": int(tb.ticks),
        "bubble_ticks": int(tb.bubble_ticks),
        "parallel_makespan_s": float(per_tick_max.sum()),
        "serialized_work_s": float(cost.sum()),
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--fast", action="store_true",
                    help="fewer reps / one M (CI smoke)")
    ap.add_argument("--out", default=os.path.join(ART, "RECORD.json"))
    args = ap.parse_args()
    reps = 2 if args.fast else 5
    ms = (8,) if args.fast else (8, 16)
    cfg = _cfg()
    os.makedirs(os.path.dirname(args.out), exist_ok=True)

    record = {
        "task": "schedule family wall-clock vs tick model "
                "(VERDICT r4 weak item 2)",
        "config": {
            "S": S, "L": L, "d_model": D_MODEL, "d_ff": D_FF, "seq": SEQ,
            "micro_batch": MICRO_B, "Ms": list(ms),
            "backend": "8-virtual-device CPU mesh (1 physical core): "
                       "serialized wall validates the branch-cost "
                       "model; the parallel makespan column is that "
                       "model priced over the real tick tables",
        },
        "branch_costs_s": {},
        "schedules": {},
    }

    # Branch costs at both chunk widths used below.
    for width in (2, 1):
        record["branch_costs_s"][f"{width}_blocks"] = branch_costs(
            cfg, width, reps
        )
    bc = record["branch_costs_s"]
    # The asymmetries the tick model ignores, stated explicitly:
    b2 = bc["2_blocks"]
    record["asymmetry"] = {
        "split_overhead_2blocks":
            (b2["B_split_dx"] + b2["B_split_dw"]) / b2["B"],
        "note": "B_split_dx + B_split_dw vs combined B: >1 means the "
                "zero-bubble split pays real extra compute (the "
                "forward recompute happens in BOTH halves)",
    }

    mesh = build_mesh(MeshSpec(stage=S))
    opt = optax.sgd(1e-3)

    # (name, schedule, v, table builder, branch-cost overrides): the
    # zb-stash arm prices BWD_B/BWD_W with the cotangent-stash costs —
    # and is also MEASURED, since make_pipeline_lm_train_step runs the
    # real stash executor for schedule="zb-stash".
    arms = [
        ("1f1b", "1f1b", 1,
         lambda M: st.build_interleaved_1f1b(S, 1, M), None),
        ("interleaved", "interleaved", 2,
         lambda M: st.build_interleaved_1f1b(S, 2, M), None),
        ("zb", "zb", 1, lambda M: st.build_zero_bubble(S, 1, M), None),
        ("zb-v", "zb-v", 2, lambda M: st.build_zb_v(S, M), None),
        ("zb-stash", "zb-stash", 1,
         lambda M: st.build_zero_bubble(S, 1, M),
         {"B_split_dx": "B_stash", "B_split_dw": "W_gemm"}),
    ]
    for name, sched, v, build, cost_overrides in arms:
        chunk_w = L // (S * v)
        c = dict(record["branch_costs_s"][f"{chunk_w}_blocks"])
        if cost_overrides:
            for dst, src in cost_overrides.items():
                c[dst] = c[src]
        per_m = {}
        for M in ms:
            tb = build(M)
            pricing = price_tables(tb, c)
            step = make_pipeline_lm_train_step(
                mesh, cfg, S, M, opt, schedule=sched, num_virtual=v,
            )
            params = init_transformer(jax.random.key(3), cfg)
            from tpu_dist_nn.parallel.transformer_pipeline import (
                shard_blocks,
                shard_blocks_interleaved,
                shard_blocks_vshape,
            )

            if sched == "zb-v":
                p = dict(params,
                         blocks=shard_blocks_vshape(params["blocks"], S))
            elif sched in ("interleaved", "zb", "zb-stash"):
                p = dict(params, blocks=shard_blocks_interleaved(
                    params["blocks"], S, v))
            else:
                p = dict(params, blocks=shard_blocks(params["blocks"], S))
            tokens = jnp.asarray(
                np.random.default_rng(M).integers(
                    0, 64, (MICRO_B * M, SEQ + 1)
                ),
                jnp.int32,
            )
            o = opt.init(p)
            measured = _time(
                lambda pp, oo, tt: step(pp, oo, tt)[2], p, o, tokens,
                reps=reps,
            )
            overhead_per_tick = (
                (measured - pricing["serialized_work_s"]) / pricing["ticks"]
            )
            per_m[f"M{M}"] = {
                **pricing,
                "measured_serialized_s": round(measured, 4),
                "serialized_model_error":
                    round(measured / pricing["serialized_work_s"] - 1, 3),
                "overhead_per_tick_s": round(overhead_per_tick, 6),
                "parallel_makespan_with_overhead_s": round(
                    pricing["parallel_makespan_s"]
                    + max(overhead_per_tick, 0.0) * pricing["ticks"], 4
                ),
            }
        record["schedules"][name] = {
            "num_virtual": v, "blocks_per_chunk": chunk_w, **per_m,
        }
        _write(record, args.out)

    # Ratios at the largest M, within MATCHED-GRANULARITY pairs only —
    # raw tick counts across different chunk widths are incomparable.
    # "canonical" prices ticks with the ZB paper's idealized weights
    # (F=1, combined B=2, split B=1, W=1 — no recompute); "measured"
    # prices them with this box's branch costs (split halves each pay
    # the forward recompute). The gap between the two columns IS the
    # answer to "does the tick model translate to time".
    Mk = f"M{ms[-1]}"
    canon = {"F": 1.0, "B": 2.0, "B_split_dx": 1.0, "B_split_dw": 1.0}

    def canon_makespan(name):
        _, sched, v, build, _ov = next(a for a in arms if a[0] == name)
        tb = build(ms[-1])
        return price_tables(tb, canon)["parallel_makespan_s"]

    record["matched_pairs"] = {}
    for a, b in (("1f1b", "zb"), ("interleaved", "zb-v"),
                 ("1f1b", "zb-stash")):
        chunk_w = record["schedules"][a]["blocks_per_chunk"]
        c = record["branch_costs_s"][f"{chunk_w}_blocks"]
        # Price the split schedule's tables with the COTANGENT-STASH
        # branch costs (split_backward.py: B_stash carries the one
        # forward + backbone + dx, W_gemm is pure dW GEMMs) — the
        # executor-side fix this experiment motivates, priced before
        # it is wired into the executor.
        _, _, _, build, _ov2 = next(x for x in arms if x[0] == b)
        tb = build(ms[-1])
        stash_costs = dict(c)
        stash_costs["B_split_dx"] = c["B_stash"]
        stash_costs["B_split_dw"] = c["W_gemm"]
        stash_pricing = price_tables(tb, stash_costs)
        base = record["schedules"][a][Mk]
        record["matched_pairs"][f"{b}_vs_{a}"] = {
            "canonical_tick_model": round(
                canon_makespan(b) / canon_makespan(a), 4
            ),
            "measured_cost_parallel_makespan": round(
                record["schedules"][b][Mk]
                ["parallel_makespan_with_overhead_s"]
                / base["parallel_makespan_with_overhead_s"], 4
            ),
            "stash_split_parallel_makespan": round(
                stash_pricing["parallel_makespan_s"]
                / base["parallel_makespan_s"], 4
            ),
            "granularity_blocks_per_chunk": chunk_w,
        }
    _write(record, args.out)
    print(json.dumps(record["matched_pairs"], indent=2))
    return 0


def _write(record, out):
    with open(out, "w") as f:
        json.dump(record, f, indent=2)
        f.write("\n")


if __name__ == "__main__":
    raise SystemExit(main())
