// Native data-loader primitives: multithreaded shuffled-batch assembly.
//
// The reference feeds its pipeline from Python lists serialized through
// proto on every hop (run_grpc_inference.py:135-137); the TPU build
// feeds HBM through an async queue (tpu_dist_nn/data/feed.py), and the
// host-side cost that remains is assembling shuffled batches: a row
// gather (plus dtype normalize for integer wire formats) over a large
// training array. These kernels do that assembly with std::thread
// fan-out so epoch shuffling never stalls the device queue.
//
// Exposed via ctypes from tpu_dist_nn/native/loader.py; every entry
// point is plain C ABI and thread-safe (no shared state).

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

int clamp_threads(long work_items, int requested) {
  unsigned hw = std::thread::hardware_concurrency();
  if (hw == 0) hw = 4;
  long t = requested > 0 ? requested : static_cast<long>(hw);
  t = std::min<long>(t, work_items);
  return static_cast<int>(std::max<long>(1, t));
}

template <typename Fn>
void parallel_for(long n, int n_threads, Fn&& fn) {
  int t = clamp_threads(n, n_threads);
  if (t == 1) {
    fn(0L, n);
    return;
  }
  std::vector<std::thread> workers;
  workers.reserve(t);
  long chunk = (n + t - 1) / t;
  for (int i = 0; i < t; ++i) {
    long lo = i * chunk;
    long hi = std::min(n, lo + chunk);
    if (lo >= hi) break;
    workers.emplace_back([lo, hi, &fn] { fn(lo, hi); });
  }
  for (auto& w : workers) w.join();
}

}  // namespace

extern "C" {

// Gather rows: dst[i] = src[idx[i]] for arbitrary row_bytes.
// Returns 0 on success, -1 on bad arguments.
int tdn_gather_rows(const void* src, long n_rows, long row_bytes,
                    const long* idx, long n_idx, void* dst, int n_threads) {
  if (src == nullptr || idx == nullptr || dst == nullptr || row_bytes <= 0)
    return -1;
  const char* s = static_cast<const char*>(src);
  char* d = static_cast<char*>(dst);
  std::atomic<bool> ok{true};
  parallel_for(n_idx, n_threads, [&](long lo, long hi) {
    for (long i = lo; i < hi; ++i) {
      long r = idx[i];
      if (r < 0 || r >= n_rows) {
        ok.store(false, std::memory_order_relaxed);
        continue;
      }
      std::memcpy(d + i * row_bytes, s + r * row_bytes,
                  static_cast<size_t>(row_bytes));
    }
  });
  return ok.load() ? 0 : -1;
}

// Fused gather + uint8 -> float32 normalize:
// dst[i, j] = float(src[idx[i], j]) * scale.
int tdn_gather_norm_u8(const uint8_t* src, long n_rows, long dim,
                       const long* idx, long n_idx, float* dst, float scale,
                       int n_threads) {
  if (src == nullptr || idx == nullptr || dst == nullptr || dim <= 0)
    return -1;
  std::atomic<bool> ok{true};
  parallel_for(n_idx, n_threads, [&](long lo, long hi) {
    for (long i = lo; i < hi; ++i) {
      long r = idx[i];
      if (r < 0 || r >= n_rows) {
        ok.store(false, std::memory_order_relaxed);
        continue;
      }
      const uint8_t* sp = src + r * dim;
      float* dp = dst + i * dim;
      for (long j = 0; j < dim; ++j) dp[j] = static_cast<float>(sp[j]) * scale;
    }
  });
  return ok.load() ? 0 : -1;
}

}  // extern "C"
