// Native codec for the framework's two public JSON schemas.
//
// Role: the reference's hot ser/de path ran on vendored native code —
// protobuf's C++ descriptor fast path (dist_nn_pb2.py:32) plus the
// per-hop Matrix pack/unpack (grpc_node.py:107,126). This framework has
// no wire format (stage hand-off is a device copy), so its only ser/de
// is the host-side JSON contract: model files
// {"layers":[{"neurons":[{"weights","bias","activation"}]}]} and
// example files {"examples":[{"input","label"}]}
// (config/config_sample.json, SURVEY.md C12). Python json.load on a
// 60k-example file is seconds of pure-Python list work; this parser
// reads the same schemas directly into packed float64/int32 buffers.
//
// Deliberately a *specialized* JSON reader: objects/arrays/numbers/
// strings/true/false/null, no \uXXXX escapes beyond pass-through (the
// schema carries no exotic strings). Any layer without a "neurons"
// array (e.g. conv2d) reports unsupported → the caller falls back to
// the Python path.
//
// C ABI only; bound from Python via ctypes (no pybind11 in the image).

#include <locale.h>

#include <cctype>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

// strtod is LC_NUMERIC-sensitive (a comma-decimal host locale would
// mis-parse "0.5"); JSON is locale-independent, so parse under a
// process-lifetime C locale.
static locale_t c_locale() {
  static locale_t loc = newlocale(LC_ALL_MASK, "C", static_cast<locale_t>(0));
  return loc;
}

namespace {

struct Parser {
  const char* p;
  const char* end;
  std::string err;

  explicit Parser(const char* data, long len) : p(data), end(data + len) {}

  bool fail(const std::string& msg) {
    if (err.empty()) {
      long off = static_cast<long>(p - (end - (end - p)));
      (void)off;
      err = msg;
    }
    return false;
  }

  void skip_ws() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r')) ++p;
  }

  bool expect(char c) {
    skip_ws();
    if (p < end && *p == c) {
      ++p;
      return true;
    }
    return fail(std::string("expected '") + c + "'");
  }

  bool peek(char c) {
    skip_ws();
    return p < end && *p == c;
  }

  bool parse_string(std::string* out) {
    skip_ws();
    if (p >= end || *p != '"') return fail("expected string");
    ++p;
    out->clear();
    while (p < end && *p != '"') {
      if (*p == '\\' && p + 1 < end) {
        ++p;
        switch (*p) {
          case 'n': out->push_back('\n'); break;
          case 't': out->push_back('\t'); break;
          case 'r': out->push_back('\r'); break;
          case 'b': out->push_back('\b'); break;
          case 'f': out->push_back('\f'); break;
          case 'u':
            // Pass the escape through verbatim; schema strings are
            // activation names / metadata keys, never \u sequences we
            // must decode to parse structure.
            out->push_back('\\');
            out->push_back('u');
            break;
          default: out->push_back(*p); break;
        }
        ++p;
      } else {
        out->push_back(*p);
        ++p;
      }
    }
    if (p >= end) return fail("unterminated string");
    ++p;  // closing quote
    return true;
  }

  bool parse_number(double* out) {
    skip_ws();
    char* num_end = nullptr;
    *out = strtod_l(p, &num_end, c_locale());
    if (num_end == p) return fail("expected number");
    p = num_end;
    return true;
  }

  // Strictly 1-D numeric array (neuron weights/bias rows — nesting here
  // is a malformed model the Python path rejects, not data to flatten).
  bool parse_numbers_1d(std::vector<double>* out) {
    if (!expect('[')) return false;
    if (peek(']')) { ++p; return true; }
    while (true) {
      skip_ws();
      if (p < end && *p == '[')
        return fail("weights must be a flat array of numbers");
      double d;
      if (!parse_number(&d)) return false;
      out->push_back(d);
      skip_ws();
      if (p < end && *p == ',') { ++p; continue; }
      return expect(']');
    }
  }

  // Skip any JSON value (used for keys we don't interpret).
  bool skip_value() {
    skip_ws();
    if (p >= end) return fail("unexpected end of input");
    char c = *p;
    if (c == '"') {
      std::string s;
      return parse_string(&s);
    }
    if (c == '{') {
      ++p;
      if (peek('}')) { ++p; return true; }
      while (true) {
        std::string key;
        if (!parse_string(&key)) return false;
        if (!expect(':')) return false;
        if (!skip_value()) return false;
        skip_ws();
        if (p < end && *p == ',') { ++p; continue; }
        return expect('}');
      }
    }
    if (c == '[') {
      ++p;
      if (peek(']')) { ++p; return true; }
      while (true) {
        if (!skip_value()) return false;
        skip_ws();
        if (p < end && *p == ',') { ++p; continue; }
        return expect(']');
      }
    }
    if (c == 't') {
      if (end - p >= 4 && strncmp(p, "true", 4) == 0) { p += 4; return true; }
      return fail("bad literal");
    }
    if (c == 'f') {
      if (end - p >= 5 && strncmp(p, "false", 5) == 0) { p += 5; return true; }
      return fail("bad literal");
    }
    if (c == 'n') {
      if (end - p >= 4 && strncmp(p, "null", 4) == 0) { p += 4; return true; }
      return fail("bad literal");
    }
    double d;
    return parse_number(&d);
  }

  // Flatten an arbitrarily nested numeric array into `out`.
  bool parse_flat_numbers(std::vector<double>* out) {
    if (!expect('[')) return false;
    if (peek(']')) { ++p; return true; }
    while (true) {
      skip_ws();
      if (p < end && *p == '[') {
        if (!parse_flat_numbers(out)) return false;
      } else {
        double d;
        if (!parse_number(&d)) return false;
        out->push_back(d);
      }
      skip_ws();
      if (p < end && *p == ',') { ++p; continue; }
      return expect(']');
    }
  }
};

struct LayerData {
  std::vector<double> weights;  // neuron-major rows: (out_dim, in_dim)
  std::vector<double> bias;
  std::string activation;
  std::string type;
  long in_dim = 0;
  long out_dim = 0;
};

}  // namespace

struct TdnModel {
  std::vector<LayerData> layers;
  long layers_start = -1;  // byte span of the "layers" value in the input
  long layers_end = -1;
  int unsupported = 0;  // a layer had no "neurons" array → Python fallback
  std::string err;
};

static void set_err(char* err, int errlen, const std::string& msg) {
  if (err && errlen > 0) {
    snprintf(err, static_cast<size_t>(errlen), "%s", msg.c_str());
  }
}

// Parse one {"weights": [...], "bias": x, "activation": "..."} neuron.
static bool parse_neuron(Parser& ps, std::vector<double>* row, double* bias,
                         std::string* activation, bool first) {
  if (!ps.expect('{')) return false;
  bool saw_weights = false, saw_bias = false;
  if (ps.peek('}')) { ++ps.p; return ps.fail("neuron object is empty"); }
  while (true) {
    std::string key;
    if (!ps.parse_string(&key)) return false;
    if (!ps.expect(':')) return false;
    if (key == "weights") {
      if (!ps.parse_numbers_1d(row)) return false;
      saw_weights = true;
    } else if (key == "bias") {
      if (!ps.parse_number(bias)) return false;
      saw_bias = true;
    } else if (key == "activation" && first) {
      if (!ps.parse_string(activation)) return false;
    } else {
      if (!ps.skip_value()) return false;
    }
    ps.skip_ws();
    if (ps.p < ps.end && *ps.p == ',') { ++ps.p; continue; }
    if (!ps.expect('}')) return false;
    break;
  }
  if (!saw_weights) return ps.fail("neuron has no weights");
  if (!saw_bias) return ps.fail("neuron has no bias");
  return true;
}

extern "C" {

// Parse a model JSON. Returns a handle (free with tdn_model_free) or
// nullptr with `err` set. A handle may still flag `unsupported` (layer
// without neurons) — caller then uses the Python path.
TdnModel* tdn_model_parse(const char* json, long len, char* err, int errlen) {
  Parser ps(json, len);
  TdnModel* m = new TdnModel();
  bool saw_layers = false;

  if (!ps.expect('{')) goto bad;
  if (ps.peek('}')) { set_err(err, errlen, "model has no layers"); delete m; return nullptr; }
  while (true) {
    std::string key;
    if (!ps.parse_string(&key)) goto bad;
    if (!ps.expect(':')) goto bad;
    if (key == "layers") {
      saw_layers = true;
      ps.skip_ws();
      m->layers_start = static_cast<long>(ps.p - json);
      if (!ps.expect('[')) goto bad;
      if (ps.peek(']')) {
        set_err(err, errlen, "model has no layers");
        delete m;
        return nullptr;
      }
      while (true) {
        // One layer object.
        if (!ps.expect('{')) goto bad;
        LayerData layer;
        bool saw_neurons = false;
        if (!ps.peek('}')) {
          while (true) {
            std::string lkey;
            if (!ps.parse_string(&lkey)) goto bad;
            if (!ps.expect(':')) goto bad;
            if (lkey == "neurons") {
              saw_neurons = true;
              if (!ps.expect('[')) goto bad;
              if (ps.peek(']')) { ++ps.p; ps.fail("layer has no neurons"); goto bad; }
              bool first = true;
              while (true) {
                std::vector<double> row;
                double bias = 0.0;
                if (!parse_neuron(ps, &row, &bias, &layer.activation, first))
                  goto bad;
                if (first) {
                  layer.in_dim = static_cast<long>(row.size());
                  if (layer.activation.empty()) layer.activation = "linear";
                } else if (static_cast<long>(row.size()) != layer.in_dim) {
                  ps.fail("neurons in a layer must have equal weight counts");
                  goto bad;
                }
                first = false;
                layer.weights.insert(layer.weights.end(), row.begin(), row.end());
                layer.bias.push_back(bias);
                ps.skip_ws();
                if (ps.p < ps.end && *ps.p == ',') { ++ps.p; continue; }
                if (!ps.expect(']')) goto bad;
                break;
              }
            } else if (lkey == "type") {
              if (!ps.parse_string(&layer.type)) goto bad;
            } else {
              if (!ps.skip_value()) goto bad;
            }
            ps.skip_ws();
            if (ps.p < ps.end && *ps.p == ',') { ++ps.p; continue; }
            if (!ps.expect('}')) goto bad;
            break;
          }
        } else {
          ++ps.p;  // consume '}' of empty layer object
        }
        if (!saw_neurons) m->unsupported = 1;
        layer.out_dim = static_cast<long>(layer.bias.size());
        if (layer.type.empty()) layer.type = "hidden";
        m->layers.push_back(std::move(layer));
        ps.skip_ws();
        if (ps.p < ps.end && *ps.p == ',') { ++ps.p; continue; }
        if (!ps.expect(']')) goto bad;
        break;
      }
      m->layers_end = static_cast<long>(ps.p - json);
    } else {
      if (!ps.skip_value()) goto bad;
    }
    ps.skip_ws();
    if (ps.p < ps.end && *ps.p == ',') { ++ps.p; continue; }
    if (!ps.expect('}')) goto bad;
    break;
  }
  if (!saw_layers) {
    set_err(err, errlen, "model has no layers");
    delete m;
    return nullptr;
  }
  return m;

bad:
  set_err(err, errlen, ps.err.empty() ? "parse error" : ps.err);
  delete m;
  return nullptr;
}

int tdn_model_unsupported(TdnModel* m) { return m->unsupported; }

int tdn_model_num_layers(TdnModel* m) {
  return static_cast<int>(m->layers.size());
}

int tdn_model_layers_span(TdnModel* m, long* start, long* end) {
  *start = m->layers_start;
  *end = m->layers_end;
  return 0;
}

int tdn_model_layer_dims(TdnModel* m, int i, long* in_dim, long* out_dim) {
  if (i < 0 || i >= static_cast<int>(m->layers.size())) return 1;
  *in_dim = m->layers[i].in_dim;
  *out_dim = m->layers[i].out_dim;
  return 0;
}

const char* tdn_model_layer_activation(TdnModel* m, int i) {
  if (i < 0 || i >= static_cast<int>(m->layers.size())) return "";
  return m->layers[i].activation.c_str();
}

const char* tdn_model_layer_type(TdnModel* m, int i) {
  if (i < 0 || i >= static_cast<int>(m->layers.size())) return "";
  return m->layers[i].type.c_str();
}

// Copy layer i's weights (neuron-major (out_dim, in_dim) rows — the
// schema's per-neuron layout; Python transposes per grpc_node.py:51)
// and bias into caller-allocated buffers.
int tdn_model_layer_fill(TdnModel* m, int i, double* w, double* b) {
  if (i < 0 || i >= static_cast<int>(m->layers.size())) return 1;
  const LayerData& L = m->layers[i];
  memcpy(w, L.weights.data(), L.weights.size() * sizeof(double));
  memcpy(b, L.bias.data(), L.bias.size() * sizeof(double));
  return 0;
}

void tdn_model_free(TdnModel* m) { delete m; }

// Parse an examples JSON → packed (n, dim) float64 inputs + int32
// labels (missing label → -1, load_examples parity). Nested "input"
// arrays are flattened. Buffers are malloc'd; free with tdn_buffer_free.
int tdn_parse_examples(const char* json, long len, double** inputs, long* n,
                       long* dim, int32_t** labels, char* err, int errlen) {
  Parser ps(json, len);
  std::vector<double> xs;
  std::vector<int32_t> ys;
  long d = -1;
  long count = 0;
  bool saw_examples = false;

  if (!ps.expect('{')) goto bad;
  if (ps.peek('}')) { set_err(err, errlen, "no examples"); return 1; }
  while (true) {
    std::string key;
    if (!ps.parse_string(&key)) goto bad;
    if (!ps.expect(':')) goto bad;
    if (key == "examples") {
      saw_examples = true;
      if (!ps.expect('[')) goto bad;
      if (ps.peek(']')) { ++ps.p; }
      else {
        while (true) {
          if (!ps.expect('{')) goto bad;
          double label = -1;
          size_t xs_before = xs.size();
          bool saw_input = false;
          if (!ps.peek('}')) {
            while (true) {
              std::string ekey;
              if (!ps.parse_string(&ekey)) goto bad;
              if (!ps.expect(':')) goto bad;
              if (ekey == "input") {
                if (!ps.parse_flat_numbers(&xs)) goto bad;
                saw_input = true;
              } else if (ekey == "label") {
                if (!ps.parse_number(&label)) goto bad;
              } else {
                if (!ps.skip_value()) goto bad;
              }
              ps.skip_ws();
              if (ps.p < ps.end && *ps.p == ',') { ++ps.p; continue; }
              if (!ps.expect('}')) goto bad;
              break;
            }
          } else {
            ++ps.p;
          }
          if (!saw_input) { ps.fail("example has no input"); goto bad; }
          long this_dim = static_cast<long>(xs.size() - xs_before);
          if (d < 0) d = this_dim;
          else if (this_dim != d) {
            ps.fail("examples have inconsistent input dimensions");
            goto bad;
          }
          ys.push_back(static_cast<int32_t>(label));
          ++count;
          ps.skip_ws();
          if (ps.p < ps.end && *ps.p == ',') { ++ps.p; continue; }
          if (!ps.expect(']')) goto bad;
          break;
        }
      }
    } else {
      if (!ps.skip_value()) goto bad;
    }
    ps.skip_ws();
    if (ps.p < ps.end && *ps.p == ',') { ++ps.p; continue; }
    if (!ps.expect('}')) goto bad;
    break;
  }
  if (!saw_examples) { set_err(err, errlen, "no examples key"); return 1; }

  *n = count;
  *dim = d < 0 ? 0 : d;
  *inputs = static_cast<double*>(malloc(xs.size() * sizeof(double)));
  *labels = static_cast<int32_t*>(malloc(ys.size() * sizeof(int32_t)));
  if ((xs.size() && !*inputs) || (ys.size() && !*labels)) {
    free(*inputs);
    free(*labels);
    set_err(err, errlen, "out of memory");
    return 1;
  }
  memcpy(*inputs, xs.data(), xs.size() * sizeof(double));
  memcpy(*labels, ys.data(), ys.size() * sizeof(int32_t));
  return 0;

bad:
  set_err(err, errlen, ps.err.empty() ? "parse error" : ps.err);
  return 1;
}

// Serialize (n, dim) inputs + labels to the examples JSON. Returns a
// malloc'd NUL-terminated string via *out (free with tdn_buffer_free)
// and its length, or -1 on allocation failure.
long tdn_write_examples(const double* x, const int32_t* labels, long n,
                        long dim, char** out) {
  std::string buf;
  buf.reserve(static_cast<size_t>(n) * (static_cast<size_t>(dim) * 20 + 32) + 16);
  buf += "{\"examples\": [";
  char num[32];
  for (long i = 0; i < n; ++i) {
    if (i) buf += ", ";
    buf += "{\"input\": [";
    for (long j = 0; j < dim; ++j) {
      if (j) buf += ", ";
      // %.17g round-trips every float64 exactly (shortest-exact would
      // need Ryu; json.dumps uses repr which is shortest — outputs
      // differ textually but re-parse identically).
      snprintf(num, sizeof(num), "%.17g", x[i * dim + j]);
      buf += num;
    }
    buf += "], \"label\": ";
    snprintf(num, sizeof(num), "%d", labels[i]);
    buf += num;
    buf += "}";
  }
  buf += "]}";
  *out = static_cast<char*>(malloc(buf.size() + 1));
  if (!*out) return -1;
  memcpy(*out, buf.data(), buf.size() + 1);
  return static_cast<long>(buf.size());
}

void tdn_buffer_free(void* ptr) { free(ptr); }

}  // extern "C"
