"""tdnlint acceptance: each rule fires on its violating fixture with
the right id and line, stays silent on the clean twin, and the `tdn
lint` gate holds in both directions — exit 0 on the shipped tree
(zero non-baselined findings), exit 1 on a planted violation. Also
covers the suppression and baseline workflows (docs/STATIC_ANALYSIS.md)
and the bench_gate report-header integration."""

import json
import os
import shutil
import subprocess
import sys

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "lint_fixtures")


def _load_tdnlint():
    # One loading contract for the whole repo: the CLI's by-path loader
    # (tests exercising it here keeps it from drifting).
    from tpu_dist_nn.cli import _load_tdnlint as load

    return load()


def _marker_lines(path):
    """Expected finding lines = the fixture's `# <- violation` markers,
    so editing a fixture cannot desynchronize the assertions."""
    with open(path) as f:
        return sorted(
            i for i, ln in enumerate(f, start=1) if "# <- violation" in ln
        )


RULE_FIXTURES = [
    ("lock-discipline", "lock_discipline"),
    ("tick-purity", "tick_purity"),
    ("metric-series-lifecycle", "metric_lifecycle"),
    ("admin-actuation", "admin_actuation"),
    ("jit-purity", "jit_purity"),
    # ISSUE 14 twins: the goodput tick callback rides the sampler via
    # the NEW add_goodput verb (tick-purity must cover it), and its
    # closed-label-space families carry no lifecycle obligation while
    # a per-replica fleet exporter does.
    ("tick-purity", "goodput_tick"),
    ("metric-series-lifecycle", "goodput_metrics"),
]


@pytest.mark.parametrize("rule,stem", RULE_FIXTURES)
def test_rule_fires_on_violating_fixture(rule, stem):
    tdnlint = _load_tdnlint()
    bad = os.path.join(FIXTURES, f"{stem}_bad.py")
    result = tdnlint.run_lint([bad])
    assert result["new"], f"{rule} found nothing in {bad}"
    assert {f.rule for f in result["new"]} == {rule}
    assert sorted(f.line for f in result["new"]) == _marker_lines(bad)


@pytest.mark.parametrize("rule,stem", RULE_FIXTURES)
def test_rule_silent_on_clean_twin(rule, stem):
    tdnlint = _load_tdnlint()
    clean = os.path.join(FIXTURES, f"{stem}_clean.py")
    result = tdnlint.run_lint([clean])
    assert result["new"] == [], [f.render() for f in result["new"]]


def test_shipped_tree_is_clean_via_tdn_lint_cli(capsys):
    """The acceptance gate's zero direction: `tdn lint tpu_dist_nn/`
    exits 0 with zero non-baselined findings on the shipped tree."""
    from tpu_dist_nn.cli import main

    rc = main(["lint", os.path.join(REPO_ROOT, "tpu_dist_nn")])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "0 findings" in out


def test_tdn_lint_exits_nonzero_on_planted_violation(tmp_path, capsys):
    """The other direction: a planted violation fails the gate with
    the offending rule id in the report."""
    planted = tmp_path / "planted.py"
    shutil.copyfile(
        os.path.join(FIXTURES, "lock_discipline_bad.py"), planted
    )
    from tpu_dist_nn.cli import main

    rc = main(["lint", str(planted), "--baseline", ""])
    out = capsys.readouterr().out
    assert rc == 1
    assert "[lock-discipline]" in out


def test_inline_suppression_silences_one_line(tmp_path):
    tdnlint = _load_tdnlint()
    src = open(
        os.path.join(FIXTURES, "lock_discipline_bad.py")
    ).read().replace(
        "# <- violation", "# tdnlint: disable=lock-discipline"
    )
    planted = tmp_path / "suppressed.py"
    planted.write_text(src)
    result = tdnlint.run_lint([str(planted)])
    assert result["new"] == []
    assert result["suppressed_total"] == 1


def test_baseline_workflow_grandfathers_then_reports_stale(tmp_path,
                                                          capsys):
    """--update-baseline grandfathers current findings (TODO
    justification), the next run exits 0 against it, and an entry whose
    finding was fixed is reported stale instead of rotting silently."""
    tdnlint = _load_tdnlint()
    planted = tmp_path / "mod.py"
    shutil.copyfile(
        os.path.join(FIXTURES, "lock_discipline_bad.py"), planted
    )
    base = tmp_path / "baseline.json"
    rc = tdnlint.main([str(planted), "--baseline", str(base),
                       "--update-baseline"])
    assert rc == 0
    doc = json.loads(base.read_text())
    assert len(doc["findings"]) == 1
    assert "TODO" in doc["findings"][0]["justification"]
    rc = tdnlint.main([str(planted), "--baseline", str(base)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "1 baselined" in out
    # Fix the violation: the entry goes stale (stderr warning, exit 0).
    shutil.copyfile(
        os.path.join(FIXTURES, "lock_discipline_clean.py"), planted
    )
    rc = tdnlint.main([str(planted), "--baseline", str(base)])
    captured = capsys.readouterr()
    assert rc == 0
    assert "stale baseline entry" in captured.err


def test_baseline_fingerprints_survive_line_drift(tmp_path):
    """Fingerprints are line-number-free: unrelated edits above a
    grandfathered finding must not invalidate its baseline entry."""
    tdnlint = _load_tdnlint()
    planted = tmp_path / "mod.py"
    src = open(os.path.join(FIXTURES, "lock_discipline_bad.py")).read()
    planted.write_text(src)
    base = tmp_path / "baseline.json"
    assert tdnlint.main([str(planted), "--baseline", str(base),
                         "--update-baseline"]) == 0
    planted.write_text("# an unrelated comment pushing lines down\n"
                       "# and another one\n" + src)
    result = tdnlint.run_lint([str(planted)],
                              baseline_path=str(base))
    assert result["new"] == []
    assert len(result["baselined"]) == 1


def test_list_rules_names_all_five(capsys):
    from tpu_dist_nn.cli import main

    assert main(["lint", "--list-rules"]) == 0
    out = capsys.readouterr().out.split()
    assert out == ["lock-discipline", "tick-purity",
                   "metric-series-lifecycle", "admin-actuation",
                   "jit-purity"]


def test_lint_json_line_is_machine_readable(tmp_path, capsys):
    from tpu_dist_nn.cli import main

    planted = tmp_path / "planted.py"
    shutil.copyfile(
        os.path.join(FIXTURES, "metric_lifecycle_bad.py"), planted
    )
    rc = main(["lint", str(planted), "--baseline", "", "--json"])
    out = capsys.readouterr().out
    assert rc == 1
    doc = json.loads(out.strip().splitlines()[-1])
    assert doc["findings"][0]["rule"] == "metric-series-lifecycle"
    assert doc["findings"][0]["line"] == _marker_lines(planted)[0]


def test_bench_gate_report_only_mentions_lint_status():
    """The regression report and invariant drift surface in one place:
    --report-only carries a lint: header line (clean on the shipped
    tree), enforce mode stays a pure perf verdict."""
    gate = os.path.join(REPO_ROOT, "tools", "bench_gate.py")
    base = [sys.executable, gate,
            "--current", os.path.join(REPO_ROOT, "BENCH_r05.json"),
            "--previous", os.path.join(REPO_ROOT, "BENCH_r04.json")]
    report = subprocess.run(base + ["--report-only"],
                            capture_output=True, text=True)
    assert report.returncode == 0, report.stderr
    assert "lint: clean" in report.stdout
    enforce = subprocess.run(base, capture_output=True, text=True)
    assert "lint:" not in enforce.stdout
