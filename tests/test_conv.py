"""Conv/pool layer family: parity, round-trip, training, engine routing."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_dist_nn.api.engine import Engine
from tpu_dist_nn.core.schema import Conv2DSpec, MaxPool2DSpec, load_model, save_model
from tpu_dist_nn.data.datasets import synthetic_mnist
from tpu_dist_nn.models.network import (
    build_network,
    init_conv_mlp,
    network_forward,
    network_logits,
    network_model_from_params,
)
from tpu_dist_nn.testing.oracle import oracle_forward_batch
from tpu_dist_nn.train import TrainConfig
from tpu_dist_nn.train.trainer import train_network


@pytest.fixture
def conv_model():
    # Tiny CIFAR-style hybrid: conv-pool-conv-pool-dense-dense.
    return init_conv_mlp(
        jax.random.key(0),
        in_shape=(8, 8, 3),
        conv_filters=(4, 8),
        hidden=(16,),
        num_classes=4,
    )


def test_conv_model_structure(conv_model):
    kinds = [l.kind for l in conv_model.layers]
    assert kinds == ["conv2d", "maxpool2d", "conv2d", "maxpool2d", "dense", "dense"]
    conv_model.validate_chain()
    assert not conv_model.is_dense
    assert conv_model.input_dim == 8 * 8 * 3
    assert conv_model.output_dim == 4


def test_conv_forward_matches_oracle(conv_model):
    plan, params = build_network(conv_model)
    x = np.random.default_rng(0).uniform(size=(5, conv_model.input_dim))
    got = np.asarray(jax.jit(lambda p, v: network_forward(plan, p, v))(params, jnp.asarray(x, jnp.float32)))
    want = oracle_forward_batch(conv_model, x)
    np.testing.assert_allclose(got, want, rtol=5e-4, atol=1e-5)
    np.testing.assert_allclose(got.sum(-1), np.ones(5), rtol=1e-5)


def test_conv_strided_valid_padding():
    spec = Conv2DSpec(
        in_shape=(7, 7, 2),
        weights=np.random.default_rng(1).normal(size=(3, 3, 2, 5)) * 0.3,
        biases=np.random.default_rng(2).normal(size=5) * 0.1,
        stride=(2, 2),
        padding="valid",
        activation="tanh",
    )
    from tpu_dist_nn.core.schema import ModelSpec

    model = ModelSpec(layers=[spec])
    assert spec.out_shape == (3, 3, 5)
    plan, params = build_network(model)
    x = np.random.default_rng(3).uniform(size=(3, spec.in_dim))
    got = np.asarray(network_forward(plan, params, jnp.asarray(x, jnp.float32)))
    want = oracle_forward_batch(model, x)
    np.testing.assert_allclose(got, want, rtol=5e-4, atol=1e-5)


def test_conv_json_round_trip(conv_model, tmp_path):
    p = tmp_path / "conv.json"
    save_model(conv_model, p)
    loaded = load_model(p)
    assert [l.kind for l in loaded.layers] == [l.kind for l in conv_model.layers]
    x = np.random.default_rng(4).uniform(size=(2, conv_model.input_dim))
    np.testing.assert_allclose(
        oracle_forward_batch(loaded, x), oracle_forward_batch(conv_model, x)
    )


def test_conv_validation_errors():
    with pytest.raises(ValueError, match="channels"):
        Conv2DSpec.from_json(
            {"in_shape": [4, 4, 3], "weights": np.zeros((3, 3, 2, 4)).tolist(),
             "bias": [0.0] * 4}
        )
    with pytest.raises(ValueError, match="padding"):
        Conv2DSpec(
            in_shape=(4, 4, 2), weights=np.zeros((3, 3, 2, 4)),
            biases=np.zeros(4), padding="reflect",
        ).validate()


def test_conv_training_learns():
    model = init_conv_mlp(
        jax.random.key(1), in_shape=(6, 6, 1), conv_filters=(4,),
        hidden=(16,), num_classes=3,
    )
    data = synthetic_mnist(400, num_classes=3, dim=36, noise=0.25, seed=7)
    train, test = data.split(0.8, seed=1)
    plan, params = build_network(model)
    params, history = train_network(
        plan, params, train, TrainConfig(epochs=25, batch_size=32), eval_data=test
    )
    assert history[-1]["loss"] < history[0]["loss"] * 0.7
    assert history[-1]["eval"]["accuracy"] > 0.8
    trained = network_model_from_params(model, params)
    # Pool layers keep their (parameterless) spec; conv weights updated.
    assert trained.layers[1].kind == "maxpool2d"
    assert not np.allclose(trained.layers[0].weights, model.layers[0].weights)


def test_engine_routes_conv_model(conv_model):
    # A pipelined placement request on a conv model runs on the
    # heterogeneous per-stage executor (not the dense SPMD pipeline,
    # whose uniform-shape shard_map can't carry shrinking feature maps).
    engine = Engine.up(conv_model, [3, 3])
    assert engine.pipelined
    assert engine.placement()["stage_kinds"][0][0] == "conv2d"
    x = np.random.default_rng(5).uniform(size=(4, conv_model.input_dim))
    got = engine.infer(x)
    want = oracle_forward_batch(conv_model, x)
    np.testing.assert_allclose(got, want, rtol=5e-4, atol=1e-5)


def test_engine_trains_conv_model(tmp_path):
    model = init_conv_mlp(
        jax.random.key(2), in_shape=(6, 6, 1), conv_filters=(4,),
        hidden=(8,), num_classes=3,
    )
    data = synthetic_mnist(200, num_classes=3, dim=36, noise=0.3, seed=8)
    engine = Engine.up(model)
    history = engine.train(data, TrainConfig(epochs=3, batch_size=32))
    assert history[-1]["loss"] < history[0]["loss"]
    out = tmp_path / "conv_trained.json"
    engine.export(out)
    reloaded = load_model(out)
    x = np.random.default_rng(6).uniform(size=(3, 36))
    np.testing.assert_allclose(
        engine.infer(x), oracle_forward_batch(reloaded, x), rtol=5e-4, atol=1e-5
    )


def test_maxpool_spec_round_trip():
    spec = MaxPool2DSpec(in_shape=(8, 8, 4), window=(2, 2))
    back = MaxPool2DSpec.from_json(spec.to_json())
    assert back.out_shape == (4, 4, 4)
    assert back.in_dim == 256 and back.out_dim == 64
