"""Fleet autopilot (ISSUE 12): burn-rate-driven autoscaling, request
hedging, weighted p2c, and the fleet manifest generator.

The control loop is unit-tested on a real :class:`ReplicaPool` with
synthetic targets and driven ticks (injected clock — no sleeps paced
by cooldowns); the quick-tier smoke runs the REAL loop over loopback
fake-engine replicas with a deterministic ``faults.py``-paced burst:
2 replicas scale to 3 under the burst and back down after it, with
every request answered. Hedging races fake futures so first-reply-
wins / loser-cancelled are asserted exactly, plus a real loopback
straggler-rescue; the manifest generator is asserted on content.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from tests.test_batcher_pipeline import AsyncFakeEngine
from tpu_dist_nn.obs.exposition import MetricsServer
from tpu_dist_nn.obs.registry import REGISTRY, Registry
from tpu_dist_nn.serving import (
    CircuitBreaker,
    GrpcClient,
    ReplicaPool,
    serve_engine,
    serve_router,
)
from tpu_dist_nn.serving.autoscale import Autoscaler
from tpu_dist_nn.serving.pool import ACTIVE, DRAINING
from tpu_dist_nn.serving.router import (
    HedgePolicy,
    Router,
    admin_post_routes,
    admin_routes,
)
from tpu_dist_nn.testing import faults


def _counter_total(name: str) -> float:
    m = REGISTRY.get(name)
    if m is None:
        return 0.0
    return float(sum(child.value for _, child in m.samples()))


def _fresh_targets(*names):
    for n in names:
        CircuitBreaker.evict(n)
    return names


def _wait_until(pred, timeout=5.0, interval=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


class _FakeSLO:
    """An SLOTracker stand-in whose fast burn the test dials."""

    def __init__(self, burn=0.0, total=10.0):
        self.burn = burn
        self.total = total

    def status(self):
        return {"objectives": [{
            "name": "synthetic",
            "windows": {"fast": {"burn_rate": self.burn,
                                 "total": self.total}},
        }]}


def _scaler(pool, **kw):
    """An Autoscaler with test-friendly defaults: everything decided
    in one tick, no cooldowns, virtual clock."""
    clk = kw.pop("clk", [0.0])
    defaults = dict(
        min_replicas=1, max_replicas=5,
        up_cooldown=0.0, down_cooldown=0.0,
        up_stable_ticks=1, down_stable_ticks=1,
        decommission_grace=30.0,
        clock=lambda: clk[0],
    )
    defaults.update(kw)
    a = Autoscaler(pool, **defaults)
    a._clk = clk  # the test advances it
    return a


def _recording_spawner(pool, prefix="spawned"):
    """A spawner that adds a synthetic replica and records the call."""
    calls = []

    def spawner():
        t = f"{prefix}:{len(calls)}"
        CircuitBreaker.evict(t)
        calls.append(t)
        pool.add(t)

    return spawner, calls


# ----------------------------------------------------- control loop


def test_synthetic_burn_scales_up_within_one_tick():
    targets = _fresh_targets("as-burn:a")
    pool = ReplicaPool(list(targets), seed=0)
    slo = _FakeSLO(burn=5.0)
    spawner, calls = _recording_spawner(pool, "as-burn-spawn")
    a = _scaler(pool, spawner=spawner, slo=slo, min_replicas=1,
                max_replicas=3)
    ups0 = _counter_total("tdn_autoscale_decisions_total")
    a.tick()
    assert _wait_until(lambda: calls and a._spawning == 0), \
        "fast burn > 1 must trigger a spawn within ONE evaluation tick"
    assert len(calls) == 1
    assert len(pool.targets()) == 2
    assert _counter_total("tdn_autoscale_decisions_total") == ups0 + 1
    pool.close()


def test_occupancy_over_ceiling_scales_up_and_band_is_quiet():
    targets = _fresh_targets("as-occ:a", "as-occ:b")
    pool = ReplicaPool(list(targets), seed=0)
    spawner, calls = _recording_spawner(pool, "as-occ-spawn")
    a = _scaler(pool, spawner=spawner, target_occupancy=0.6,
                hysteresis=0.25, min_replicas=2, max_replicas=4)
    now = time.monotonic()
    # Inside the hysteresis band (util == target): no decision.
    for r in pool.replicas():
        r.occupancy, r.pending_rows, r.scraped_at = 0.6, 0.0, now
    a.tick()
    time.sleep(0.05)
    assert not calls, "utilization inside the band must not scale"
    # Saturated decode ladders (occupancy 1.0 > 0.75 ceiling): scale.
    for r in pool.replicas():
        r.occupancy = 1.0
        r.scraped_at = time.monotonic()
    a.tick()
    assert _wait_until(lambda: calls and a._spawning == 0)
    assert len(calls) == 1
    pool.close()


def test_scale_down_below_floor_via_observed_drain_zero_dropped():
    """The victim drains before it is removed: with a forward still
    outstanding it stays DRAINING (un-placed but alive); only at
    outstanding == 0 does the next tick remove it. (Pool-SPAWNED
    replicas get membership removal; static ones are parked — see
    the park/unpark test below.)"""
    targets = _fresh_targets("as-down:a", "as-down:b", "as-down:c")
    pool = ReplicaPool(list(targets), seed=0)
    a = _scaler(pool, min_replicas=2, max_replicas=3)
    reps = {r.target: r for r in pool.replicas()}
    for r in reps.values():
        r.spawn_argv = ["stub"]  # pool-spawned: removal is the end state
    # Idle fleet except the victim's one in-flight forward; the others
    # look busier so the victim choice is deterministic.
    pool.begin(reps["as-down:a"])
    for _ in range(5):
        pool.begin(reps["as-down:b"])
        pool.begin(reps["as-down:c"])
    # Utilization: (1 + 5 + 5) / (32 * 3) ~ 0.11 < 0.45 floor.
    a.tick()
    assert reps["as-down:a"].state == DRAINING
    assert reps["as-down:a"].decommissioning
    assert "as-down:a" in pool.targets(), \
        "a replica with an outstanding forward must NOT be removed"
    a.tick()
    assert "as-down:a" in pool.targets()
    pool.done(reps["as-down:a"])  # the in-flight reply lands
    a.tick()
    assert "as-down:a" not in pool.targets(), \
        "drain observed (outstanding 0) -> removed"
    assert sorted(pool.targets()) == ["as-down:b", "as-down:c"]
    # At min_replicas now: no further shrink.
    a.tick()
    a.tick()
    assert len(pool.targets()) == 2
    pool.close()


def test_operator_undrain_cancels_decommission_not_removed():
    """Regression: pool.undrain during a scale-down clears the
    replica's decommissioning flag (it is back in service), but the
    autoscaler's pending-removal entry used to survive — and the next
    tick silently removed the in-service replica."""
    targets = _fresh_targets("as-cancel:a", "as-cancel:b", "as-cancel:c")
    pool = ReplicaPool(list(targets), seed=0)
    slo = _FakeSLO(burn=0.0)
    a = _scaler(pool, slo=slo, min_replicas=2, max_replicas=3)
    reps = {r.target: r for r in pool.replicas()}
    for r in reps.values():
        r.spawn_argv = ["stub"]
    pool.begin(reps["as-cancel:a"])  # deterministic victim, held busy
    for _ in range(5):
        pool.begin(reps["as-cancel:b"])
        pool.begin(reps["as-cancel:c"])
    a.tick()
    assert reps["as-cancel:a"].decommissioning
    assert pool.undrain("as-cancel:a"), "operator cancels the scale-down"
    assert not reps["as-cancel:a"].decommissioning
    pool.done(reps["as-cancel:a"])  # now idle AND removable-looking
    slo.burn = 5.0  # burning budget: no further scale-down decisions
    a.tick()
    a.tick()
    assert "as-cancel:a" in pool.targets(), \
        "an undrained (in-service) replica must never be removed"
    assert reps["as-cancel:a"].state == ACTIVE
    assert a.status()["decommissioning"] == []
    pool.close()


def test_static_fleet_parks_and_unparks_instead_of_ratcheting():
    """Regression: on a fleet the pool did not spawn (static /
    manifest-managed), scale-down used to REMOVE membership — and with
    no spawner, nothing could ever grow the fleet back. Static victims
    are parked (drained, rejoin-exempt) and scale-up un-parks them."""
    targets = _fresh_targets("as-park:a", "as-park:b", "as-park:c")
    pool = ReplicaPool(list(targets), seed=0)
    slo = _FakeSLO(burn=0.0)
    a = _scaler(pool, slo=slo, min_replicas=1, max_replicas=3,
                flap_reversals=99)  # the down→up cycle IS the test
    a.tick()  # idle: park one
    assert sorted(pool.targets()) == sorted(targets), \
        "static membership must survive a scale-down"
    parked = a.status()["parked"]
    assert len(parked) == 1
    rep = {r.target: r for r in pool.replicas()}[parked[0]]
    assert rep.state == DRAINING and rep.decommissioning
    a._clk[0] += 10.0
    a.tick()
    assert len(a.status()["parked"]) == 2, "keeps parking down to min"
    # Load returns: scale-up re-admits parked capacity (no spawner
    # needed) instead of being stuck at min forever.
    slo.burn = 5.0
    a._clk[0] += 10.0
    a.tick()
    assert a.current_size() == 2
    a._clk[0] += 10.0
    a.tick()
    assert a.current_size() == 3
    assert a.status()["parked"] == []
    assert all(r.state == ACTIVE and not r.decommissioning
               for r in pool.replicas())
    pool.close()


def test_up_cooldown_suppresses_back_to_back_spawns():
    targets = _fresh_targets("as-cool:a")
    pool = ReplicaPool(list(targets), seed=0)
    slo = _FakeSLO(burn=5.0)
    spawner, calls = _recording_spawner(pool, "as-cool-spawn")
    a = _scaler(pool, spawner=spawner, slo=slo, up_cooldown=100.0,
                max_replicas=5)
    a.tick()
    assert _wait_until(lambda: len(calls) == 1 and a._spawning == 0)
    a._clk[0] += 1.0  # still inside the cooldown
    a.tick()
    a.tick()
    time.sleep(0.05)
    assert len(calls) == 1, "a second spawn inside up_cooldown"
    a._clk[0] += 200.0  # cooldown over, burn persists
    a.tick()
    assert _wait_until(lambda: len(calls) == 2 and a._spawning == 0)
    pool.close()


def test_flap_reversals_suppress_and_count_and_recover():
    targets = _fresh_targets("as-flap:a", "as-flap:b")
    pool = ReplicaPool(list(targets), seed=0)
    slo = _FakeSLO(burn=5.0)
    spawner, calls = _recording_spawner(pool, "as-flap-spawn")
    a = _scaler(pool, spawner=spawner, slo=slo, min_replicas=1,
                max_replicas=5, flap_window=1000.0, flap_reversals=2,
                flap_cooldown=500.0)
    flaps0 = _counter_total("tdn_autoscale_flaps_total")
    a.tick()  # up
    assert _wait_until(lambda: len(calls) == 1 and a._spawning == 0)
    slo.burn = 0.0  # idle fleet -> down (reversal #1, allowed)
    a._clk[0] += 1.0
    a.tick()
    assert any(r.decommissioning for r in pool.replicas())
    slo.burn = 5.0  # burn again -> up would be reversal #2: FLAP
    a._clk[0] += 1.0
    a.tick()
    time.sleep(0.05)
    assert len(calls) == 1, "the flapping reversal must be suppressed"
    assert _counter_total("tdn_autoscale_flaps_total") == flaps0 + 1
    assert a.status()["flap_suppressed"] is True
    # Still muted inside the flap cooldown.
    a._clk[0] += 100.0
    a.tick()
    time.sleep(0.05)
    assert len(calls) == 1
    assert a.current_size() == 2
    # Past the cooldown the policy re-arms: the scale-up re-admits the
    # PARKED static victim (cheaper than a spawn) and capacity is back.
    a._clk[0] += 1000.0
    a.tick()
    assert _wait_until(lambda: a.current_size() == 3
                       and a._spawning == 0)
    assert len(calls) == 1, "un-park must be preferred over a spawn"
    assert a.status()["flap_suppressed"] is False
    assert a.status()["parked"] == []
    pool.close()


def test_bounds_are_hard_and_crash_respawn_counts_as_capacity():
    targets = _fresh_targets("as-bound:a", "as-bound:b")
    pool = ReplicaPool(list(targets), seed=0)
    slo = _FakeSLO(burn=9.0)
    spawner, calls = _recording_spawner(pool, "as-bound-spawn")
    a = _scaler(pool, spawner=spawner, slo=slo, min_replicas=2,
                max_replicas=2)
    a.tick()
    time.sleep(0.05)
    assert not calls, "at max_replicas a burning SLO must not spawn"
    # A crashed child mid-respawn is DRAINING but still counts as
    # capacity: min_replicas is satisfied, so no double-spawn.
    rep = pool.replicas()[0]
    rep.state = DRAINING
    rep.respawning = True
    slo.burn = 0.0
    a._clk[0] += 10.0
    a.tick()
    time.sleep(0.05)
    assert not calls, \
        "a crash-respawn in flight must not read as a shrunken fleet"
    assert a.current_size() == 2
    pool.close()


def test_manual_scale_override_via_post_route_and_status_route():
    targets = _fresh_targets("as-post:a")
    pool = ReplicaPool(list(targets), seed=0)
    spawner, calls = _recording_spawner(pool, "as-post-spawn")
    a = _scaler(pool, spawner=spawner, min_replicas=1, max_replicas=3,
                up_cooldown=1e9)  # cooldown must NOT gate the override
    srv = MetricsServer(0, "127.0.0.1",
                        routes=admin_routes(pool, autoscaler=a),
                        post_routes=admin_post_routes(pool, a))
    try:
        base = f"http://127.0.0.1:{srv.port}"

        def post(path):
            req = urllib.request.Request(base + path, data=b"",
                                         method="POST")
            with urllib.request.urlopen(req, timeout=5) as resp:
                return resp.status, json.loads(resp.read())

        status, doc = post("/router/scale?replicas=3")
        assert status == 200 and doc["mode"] == "manual"
        assert doc["granted"] == 3
        a.tick()
        assert _wait_until(lambda: len(calls) == 1 and a._spawning == 0)
        a.tick()
        assert _wait_until(lambda: len(calls) == 2 and a._spawning == 0)
        a.tick()
        time.sleep(0.05)
        assert len(calls) == 2, "override converged at 3, stop there"
        # Out-of-bounds requests clamp to the envelope.
        _, doc = post("/router/scale?replicas=99")
        assert doc["granted"] == 3
        # GET on the POST-only path is rejected (a scraper sweep must
        # not actuate the fleet).
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(base + "/router/scale?replicas=1",
                                   timeout=5)
        assert ei.value.code == 405
        # Back to the policy.
        status, doc = post("/router/scale?mode=auto")
        assert status == 200 and doc["mode"] == "auto"
        with urllib.request.urlopen(base + "/router/autoscale",
                                    timeout=5) as resp:
            doc = json.loads(resp.read())
        assert doc["mode"] == "auto" and doc["current"] == 3
    finally:
        srv.close()
        pool.close()


def test_override_resets_stability_counters():
    """Regression: a breach tick counted BEFORE a manual override used
    to survive it frozen — one noisy scrape after mode=auto completed
    the streak and scaled immediately. The streak restarts."""
    targets = _fresh_targets("as-reset:a")
    pool = ReplicaPool(list(targets), seed=0)
    slo = _FakeSLO(burn=5.0)
    spawner, calls = _recording_spawner(pool, "as-reset-spawn")
    a = _scaler(pool, spawner=spawner, slo=slo, up_stable_ticks=2,
                min_replicas=1, max_replicas=3)
    a.tick()  # breach tick 1 of 2: no action yet
    time.sleep(0.05)
    assert not calls
    a.set_override(1)  # park the fleet at its current size
    a._clk[0] += 1.0
    a.tick()
    a.clear_override()
    a._clk[0] += 1.0
    a.tick()  # back to auto, still breaching: tick 1 of 2 AGAIN
    time.sleep(0.05)
    assert not calls, "stability streak must restart after an override"
    a._clk[0] += 1.0
    a.tick()  # second consecutive breach: now act
    assert _wait_until(lambda: len(calls) == 1 and a._spawning == 0)
    pool.close()


def test_stale_park_pruned_and_noop_scale_up_burns_no_cooldown():
    """Regression: an operator undraining a parked replica left a
    stale park entry; the next scale-up consumed its cooldown slot and
    a flap-history action on an un-park that could not happen."""
    targets = _fresh_targets("as-stale:a", "as-stale:b")
    pool = ReplicaPool(list(targets), seed=0)
    slo = _FakeSLO(burn=0.0)
    a = _scaler(pool, slo=slo, min_replicas=1, max_replicas=3,
                flap_reversals=99)
    a.tick()  # idle: parks one
    parked = a.status()["parked"]
    assert len(parked) == 1
    assert pool.undrain(parked[0]), "operator takes the replica back"
    slo.burn = 5.0
    a._clk[0] += 10.0
    a.tick()  # prune drops the stale entry; no actuator remains
    assert a.status()["parked"] == []
    assert a._last_up is None, \
        "a no-op scale-up must not consume the cooldown slot"
    pool.close()


def test_post_scale_without_autoscaler_is_conflict():
    srv = MetricsServer(0, "127.0.0.1",
                        post_routes=admin_post_routes())
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/router/scale?replicas=2",
            data=b"", method="POST",
        )
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=5)
        assert ei.value.code == 409
        assert b"--autoscale-min" in ei.value.read()
    finally:
        srv.close()


# ----------------------------------------------------- weighted p2c


def test_weighted_p2c_explicit_weights_blend_heterogeneous_fleet():
    a, b = _fresh_targets("w:fast", "w:slow")
    pool = ReplicaPool([a, b], weights=[4.0, 1.0], seed=0)
    ra, rb = pool.replicas()
    # Equal raw backlog: the 4x replica scores 1/4 of the 1x one and
    # keeps winning until it holds ~4x the work.
    for _ in range(4):
        pool.begin(ra)
        pool.begin(rb)
    assert {pool.place().target for _ in range(20)} == {a}
    for _ in range(12):
        pool.begin(ra)  # fast replica now at 16 vs 4: scores 4 vs 4
    for _ in range(2):
        pool.begin(ra)  # past its fair share: slow one wins again
    assert {pool.place().target for _ in range(20)} == {b}
    pool.close()


def test_weight_derives_from_scraped_warm_buckets_unless_explicit():
    a, b = _fresh_targets("w:warm", "w:cold")
    pool = ReplicaPool([a, b], seed=0)
    ra, rb = pool.replicas()
    assert ra.capacity_weight == 1.0, "no signal -> homogeneous"
    ra.warm_buckets = 8.0
    rb.warm_buckets = 2.0
    assert ra.capacity_weight == 8.0 and rb.capacity_weight == 2.0
    ra.weight = 1.5  # explicit flag beats the derived signal
    assert ra.capacity_weight == 1.5
    pool.close()


# --------------------------------------------------------- hedging


class _FakeFuture:
    def __init__(self, result=None, error=None, delay=0.0):
        self._result = result
        self._error = error
        self._done = threading.Event()
        self._cancelled = False
        self._callbacks = []
        self._lock = threading.Lock()
        if delay <= 0:
            self._complete()
        else:
            t = threading.Timer(delay, self._complete)
            t.daemon = True  # a cancelled long-delay fake must not
            t.start()        # hold interpreter exit hostage

    def _complete(self):
        with self._lock:
            if self._done.is_set():
                return
            self._done.set()
            callbacks = list(self._callbacks)
        for cb in callbacks:
            cb(self)

    def add_done_callback(self, cb):
        with self._lock:
            if not self._done.is_set():
                self._callbacks.append(cb)
                return
        cb(self)

    def done(self):
        return self._done.is_set()

    def cancelled(self):
        return self._cancelled

    def cancel(self):
        with self._lock:
            if self._done.is_set():
                return False
            self._cancelled = True
            self._error = RuntimeError("cancelled")
        self._complete()
        return True

    def result(self, timeout=None):
        self._done.wait(timeout)
        if self._error is not None:
            raise self._error
        return self._result


class _Ctx:
    def invocation_metadata(self):
        return ()

    def time_remaining(self):
        return None

    def set_trailing_metadata(self, md):
        pass

    def abort(self, code, msg):
        raise AssertionError(f"aborted {code}: {msg}")


def _primed_latency(seconds=0.02, n=30):
    reg = Registry()
    fam = reg.histogram("t_hedge_seconds", "test latency",
                        labels=("method",))
    child = fam.labels(method="Process")
    for _ in range(n):
        child.observe(seconds)
    return fam


def test_hedge_fires_once_first_reply_wins_loser_cancelled():
    a, b = _fresh_targets("hedge:slow", "hedge:fast")
    pool = ReplicaPool([a, b], seed=0)
    ra, rb = pool.replicas()
    futures = {}

    def make_call_future(rep, result, delay):
        def call_future(method, payload, *, timeout=None, metadata=()):
            fut = _FakeFuture(result=result, delay=delay)
            futures[rep.target] = fut
            return fut

        return call_future

    ra.call_future = make_call_future(ra, b"slow-reply", 1.0)
    rb.call_future = make_call_future(rb, b"fast-reply", 0.01)
    # p2c must pick the slow replica as primary.
    for _ in range(5):
        pool.begin(rb)
    hedge = HedgePolicy(1.0, min_observations=1,
                        latency=_primed_latency(0.02))
    router = Router(pool, hedge=hedge)
    fired0 = _counter_total("tdn_router_hedges_total")
    wins0 = _counter_total("tdn_router_hedge_wins_total")
    reply = router.handle("Process", b"req", _Ctx())
    assert reply == b"fast-reply", "first reply wins"
    assert _counter_total("tdn_router_hedges_total") == fired0 + 1, \
        "exactly one hedge per request"
    assert _counter_total("tdn_router_hedge_wins_total") == wins0 + 1
    assert futures["hedge:slow"].cancelled(), "the loser is cancelled"
    assert _wait_until(
        lambda: ra.outstanding == 0 and rb.outstanding == 5
    ), "both copies' outstanding bookkeeping must settle"
    pool.close()


def test_hedge_primary_wins_inside_patience_no_hedge_fired():
    a, b = _fresh_targets("hedgefast:a", "hedgefast:b")
    pool = ReplicaPool([a, b], seed=0)
    for rep in pool.replicas():
        rep.call_future = (
            lambda method, payload, timeout=None, metadata=():
            _FakeFuture(result=b"quick", delay=0.0)
        )
    hedge = HedgePolicy(1.0, min_observations=1,
                        latency=_primed_latency(0.05))
    router = Router(pool, hedge=hedge)
    fired0 = _counter_total("tdn_router_hedges_total")
    assert router.handle("Process", b"req", _Ctx()) == b"quick"
    assert _counter_total("tdn_router_hedges_total") == fired0, \
        "a primary inside the patience window must not hedge"
    pool.close()


def test_hedge_deterministic_error_propagates_without_waiting():
    """Regression: a non-transient verdict (INVALID_ARGUMENT) from one
    hedge copy used to wait out the OTHER in-flight copy before
    surfacing — up to the full forward timeout. It must propagate
    immediately and cancel the survivor."""
    import grpc

    a, b = _fresh_targets("hedgedet:a", "hedgedet:b")
    pool = ReplicaPool([a, b], seed=0)
    ra, rb = pool.replicas()

    class _Invalid(grpc.RpcError):
        def code(self):
            return grpc.StatusCode.INVALID_ARGUMENT

        def details(self):
            return "bad matrix"

    futures = {}

    def make(rep, **kw):
        def call_future(method, payload, *, timeout=None, metadata=()):
            fut = _FakeFuture(**kw)
            futures[rep.target] = fut
            return fut

        return call_future

    # Primary stalls (hedge fires), then errors DETERMINISTICALLY at
    # ~60ms while the hedge would take 10s.
    ra.call_future = make(ra, error=_Invalid(), delay=0.06)
    rb.call_future = make(rb, result=b"slow", delay=10.0)
    for _ in range(5):
        pool.begin(rb)  # primary = ra
    hedge = HedgePolicy(1.0, min_observations=1,
                        latency=_primed_latency(0.02))
    router = Router(pool, hedge=hedge)

    class AbortCtx(_Ctx):
        def abort(self, code, msg):
            raise _Abort(code, msg)

    class _Abort(Exception):
        def __init__(self, code, msg):
            super().__init__(msg)
            self.code = code

    t0 = time.monotonic()
    with pytest.raises(_Abort) as ei:
        router.handle("Process", b"req", AbortCtx())
    elapsed = time.monotonic() - t0
    assert ei.value.code == grpc.StatusCode.INVALID_ARGUMENT
    assert elapsed < 2.0, (
        f"deterministic verdict must not wait out the 10s hedge copy "
        f"(took {elapsed:.1f}s)"
    )
    assert futures["hedgedet:b"].cancelled(), \
        "the surviving copy is cancelled, not awaited"
    pool.close()


def test_hedge_wedged_copies_cancelled_no_outstanding_leak():
    """Regression: when BOTH hedge copies wedge past the wait cap, the
    pending futures must be cancelled on the bail-out path — each
    holds a pool.begin() that only its done callback releases, and
    leaking it biased p2c away from the replica forever and wedged
    any later drain's outstanding==0 barrier."""
    import grpc

    a, b = _fresh_targets("hedgewedge:a", "hedgewedge:b")
    pool = ReplicaPool([a, b], seed=0)
    ra, rb = pool.replicas()
    futures = {}

    def make(rep):
        def call_future(method, payload, *, timeout=None, metadata=()):
            fut = _FakeFuture(result=b"never", delay=3600.0)
            futures[rep.target] = fut
            return fut

        return call_future

    ra.call_future = make(ra)
    rb.call_future = make(rb)
    for _ in range(3):
        pool.begin(rb)  # primary = ra
    hedge = HedgePolicy(1.0, min_observations=1,
                        latency=_primed_latency(0.02))
    # retry=None: one attempt, so the bail-out path surfaces directly.
    router = Router(pool, retry=None, forward_timeout=0.2, hedge=hedge)

    class _Abort(Exception):
        def __init__(self, code, msg):
            super().__init__(msg)
            self.code = code

    class AbortCtx(_Ctx):
        def abort(self, code, msg):
            raise _Abort(code, msg)

    with pytest.raises(_Abort) as ei:
        router.handle("Process", b"req", AbortCtx())
    assert ei.value.code == grpc.StatusCode.DEADLINE_EXCEEDED
    assert futures["hedgewedge:a"].cancelled()
    assert futures["hedgewedge:b"].cancelled()
    assert _wait_until(
        lambda: ra.outstanding == 0 and rb.outstanding == 3
    ), "wedged copies must release their outstanding accounting"
    pool.close()


def test_hedge_off_for_generate_by_default():
    a, b = _fresh_targets("hedgegen:a", "hedgegen:b")
    pool = ReplicaPool([a, b], seed=0)
    for rep in pool.replicas():
        rep.call = (
            lambda method, payload, timeout=None, metadata=(): b"tokens"
        )
        rep.call_future = _boom
    hedge = HedgePolicy(1.0, min_observations=1,
                        latency=_primed_latency(0.02))
    assert not hedge.applies("Generate")
    router = Router(pool, hedge=hedge)
    fired0 = _counter_total("tdn_router_hedges_total")
    assert router.handle("Generate", b"req", _Ctx()) == b"tokens"
    assert _counter_total("tdn_router_hedges_total") == fired0, \
        "Generate is not idempotent under sampling: no hedging unless " \
        "opted in"
    pool.close()


def _boom(*a, **k):
    raise AssertionError("call_future must not be used on this path")


def test_hedge_skipped_without_latency_history():
    a, b = _fresh_targets("hedgecold:a", "hedgecold:b")
    pool = ReplicaPool([a, b], seed=0)
    for rep in pool.replicas():
        rep.call = (
            lambda method, payload, timeout=None, metadata=(): b"ok"
        )
        rep.call_future = _boom
    reg = Registry()
    empty = reg.histogram("t_cold_seconds", "", labels=("method",))
    hedge = HedgePolicy(1.0, min_observations=5, latency=empty)
    assert hedge.delay("Process") is None
    router = Router(pool, hedge=hedge)
    assert router.handle("Process", b"req", _Ctx()) == b"ok"
    pool.close()


def test_hedge_rescues_straggler_over_loopback_wire():
    """End-to-end: a 2-replica loopback fleet where one replica is a
    deliberate straggler; hedged Process requests are rescued by the
    fast replica and p99 improves vs the same fleet unhedged."""
    slow = AsyncFakeEngine(dim=8, dispatch_seconds=0.12)
    fast = AsyncFakeEngine(dim=8, dispatch_seconds=0.002)
    servers, targets = [], []
    for e in (slow, fast):
        srv, port = serve_engine(e, 0, host="127.0.0.1")
        servers.append(srv)
        targets.append(f"127.0.0.1:{port}")
    _fresh_targets(*targets)
    hedge = HedgePolicy(1.0, min_observations=1, min_delay_s=0.02,
                        latency=_primed_latency(0.02))
    pool = ReplicaPool(targets, seed=0)
    rsrv, rport = serve_router(pool, 0, host="127.0.0.1", hedge=hedge)
    try:
        c = GrpcClient(f"127.0.0.1:{rport}", timeout=10.0, breaker=None)
        x = np.zeros((1, 8))
        fired0 = _counter_total("tdn_router_hedges_total")
        lats = []
        for _ in range(12):
            t0 = time.monotonic()
            out = c.process(x)
            lats.append(time.monotonic() - t0)
            assert out.shape == (1, 8)
        c.close()
        assert _counter_total("tdn_router_hedges_total") > fired0, \
            "requests placed on the straggler must have hedged"
        # Every request beat the straggler's 120ms dispatch: the hedge
        # (patience ~20-30ms + fast replica ~ms) rescued the tail.
        assert max(lats) < 0.12, (
            f"hedge should cap the tail below the straggler's 120ms "
            f"service time, got max {max(lats) * 1e3:.0f}ms"
        )
    finally:
        rsrv.stop(0)
        pool.close()
        for srv in servers:
            srv.stop(0)


# ------------------------------------------------ quick-tier smoke


def test_autoscale_smoke_fleet_scales_up_and_back_down():
    """The acceptance drill: a 2-replica loopback fleet under a
    deterministic faults.py-paced burst scales to 3 within the burst
    and drains back to 2 after it, with every request answered (zero
    dropped). The control loop is driven tick-by-tick so nothing
    depends on wall-clock cadence."""
    engines, servers, targets = [], [], []

    def add_replica():
        e = AsyncFakeEngine(dim=8)
        # The deterministic pacing: every launch pays a fixed
        # faults.py delay, so the burst's backlog (and the signal the
        # autoscaler sees) is load-shaped, not scheduler noise.
        e.infer_async = faults.wrap(
            e.infer_async,
            faults.FaultPlan(every=1, fault=faults.delay(0.03)),
        )
        srv, port = serve_engine(e, 0, host="127.0.0.1")
        engines.append(e)
        servers.append(srv)
        t = f"127.0.0.1:{port}"
        CircuitBreaker.evict(t)
        targets.append(t)
        return t

    for _ in range(2):
        add_replica()
    pool = ReplicaPool(targets[:2], seed=0)
    rsrv, rport = serve_router(pool, 0, host="127.0.0.1")
    spawned = []

    def spawner():
        t = add_replica()
        spawned.append(t)
        pool.add(t)

    a = Autoscaler(
        pool, min_replicas=2, max_replicas=3, spawner=spawner,
        rows_capacity=2.0, target_occupancy=0.6, hysteresis=0.25,
        up_cooldown=0.0, down_cooldown=0.0,
        up_stable_ticks=2, down_stable_ticks=2,
        decommission_grace=10.0,
    )
    replies = []
    errors = []
    lock = threading.Lock()

    def worker(i):
        try:
            c = GrpcClient(f"127.0.0.1:{rport}", timeout=30.0,
                           breaker=None)
            x = np.full((1, 8), float(i))
            for _ in range(6):
                out = c.process(x)
                with lock:
                    replies.append(out[0, 0])
            c.close()
        except Exception as e:  # noqa: BLE001 — the assertion below reports it
            with lock:
                errors.append(f"{type(e).__name__}: {e}")

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(8)]
    for th in threads:
        th.start()
    # Drive the control loop while the burst runs: 8 concurrent rows
    # over 2 replicas at rows_capacity 2 pushes utilization ~2x the
    # 0.75 ceiling; two stable ticks fire the spawn.
    deadline = time.monotonic() + 20.0
    while any(th.is_alive() for th in threads):
        a.tick()
        if time.monotonic() > deadline:
            break
        time.sleep(0.02)
    for th in threads:
        th.join(timeout=30.0)
    assert not errors, f"burst must complete cleanly: {errors[:3]}"
    assert len(replies) == 48, "zero dropped requests through the scale-up"
    assert len(spawned) == 1 and len(targets) == 3, \
        "the burst must have scaled 2 -> 3"
    # Post-burst: idle utilization below the floor drains capacity
    # back out through the observed-drain choreography. The replicas
    # here are in-process (not pool-spawned), so the victim is PARKED
    # — drained, out of rotation, re-admittable — not removed.
    assert _wait_until(lambda: a._spawning == 0)
    active = []
    for _ in range(20):
        a.tick()
        active = [r for r in pool.replicas() if r.state == ACTIVE
                  and not r.decommissioning]
        if len(active) == 2:
            break
        time.sleep(0.02)
    assert len(active) == 2, "idle fleet must scale back down"
    assert a.current_size() == 2
    assert len(a.status()["parked"]) == 1
    assert _counter_total("tdn_autoscale_decisions_total") >= 2
    rsrv.stop(0)
    pool.close()
    for srv in servers:
        srv.stop(0)


# ------------------------------------------------------- manifests


def test_compose_manifest_wires_drain_choreography():
    from tpu_dist_nn.serving.manifest import build_spec, compose_manifest

    spec = build_spec(3, drain_grace_seconds=10.0,
                      autoscale={"min": 2, "max": 4,
                                 "target_occupancy": 0.7},
                      hedge_after_p99_ratio=2.0)
    text = compose_manifest(spec)
    for i in range(3):
        assert f"tdn-replica-{i}:" in text
    assert "/healthz" in text, "healthcheck must speak the pool's probe"
    assert "stop_grace_period: 15s" in text, \
        "stop grace must cover --drain-grace-seconds"
    assert "restart: unless-stopped" in text
    assert ("\"--replicas\", \"tdn-replica-0:5101,tdn-replica-1:5101,"
            "tdn-replica-2:5101\"") in text
    assert "\"--replica-metrics\", \"tdn-replica-0:9101" in text
    assert "--autoscale-min" in text and "--hedge-after-p99-ratio" in text
    assert "condition: service_healthy" in text


def test_k8s_manifest_stable_dns_probes_and_grace():
    from tpu_dist_nn.serving.manifest import build_spec, k8s_manifest

    spec = build_spec(2, drain_grace_seconds=10.0)
    text = k8s_manifest(spec)
    assert "kind: StatefulSet" in text and "clusterIP: None" in text, \
        "replicas need stable per-pod DNS (headless Service)"
    assert "tdn-replica-0.tdn-replica:5101,tdn-replica-1.tdn-replica:5101" \
        in text.replace('", "', "|").replace('"', "").replace("|", ",") \
        or "tdn-replica-0.tdn-replica" in text
    assert "readinessProbe" in text and "path: /healthz" in text
    assert "terminationGracePeriodSeconds: 15" in text
    assert "kind: Deployment" in text  # the router
    assert text.count("kind: Service") == 2


def test_manifest_rejects_invalid_autoscale_bounds():
    """The same envelope Autoscaler enforces: an invalid manifest must
    fail at generation, not crash-loop the deployed router."""
    from tpu_dist_nn.serving.manifest import build_spec

    with pytest.raises(ValueError):
        build_spec(2, autoscale={"min": 5, "max": 2})
    with pytest.raises(ValueError):
        build_spec(2, autoscale={"min": 0, "max": 2})
    with pytest.raises(ValueError):
        build_spec(2, autoscale={"min": 1, "max": 2,
                                 "target_occupancy": 0.0})
    with pytest.raises(ValueError):
        build_spec(2, autoscale={"max": 2})


def test_manifest_sized_from_running_pool_snapshot():
    from tpu_dist_nn.serving.manifest import spec_from_snapshot

    snap = [
        {"target": "a:1", "state": "active"},
        {"target": "b:1", "state": "draining"},
        {"target": "c:1", "state": "removed"},
    ]
    spec = spec_from_snapshot(snap)
    assert spec["replicas"] == 2, "removed replicas don't count"
    with pytest.raises(ValueError):
        spec_from_snapshot([{"target": "x", "state": "removed"}])


def test_fleet_manifest_cli_emits_compose(capsys):
    from tpu_dist_nn import cli

    rc = cli.main(["fleet", "manifest", "--replicas-count", "2"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "services:" in out and "tdn-replica-1:" in out
    assert "tdn-router:" in out


# ------------------------------------------------------ bench gate


def test_bench_gate_autoscale_ratio_skip_and_fail():
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "bench_gate",
        os.path.join(os.path.dirname(__file__), "..", "tools",
                     "bench_gate.py"),
    )
    bench_gate = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench_gate)
    base = {"backend": "cpu", "value": 100.0}
    prev_no_section = dict(base, serving={"coalesced": {"rps": 50.0}})
    cur = dict(base, serving={
        "coalesced": {"rps": 50.0},
        "autoscale": {"replica_seconds_ratio": 0.7},
    })
    verdict = bench_gate.compare(prev_no_section, cur)
    rows = {r["metric"]: r for r in verdict["metrics"]}
    assert "skipped" in rows["autoscale_replica_seconds_ratio"], \
        "rounds predating ISSUE 12 must skip, not fail"
    prev = dict(base, serving={"autoscale": {"replica_seconds_ratio": 0.7}})
    cur_reg = dict(base,
                   serving={"autoscale": {"replica_seconds_ratio": 0.8}})
    verdict = bench_gate.compare(prev, cur_reg)
    assert "autoscale_replica_seconds_ratio" in verdict["regressions"], \
        "lower-is-better: the ratio rising >5% is a regression"
    cur_ok = dict(base,
                  serving={"autoscale": {"replica_seconds_ratio": 0.6}})
    verdict = bench_gate.compare(prev, cur_ok)
    assert "autoscale_replica_seconds_ratio" not in verdict["regressions"]
